"""ABD linearizable register: quorum-replicated shared memory.

Mirrors ``/root/reference/examples/linearizable-register.rs``: the Attiya,
Bar-Noy, Dolev algorithm ("Sharing Memory Robustly in Message-Passing
Systems", doi:10.1145/200836.200869). Every operation runs two phases:

1. **Query**: poll a quorum for (logical-clock sequencer, value) pairs;
2. **Record**: write back the maximal pair (for a write: the incremented
   sequencer and the new value) and wait for a quorum of acks.

Because both reads and writes perform the write-back phase, the register is
linearizable with any majority quorum.

Exact-count oracle from the reference's own test
(linearizable-register.rs:289,316): 544 unique states at 2 clients /
2 servers on an unordered non-duplicating network, both BFS and DFS.
"""

from __future__ import annotations

from typing import Any, FrozenSet, NamedTuple, Optional, Tuple

from ..actor import (
    Actor,
    ActorModel,
    Id,
    Network,
    Out,
    StateRef,
    majority,
    model_peers,
)
from ..actor import register as reg
from ..core import Expectation
from ..packing import PackedModelAdapter
from ..semantics import LinearizabilityTester
from ..semantics.register import Register
from ..utils.variant import variant

Seq = Tuple[int, Id]  # (logical clock, writer id) — totally ordered

# Internal ABD protocol messages (linearizable-register.rs:28-33).
Query = variant("Query", ["request_id"])
AckQuery = variant("AckQuery", ["request_id", "seq", "value"])
Record = variant("Record", ["request_id", "seq", "value"])
AckRecord = variant("AckRecord", ["request_id"])

# The two client-request phases (linearizable-register.rs:44-57).
# ``responses`` is a map Id -> (Seq, Value) stored as a frozenset of pairs;
# ``acks`` is a frozenset of replica ids.  ``write`` (phase 1) and ``read``
# (phase 2) are ``None`` for the other operation kind and a 1-tuple
# ``(value,)`` otherwise — the tuple keeps a value of ``None`` (a read of
# the unwritten default, or a Put of None) distinct from "not this kind of
# operation" (Rust's Option<Value> makes the same distinction, rs:48,54).
Phase1 = variant("Phase1", ["request_id", "requester_id", "write", "responses"])
Phase2 = variant("Phase2", ["request_id", "requester_id", "read", "acks"])


class AbdState(NamedTuple):
    """Replica state (linearizable-register.rs:37-41)."""

    seq: Seq
    val: Any
    phase: Optional[Any]


def _map_insert(m: FrozenSet, k: Any, v: Any) -> FrozenSet:
    d = dict(m)
    d[k] = v
    return frozenset(d.items())


class AbdActor(Actor):
    """One ABD replica; also coordinates client requests
    (linearizable-register.rs:64-214)."""

    def __init__(self, peers):
        self.peers = list(peers)

    def on_start(self, id: Id, out: Out) -> AbdState:
        return AbdState(seq=(0, id), val=None, phase=None)

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        s: AbdState = state.get()

        if isinstance(msg, (reg.Put, reg.Get)) and s.phase is None:
            # Begin phase 1: poll a quorum, seeding with our own pair
            # (linearizable-register.rs:86-111). ``write`` is a 1-tuple so a
            # Put of ``None`` stays distinct from a Get (same trick as
            # ``read`` below).
            write = (msg.value,) if isinstance(msg, reg.Put) else None
            out.broadcast(self.peers, reg.Internal(Query(msg.request_id)))
            state.set(
                s._replace(
                    phase=Phase1(
                        request_id=msg.request_id,
                        requester_id=src,
                        write=write,
                        responses=_map_insert(frozenset(), id, (s.seq, s.val)),
                    )
                )
            )
            return

        if not isinstance(msg, reg.Internal):
            return
        m = msg.msg

        if isinstance(m, Query):
            out.send(src, reg.Internal(AckQuery(m.request_id, s.seq, s.val)))

        elif (
            isinstance(m, AckQuery)
            and isinstance(s.phase, Phase1)
            and s.phase.request_id == m.request_id
        ):
            # Collect quorum responses; on quorum, pick the maximal
            # (seq, value), bump the clock for writes, and move to phase 2
            # with Record/AckRecord self-sends applied inline
            # (linearizable-register.rs:118-176).
            p = s.phase
            responses = _map_insert(p.responses, src, (m.seq, m.value))
            if len(responses) < majority(len(self.peers) + 1):
                state.set(s._replace(phase=p._replace(responses=responses)))
                return
            # Sequencers are distinct ((clock, id) pairs), so max is
            # deterministic (comment at linearizable-register.rs:139-142).
            seq, val = max((v for _k, v in responses), key=lambda sv: sv[0])
            read = None
            if p.write is not None:
                seq = (seq[0] + 1, id)
                val = p.write[0]
            else:
                read = (val,)
            out.broadcast(self.peers, reg.Internal(Record(p.request_id, seq, val)))
            s2 = s
            if seq > s.seq:  # self-send Record
                s2 = s2._replace(seq=seq, val=val)
            state.set(
                s2._replace(
                    phase=Phase2(
                        request_id=p.request_id,
                        requester_id=p.requester_id,
                        read=read,
                        acks=frozenset((id,)),  # self-send AckRecord
                    )
                )
            )

        elif isinstance(m, Record):
            # Adopt newer pairs; always ack (linearizable-register.rs:177-184).
            out.send(src, reg.Internal(AckRecord(m.request_id)))
            if m.seq > s.seq:
                state.set(s._replace(seq=m.seq, val=m.value))

        elif (
            isinstance(m, AckRecord)
            and isinstance(s.phase, Phase2)
            and s.phase.request_id == m.request_id
            and src not in s.phase.acks
        ):
            # On an ack quorum, answer the client and clear the phase
            # (linearizable-register.rs:185-210).
            p = s.phase
            acks = p.acks | {src}
            if len(acks) == majority(len(self.peers) + 1):
                if p.read is not None:
                    out.send(p.requester_id, reg.GetOk(p.request_id, p.read[0]))
                else:
                    out.send(p.requester_id, reg.PutOk(p.request_id))
                state.set(s._replace(phase=None))
            else:
                state.set(s._replace(phase=p._replace(acks=acks)))


def linearizable_register_model(
    client_count: int = 2,
    server_count: int = 2,
    network: Optional[Network] = None,
) -> ActorModel:
    """Build the checkable model (linearizable-register.rs:223-257)."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    model = ActorModel(cfg=None, init_history=LinearizabilityTester(Register(None)))
    for i in range(server_count):
        model.actor(AbdActor(model_peers(i, server_count)))
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, "linearizable", reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


class PackedAbd(reg.PackedClientsMixin, PackedModelAdapter):
    """The ABD quorum register on the device engine (``spawn_xla``): the
    oracle configuration (2 clients / 2 servers, 544 unique states,
    linearizable-register.rs:289,316) and the 3-client / 2-server
    configuration, whose ``linearizable`` property runs device-EXACT over
    the 3-thread interleaving enumeration
    (:mod:`stateright_tpu.semantics.device`).

    Same construction as :class:`~stateright_tpu.models.paxos.PackedPaxos`:
    a syntactically closed envelope universe as presence bits (empirically
    all counts stay at 1), per-message-family vectorized delivery bodies
    vmapped over parameter tables, and the ``LinearizabilityTester`` history
    carried via :class:`~stateright_tpu.packing.BoundedHistory` with the
    ``linearizable`` property checked exactly on device
    (``device_linearizable_register``).

    Codec bounds (verified by full enumeration of the object model):
    logical clocks are bounded by the Put count (each Put bumps the max
    clock once), so sequencers form the closed set ``(clock 0..C, writer)``;
    Phase1 response values and AckQuery/Record payloads pack as
    ``seq_code * NV + val_code``. The 2-server restriction keeps quorum
    arithmetic static (majority = 2: the coordinator's self-entry plus the
    single peer); wider clusters model-check on the host engines.

    Requests are keyed ``(coordinator s, local index r)``: server ``s``
    coordinates client k's Put when ``(S+k) % S == s`` and client k's Get
    when ``(S+k+1) % S == s`` (the RegisterClient round-robin,
    register.rs:118-120) — ``self._reqs[s]`` lists ``(client, kind)`` with
    kind 0 = Put, 1 = Get.
    """

    def __init__(self, client_count: int = 2, server_count: int = 2):
        from ..actor.network import Envelope
        from ..packing import BoundedHistory, LayoutBuilder, OverflowError32, bits_for

        if server_count != 2 or client_count not in (2, 3):
            raise ValueError(
                "PackedAbd packs S=2 (single-peer quorum arithmetic) with "
                "2 or 3 clients; other sizes run on the host engines"
            )
        C, S = client_count, server_count
        self._init_core(C, S, OverflowError32)
        self._inner = linearizable_register_model(C, S)
        NV, NSQ = self.NV, self.NSQ
        NSV = NSQ * NV  # (seq, value) pair codes
        reqs, rix = self._reqs, self._rix
        req_id = self._req_id

        # --- the closed envelope universe -------------------------------
        envs: list = []
        handlers: list = []
        self._code_put: list = []
        self._code_putok: list = []
        self._code_get: list = []
        self._base_getok: list = []
        self._code_query: dict = {}
        self._base_ackquery: dict = {}
        self._base_record: dict = {}
        self._code_ackrecord: dict = {}

        for k in range(C):
            i = S + k
            self._code_put.append(len(envs))
            envs.append(Envelope(Id(i), Id(i % S), reg.Put(i, self.values[1 + k])))
            handlers.append(("begin", rix[(k, 0)]))
        for k in range(C):
            self._code_putok.append(len(envs))
            envs.append(Envelope(Id(k % S), Id(S + k), reg.PutOk(S + k)))
            handlers.append(("putok", (k,)))
        for k in range(C):
            i = S + k
            self._code_get.append(len(envs))
            envs.append(Envelope(Id(i), Id((i + 1) % S), reg.Get(2 * i)))
            handlers.append(("begin", rix[(k, 1)]))
        for k in range(C):
            i = S + k
            self._base_getok.append(len(envs))
            for v in range(NV):
                envs.append(
                    Envelope(Id((i + 1) % S), Id(i), reg.GetOk(2 * i, self.values[v]))
                )
                handlers.append(("getok", (k, v)))
        for c in range(S):  # Query: coordinator c -> its peer
            p = (c + 1) % S
            for r in range(len(reqs[c])):
                self._code_query[(c, r)] = len(envs)
                envs.append(Envelope(Id(c), Id(p), reg.Internal(Query(req_id(c, r)))))
                handlers.append(("query", (p, c, r)))
        for c in range(S):  # AckQuery: peer -> coordinator, contiguous in (seq, val)
            p = (c + 1) % S
            for r in range(len(reqs[c])):
                self._base_ackquery[(c, r)] = len(envs)
                for sq in range(NSQ):
                    for v in range(NV):
                        envs.append(
                            Envelope(
                                Id(p),
                                Id(c),
                                reg.Internal(
                                    AckQuery(
                                        req_id(c, r), self._seqs[sq], self.values[v]
                                    )
                                ),
                            )
                        )
                        handlers.append(("ackquery", (c, r, p, sq * NV + v)))
        for c in range(S):  # Record: coordinator -> peer, contiguous in (seq, val)
            p = (c + 1) % S
            for r in range(len(reqs[c])):
                self._base_record[(c, r)] = len(envs)
                for sq in range(NSQ):
                    for v in range(NV):
                        envs.append(
                            Envelope(
                                Id(c),
                                Id(p),
                                reg.Internal(
                                    Record(
                                        req_id(c, r), self._seqs[sq], self.values[v]
                                    )
                                ),
                            )
                        )
                        handlers.append(("record", (p, c, r, sq * NV + v)))
        for c in range(S):  # AckRecord: peer -> coordinator
            p = (c + 1) % S
            for r in range(len(reqs[c])):
                self._code_ackrecord[(c, r)] = len(envs)
                envs.append(
                    Envelope(Id(p), Id(c), reg.Internal(AckRecord(req_id(c, r))))
                )
                handlers.append(("ackrecord", (c, r, p)))

        self._envs = envs
        self._handlers = handlers
        self._env_code = {env: code for code, env in enumerate(envs)}
        self._U = len(envs)
        self.max_actions = self._U

        # --- layout ------------------------------------------------------
        b = LayoutBuilder()
        self._server_layout(b, bits_for)
        self._client_layout(b)
        b.array("net", self._U, 1)
        code_bits = bits_for(NV)
        self._hist = BoundedHistory(
            b,
            thread_ids=[Id(S + k) for k in range(C)],
            max_ops=2,
            op_bits=code_bits,
            ret_bits=code_bits,
        )
        self._layout = b.finish()
        self._hist.bind(self._layout)
        self.state_words = self._layout.words

        codecs = reg.history_codecs(self.values)
        self._op_code, self._code_op, self._ret_code, self._code_ret = codecs

        self._families = self._build_families()

    # --- code helpers -------------------------------------------------------

    def _seq_code(self, seq) -> int:
        try:
            return self._seqs.index(seq)
        except ValueError:
            raise self._OverflowError32(f"sequencer outside universe: {seq!r}")

    def _sv_code(self, seq, val) -> int:
        return self._seq_code(seq) * self.NV + self._val_code(val)

    def _init_core(self, C: int, S: int, OverflowError32) -> None:
        """Protocol structure shared by the unordered and ordered packed
        forms: the value/sequencer universes and the per-server request
        table (class docstring)."""
        self.C, self.S = C, S
        self.majority = S // 2 + 1
        self._OverflowError32 = OverflowError32

        #: values[0] is the unwritten None; client k writes values[1+k].
        self.values = self._client_values()
        self.NV = len(self.values)
        #: seq codes, monotone in the model's (clock, Id) order:
        #: code = clock * S + writer, clock 0..C.
        self._seqs = [(c, Id(w)) for c in range(C + 1) for w in range(S)]
        self.NSQ = len(self._seqs)

        # Per-server request table (see class docstring): Puts first, then
        # Gets, so the 2-client table reproduces the round-1 (Put, Get)
        # req_bit order exactly.
        reqs = {s: [] for s in range(S)}
        for k in range(C):
            reqs[(S + k) % S].append((k, 0))
        for k in range(C):
            reqs[(S + k + 1) % S].append((k, 1))
        self._reqs = reqs
        self._maxR = max(len(v) for v in reqs.values())

        def req_id(s: int, r: int) -> int:
            k, kind = reqs[s][r]
            return (S + k) if kind == 0 else 2 * (S + k)

        def requester(s: int, r: int) -> int:
            return S + reqs[s][r][0]

        self._req_id, self._requester = req_id, requester
        rix = {}  # (client, kind) -> (coordinator, local request index)
        for s in range(S):
            for r, (k, kind) in enumerate(reqs[s]):
                rix[(k, kind)] = (s, r)
        self._rix = rix

    def _server_layout(self, b, bits_for) -> None:
        """Per-server replica + phase fields (shared by both network
        packings)."""
        S, NV, NSQ = self.S, self.NV, self.NSQ
        b.array("seq", S, bits_for(NSQ - 1))
        b.array("val", S, bits_for(NV - 1))
        b.array("kind", S, 2)  # 0 = no phase, 1 = Phase1, 2 = Phase2
        # Local request index of the active phase (see self._reqs).
        b.array("p_req", S, max(bits_for(self._maxR - 1), 1))
        # Phase2: 0 = write op, 1+v = read of values[v].
        b.array("read", S, bits_for(NV))
        b.array("rp", S * S, 1)  # Phase1 responses presence, idx s*S + key
        b.array("rv", S * S, bits_for(NSQ * NV - 1))  # Phase1 (seq,val) codes
        b.array("ak", S * S, 1)  # Phase2 acks, idx s*S + voter

    def _phase_req(self, s: int, phase) -> int:
        """The validated local request index of server ``s``'s active phase:
        its request id and requester must be ones this server coordinates."""
        for r in range(len(self._reqs[s])):
            if phase.request_id == self._req_id(s, r) and int(
                phase.requester_id
            ) == self._requester(s, r):
                return r
        raise self._OverflowError32(f"phase request outside universe: {phase!r}")

    def _build_families(self):
        def params_for(kind: str, params) -> list:
            if kind == "begin":
                c, r = params
                return [c, r, self._code_query[(c, r)]]
            if kind == "putok":
                (k,) = params
                return [k, self._code_get[k]]
            if kind == "getok":
                k, v = params
                return [k, 1 + v]  # ReadOk(values[v]) ret code
            if kind == "query":
                p, c, r = params
                return [p, self._base_ackquery[(c, r)]]
            if kind == "ackquery":
                c, r, p, sv = params
                k, req_kind = self._reqs[c][r]
                is_write = 1 if req_kind == 0 else 0
                wval = 1 + k if req_kind == 0 else 0
                return [c, r, p, sv, self._base_record[(c, r)], wval, is_write]
            if kind == "record":
                p, c, r, sv = params
                return [p, sv, self._code_ackrecord[(c, r)]]
            # "ackrecord"
            c, r, p = params
            k, req_kind = self._reqs[c][r]
            putok = self._code_putok[k] if req_kind == 0 else 0
            getok_base = self._base_getok[k] if req_kind == 1 else 0
            return [c, r, p, putok, getok_base, 1 if req_kind == 1 else 0]

        return self._group_families(params_for)

    # --- codec -------------------------------------------------------------

    def _pack_server_fields(self, state) -> dict:
        """Replica + phase + client fields (shared by both network forms)."""
        S = self.S
        fields: dict = {
            "seq": [0] * S,
            "val": [0] * S,
            "kind": [0] * S,
            "p_req": [0] * S,
            "read": [0] * S,
            "rp": [0] * (S * S),
            "rv": [0] * (S * S),
            "ak": [0] * (S * S),
        }
        for s in range(S):
            a: AbdState = state.actor_states[s]
            fields["seq"][s] = self._seq_code(a.seq)
            fields["val"][s] = self._val_code(a.val)
            if isinstance(a.phase, Phase1):
                r = self._phase_req(s, a.phase)
                k, req_kind = self._reqs[s][r]
                expected_write = (self.values[1 + k],) if req_kind == 0 else None
                if a.phase.write != expected_write:
                    raise self._OverflowError32(
                        f"phase write outside universe: {a.phase!r}"
                    )
                fields["kind"][s] = 1
                fields["p_req"][s] = r
                for key, (sq, v) in a.phase.responses:
                    j = int(key)
                    if not 0 <= j < S:
                        raise self._OverflowError32(f"response key {key!r}")
                    fields["rp"][s * S + j] = 1
                    fields["rv"][s * S + j] = self._sv_code(sq, v)
            elif isinstance(a.phase, Phase2):
                r = self._phase_req(s, a.phase)
                fields["kind"][s] = 2
                fields["p_req"][s] = r
                if a.phase.read is not None:
                    fields["read"][s] = 1 + self._val_code(a.phase.read[0])
                for j in a.phase.acks:
                    fields["ak"][s * S + int(j)] = 1
            elif a.phase is not None:  # pragma: no cover
                raise self._OverflowError32(f"unknown phase {a.phase!r}")
        self._pack_clients(fields, state)
        return fields

    def pack(self, state):
        fields = self._pack_server_fields(state)
        self._pack_presence_net(fields, state)
        fields.update(
            self._hist.from_tester(state.history, self._op_code, self._ret_code)
        )
        return self._layout.pack(**fields)

    def _unpack_server_states(self, f) -> list:
        """Inverse of :meth:`_pack_server_fields` (servers + clients)."""
        S, NV = self.S, self.NV
        actor_states = []
        for s in range(S):
            kind = f["kind"][s]
            r = f["p_req"][s]
            phase = None
            if kind == 1:
                k, req_kind = self._reqs[s][r]
                responses = frozenset(
                    (
                        Id(j),
                        (
                            self._seqs[f["rv"][s * S + j] // NV],
                            self.values[f["rv"][s * S + j] % NV],
                        ),
                    )
                    for j in range(S)
                    if f["rp"][s * S + j]
                )
                phase = Phase1(
                    request_id=self._req_id(s, r),
                    requester_id=Id(self._requester(s, r)),
                    write=(self.values[1 + k],) if req_kind == 0 else None,
                    responses=responses,
                )
            elif kind == 2:
                read = None
                if f["read"][s]:
                    read = (self.values[f["read"][s] - 1],)
                phase = Phase2(
                    request_id=self._req_id(s, r),
                    requester_id=Id(self._requester(s, r)),
                    read=read,
                    acks=frozenset(Id(j) for j in range(S) if f["ak"][s * S + j]),
                )
            actor_states.append(
                AbdState(
                    seq=self._seqs[f["seq"][s]],
                    val=self.values[f["val"][s]],
                    phase=phase,
                )
            )
        self._unpack_clients(f, actor_states)
        return actor_states

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import UnorderedNonDuplicatingNetwork
        from ..actor.timers import Timers
        from ..semantics import LinearizabilityTester
        from ..semantics.register import Register

        f = self._layout.unpack(words)
        actor_states = self._unpack_server_states(f)
        counts = {
            self._envs[code]: count for code, count in enumerate(f["net"]) if count
        }
        history = self._hist.to_tester(
            f,
            lambda: LinearizabilityTester(Register(None)),
            self._code_op,
            self._code_ret,
        )
        return ActorModelState(
            actor_states=tuple(actor_states),
            network=UnorderedNonDuplicatingNetwork(counts),
            timers_set=tuple(Timers() for _ in range(self.S + self.C)),
            history=history,
        )

    # --- device kernels -----------------------------------------------------

    def _body_begin(self, words, e, prm):
        """Put/Get -> its coordinator: begin phase 1 seeded with the local
        pair, Query the peer (linearizable-register.rs:86-111)."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        c, r, query_code = prm[0], prm[1], prm[2]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "kind", c) == 0)
        w = L.set(w, "kind", 1, c)
        w = L.set(w, "p_req", r, c)
        own = L.get(words, "seq", c) * u32(self.NV) + L.get(words, "val", c)
        w = L.set(w, "rp", 1, c * S + c)
        w = L.set(w, "rv", own, c * S + c)
        w, dup = self._net_send(w, query_code)
        return w, ok, ok & dup

    def _body_query(self, words, e, prm):
        """Query -> the peer: reply with the local pair, no state change
        (linearizable-register.rs:113-116)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        d, ackq_base = prm[0], prm[1]
        deliv, w = self._net_take(words, e)
        own = L.get(words, "seq", d) * u32(self.NV) + L.get(words, "val", d)
        w, dup = self._net_send(w, ackq_base + own)
        return w, deliv, deliv & dup

    def _body_ackquery(self, words, e, prm):
        """AckQuery -> the coordinator: collect; on quorum pick the maximal
        pair, bump the clock for writes, Record to the peer, move to phase 2
        (linearizable-register.rs:118-176)."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        NV = self.NV
        c, r, p, sv, record_base, wval, is_write_p = (
            prm[0],
            prm[1],
            prm[2],
            prm[3],
            prm[4],
            prm[5],
            prm[6],
        )
        deliv, w = self._net_take(words, e)
        ok = (
            deliv
            & (L.get(words, "kind", c) == 1)
            & (L.get(words, "p_req", c) == r)
        )
        w = L.set(w, "rp", 1, c * S + p)
        w = L.set(w, "rv", sv, c * S + p)
        w2, sv2, quorum, o = self._ackquery_core(
            words, w, c, p, sv, wval, is_write_p
        )
        w2, dup = self._net_send(w2, record_base + sv2)
        o = o | (quorum & dup)
        w = jnp.where(quorum, w2, w)
        return w, ok, ok & o

    def _ackquery_core(self, words, w, c, p, sv, wval, is_write_p):
        """Quorum check + Phase1->Phase2 transition on coordinator ``c``
        given peer ``p``'s response ``sv`` (linearizable-register.rs:118-176)
        — every index may be traced, so both network forms share it.

        ``words`` is the pre-delivery state (reads), ``w`` the
        response-recorded working copy. Returns ``(w2, sv2, quorum,
        clock_overflow)``: ``w2`` is the full transition (the caller sends
        Record(sv2) on its network and selects ``where(quorum, w2, w)``).
        """
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        NV = self.NV
        count = u32(0)
        best = u32(0)
        for j in range(S):
            mine = p == u32(j)
            pj = jnp.where(mine, u32(1), L.get(words, "rp", c * S + j))
            vj = jnp.where(mine, sv, L.get(words, "rv", c * S + j))
            count = count + pj
            # max by (seq, val) == max by seq: equal sequencers carry equal
            # values (linearizable-register.rs:139-142).
            best = jnp.maximum(best, jnp.where(pj != 0, vj, u32(0)))
        quorum = count == u32(self.majority)
        best_seq = best // u32(NV)
        clock = best_seq // u32(S)
        is_write = is_write_p != 0
        o = quorum & is_write & (clock >= u32(self.C))  # clock would overflow
        seq2 = jnp.where(
            is_write, (clock + u32(1)) * u32(S) + u32(c), best_seq
        )
        val2 = jnp.where(is_write, wval, best % u32(NV))
        sv2 = seq2 * u32(NV) + val2
        w2 = w
        for j in range(S):  # responses cleared on the phase switch
            w2 = L.set(w2, "rp", 0, c * S + j)
            w2 = L.set(w2, "rv", 0, c * S + j)
        w2 = L.set(w2, "kind", 2, c)
        w2 = L.set(w2, "read", jnp.where(is_write, u32(0), u32(1) + val2), c)
        for j in range(S):  # acks := {c}
            w2 = L.set(w2, "ak", 0, c * S + j)
        w2 = L.set(w2, "ak", 1, c * S + c)
        # Self-send Record: adopt if newer (seq codes are order-monotone).
        newer = seq2 > L.get(words, "seq", c)
        w2 = L.set(
            w2, "seq", jnp.where(newer, seq2, L.get(words, "seq", c)), c
        )
        w2 = L.set(
            w2, "val", jnp.where(newer, val2, L.get(words, "val", c)), c
        )
        return w2, sv2, quorum, o

    def _body_record(self, words, e, prm):
        """Record -> the peer: adopt newer pairs, always ack
        (linearizable-register.rs:177-184)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        d, sv, ackrecord_code = prm[0], prm[1], prm[2]
        deliv, w = self._net_take(words, e)
        seq = sv // u32(self.NV)
        newer = seq > L.get(words, "seq", d)
        w = L.set(w, "seq", jnp.where(newer, seq, L.get(words, "seq", d)), d)
        w = L.set(
            w, "val", jnp.where(newer, sv % u32(self.NV), L.get(words, "val", d)), d
        )
        w, dup = self._net_send(w, ackrecord_code)
        return w, deliv, deliv & dup

    def _body_ackrecord(self, words, e, prm):
        """AckRecord -> the coordinator: on an ack quorum answer the client
        and clear the phase (linearizable-register.rs:185-210)."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        c, r, p, putok_code, getok_base, is_read_p = (
            prm[0],
            prm[1],
            prm[2],
            prm[3],
            prm[4],
            prm[5],
        )
        deliv, w = self._net_take(words, e)
        ok = (
            deliv
            & (L.get(words, "kind", c) == 2)
            & (L.get(words, "p_req", c) == r)
            & (L.get(words, "ak", c * S + p) == 0)
        )
        w = L.set(w, "ak", 1, c * S + p)
        w2, quorum, read = self._ackrecord_core(words, w, c, p)
        is_read = is_read_p != 0
        reply = jnp.where(is_read, getok_base + read - u32(1), putok_code)
        w2, dup = self._net_send(w2, reply)
        # A read phase always recorded a read value (read != 0).
        o = quorum & (dup | (is_read & (read == 0)))
        w = jnp.where(quorum, w2, w)
        return w, ok, ok & o

    def _ackrecord_core(self, words, w, c, p):
        """Ack-quorum check + phase clear on coordinator ``c`` given peer
        ``p``'s ack (linearizable-register.rs:185-210); traced indices OK.
        Returns ``(w2, quorum, read)``: the caller sends the PutOk/GetOk
        reply on its network form and selects ``where(quorum, w2, w)``."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        count = u32(0)
        for j in range(S):
            count = count + jnp.where(
                p == u32(j), u32(1), L.get(words, "ak", c * S + j)
            )
        quorum = count == u32(self.majority)
        read = L.get(words, "read", c)
        w2 = w
        for j in range(S):  # clear the phase
            w2 = L.set(w2, "ak", 0, c * S + j)
        w2 = L.set(w2, "kind", 0, c)
        w2 = L.set(w2, "p_req", 0, c)
        w2 = L.set(w2, "read", 0, c)
        return w2, quorum, read

    def packed_properties(self, words):
        """[linearizable, value chosen] — order of
        ``properties()``. The second mirrors ``value_chosen_condition``:
        some deliverable GetOk with a real (non-None) value."""
        import jax.numpy as jnp

        L = self._layout
        lin = self.device_linearizable_register(words)
        chosen = jnp.bool_(False)
        for k in range(self.C):
            for v in range(1, self.NV):  # written values only
                chosen = chosen | (L.get(words, "net", self._base_getok[k] + v) != 0)
        return jnp.stack([lin, chosen])


class PackedAbdOrdered(PackedAbd):
    """The ABD quorum register over the **ordered** network on the device
    engine — the ``linearizable-register check 2 ordered`` configuration of
    the reference harness (bench.sh:33, BASELINE.json), packed with
    :class:`~stateright_tpu.packing.FifoLanes`.

    Shares the protocol structure (request table, sequencer/value codes,
    phase fields, quorum cores) with :class:`PackedAbd`; only the network
    differs: per-directed-pair FIFO channels where exactly the lane HEADS
    are deliverable (network.rs:57-67, 221-293). One action slot per lane;
    a head whose delivery is a no-op (an ack the coordinator's phase does
    not match) BLOCKS its lane, exactly like the object model's
    head-of-channel-only rule.

    Lanes: per client k (abs id i = S+k) four depth-1 lanes — Put
    (i -> i%S), Get (i -> (i+1)%S), PutOk (i%S -> i), GetOk ((i+1)%S -> i,
    one code per value) — plus one depth-3 server-server lane per
    direction carrying the structured internal traffic: codes pack as
    ``[Query(r) | Record(r, sv) | AckQuery(r', sv) | AckRecord(r')]`` with
    ``r`` indexing the sender's requests and ``r'`` the receiver's.

    The reference has no exact-count oracle for ordered ABD (its tests use
    unordered networks; bench.sh runs ordered configs as benchmarks), so
    parity is engine-vs-engine against this package's object
    ``OrderedNetwork`` model — which itself passes the reference's
    ordered-semantics regression matrix (model.rs:795-964).
    """

    def __init__(self, client_count: int = 2, server_count: int = 2):
        # Deliberately does NOT call PackedAbd.__init__ (which builds the
        # presence-bit envelope universe); shares its protocol helpers.
        from ..packing import (
            BoundedHistory,
            FifoLanes,
            LayoutBuilder,
            OverflowError32,
            bits_for,
        )

        if server_count != 2 or client_count not in (2, 3):
            raise ValueError(
                "PackedAbdOrdered packs S=2 (single-peer quorum arithmetic) "
                "with 2 or 3 clients; other sizes run on the host engines"
            )
        C, S = client_count, server_count
        self._init_core(C, S, OverflowError32)
        self._inner = linearizable_register_model(C, S, Network.new_ordered())
        NV, NSQ = self.NV, self.NSQ
        NSV = NSQ * NV
        self._NSV = NSV

        # Server-server lane code layout (see class docstring).
        self._R = [len(self._reqs[s]) for s in range(S)]
        self._ss_codes = [
            self._R[d] * (1 + NSV) + self._R[1 - d] * (NSV + 1) for d in range(S)
        ]
        #: request id -> local request index, per server.
        self._rid2r = [
            {self._req_id(s, r): r for r in range(self._R[s])} for s in range(S)
        ]

        self.max_actions = 4 * C + S  # one slot per lane

        b = LayoutBuilder()
        self._server_layout(b, bits_for)
        self._client_layout(b)
        # Client-side lanes (depth 1): lane k = Put, C+k = Get, 2C+k =
        # PutOk, 3C+k = GetOk(value) — codes per class docstring.
        self._clanes = FifoLanes(
            b, "cl_flows", lanes=4 * C, depth=1, code_bits=bits_for(NV - 1)
        )
        # Server-server lanes (depth 3): lane d = server d -> server 1-d.
        self._slanes = FifoLanes(
            b,
            "ss_flows",
            lanes=S,
            depth=3,
            code_bits=bits_for(max(self._ss_codes) - 1),
        )
        code_bits = bits_for(NV)
        self._hist = BoundedHistory(
            b,
            thread_ids=[Id(S + k) for k in range(C)],
            max_ops=2,
            op_bits=code_bits,
            ret_bits=code_bits,
        )
        self._layout = b.finish()
        self._hist.bind(self._layout)
        self._clanes.bind(self._layout)
        self._slanes.bind(self._layout)
        self.state_words = self._layout.words

        codecs = reg.history_codecs(self.values)
        self._op_code, self._code_op, self._ret_code, self._code_ret = codecs

    # --- lane codec ---------------------------------------------------------

    def _clane_key(self, lane: int):
        """(src, dst) of client lane ``lane``."""
        C, S = self.C, self.S
        k = lane % C
        i = S + k
        return [
            (Id(i), Id(i % S)),
            (Id(i), Id((i + 1) % S)),
            (Id(i % S), Id(i)),
            (Id((i + 1) % S), Id(i)),
        ][lane // C]

    def _clane_msg_code(self, lane: int, msg) -> int:
        C, S = self.C, self.S
        k = lane % C
        i = S + k
        group = lane // C
        if group == 0 and isinstance(msg, reg.Put) and msg == reg.Put(i, self.values[1 + k]):
            return 0
        if group == 1 and isinstance(msg, reg.Get) and msg == reg.Get(2 * i):
            return 0
        if group == 2 and isinstance(msg, reg.PutOk) and msg == reg.PutOk(i):
            return 0
        if group == 3 and isinstance(msg, reg.GetOk) and msg.request_id == 2 * i:
            return self._val_code(msg.value)
        raise self._OverflowError32(f"message outside universe on lane {lane}: {msg!r}")

    def _clane_code_msg(self, lane: int, code: int):
        C, S = self.C, self.S
        k = lane % C
        i = S + k
        group = lane // C
        if group == 0:
            return reg.Put(i, self.values[1 + k])
        if group == 1:
            return reg.Get(2 * i)
        if group == 2:
            return reg.PutOk(i)
        return reg.GetOk(2 * i, self.values[code])

    def _ss_msg_code(self, d: int, msg) -> int:
        """Code of an internal message on lane ``d`` (server d -> 1-d)."""
        NSV = self._NSV
        R_s, R_p = self._R[d], self._R[1 - d]
        if not isinstance(msg, reg.Internal):
            raise self._OverflowError32(f"non-internal on ss lane {d}: {msg!r}")
        m = msg.msg
        if isinstance(m, Query):
            return self._rid2r[d][m.request_id]
        if isinstance(m, Record):
            r = self._rid2r[d][m.request_id]
            return R_s + r * NSV + self._sv_code(m.seq, m.value)
        if isinstance(m, AckQuery):
            r = self._rid2r[1 - d][m.request_id]
            return R_s + R_s * NSV + r * NSV + self._sv_code(m.seq, m.value)
        if isinstance(m, AckRecord):
            r = self._rid2r[1 - d][m.request_id]
            return R_s + R_s * NSV + R_p * NSV + r
        raise self._OverflowError32(f"unknown internal on ss lane {d}: {m!r}")

    def _ss_code_msg(self, d: int, code: int):
        NSV = self._NSV
        R_s, R_p = self._R[d], self._R[1 - d]
        if code < R_s:
            return reg.Internal(Query(self._req_id(d, code)))
        code -= R_s
        if code < R_s * NSV:
            r, sv = divmod(code, NSV)
            return reg.Internal(
                Record(self._req_id(d, r), self._seqs[sv // self.NV], self.values[sv % self.NV])
            )
        code -= R_s * NSV
        if code < R_p * NSV:
            r, sv = divmod(code, NSV)
            return reg.Internal(
                AckQuery(self._req_id(1 - d, r), self._seqs[sv // self.NV], self.values[sv % self.NV])
            )
        code -= R_p * NSV
        return reg.Internal(AckRecord(self._req_id(1 - d, code)))

    # --- codec -------------------------------------------------------------

    def pack(self, state):
        C, S = self.C, self.S
        fields = self._pack_server_fields(state)
        flows = dict(state.network.flows)

        def pack_lanes(lanes_obj, n_lanes, key_of, code_of, cells_name, lens_name):
            cells = [0] * (n_lanes * lanes_obj.depth)
            lens = [0] * n_lanes
            for lane in range(n_lanes):
                msgs = flows.pop(key_of(lane), ())
                lane_cells, n = lanes_obj.host_pack_lane(
                    [code_of(lane, m) for m in msgs]
                )
                cells[lane * lanes_obj.depth : (lane + 1) * lanes_obj.depth] = lane_cells
                lens[lane] = n
            fields[cells_name] = cells
            fields[lens_name] = lens

        pack_lanes(
            self._clanes, 4 * C, self._clane_key, self._clane_msg_code,
            "cl_flows_cells", "cl_flows_lens",
        )
        pack_lanes(
            self._slanes, S, lambda d: (Id(d), Id(1 - d)), self._ss_msg_code,
            "ss_flows_cells", "ss_flows_lens",
        )
        if flows:
            raise self._OverflowError32(f"flows outside universe: {list(flows)!r}")
        fields.update(
            self._hist.from_tester(state.history, self._op_code, self._ret_code)
        )
        return self._layout.pack(**fields)

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import OrderedNetwork
        from ..actor.timers import Timers
        from ..semantics import LinearizabilityTester
        from ..semantics.register import Register

        f = self._layout.unpack(words)
        C, S = self.C, self.S
        actor_states = self._unpack_server_states(f)
        flows = {}
        for lane in range(4 * C):
            n = f["cl_flows_lens"][lane]
            if n:
                cells = f["cl_flows_cells"][
                    lane * self._clanes.depth : lane * self._clanes.depth + n
                ]
                flows[self._clane_key(lane)] = tuple(
                    self._clane_code_msg(lane, c - 1) for c in cells
                )
        for d in range(S):
            n = f["ss_flows_lens"][d]
            if n:
                cells = f["ss_flows_cells"][
                    d * self._slanes.depth : d * self._slanes.depth + n
                ]
                flows[(Id(d), Id(1 - d))] = tuple(
                    self._ss_code_msg(d, c - 1) for c in cells
                )
        history = self._hist.to_tester(
            f,
            lambda: LinearizabilityTester(Register(None)),
            self._code_op,
            self._code_ret,
        )
        return ActorModelState(
            actor_states=tuple(actor_states),
            network=OrderedNetwork(flows),
            timers_set=tuple(Timers() for _ in range(S + C)),
            history=history,
        )

    # --- device kernels -----------------------------------------------------

    def packed_step(self, words):
        """One action slot per lane, in lane order: Put lanes, Get lanes,
        PutOk lanes, GetOk lanes, then the two server-server lanes."""
        import jax.numpy as jnp

        C = self.C
        nxt, valid, ovf = [], [], []
        for k in range(C):
            w, v, o = self._body_lane_request(words, k, put=True)
            nxt.append(w); valid.append(v); ovf.append(o)
        for k in range(C):
            w, v, o = self._body_lane_request(words, k, put=False)
            nxt.append(w); valid.append(v); ovf.append(o)
        for k in range(C):
            w, v, o = self._body_lane_putok(words, k)
            nxt.append(w); valid.append(v); ovf.append(o)
        for k in range(C):
            w, v, o = self._body_lane_getok(words, k)
            nxt.append(w); valid.append(v); ovf.append(o)
        for d in range(self.S):
            w, v, o = self._body_lane_ss(words, d)
            nxt.append(w); valid.append(v); ovf.append(o)
        valid = jnp.stack(valid)
        return jnp.stack(nxt), valid, jnp.stack(ovf) & valid

    def _body_lane_request(self, words, k, *, put: bool):
        """Head of client k's Put/Get lane -> its coordinator: begin phase 1
        (linearizable-register.rs:86-111) and Query the peer. Blocked while
        the coordinator is mid-phase (the object model's no-op rule)."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        s, r = self._rix[(k, 0 if put else 1)]
        lane = k if put else self.C + k
        _code, nonempty = self._clanes.head(words, lane)
        ok = nonempty & (L.get(words, "kind", s) == 0)
        w = self._clanes.pop(words, lane, enabled=ok)
        w = L.set(w, "kind", 1, s)
        w = L.set(w, "p_req", r, s)
        own = L.get(words, "seq", s) * u32(self.NV) + L.get(words, "val", s)
        w = L.set(w, "rp", 1, s * S + s)
        w = L.set(w, "rv", own, s * S + s)
        w, ovf = self._slanes.push(w, s, r, enabled=ok)  # Query(r)
        return w, ok, ok & ovf

    def _body_lane_putok(self, words, k):
        """Head of the PutOk lane -> client k: record WriteOk, invoke the
        Read, push Get (register.rs:170-185)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        lane = 2 * self.C + k
        _code, nonempty = self._clanes.head(words, lane)
        ok = nonempty & (L.get(words, "cl_await", k) == u32(1))
        w = self._clanes.pop(words, lane, enabled=ok)
        w = L.set(w, "cl_await", 2, k)
        w = L.set(w, "cl_ops", 2, k)
        o = jnp.bool_(False)
        for t in range(self.C):
            on = ok & (u32(k) == u32(t))
            w, ot = self._hist.on_return(w, t, u32(0), enabled=on)  # WriteOk
            w = self._hist.on_invoke(w, t, u32(0), enabled=on)  # Read
            o = o | ot
        w, povf = self._clanes.push(w, self.C + k, 0, enabled=ok)  # Get
        return w, ok, ok & (o | povf)

    def _body_lane_getok(self, words, k):
        """Head of the GetOk lane -> client k: record ReadOk(value); the
        script completes (register.rs:186-187)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        lane = 3 * self.C + k
        code, nonempty = self._clanes.head(words, lane)
        ok = nonempty & (L.get(words, "cl_await", k) == u32(2))
        w = self._clanes.pop(words, lane, enabled=ok)
        w = L.set(w, "cl_await", 0, k)
        w = L.set(w, "cl_ops", 3, k)
        o = jnp.bool_(False)
        for t in range(self.C):
            w, ot = self._hist.on_return(
                w, t, u32(1) + code, enabled=ok & (u32(k) == u32(t))
            )
            o = o | ot
        return w, ok, ok & o

    def _body_lane_ss(self, words, d):
        """Head of the server-server lane d -> me (= 1-d): dispatch on the
        structured code ranges. Query/Record process unconditionally
        (linearizable-register.rs:113-116, 177-184); AckQuery/AckRecord
        must match my active phase or the lane blocks."""
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        NSV, NV = self._NSV, self.NV
        me = 1 - d
        R_s, R_p = self._R[d], self._R[me]
        R_mine = R_p  # my requests, as the receiving server
        # Request-metadata tables for MY requests (indexed by a traced local
        # request index): write value, is-write flag, requesting client.
        # Shared by the AckQuery and AckRecord branches below.
        wval_tbl = jnp.asarray(
            [1 + self._reqs[me][r][0] if self._reqs[me][r][1] == 0 else 0
             for r in range(R_mine)] or [0],
            jnp.uint32,
        )
        iw_tbl = jnp.asarray(
            [1 if self._reqs[me][r][1] == 0 else 0 for r in range(R_mine)] or [0],
            jnp.uint32,
        )
        kcl_tbl = jnp.asarray(
            [self._reqs[me][r][0] for r in range(R_mine)] or [0], jnp.uint32
        )
        code, nonempty = self._slanes.head(words, d)

        is_query = code < u32(R_s)
        is_record = ~is_query & (code < u32(R_s + R_s * NSV))
        is_ackq = (
            ~is_query & ~is_record & (code < u32(R_s + R_s * NSV + R_p * NSV))
        )
        is_ackrec = ~is_query & ~is_record & ~is_ackq

        # --- Query(r): reply AckQuery(r, own pair) on my lane -------------
        own = L.get(words, "seq", me) * u32(NV) + L.get(words, "val", me)
        # On lane `me`, AckQuery codes describe requests of server d.
        ackq_code = u32(R_mine + R_mine * NSV) + code * u32(NSV) + own
        w_q = self._slanes.pop(words, d, enabled=nonempty & is_query)
        w_q, o_q = self._slanes.push(w_q, me, ackq_code, enabled=nonempty & is_query)

        # --- Record(r, sv): adopt if newer, AckRecord(r) ------------------
        rec = code - u32(R_s)
        rec_r, rec_sv = rec // u32(NSV), rec % u32(NSV)
        rec_seq = rec_sv // u32(NV)
        newer = rec_seq > L.get(words, "seq", me)
        w_r = self._slanes.pop(words, d, enabled=nonempty & is_record)
        w_r = L.set(
            w_r, "seq", jnp.where(newer, rec_seq, L.get(words, "seq", me)), me
        )
        w_r = L.set(
            w_r,
            "val",
            jnp.where(newer, rec_sv % u32(NV), L.get(words, "val", me)),
            me,
        )
        ackrec_code = u32(R_mine + R_mine * NSV + R_s * NSV) + rec_r
        w_r, o_r = self._slanes.push(
            w_r, me, ackrec_code, enabled=nonempty & is_record
        )

        # --- AckQuery(r', sv): my Phase1 completes on quorum --------------
        aq = code - u32(R_s + R_s * NSV)
        aq_r, aq_sv = aq // u32(NSV), aq % u32(NSV)
        ok_aq = (
            nonempty
            & is_ackq
            & (L.get(words, "kind", me) == 1)
            & (L.get(words, "p_req", me) == aq_r)
        )
        w_a = self._slanes.pop(words, d, enabled=ok_aq)
        w_a = L.set(w_a, "rp", 1, me * S + d)
        w_a = L.set(w_a, "rv", aq_sv, me * S + d)
        w2, sv2, quorum, o_clock = self._ackquery_core(
            words, w_a, me, u32(d), aq_sv, wval_tbl[aq_r], iw_tbl[aq_r]
        )
        # Record(r', sv2) on my lane (r' indexes MY requests there).
        w2, o_push = self._slanes.push(
            w2, me, u32(R_mine) + aq_r * u32(NSV) + sv2, enabled=ok_aq & quorum
        )
        o_a = ok_aq & (o_clock | (quorum & o_push))
        w_a = jnp.where(quorum, w2, w_a)

        # --- AckRecord(r'): my Phase2 completes on ack quorum -------------
        ar_r = code - u32(R_s + R_s * NSV + R_p * NSV)
        ok_ar = (
            nonempty
            & is_ackrec
            & (L.get(words, "kind", me) == 2)
            & (L.get(words, "p_req", me) == ar_r)
            & (L.get(words, "ak", me * S + d) == 0)
        )
        w_c = self._slanes.pop(words, d, enabled=ok_ar)
        w_c = L.set(w_c, "ak", 1, me * S + d)
        w3, quorum_r, read = self._ackrecord_core(words, w_c, me, u32(d))
        # Reply lane: PutOk lane 2C+k' for writes, GetOk lane 3C+k' for
        # reads (code = read value).
        k_cl = kcl_tbl[ar_r]
        is_read_req = iw_tbl[ar_r] == 0
        reply_lane = jnp.where(
            is_read_req, u32(3 * self.C) + k_cl, u32(2 * self.C) + k_cl
        )
        reply_code = jnp.where(is_read_req, read - u32(1), u32(0))
        w3, o_reply = self._clanes.push(
            w3, reply_lane, reply_code, enabled=ok_ar & quorum_r
        )
        o_c = ok_ar & quorum_r & (o_reply | (is_read_req & (read == 0)))
        w_c = jnp.where(quorum_r, w3, w_c)

        # --- combine ------------------------------------------------------
        w = jnp.where(
            is_query, w_q, jnp.where(is_record, w_r, jnp.where(is_ackq, w_a, w_c))
        )
        ok = nonempty & (is_query | is_record | ok_aq | ok_ar)
        o = (
            (nonempty & is_query & o_q)
            | (nonempty & is_record & o_r)
            | o_a
            | o_c
        )
        return w, ok, o

    def packed_properties(self, words):
        """[linearizable, value chosen]; "chosen" checks GetOk lane HEADS
        only — under ordered semantics only heads are deliverable."""
        import jax.numpy as jnp

        lin = self.device_linearizable_register(words)
        chosen = jnp.bool_(False)
        for k in range(self.C):
            code, nonempty = self._clanes.head(words, 3 * self.C + k)
            chosen = chosen | (nonempty & (code >= jnp.uint32(1)))
        return jnp.stack([lin, chosen])


def main(argv=None) -> None:
    """CLI mirroring linearizable-register.rs:319-430."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        # ``check`` runs the device (XLA) engine on the packed ABD model —
        # defined at the reference's *test* shape (2 servers,
        # linearizable-register.rs:289) for 2-3 clients, unordered or
        # ordered network. Shapes the packed codec does not cover (other
        # server counts, other network semantics) fall back to the host
        # oracle at the reference CLI's 3-server shape.
        client_count = int(args.pop(0)) if args else 2
        netname = args.pop(0) if args else None
        # "unordered" / "unordered_nonduplicating" both spell the packed
        # models' default network: naming the default explicitly must
        # route to the SAME device check as omitting it — never a
        # different engine/state space under the user (ADVICE r4).
        if netname == "unordered":
            netname = "unordered_nonduplicating"
        if client_count in (2, 3) and netname in (
            None, "unordered_nonduplicating", "ordered",
        ):
            from ..backend import guarded_main

            guarded_main(
                "stateright_tpu.models.linearizable_register", orig_args
            )
            cls = PackedAbdOrdered if netname == "ordered" else PackedAbd
            print(
                f"Model checking a linearizable register with {client_count} "
                f"clients and 2 servers on XLA"
                + (" (ordered network)." if netname == "ordered" else ".")
            )
            (
                cls(client_count, 2)
                .checker()
                .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 13)
                .report(WriteReporter())
            )
        else:
            network = Network.from_name(netname) if netname else None
            print(
                f"Model checking a linearizable register with {client_count} "
                "clients (host oracle, reference CLI 3-server shape)."
            )
            (
                linearizable_register_model(client_count, 3, network)
                .checker()
                .spawn_dfs()
                .report(WriteReporter())
            )
    elif cmd == "check-host":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking a linearizable register with {client_count} clients.")
        (
            linearizable_register_model(client_count, 3, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for linearizable register with "
            f"{client_count} clients on {address}."
        )
        linearizable_register_model(client_count, 3, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        serialize, deserialize = json_codec(
            reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
            Query, AckQuery, Record, AckRecord,
        )
        print("  Three servers that implement a linearizable register.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [
                (ids[i], AbdActor([x for x in ids if x != ids[i]]))
                for i in range(3)
            ],
        )
    else:
        print("USAGE:")
        print("  linearizable-register check [CLIENT_COUNT] [NETWORK]  (device/XLA engine for 2-3 clients")
        print("      at the reference test shape, 2 servers; other shapes/networks fall back to the")
        print("      host oracle at the reference CLI's 3-server shape)")
        print("  linearizable-register check-host [CLIENT_COUNT] [NETWORK]  (sequential host oracle)")
        print("  linearizable-register check-xla   (alias of check)")
        print("  linearizable-register explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  linearizable-register spawn")
        print(
            f"NETWORK: {' | '.join(Network.names())}"
            "  ('unordered' = unordered_nonduplicating, the packed default)"
        )


if __name__ == "__main__":
    main()
