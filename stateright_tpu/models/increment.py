"""Racy shared counter: the canonical symmetry-reduction demo.

Mirrors ``/root/reference/examples/increment.rs``: N threads each execute
``1: t = SHARED; 2: SHARED = t + 1; 3:`` with the two instructions atomic but
interleavable, so the final counter can undercount. The ``fin`` invariant
("SHARED equals the number of finished threads") is intentionally violated.

The reference's doc comment enumerates the state space for 2 threads: 13
unique states without symmetry reduction, 8 with it (increment.rs:31-105) —
those are the exact-count oracles for the tests here.

States are plain nested tuples — hashable, orderable, and trivially
canonicalizable by sorting the per-thread slice.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

from ..core import Model, Property
from ..utils.variant import variant

Proc = Tuple[int, int]  # (thread-local value t, program counter pc)

Read = variant("Read", ["thread"])
Write = variant("Write", ["thread"])


class IncrementState(NamedTuple):
    """(shared counter, per-thread (t, pc) slices) — increment.rs:117-131."""

    i: int
    s: Tuple[Proc, ...]

    def representative(self) -> "IncrementState":
        """Threads are interchangeable: the canonical class member sorts the
        thread slice (increment.rs:142-151)."""
        return IncrementState(self.i, tuple(sorted(self.s)))


class Increment(Model):
    """The model (increment.rs:153-197): the initial state doubles as the
    model value, as in the reference."""

    def __init__(self, thread_count: int = 3):
        self.thread_count = thread_count

    def init_states(self) -> List[IncrementState]:
        return [IncrementState(0, tuple((0, 1) for _ in range(self.thread_count)))]

    def actions(self, state: IncrementState, actions: List[Any]) -> None:
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 1:
                actions.append(Read(thread_id))
            elif pc == 2:
                actions.append(Write(thread_id))

    def next_state(self, last_state: IncrementState, action: Any):
        s = list(last_state.s)
        if isinstance(action, Read):
            s[action.thread] = (last_state.i, 2)
            return IncrementState(last_state.i, tuple(s))
        t, _pc = s[action.thread]
        s[action.thread] = (t, 3)
        return IncrementState(t + 1, tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, state: sum(1 for _t, pc in state.s if pc == 3) == state.i,
            )
        ]


class PackedIncrement(Increment):
    """The racy counter on the device engine (``spawn_xla``), declared via
    :mod:`stateright_tpu.packing`: the shared counter and per-thread
    ``(t, pc)`` slices are plain layout fields. One action slot per thread
    (its program counter enables at most one instruction, increment.rs:158-169).

    Includes ``packed_representative`` — threads sort by ``(t, pc)``
    (increment.rs:142-151) — so ``check-sym`` runs on device too.
    """

    def __init__(self, thread_count: int = 3):
        from ..packing import LayoutBuilder, bits_for

        super().__init__(thread_count)
        n = thread_count
        tb = bits_for(n)
        self._layout = (
            LayoutBuilder()
            .uint("i", bits_for(n))
            .array("t", n, tb)
            .array("pc", n, 2)  # 1..3
            .finish()
        )
        self.state_words = self._layout.words
        self.max_actions = n
        if n >= 2:
            # Declarative device symmetry (stateright_tpu/sym): thread
            # block k = its (t, pc) layout elements; both lanes key the
            # sort, so the spec kernel equals packed_representative
            # bit-for-bit (the (t, pc) pair IS the whole block — the
            # hand-written sort was already a full canonicalization).
            from ..sym import SymmetrySpec

            self.symmetry_spec = SymmetrySpec.from_layout(
                self._layout, ["t", "pc"], group="threads", name="increment"
            )

    # --- host codec --------------------------------------------------------

    def pack(self, state: IncrementState):
        return self._layout.pack(
            i=state.i,
            t=[t for t, _pc in state.s],
            pc=[pc for _t, pc in state.s],
        )

    def unpack(self, words) -> IncrementState:
        f = self._layout.unpack(words)
        return IncrementState(
            f["i"], tuple(zip((int(x) for x in f["t"]), (int(x) for x in f["pc"])))
        )

    def packed_init(self):
        import numpy as np

        return np.stack([self.pack(s) for s in self.init_states()])

    # --- device kernels -----------------------------------------------------

    def packed_step(self, words):
        """Slot k = thread k's enabled instruction: Read at pc=1 (t := i,
        pc := 2), Write at pc=2 (i := t+1, pc := 3)."""
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        i_val = L.get(words, "i")
        nxt, valid = [], []
        for k in range(n):
            pc = L.get(words, "pc", k)
            t = L.get(words, "t", k)
            read_w = L.set(L.set(words, "t", i_val, k), "pc", 2, k)
            write_w = L.set(L.set(words, "i", t + jnp.uint32(1)), "pc", 3, k)
            is_read = pc == 1
            w = jnp.where(is_read, read_w, write_w)
            nxt.append(w)
            valid.append(is_read | (pc == 2))
        return jnp.stack(nxt), jnp.stack(valid)

    def packed_properties(self, words):
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        fin = jnp.uint32(0)
        for k in range(n):
            fin = fin + (L.get(words, "pc", k) == 3).astype(jnp.uint32)
        return jnp.stack([fin == L.get(words, "i")])

    def packed_representative(self, words):
        """Sort the interchangeable thread slice by ``(t, pc)`` — the
        device form of :meth:`IncrementState.representative`."""
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        t = jnp.stack([L.get(words, "t", k) for k in range(n)])
        pc = jnp.stack([L.get(words, "pc", k) for k in range(n)])
        keys = t * jnp.uint32(4) + pc  # pc < 4; lexicographic (t, pc)
        order = jnp.argsort(keys, stable=True)
        t, pc = t[order], pc[order]
        w = words
        for k in range(n):
            w = L.set(L.set(w, "t", t[k], k), "pc", pc[k], k)
        return w


def main(argv=None) -> None:
    """CLI mirroring increment.rs:199-254. ``check`` runs the device (XLA)
    engine — the reference's ``check`` likewise runs its fastest checker;
    ``check-host`` is the sequential Python oracle."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        from ..backend import guarded_main

        guarded_main("stateright_tpu.models.increment", orig_args)
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment with {thread_count} threads on XLA.")
        PackedIncrement(thread_count).checker().spawn_xla(
            frontier_capacity=1 << 12, table_capacity=1 << 16
        ).report(WriteReporter())
    elif cmd == "check-host":
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment with {thread_count} threads.")
        Increment(thread_count).checker().spawn_dfs().report(WriteReporter())
    elif cmd == "check-sym":
        thread_count = int(args.pop(0)) if args else 3
        print(
            f"Model checking increment with {thread_count} threads "
            f"using symmetry reduction."
        )
        Increment(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter()
        )
    elif cmd == "explore":
        thread_count = int(args.pop(0)) if args else 3
        address = args.pop(0) if args else "localhost:3000"
        print(
            f"Exploring the state space of increment with {thread_count} "
            f"threads on {address}."
        )
        Increment(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  increment check [THREAD_COUNT]        (device/XLA engine)")
        print("  increment check-host [THREAD_COUNT]   (sequential host oracle)")
        print("  increment check-sym [THREAD_COUNT]")
        print("  increment check-xla [THREAD_COUNT]    (alias of check)")
        print("  increment explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
