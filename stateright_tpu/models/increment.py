"""Racy shared counter: the canonical symmetry-reduction demo.

Mirrors ``/root/reference/examples/increment.rs``: N threads each execute
``1: t = SHARED; 2: SHARED = t + 1; 3:`` with the two instructions atomic but
interleavable, so the final counter can undercount. The ``fin`` invariant
("SHARED equals the number of finished threads") is intentionally violated.

The reference's doc comment enumerates the state space for 2 threads: 13
unique states without symmetry reduction, 8 with it (increment.rs:31-105) —
those are the exact-count oracles for the tests here.

States are plain nested tuples — hashable, orderable, and trivially
canonicalizable by sorting the per-thread slice.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

from ..core import Model, Property
from ..utils.variant import variant

Proc = Tuple[int, int]  # (thread-local value t, program counter pc)

Read = variant("Read", ["thread"])
Write = variant("Write", ["thread"])


class IncrementState(NamedTuple):
    """(shared counter, per-thread (t, pc) slices) — increment.rs:117-131."""

    i: int
    s: Tuple[Proc, ...]

    def representative(self) -> "IncrementState":
        """Threads are interchangeable: the canonical class member sorts the
        thread slice (increment.rs:142-151)."""
        return IncrementState(self.i, tuple(sorted(self.s)))


class Increment(Model):
    """The model (increment.rs:153-197): the initial state doubles as the
    model value, as in the reference."""

    def __init__(self, thread_count: int = 3):
        self.thread_count = thread_count

    def init_states(self) -> List[IncrementState]:
        return [IncrementState(0, tuple((0, 1) for _ in range(self.thread_count)))]

    def actions(self, state: IncrementState, actions: List[Any]) -> None:
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 1:
                actions.append(Read(thread_id))
            elif pc == 2:
                actions.append(Write(thread_id))

    def next_state(self, last_state: IncrementState, action: Any):
        s = list(last_state.s)
        if isinstance(action, Read):
            s[action.thread] = (last_state.i, 2)
            return IncrementState(last_state.i, tuple(s))
        t, _pc = s[action.thread]
        s[action.thread] = (t, 3)
        return IncrementState(t + 1, tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, state: sum(1 for _t, pc in state.s if pc == 3) == state.i,
            )
        ]


def main(argv=None) -> None:
    """CLI mirroring increment.rs:199-254."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment with {thread_count} threads.")
        Increment(thread_count).checker().spawn_dfs().report(WriteReporter())
    elif cmd == "check-sym":
        thread_count = int(args.pop(0)) if args else 3
        print(
            f"Model checking increment with {thread_count} threads "
            f"using symmetry reduction."
        )
        Increment(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter()
        )
    elif cmd == "explore":
        thread_count = int(args.pop(0)) if args else 3
        address = args.pop(0) if args else "localhost:3000"
        print(
            f"Exploring the state space of increment with {thread_count} "
            f"threads on {address}."
        )
        Increment(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  increment check [THREAD_COUNT]")
        print("  increment check-sym [THREAD_COUNT]")
        print("  increment explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
