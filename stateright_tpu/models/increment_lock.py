"""Shared counter guarded by a lock: the fixed version of ``increment``.

Mirrors ``/root/reference/examples/increment_lock.rs``: each thread executes
``0: lock; 1: t = SHARED; 2: SHARED = t + 1; 3: unlock; 4:``, so the ``fin``
invariant ("SHARED equals the number of threads past their write") and the
``mutex`` invariant ("at most one thread inside the critical section") both
hold — the checker finds no counterexample.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

from ..core import Model, Property
from ..utils.variant import variant

Proc = Tuple[int, int]  # (thread-local value t, program counter pc)

Lock = variant("Lock", ["thread"])
Read = variant("Read", ["thread"])
Write = variant("Write", ["thread"])
Release = variant("Release", ["thread"])


class IncrementLockState(NamedTuple):
    """(shared counter, lock bit, per-thread (t, pc)) — increment_lock.rs:19-33."""

    i: int
    lock: bool
    s: Tuple[Proc, ...]

    def representative(self) -> "IncrementLockState":
        """Sort the interchangeable thread slice (increment_lock.rs:36-46)."""
        return IncrementLockState(self.i, self.lock, tuple(sorted(self.s)))


class IncrementLock(Model):
    """The model (increment_lock.rs:48-107)."""

    def __init__(self, thread_count: int = 3):
        self.thread_count = thread_count

    def init_states(self) -> List[IncrementLockState]:
        return [
            IncrementLockState(0, False, tuple((0, 0) for _ in range(self.thread_count)))
        ]

    def actions(self, state: IncrementLockState, actions: List[Any]) -> None:
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 0 and not state.lock:
                actions.append(Lock(thread_id))
            elif pc == 1:
                actions.append(Read(thread_id))
            elif pc == 2:
                actions.append(Write(thread_id))
            elif pc == 3 and state.lock:
                actions.append(Release(thread_id))

    def next_state(self, last_state: IncrementLockState, action: Any):
        s = list(last_state.s)
        t, _pc = s[action.thread]
        if isinstance(action, Lock):
            s[action.thread] = (t, 1)
            return last_state._replace(lock=True, s=tuple(s))
        if isinstance(action, Read):
            s[action.thread] = (last_state.i, 2)
            return last_state._replace(s=tuple(s))
        if isinstance(action, Write):
            s[action.thread] = (t, 3)
            return last_state._replace(i=t + 1, s=tuple(s))
        s[action.thread] = (t, 4)
        return last_state._replace(lock=False, s=tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, state: sum(1 for _t, pc in state.s if pc >= 3) == state.i,
            ),
            Property.always(
                "mutex",
                lambda _m, state: sum(1 for _t, pc in state.s if 1 <= pc < 4) <= 1,
            ),
        ]


class PackedIncrementLock(IncrementLock):
    """The lock-guarded counter on the device engine (``spawn_xla``).

    Same layout style as :class:`~stateright_tpu.models.increment.PackedIncrement`
    plus a global lock flag; one action slot per thread (each program
    counter enables at most one of Lock/Read/Write/Release,
    increment_lock.rs:61-73)."""

    def __init__(self, thread_count: int = 3):
        from ..packing import LayoutBuilder, bits_for

        super().__init__(thread_count)
        n = thread_count
        self._layout = (
            LayoutBuilder()
            .uint("i", bits_for(n))
            .flag("lock")
            .array("t", n, bits_for(n))
            .array("pc", n, 3)  # 0..4
            .finish()
        )
        self.state_words = self._layout.words
        self.max_actions = n
        if n >= 2:
            # Declarative device symmetry (stateright_tpu/sym): same
            # thread-block declaration as PackedIncrement — (t, pc) is
            # the whole block, so the spec kernel matches
            # packed_representative bit-for-bit.
            from ..sym import SymmetrySpec

            self.symmetry_spec = SymmetrySpec.from_layout(
                self._layout, ["t", "pc"], group="threads",
                name="increment-lock",
            )

    def pack(self, state: IncrementLockState):
        return self._layout.pack(
            i=state.i,
            lock=int(state.lock),
            t=[t for t, _pc in state.s],
            pc=[pc for _t, pc in state.s],
        )

    def unpack(self, words) -> IncrementLockState:
        f = self._layout.unpack(words)
        return IncrementLockState(
            f["i"],
            bool(f["lock"]),
            tuple(zip((int(x) for x in f["t"]), (int(x) for x in f["pc"]))),
        )

    def packed_init(self):
        import numpy as np

        return np.stack([self.pack(s) for s in self.init_states()])

    def packed_step(self, words):
        """Slot k: thread k's one enabled instruction, by program counter —
        Lock (pc=0, lock free), Read (1), Write (2), Release (3)."""
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        i_val = L.get(words, "i")
        lock = L.get(words, "lock") != 0
        nxt, valid = [], []
        for k in range(n):
            pc = L.get(words, "pc", k)
            t = L.get(words, "t", k)
            lock_w = L.set(L.set(words, "lock", 1), "pc", 1, k)
            read_w = L.set(L.set(words, "t", i_val, k), "pc", 2, k)
            write_w = L.set(L.set(words, "i", t + jnp.uint32(1)), "pc", 3, k)
            rel_w = L.set(L.set(words, "lock", 0), "pc", 4, k)
            w = jnp.where(
                pc == 0, lock_w,
                jnp.where(pc == 1, read_w, jnp.where(pc == 2, write_w, rel_w)),
            )
            ok = jnp.where(
                pc == 0, ~lock,
                jnp.where((pc == 1) | (pc == 2), jnp.bool_(True),
                          (pc == 3) & lock),
            )
            nxt.append(w)
            valid.append(ok & (pc < 4))
        return jnp.stack(nxt), jnp.stack(valid)

    def packed_properties(self, words):
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        fin = jnp.uint32(0)
        crit = jnp.uint32(0)
        for k in range(n):
            pc = L.get(words, "pc", k)
            fin = fin + (pc >= 3).astype(jnp.uint32)
            crit = crit + ((pc >= 1) & (pc < 4)).astype(jnp.uint32)
        return jnp.stack([fin == L.get(words, "i"), crit <= 1])

    def packed_representative(self, words):
        import jax.numpy as jnp

        L = self._layout
        n = self.thread_count
        t = jnp.stack([L.get(words, "t", k) for k in range(n)])
        pc = jnp.stack([L.get(words, "pc", k) for k in range(n)])
        keys = t * jnp.uint32(8) + pc  # pc < 8; lexicographic (t, pc)
        order = jnp.argsort(keys, stable=True)
        t, pc = t[order], pc[order]
        w = words
        for k in range(n):
            w = L.set(L.set(w, "t", t[k], k), "pc", pc[k], k)
        return w


def main(argv=None) -> None:
    """CLI mirroring increment_lock.rs:109-161. ``check`` runs the device
    (XLA) engine — the reference's ``check`` likewise runs its fastest
    checker; ``check-host`` is the sequential Python oracle."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        from ..backend import guarded_main

        guarded_main("stateright_tpu.models.increment_lock", orig_args)
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment_lock with {thread_count} threads on XLA.")
        PackedIncrementLock(thread_count).checker().spawn_xla(
            frontier_capacity=1 << 12, table_capacity=1 << 16
        ).report(WriteReporter())
    elif cmd == "check-host":
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment_lock with {thread_count} threads.")
        IncrementLock(thread_count).checker().spawn_dfs().report(WriteReporter())
    elif cmd == "check-sym":
        thread_count = int(args.pop(0)) if args else 3
        print(
            f"Model checking increment_lock with {thread_count} threads "
            f"using symmetry reduction."
        )
        IncrementLock(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter()
        )
    elif cmd == "explore":
        thread_count = int(args.pop(0)) if args else 3
        address = args.pop(0) if args else "localhost:3000"
        print(
            f"Exploring the state space of increment_lock with {thread_count} "
            f"threads on {address}."
        )
        IncrementLock(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  increment_lock check [THREAD_COUNT]        (device/XLA engine)")
        print("  increment_lock check-host [THREAD_COUNT]   (sequential host oracle)")
        print("  increment_lock check-sym [THREAD_COUNT]")
        print("  increment_lock check-xla [THREAD_COUNT]    (alias of check)")
        print("  increment_lock explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
