"""Shared counter guarded by a lock: the fixed version of ``increment``.

Mirrors ``/root/reference/examples/increment_lock.rs``: each thread executes
``0: lock; 1: t = SHARED; 2: SHARED = t + 1; 3: unlock; 4:``, so the ``fin``
invariant ("SHARED equals the number of threads past their write") and the
``mutex`` invariant ("at most one thread inside the critical section") both
hold — the checker finds no counterexample.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

from ..core import Model, Property
from ..utils.variant import variant

Proc = Tuple[int, int]  # (thread-local value t, program counter pc)

Lock = variant("Lock", ["thread"])
Read = variant("Read", ["thread"])
Write = variant("Write", ["thread"])
Release = variant("Release", ["thread"])


class IncrementLockState(NamedTuple):
    """(shared counter, lock bit, per-thread (t, pc)) — increment_lock.rs:19-33."""

    i: int
    lock: bool
    s: Tuple[Proc, ...]

    def representative(self) -> "IncrementLockState":
        """Sort the interchangeable thread slice (increment_lock.rs:36-46)."""
        return IncrementLockState(self.i, self.lock, tuple(sorted(self.s)))


class IncrementLock(Model):
    """The model (increment_lock.rs:48-107)."""

    def __init__(self, thread_count: int = 3):
        self.thread_count = thread_count

    def init_states(self) -> List[IncrementLockState]:
        return [
            IncrementLockState(0, False, tuple((0, 0) for _ in range(self.thread_count)))
        ]

    def actions(self, state: IncrementLockState, actions: List[Any]) -> None:
        for thread_id, (_t, pc) in enumerate(state.s):
            if pc == 0 and not state.lock:
                actions.append(Lock(thread_id))
            elif pc == 1:
                actions.append(Read(thread_id))
            elif pc == 2:
                actions.append(Write(thread_id))
            elif pc == 3 and state.lock:
                actions.append(Release(thread_id))

    def next_state(self, last_state: IncrementLockState, action: Any):
        s = list(last_state.s)
        t, _pc = s[action.thread]
        if isinstance(action, Lock):
            s[action.thread] = (t, 1)
            return last_state._replace(lock=True, s=tuple(s))
        if isinstance(action, Read):
            s[action.thread] = (last_state.i, 2)
            return last_state._replace(s=tuple(s))
        if isinstance(action, Write):
            s[action.thread] = (t, 3)
            return last_state._replace(i=t + 1, s=tuple(s))
        s[action.thread] = (t, 4)
        return last_state._replace(lock=False, s=tuple(s))

    def properties(self) -> List[Property]:
        return [
            Property.always(
                "fin",
                lambda _m, state: sum(1 for _t, pc in state.s if pc >= 3) == state.i,
            ),
            Property.always(
                "mutex",
                lambda _m, state: sum(1 for _t, pc in state.s if 1 <= pc < 4) <= 1,
            ),
        ]


def main(argv=None) -> None:
    """CLI mirroring increment_lock.rs:109-161."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        thread_count = int(args.pop(0)) if args else 3
        print(f"Model checking increment_lock with {thread_count} threads.")
        IncrementLock(thread_count).checker().spawn_dfs().report(WriteReporter())
    elif cmd == "check-sym":
        thread_count = int(args.pop(0)) if args else 3
        print(
            f"Model checking increment_lock with {thread_count} threads "
            f"using symmetry reduction."
        )
        IncrementLock(thread_count).checker().symmetry().spawn_dfs().report(
            WriteReporter()
        )
    elif cmd == "explore":
        thread_count = int(args.pop(0)) if args else 3
        address = args.pop(0) if args else "localhost:3000"
        print(
            f"Exploring the state space of increment_lock with {thread_count} "
            f"threads on {address}."
        )
        IncrementLock(thread_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  increment_lock check [THREAD_COUNT]")
        print("  increment_lock check-sym [THREAD_COUNT]")
        print("  increment_lock explore [THREAD_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
