"""Sliding puzzle: the reference's first-model doc example.

Mirrors the doc-test model in ``/root/reference/src/lib.rs:40-115``: a 3x3
(generally n x n) sliding puzzle whose single ``sometimes`` property asserts
the board configuration has a solution; ``assert_discovery`` then pins an
actual solution path. This is the "first model" of the tutorial
(``docs/tutorial.md``), in both object and packed (device-checkable) forms.

State: a tuple of ``n*n`` cell values, ``0`` marking the hole. An action
slides the named neighbour *into* the hole (``Slide::Down`` moves the tile
above the hole down, lib.rs:63-69).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

from ..core import Model, Property

# Action = which tile slides into the hole: "Down" slides the tile above
# the hole down, etc. (lib.rs:63-69). Deltas/guards depend on the board
# side, so each form derives them where it needs them.
_MOVES = ("Down", "Up", "Right", "Left")


class Puzzle(Model):
    """Object form (lib.rs:46-88). ``board`` is row-major, 0 = hole."""

    def __init__(self, board: Sequence[int], side: int = 3):
        assert len(board) == side * side, (len(board), side)
        self.board = tuple(board)
        self.side = side

    def init_states(self) -> List[Tuple[int, ...]]:
        return [self.board]

    def actions(self, state, actions: List[Any]) -> None:
        actions.extend(_MOVES)

    def _slide_from(self, state, action):
        """Index of the tile that slides into the hole, or None (the
        reference's ``maybe_from``, lib.rs:62-70)."""
        n = self.side
        empty = state.index(0)
        ey, ex = divmod(empty, n)
        if action == "Down" and ey > 0:
            return empty - n
        if action == "Up" and ey < n - 1:
            return empty + n
        if action == "Right" and ex > 0:
            return empty - 1
        if action == "Left" and ex < n - 1:
            return empty + 1
        return None

    def next_state(self, last_state, action):
        frm = self._slide_from(last_state, action)
        if frm is None:
            return None
        s = list(last_state)
        s[last_state.index(0)] = s[frm]
        s[frm] = 0
        return tuple(s)

    def properties(self) -> List[Property]:
        solved = tuple(range(self.side * self.side))
        return [Property.sometimes("solved", lambda _m, s: s == solved)]

    def format_state(self, state) -> str:
        n = self.side
        return "\n".join(
            " ".join(f"{v}" for v in state[r * n : (r + 1) * n]) for r in range(n)
        )


class PackedPuzzle(Puzzle):
    """Device form: ``n*n`` cells of ``bits_for(n*n-1)`` bits (a 3x3 board
    packs into 2 uint32 words), four action slots, the hole located with a
    single ``argmin`` over the cell vector."""

    def __init__(self, board: Sequence[int], side: int = 3):
        from ..packing import LayoutBuilder, bits_for

        super().__init__(board, side)
        nn = side * side
        self._layout = LayoutBuilder().array("cell", nn, bits_for(nn - 1)).finish()
        self.state_words = self._layout.words
        self.max_actions = 4

    def pack(self, state):
        return self._layout.pack(cell=list(state))

    def unpack(self, words):
        return tuple(int(x) for x in self._layout.unpack(words)["cell"])

    def packed_init(self):
        import numpy as np

        return np.stack([self.pack(s) for s in self.init_states()])

    def packed_step(self, words):
        import jax.numpy as jnp

        L = self._layout
        n = self.side
        cells = jnp.stack([L.get(words, "cell", k) for k in range(n * n)])
        empty = jnp.argmin(cells).astype(jnp.uint32)  # the hole holds 0
        ey, ex = empty // n, empty % n
        nxt, valid = [], []
        for delta, ok in zip(
            (-n, n, -1, 1),  # _MOVES order: Down, Up, Right, Left
            (ey > 0, ey < n - 1, ex > 0, ex < n - 1),
        ):
            frm = jnp.where(ok, empty + jnp.int32(delta).astype(jnp.uint32), 0)
            w = L.set(L.set(words, "cell", cells[frm], empty), "cell", 0, frm)
            nxt.append(w)
            valid.append(ok)
        return jnp.stack(nxt), jnp.stack(valid)

    def packed_properties(self, words):
        import jax.numpy as jnp

        L = self._layout
        solved = jnp.bool_(True)
        for k in range(self.side * self.side):
            solved = solved & (L.get(words, "cell", k) == k)
        return jnp.stack([solved])


def main(argv=None) -> None:
    """CLI in the style of the reference examples. The doc board
    (lib.rs:93-96) is the default."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None

    def pop_board():
        """(board, side): the doc board (lib.rs:93-96) unless the next arg
        parses as a square board of comma-separated ints — `explore ADDRESS`
        must not eat the address."""
        from math import isqrt

        if args and all(p.strip().isdigit() for p in args[0].split(",")):
            board = [int(x) for x in args.pop(0).split(",")]
            side = isqrt(len(board))
            if side * side != len(board):
                raise SystemExit(f"board has {len(board)} cells; need a square count")
            return board, side
        return [1, 4, 2, 3, 5, 8, 6, 7, 0], 3

    if cmd == "check":
        from ..backend import guarded_main

        guarded_main("stateright_tpu.models.puzzle", orig_args)
        board, side = pop_board()
        print("Model checking the sliding puzzle on XLA.")
        PackedPuzzle(board, side).checker().spawn_xla(
            frontier_capacity=1 << 14, table_capacity=1 << 19
        ).report(WriteReporter())
    elif cmd == "check-host":
        board, side = pop_board()
        print("Model checking the sliding puzzle.")
        Puzzle(board, side).checker().spawn_bfs().report(WriteReporter())
    elif cmd == "explore":
        board, side = pop_board()
        address = args.pop(0) if args else "localhost:3000"
        print(f"Exploring the sliding puzzle state space on {address}.")
        Puzzle(board, side).checker().serve(address)
    else:
        print("USAGE:")
        print("  puzzle check [BOARD]        (device/XLA engine)")
        print("  puzzle check-host [BOARD]   (sequential host oracle)")
        print("  puzzle explore [BOARD] [ADDRESS]")
        print("BOARD is comma-separated, e.g. 1,4,2,3,5,8,6,7,0")


if __name__ == "__main__":
    main()
