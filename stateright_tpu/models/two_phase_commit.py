"""Two-phase commit (Gray & Lamport, "Consensus on Transaction Commit").

Same transition system as the reference example
(``/root/reference/examples/2pc.rs``): a transaction manager and ``rm_count``
resource managers exchange messages through a shared message set.  Known
state-space sizes (reference tests, 2pc.rs:151-172): 288 at rm=3, 8,832 at
rm=5, 665 at rm=5 with symmetry reduction.

Two implementations of the one system:

- :class:`TwoPhaseSys` — object-level ``Model`` for the host oracle engines.
- :class:`PackedTwoPhaseSys` — the TPU form: states bit-packed into two
  uint32 words, the action fan-out evaluated as a fixed ``2 + 5N`` slot grid
  by vectorized jnp ops, properties fused as packed predicates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

from ..core import Model, Property

# RmState encoding; order matches the reference's derive(Ord) declaration
# order (2pc.rs:33-39), which symmetry-reduction sorting relies on.
WORKING, PREPARED, COMMITTED, ABORTED = 0, 1, 2, 3
# TmState encoding (2pc.rs:41-46).
TM_INIT, TM_COMMITTED, TM_ABORTED = 0, 1, 2

_RM_NAMES = ["Working", "Prepared", "Committed", "Aborted"]
_TM_NAMES = ["Init", "Committed", "Aborted"]


@dataclass(frozen=True)
class TwoPhaseState:
    """rm_state per RM, tm_state, tm_prepared per RM, and the message set.

    Messages are encoded in a frozenset as ``("Prepared", rm)``, ``"Commit"``,
    ``"Abort"`` (the closed message universe of 2pc.rs:26-31).
    """

    rm_state: Tuple[int, ...]
    tm_state: int
    tm_prepared: Tuple[bool, ...]
    msgs: frozenset

    def representative(self) -> "TwoPhaseState":
        """Canonical member of this state's symmetry class: RMs sorted by
        rm_state (stable), tm_prepared permuted along, message RM ids
        rewritten (2pc.rs:205-225)."""
        order = sorted(range(len(self.rm_state)), key=lambda i: self.rm_state[i])
        inverse = {old: new for new, old in enumerate(order)}
        msgs = frozenset(
            ("Prepared", inverse[m[1]]) if isinstance(m, tuple) else m
            for m in self.msgs
        )
        return TwoPhaseState(
            rm_state=tuple(self.rm_state[i] for i in order),
            tm_state=self.tm_state,
            tm_prepared=tuple(self.tm_prepared[i] for i in order),
            msgs=msgs,
        )


class TwoPhaseSys(Model):
    """Object-level two-phase commit model (2pc.rs:59-149)."""

    def __init__(self, rm_count: int):
        self.rm_count = rm_count

    def init_states(self) -> List[TwoPhaseState]:
        n = self.rm_count
        return [
            TwoPhaseState(
                rm_state=(WORKING,) * n,
                tm_state=TM_INIT,
                tm_prepared=(False,) * n,
                msgs=frozenset(),
            )
        ]

    def actions(self, state: TwoPhaseState, actions: List[Any]) -> None:
        # Mirrors the enablement conditions of 2pc.rs:72-98 (same order).
        if state.tm_state == TM_INIT and all(state.tm_prepared):
            actions.append(("TmCommit",))
        if state.tm_state == TM_INIT:
            actions.append(("TmAbort",))
        for rm in range(self.rm_count):
            if state.tm_state == TM_INIT and ("Prepared", rm) in state.msgs:
                actions.append(("TmRcvPrepared", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmPrepare", rm))
            if state.rm_state[rm] == WORKING:
                actions.append(("RmChooseToAbort", rm))
            if "Commit" in state.msgs:
                actions.append(("RmRcvCommitMsg", rm))
            if "Abort" in state.msgs:
                actions.append(("RmRcvAbortMsg", rm))

    def next_state(
        self, state: TwoPhaseState, action: Tuple
    ) -> Optional[TwoPhaseState]:
        kind = action[0]
        rm_state = list(state.rm_state)
        tm_prepared = list(state.tm_prepared)
        tm_state = state.tm_state
        msgs = set(state.msgs)
        if kind == "TmRcvPrepared":
            tm_prepared[action[1]] = True
        elif kind == "TmCommit":
            tm_state = TM_COMMITTED
            msgs.add("Commit")
        elif kind == "TmAbort":
            tm_state = TM_ABORTED
            msgs.add("Abort")
        elif kind == "RmPrepare":
            rm_state[action[1]] = PREPARED
            msgs.add(("Prepared", action[1]))
        elif kind == "RmChooseToAbort":
            rm_state[action[1]] = ABORTED
        elif kind == "RmRcvCommitMsg":
            rm_state[action[1]] = COMMITTED
        elif kind == "RmRcvAbortMsg":
            rm_state[action[1]] = ABORTED
        else:  # pragma: no cover
            raise ValueError(f"unknown action {action!r}")
        return TwoPhaseState(tuple(rm_state), tm_state, tuple(tm_prepared), frozenset(msgs))

    def properties(self) -> List[Property]:
        return [
            Property.sometimes(
                "abort agreement",
                lambda _, s: all(r == ABORTED for r in s.rm_state),
            ),
            Property.sometimes(
                "commit agreement",
                lambda _, s: all(r == COMMITTED for r in s.rm_state),
            ),
            Property.always(
                "consistent",
                lambda _, s: not (
                    any(r == ABORTED for r in s.rm_state)
                    and any(r == COMMITTED for r in s.rm_state)
                ),
            ),
        ]

    def format_action(self, action: Tuple) -> str:
        return action[0] if len(action) == 1 else f"{action[0]}({action[1]})"


class PackedTwoPhaseSys(TwoPhaseSys):
    """TPU-packed two-phase commit: implements the PackedModel protocol.

    Bit layout over two uint32 words (supports rm_count <= 14):

    - word0: ``rm_state[i]`` in bits ``[2i, 2i+2)``
    - word1: ``tm_state`` in bits ``[0, 2)``; ``tm_prepared[i]`` at bit
      ``2 + i``; ``Prepared{i}`` message bit at ``16 + i``; ``Commit`` at
      ``30``; ``Abort`` at ``31``.

    The action grid is ``2 + 5*rm_count`` static slots: [TmCommit, TmAbort]
    then per-RM [TmRcvPrepared, RmPrepare, RmChooseToAbort, RmRcvCommitMsg,
    RmRcvAbortMsg], mirroring the enablement conditions of 2pc.rs:72-98.
    """

    state_words = 2

    def __init__(self, rm_count: int):
        if rm_count > 14:
            raise ValueError("PackedTwoPhaseSys supports rm_count <= 14")
        super().__init__(rm_count)
        self.max_actions = 2 + 5 * rm_count
        if rm_count >= 2:
            # Declarative device symmetry (stateright_tpu/sym,
            # docs/symmetry.md): RM block i = its rm_state dibit, its
            # tm_prepared bit, and its Prepared{i} message bit. All three
            # lanes key the sort, so the spec kernel is a FULL (class-
            # invariant) canonicalization — unlike the partial rm_state
            # sort of :meth:`packed_representative`, its reduced counts
            # are traversal-order-independent (rm=5: 314 classes on any
            # engine; the partial form visits 665 under the reference
            # DFS and 508 under the device BFS).
            from ..sym import BlockGroup, SymmetrySpec

            self.symmetry_spec = SymmetrySpec(
                [
                    BlockGroup(
                        "rm",
                        rm_count,
                        (
                            SymmetrySpec.lane(
                                "rm_state", 2, word=0, count=rm_count
                            ),
                            SymmetrySpec.lane(
                                "tm_prepared", 1, word=1, shift0=2,
                                stride=1, count=rm_count,
                            ),
                            SymmetrySpec.lane(
                                "prepared_msg", 1, word=1, shift0=16,
                                stride=1, count=rm_count,
                            ),
                        ),
                    )
                ],
                name="2pc-rm",
            )

    # --- host-side codec --------------------------------------------------

    def pack(self, state: TwoPhaseState):
        import numpy as np

        w0 = 0
        for i, r in enumerate(state.rm_state):
            w0 |= r << (2 * i)
        w1 = state.tm_state
        for i, p in enumerate(state.tm_prepared):
            w1 |= int(p) << (2 + i)
        for m in state.msgs:
            if isinstance(m, tuple):
                w1 |= 1 << (16 + m[1])
            elif m == "Commit":
                w1 |= 1 << 30
            else:
                w1 |= 1 << 31
        return np.array([w0, w1], dtype=np.uint32)

    def unpack(self, words) -> TwoPhaseState:
        w0, w1 = int(words[0]), int(words[1])
        n = self.rm_count
        msgs = set()
        for i in range(n):
            if (w1 >> (16 + i)) & 1:
                msgs.add(("Prepared", i))
        if (w1 >> 30) & 1:
            msgs.add("Commit")
        if (w1 >> 31) & 1:
            msgs.add("Abort")
        return TwoPhaseState(
            rm_state=tuple((w0 >> (2 * i)) & 3 for i in range(n)),
            tm_state=w1 & 3,
            tm_prepared=tuple(bool((w1 >> (2 + i)) & 1) for i in range(n)),
            msgs=frozenset(msgs),
        )

    def packed_init(self):
        import numpy as np

        return np.stack([self.pack(s) for s in self.init_states()])

    # --- device-side kernel ----------------------------------------------

    def packed_step(self, words):
        """One state's full action fan-out: ``[2] uint32 -> ([A, 2] uint32,
        [A] bool)``. Pure jnp; vmapped over the frontier by the engine."""
        import jax.numpy as jnp

        n = self.rm_count
        w0, w1 = words[0], words[1]
        rm_ids = jnp.arange(n, dtype=jnp.uint32)
        rm_state = (w0 >> (2 * rm_ids)) & 3  # [n]
        tm_state = w1 & 3
        tm_prepared_all = ((w1 >> 2) & jnp.uint32((1 << n) - 1)) == jnp.uint32(
            (1 << n) - 1
        )
        msg_prepared = ((w1 >> (16 + rm_ids)) & 1).astype(jnp.bool_)  # [n]
        msg_commit = ((w1 >> 30) & 1).astype(jnp.bool_)
        msg_abort = ((w1 >> 31) & 1).astype(jnp.bool_)
        tm_init = tm_state == TM_INIT

        def set_rm(w0, rm, value):
            return (w0 & ~(jnp.uint32(3) << (2 * rm))) | (
                jnp.uint32(value) << (2 * rm)
            )

        # TmCommit / TmAbort (scalar slots).
        tmc_w1 = (w1 & ~jnp.uint32(3)) | jnp.uint32(TM_COMMITTED) | jnp.uint32(1 << 30)
        tma_w1 = (w1 & ~jnp.uint32(3)) | jnp.uint32(TM_ABORTED) | jnp.uint32(1 << 31)
        scalar_states = jnp.stack(
            [jnp.stack([w0, tmc_w1]), jnp.stack([w0, tma_w1])]
        )  # [2, 2]
        scalar_valid = jnp.stack([tm_init & tm_prepared_all, tm_init])  # [2]

        # Per-RM families, each vectorized over rm_ids -> [n, 2] states.
        w0b = jnp.broadcast_to(w0, (n,))
        w1b = jnp.broadcast_to(w1, (n,))
        # TmRcvPrepared(rm): set tm_prepared bit.
        rcv_prep = jnp.stack([w0b, w1b | (jnp.uint32(1) << (2 + rm_ids))], axis=1)
        rcv_prep_valid = tm_init & msg_prepared
        # RmPrepare(rm): rm -> Prepared, add Prepared{rm} msg.
        prep = jnp.stack(
            [set_rm(w0b, rm_ids, PREPARED), w1b | (jnp.uint32(1) << (16 + rm_ids))],
            axis=1,
        )
        rm_working = rm_state == WORKING
        # RmChooseToAbort(rm): rm -> Aborted.
        choose_abort = jnp.stack([set_rm(w0b, rm_ids, ABORTED), w1b], axis=1)
        # RmRcvCommitMsg(rm): rm -> Committed.
        rcv_commit = jnp.stack([set_rm(w0b, rm_ids, COMMITTED), w1b], axis=1)
        rcv_commit_valid = jnp.broadcast_to(msg_commit, (n,))
        # RmRcvAbortMsg(rm): rm -> Aborted.
        rcv_abort = jnp.stack([set_rm(w0b, rm_ids, ABORTED), w1b], axis=1)
        rcv_abort_valid = jnp.broadcast_to(msg_abort, (n,))

        per_rm_states = jnp.stack(
            [rcv_prep, prep, choose_abort, rcv_commit, rcv_abort], axis=1
        )  # [n, 5, 2]
        per_rm_valid = jnp.stack(
            [rcv_prep_valid, rm_working, rm_working, rcv_commit_valid, rcv_abort_valid],
            axis=1,
        )  # [n, 5]

        next_states = jnp.concatenate(
            [scalar_states, per_rm_states.reshape(5 * n, 2)]
        )  # [A, 2]
        valid = jnp.concatenate([scalar_valid, per_rm_valid.reshape(5 * n)])  # [A]
        return next_states, valid

    def packed_properties(self, words):
        """Property predicates on one packed state: ``[2] -> [3] bool``,
        ordered as :meth:`properties`."""
        import jax.numpy as jnp

        n = self.rm_count
        w0 = words[0]
        rm_ids = jnp.arange(n, dtype=jnp.uint32)
        rm_state = (w0 >> (2 * rm_ids)) & 3
        all_aborted = jnp.all(rm_state == ABORTED)
        all_committed = jnp.all(rm_state == COMMITTED)
        consistent = ~(jnp.any(rm_state == ABORTED) & jnp.any(rm_state == COMMITTED))
        return jnp.stack([all_aborted, all_committed, consistent])

    def packed_representative(self, words):
        """Canonical symmetry-class member of one packed state (device).

        Sorts RM slots by rm_state (stable), carrying tm_prepared and
        Prepared-message bits through the same permutation — the packed
        equivalent of :meth:`TwoPhaseState.representative`.
        """
        import jax.numpy as jnp

        n = self.rm_count
        w0, w1 = words[0], words[1]
        rm_ids = jnp.arange(n, dtype=jnp.uint32)
        rm_state = ((w0 >> (2 * rm_ids)) & 3).astype(jnp.int32)
        order = jnp.argsort(rm_state, stable=True).astype(jnp.uint32)
        sorted_rm = rm_state.astype(jnp.uint32)[order]
        u1, u2, u16 = jnp.uint32(1), jnp.uint32(2), jnp.uint32(16)
        prepared_bits = (w1 >> (u2 + order)) & u1
        msg_bits = (w1 >> (u16 + order)) & u1
        shifts = jnp.arange(n, dtype=jnp.uint32)
        new_w0 = jnp.sum(sorted_rm << (u2 * shifts), dtype=jnp.uint32)
        new_w1 = (
            (w1 & jnp.uint32(0b11 | (1 << 30) | (1 << 31)))
            | jnp.sum(prepared_bits << (u2 + shifts), dtype=jnp.uint32)
            | jnp.sum(msg_bits << (u16 + shifts), dtype=jnp.uint32)
        )
        return jnp.stack([new_w0, new_w1])


def main(argv=None) -> None:
    """CLI mirroring 2pc.rs:174-255: ``check``/``check-sym``/``check-xla``/
    ``explore`` subcommands. ``check`` runs the device (XLA) engine — the
    reference's ``check`` likewise runs its fastest checker (the 16-thread
    DFS, 2pc.rs:186-189), so the default here is the engine this framework
    is built around; ``check-host`` is the sequential Python oracle for
    semantics-exact comparison runs."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        from ..backend import guarded_main

        guarded_main("stateright_tpu.models.two_phase_commit", orig_args)
        rm_count = int(args.pop(0)) if args else 2
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            f"on the XLA engine."
        )
        PackedTwoPhaseSys(rm_count).checker().spawn_xla().report(WriteReporter())
    elif cmd == "check-host":
        rm_count = int(args.pop(0)) if args else 2
        print(f"Checking two phase commit with {rm_count} resource managers.")
        TwoPhaseSys(rm_count).checker().spawn_dfs().report(WriteReporter())
    elif cmd == "check-sym":
        rm_count = int(args.pop(0)) if args else 2
        print(
            f"Checking two phase commit with {rm_count} resource managers "
            f"using symmetry reduction."
        )
        TwoPhaseSys(rm_count).checker().symmetry().spawn_dfs().report(
            WriteReporter()
        )
    elif cmd == "explore":
        rm_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        print(
            f"Exploring state space for two phase commit with {rm_count} "
            f"resource managers on {address}."
        )
        TwoPhaseSys(rm_count).checker().serve(address)
    else:
        print("USAGE:")
        print("  two-phase-commit check [RM_COUNT]        (device/XLA engine)")
        print("  two-phase-commit check-host [RM_COUNT]   (sequential host oracle)")
        print("  two-phase-commit check-sym [RM_COUNT]")
        print("  two-phase-commit check-xla [RM_COUNT]    (alias of check)")
        print("  two-phase-commit explore [RM_COUNT] [ADDRESS]")


if __name__ == "__main__":
    main()
