"""Example model families.

Each module provides an object-level :class:`~stateright_tpu.Model` (checkable
by the host oracle engines) and, where applicable, a packed TPU implementation
of the same transition system for ``spawn_xla()``.
"""
