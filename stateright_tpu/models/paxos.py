"""Single Decree Paxos, model-checked for linearizability.

Mirrors ``/root/reference/examples/paxos.rs``: a cluster of Paxos servers
(two-phase consensus: Prepare/Prepared leadership handoff, Accept/Accepted
quorum decision, Decided dissemination — paxos.rs:66-248) fronted by the
register protocol (Put/Get), with scripted register clients and a
``LinearizabilityTester`` riding in the model history.

The exact-count oracle is the reference's own test: 16,668 unique states at
2 clients / 3 servers on an unordered non-duplicating network
(paxos.rs:321,345).

A term is coupled to the life of a client request — each Put starts a new
ballot — matching the classic single-decree presentation (paxos.rs:44-47).
"""

from __future__ import annotations

from typing import Any, FrozenSet, NamedTuple, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, majority, model_peers
from ..actor import register as reg
from ..core import Expectation
from ..semantics import LinearizabilityTester
from ..semantics.register import Register
from ..utils.variant import variant

Ballot = Tuple[int, Id]  # (round, leader id), lexicographic order
Proposal = Tuple[int, Id, Any]  # (request_id, requester, value)

# variants, not NamedTuples: Accept(b, p) must not equal Decided(b, p) in
# the modeled network (Rust enum variants never compare equal, paxos.rs:65).
Prepare = variant("Prepare", ["ballot"])
Prepared = variant("Prepared", ["ballot", "last_accepted"])
Accept = variant("Accept", ["ballot", "proposal"])
Accepted = variant("Accepted", ["ballot"])
Decided = variant("Decided", ["ballot", "proposal"])


class PaxosState(NamedTuple):
    """Combined leader/acceptor state (paxos.rs:90-103).

    ``prepares`` is a map ``Id -> Option<(Ballot, Proposal)>`` stored as a
    frozenset of pairs (the Python rendering of ``HashableHashMap``);
    ``accepts`` is a frozenset of acceptor ids."""

    ballot: Ballot
    proposal: Optional[Proposal]
    prepares: FrozenSet[Tuple[Id, Optional[Tuple[Ballot, Proposal]]]]
    accepts: FrozenSet[Id]
    accepted: Optional[Tuple[Ballot, Proposal]]
    is_decided: bool


def _map_insert(m: FrozenSet, k: Any, v: Any) -> FrozenSet:
    d = dict(m)
    d[k] = v
    return frozenset(d.items())


def _accepted_order(v: Optional[Tuple[Ballot, Proposal]]):
    # Option ordering: None < Some, Some compared lexicographically.
    return (0,) if v is None else (1, v)


class PaxosActor(Actor):
    """One Paxos server; plays both leader and acceptor (paxos.rs:110-248)."""

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=frozenset(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state, src: Id, msg: Any, out) -> None:
        s: PaxosState = state.get()
        if s.is_decided:
            # Once decided, only Gets are serviced; an undecided server does
            # not reply to Get at all, since a decision may exist elsewhere
            # (paxos.rs:139-151).
            if isinstance(msg, reg.Get):
                _ballot, (_req_id, _src, value) = s.accepted
                out.send(src, reg.GetOk(msg.request_id, value))
            return

        if isinstance(msg, reg.Put):
            if s.proposal is not None:
                return  # ignored: a proposal is already in flight
            # Start a new term; simulate Prepare/Prepared self-sends
            # (paxos.rs:154-171).
            ballot = (s.ballot[0] + 1, id)
            state.set(
                s._replace(
                    proposal=(msg.request_id, src, msg.value),
                    prepares=_map_insert(frozenset(), id, s.accepted),
                    accepts=frozenset(),
                    ballot=ballot,
                )
            )
            out.broadcast(self.peer_ids, reg.Internal(Prepare(ballot)))
            return

        if not isinstance(msg, reg.Internal):
            return
        m = msg.msg

        if isinstance(m, Prepare) and s.ballot < m.ballot:
            # Close earlier terms; report previously accepted proposal
            # (paxos.rs:172-181).
            state.set(s._replace(ballot=m.ballot))
            out.send(src, reg.Internal(Prepared(m.ballot, s.accepted)))

        elif isinstance(m, Prepared) and m.ballot == s.ballot:
            # Leadership handoff: once a quorum has closed earlier terms,
            # drive the most recently accepted proposal if any, else the
            # client's (paxos.rs:182-221).
            prepares = _map_insert(s.prepares, src, m.last_accepted)
            s2 = s._replace(prepares=prepares)
            if len(prepares) == majority(len(self.peer_ids) + 1):
                best = max((v for _k, v in prepares), key=_accepted_order)
                if best is not None:
                    proposal = best[1]
                else:
                    assert s2.proposal is not None, "proposal expected"
                    proposal = s2.proposal
                # Simulate Accept/Accepted self-sends.
                s2 = s2._replace(
                    proposal=proposal,
                    accepted=(m.ballot, proposal),
                    accepts=frozenset((id,)),
                )
                out.broadcast(
                    self.peer_ids, reg.Internal(Accept(m.ballot, proposal))
                )
            state.set(s2)

        elif isinstance(m, Accept) and s.ballot <= m.ballot:
            # Acceptor accepts the proposal of the current-or-newer term
            # (paxos.rs:222-227).
            state.set(s._replace(ballot=m.ballot, accepted=(m.ballot, m.proposal)))
            out.send(src, reg.Internal(Accepted(m.ballot)))

        elif isinstance(m, Accepted) and m.ballot == s.ballot:
            # Quorum of accepts = decision (paxos.rs:228-238).
            accepts = s.accepts | {src}
            s2 = s._replace(accepts=accepts)
            if len(accepts) == majority(len(self.peer_ids) + 1):
                s2 = s2._replace(is_decided=True)
                assert s2.proposal is not None, "proposal expected"
                request_id, requester_id, _value = s2.proposal
                out.broadcast(
                    self.peer_ids, reg.Internal(Decided(s.ballot, s2.proposal))
                )
                out.send(requester_id, reg.PutOk(request_id))
            state.set(s2)

        elif isinstance(m, Decided):
            # Learn the decision (paxos.rs:239-244).
            state.set(
                s._replace(
                    ballot=m.ballot,
                    accepted=(m.ballot, m.proposal),
                    is_decided=True,
                )
            )


def paxos_model(
    client_count: int = 2,
    server_count: int = 3,
    network: Optional[Network] = None,
) -> ActorModel:
    """Build the checkable model (paxos.rs:250-292): ``server_count`` Paxos
    servers + ``client_count`` register clients, with an ``always
    linearizable`` property over the history tester and a ``sometimes value
    chosen`` reachability property."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    model = ActorModel(
        cfg=None, init_history=LinearizabilityTester(Register(None))
    )
    for i in range(server_count):
        model.actor(PaxosActor(model_peers(i, server_count)))
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, "linearizable", reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


def main(argv=None) -> None:
    """CLI mirroring paxos.rs:348-461: ``check``/``explore``/``spawn``."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args.pop(0) if args else None
    if cmd == "check":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        (
            paxos_model(client_count, 3, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for Single Decree Paxos with "
            f"{client_count} clients on {address}."
        )
        paxos_model(client_count, 3, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        serialize, deserialize = json_codec(
            reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
            Prepare, Prepared, Accept, Accepted, Decided,
        )
        print("  A Single Decree Paxos cluster of three servers.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [
                (ids[i], PaxosActor([x for x in ids if x != ids[i]]))
                for i in range(3)
            ],
        )
    else:
        print("USAGE:")
        print("  paxos check [CLIENT_COUNT] [NETWORK]")
        print("  paxos explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  paxos spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
