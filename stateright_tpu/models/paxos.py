"""Single Decree Paxos, model-checked for linearizability.

Mirrors ``/root/reference/examples/paxos.rs``: a cluster of Paxos servers
(two-phase consensus: Prepare/Prepared leadership handoff, Accept/Accepted
quorum decision, Decided dissemination — paxos.rs:66-248) fronted by the
register protocol (Put/Get), with scripted register clients and a
``LinearizabilityTester`` riding in the model history.

The exact-count oracle is the reference's own test: 16,668 unique states at
2 clients / 3 servers on an unordered non-duplicating network
(paxos.rs:321,345).

A term is coupled to the life of a client request — each Put starts a new
ballot — matching the classic single-decree presentation (paxos.rs:44-47).
"""

from __future__ import annotations

from typing import Any, FrozenSet, NamedTuple, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, majority, model_peers
from ..actor import register as reg
from ..core import Expectation
from ..semantics import LinearizabilityTester
from ..packing import PackedModelAdapter, bits_for as _bits
from ..semantics.register import Register
from ..utils.variant import variant

Ballot = Tuple[int, Id]  # (round, leader id), lexicographic order
Proposal = Tuple[int, Id, Any]  # (request_id, requester, value)

# variants, not NamedTuples: Accept(b, p) must not equal Decided(b, p) in
# the modeled network (Rust enum variants never compare equal, paxos.rs:65).
Prepare = variant("Prepare", ["ballot"])
Prepared = variant("Prepared", ["ballot", "last_accepted"])
Accept = variant("Accept", ["ballot", "proposal"])
Accepted = variant("Accepted", ["ballot"])
Decided = variant("Decided", ["ballot", "proposal"])


class PaxosState(NamedTuple):
    """Combined leader/acceptor state (paxos.rs:90-103).

    ``prepares`` is a map ``Id -> Option<(Ballot, Proposal)>`` stored as a
    frozenset of pairs (the Python rendering of ``HashableHashMap``);
    ``accepts`` is a frozenset of acceptor ids."""

    ballot: Ballot
    proposal: Optional[Proposal]
    prepares: FrozenSet[Tuple[Id, Optional[Tuple[Ballot, Proposal]]]]
    accepts: FrozenSet[Id]
    accepted: Optional[Tuple[Ballot, Proposal]]
    is_decided: bool


def _map_insert(m: FrozenSet, k: Any, v: Any) -> FrozenSet:
    d = dict(m)
    d[k] = v
    return frozenset(d.items())


def _accepted_order(v: Optional[Tuple[Ballot, Proposal]]):
    # Option ordering: None < Some, Some compared lexicographically.
    return (0,) if v is None else (1, v)


class PaxosActor(Actor):
    """One Paxos server; plays both leader and acceptor (paxos.rs:110-248)."""

    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def on_start(self, id: Id, out) -> PaxosState:
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=frozenset(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id: Id, state, src: Id, msg: Any, out) -> None:
        s: PaxosState = state.get()
        if s.is_decided:
            # Once decided, only Gets are serviced; an undecided server does
            # not reply to Get at all, since a decision may exist elsewhere
            # (paxos.rs:139-151).
            if isinstance(msg, reg.Get):
                _ballot, (_req_id, _src, value) = s.accepted
                out.send(src, reg.GetOk(msg.request_id, value))
            return

        if isinstance(msg, reg.Put):
            if s.proposal is not None:
                return  # ignored: a proposal is already in flight
            # Start a new term; simulate Prepare/Prepared self-sends
            # (paxos.rs:154-171).
            ballot = (s.ballot[0] + 1, id)
            state.set(
                s._replace(
                    proposal=(msg.request_id, src, msg.value),
                    prepares=_map_insert(frozenset(), id, s.accepted),
                    accepts=frozenset(),
                    ballot=ballot,
                )
            )
            out.broadcast(self.peer_ids, reg.Internal(Prepare(ballot)))
            return

        if not isinstance(msg, reg.Internal):
            return
        m = msg.msg

        if isinstance(m, Prepare) and s.ballot < m.ballot:
            # Close earlier terms; report previously accepted proposal
            # (paxos.rs:172-181).
            state.set(s._replace(ballot=m.ballot))
            out.send(src, reg.Internal(Prepared(m.ballot, s.accepted)))

        elif isinstance(m, Prepared) and m.ballot == s.ballot:
            # Leadership handoff: once a quorum has closed earlier terms,
            # drive the most recently accepted proposal if any, else the
            # client's (paxos.rs:182-221).
            prepares = _map_insert(s.prepares, src, m.last_accepted)
            s2 = s._replace(prepares=prepares)
            if len(prepares) == majority(len(self.peer_ids) + 1):
                best = max((v for _k, v in prepares), key=_accepted_order)
                if best is not None:
                    proposal = best[1]
                else:
                    assert s2.proposal is not None, "proposal expected"
                    proposal = s2.proposal
                # Simulate Accept/Accepted self-sends.
                s2 = s2._replace(
                    proposal=proposal,
                    accepted=(m.ballot, proposal),
                    accepts=frozenset((id,)),
                )
                out.broadcast(
                    self.peer_ids, reg.Internal(Accept(m.ballot, proposal))
                )
            state.set(s2)

        elif isinstance(m, Accept) and s.ballot <= m.ballot:
            # Acceptor accepts the proposal of the current-or-newer term
            # (paxos.rs:222-227).
            state.set(s._replace(ballot=m.ballot, accepted=(m.ballot, m.proposal)))
            out.send(src, reg.Internal(Accepted(m.ballot)))

        elif isinstance(m, Accepted) and m.ballot == s.ballot:
            # Quorum of accepts = decision (paxos.rs:228-238).
            accepts = s.accepts | {src}
            s2 = s._replace(accepts=accepts)
            if len(accepts) == majority(len(self.peer_ids) + 1):
                s2 = s2._replace(is_decided=True)
                assert s2.proposal is not None, "proposal expected"
                request_id, requester_id, _value = s2.proposal
                out.broadcast(
                    self.peer_ids, reg.Internal(Decided(s.ballot, s2.proposal))
                )
                out.send(requester_id, reg.PutOk(request_id))
            state.set(s2)

        elif isinstance(m, Decided):
            # Learn the decision (paxos.rs:239-244).
            state.set(
                s._replace(
                    ballot=m.ballot,
                    accepted=(m.ballot, m.proposal),
                    is_decided=True,
                )
            )


def paxos_model(
    client_count: int = 2,
    server_count: int = 3,
    network: Optional[Network] = None,
) -> ActorModel:
    """Build the checkable model (paxos.rs:250-292): ``server_count`` Paxos
    servers + ``client_count`` register clients, with an ``always
    linearizable`` property over the history tester and a ``sometimes value
    chosen`` reachability property."""
    if network is None:
        network = Network.new_unordered_nonduplicating()

    model = ActorModel(
        cfg=None, init_history=LinearizabilityTester(Register(None))
    )
    for i in range(server_count):
        model.actor(PaxosActor(model_peers(i, server_count)))
    for _ in range(client_count):
        model.actor(reg.RegisterClient(put_count=1, server_count=server_count))
    return (
        model.init_network(network)
        .property(Expectation.ALWAYS, "linearizable", reg.linearizable_condition())
        .property(Expectation.SOMETIMES, "value chosen", reg.value_chosen_condition)
        .record_msg_in(reg.record_returns)
        .record_msg_out(reg.record_invocations)
    )


class PackedPaxos(reg.PackedClientsMixin, PackedModelAdapter):
    """Single Decree Paxos on the device engine (``spawn_xla``) — the
    flagship actor example packed into fixed-width state words.

    Everything is declared through :mod:`stateright_tpu.packing`; the hard
    sub-problems SURVEY §7 ranks #2 are solved here generically:

    - the **bounded per-server map** (``prepares``, paxos.rs:97-103) packs
      as per-key (present, accepted-code) scalar fields — keys are server
      ids, a closed set, so every access is statically indexed;
    - the **non-duplicating multiset network** (network.rs:54-55) packs as
      presence bits over a *syntactically closed envelope universe*: every
      send the protocol can ever perform is enumerated at construction
      (ballot rounds are bounded by the Put count, leaders by which servers
      receive Puts), and sub-families whose payload is data-dependent at
      send time (``Prepared`` carries the sender's accepted option, ``Accept``
      / ``Decided`` the driven proposal, ``GetOk`` the read value) are laid
      out contiguously so the device indexes them affinely. A state whose
      network leaves the universe — or holds two copies of one envelope —
      fails loudly (``OverflowError32`` on host, the codec-overflow output
      on device), never silently. Empirically (full 16,668-state
      enumeration) Paxos(2,3) stays within the universe with all envelope
      counts at 1.
    - the **LinearizabilityTester history** rides in the state via
      :class:`~stateright_tpu.packing.BoundedHistory` (max 2 ops/client),
      exactly as the object model carries it (paxos.rs:266-292).

    The ``linearizable`` property is checked EXACTLY on device
    (``device_linearizable_register``, SURVEY §7 M4 variant (b)): the
    bounded history these clients produce admits a static enumeration of
    every interleaving the backtracking serializer
    (linearizability.rs:197-284) would try, fused into the property pass —
    no host re-verification step and no candidate-buffer sizing needed.

    Oracle: 16,668 unique states at 2 clients / 3 servers
    (paxos.rs:321,345), reproduced differentially against the object model.
    """

    def __init__(self, client_count: int = 2, server_count: int = 3):
        from ..actor.network import Envelope
        from ..packing import BoundedHistory, LayoutBuilder, OverflowError32

        from ..semantics.device import MAX_PATTERNS_EXACT, pattern_count

        if pattern_count(client_count, 2) > MAX_PATTERNS_EXACT:
            raise ValueError(
                f"{client_count} clients exceed the exact device "
                "linearizability budget (semantics.device.MAX_PATTERNS_EXACT); "
                "larger sizes run on the host engines"
            )
        C, S = client_count, server_count
        self.C, self.S = C, S
        self.majority = S // 2 + 1
        self._inner = paxos_model(C, S)
        self._OverflowError32 = OverflowError32

        # Ballot/leader bounds: only servers that receive Puts ever start
        # ballots (client i Puts to server i % S, register.rs:118-120), and
        # each Put delivery raises the round by one, so rounds are bounded
        # by the Put count.
        self.leaders = sorted({(S + k) % S for k in range(C)})
        self.lidx = {l: i for i, l in enumerate(self.leaders)}
        NL = len(self.leaders)
        self.NL = NL
        R = C
        self.R = R
        self.values = [chr(ord("A") + k) for k in range(C)]

        # Ballot codes, monotone in the model's lexicographic (round, Id)
        # order: 0 = the initial (0, Id(0)); 1 + (r-1)*NL + leader_index.
        self._ballots: list = [(0, Id(0))]
        for r in range(1, R + 1):
            for l in self.leaders:
                self._ballots.append((r, Id(l)))
        self.NB = len(self._ballots)

        # Accepted-option codes, monotone in the model's max_by(_accepted_order):
        # 0 = None; 1 + ((r-1)*NL + leader_index)*C + proposal_index.
        self._acc_opts: list = [None]
        for r in range(1, R + 1):
            for l in self.leaders:
                for p in range(C):
                    self._acc_opts.append(((r, Id(l)), self._proposal(p)))
        self.NA = len(self._acc_opts)

        # --- the closed envelope universe -------------------------------
        # Handler metadata rides along: (kind, static params) per code.
        envs: list = []
        handlers: list = []
        self._code_put: list = []
        self._base_putok: dict = {}
        self._code_get: list = []
        self._base_getok: list = []
        self._base_prepare: dict = {}
        self._base_prepared: dict = {}
        self._base_accept: dict = {}
        self._code_accepted_env: dict = {}
        self._base_decided: dict = {}

        for k in range(C):
            i = S + k
            self._code_put.append(len(envs))
            envs.append(Envelope(Id(i), Id(i % S), reg.Put(i, self.values[k])))
            handlers.append(("put", (k, i % S)))
        for l in self.leaders:
            self._base_putok[l] = len(envs)
            for p in range(C):
                envs.append(Envelope(Id(l), Id(S + p), reg.PutOk(S + p)))
                handlers.append(("putok", (p,)))
        for k in range(C):
            i = S + k
            self._code_get.append(len(envs))
            envs.append(Envelope(Id(i), Id((i + 1) % S), reg.Get(2 * i)))
            handlers.append(("get", (k, (i + 1) % S)))
        for k in range(C):
            i = S + k
            self._base_getok.append(len(envs))
            for p in range(C):
                envs.append(
                    Envelope(Id((i + 1) % S), Id(i), reg.GetOk(2 * i, self.values[p]))
                )
                handlers.append(("getok", (k, p)))
        for l in self.leaders:
            for d in range(S):
                if d == l:
                    continue
                self._base_prepare[(l, d)] = len(envs)
                for r in range(1, R + 1):
                    envs.append(
                        Envelope(Id(l), Id(d), reg.Internal(Prepare((r, Id(l)))))
                    )
                    handlers.append(("prepare", (l, r, d)))
        for l in self.leaders:
            for r in range(1, R + 1):
                for s in range(S):
                    if s == l:
                        continue
                    self._base_prepared[(l, r, s)] = len(envs)
                    for la in range(self.NA):
                        envs.append(
                            Envelope(
                                Id(s),
                                Id(l),
                                reg.Internal(Prepared((r, Id(l)), self._acc_opts[la])),
                            )
                        )
                        handlers.append(("prepared", (l, r, s, la)))
        for l in self.leaders:
            for r in range(1, R + 1):
                for d in range(S):
                    if d == l:
                        continue
                    self._base_accept[(l, r, d)] = len(envs)
                    for p in range(C):
                        envs.append(
                            Envelope(
                                Id(l),
                                Id(d),
                                reg.Internal(Accept((r, Id(l)), self._proposal(p))),
                            )
                        )
                        handlers.append(("accept", (l, r, d, p)))
        for l in self.leaders:
            for r in range(1, R + 1):
                for s in range(S):
                    if s == l:
                        continue
                    self._code_accepted_env[(l, r, s)] = len(envs)
                    envs.append(Envelope(Id(s), Id(l), reg.Internal(Accepted((r, Id(l))))))
                    handlers.append(("accepted", (l, r, s)))
        for l in self.leaders:
            for r in range(1, R + 1):
                for d in range(S):
                    if d == l:
                        continue
                    self._base_decided[(l, r, d)] = len(envs)
                    for p in range(C):
                        envs.append(
                            Envelope(
                                Id(l),
                                Id(d),
                                reg.Internal(Decided((r, Id(l)), self._proposal(p))),
                            )
                        )
                        handlers.append(("decided", (l, r, d, p)))

        self._envs = envs
        self._handlers = handlers
        self._env_code = {env: c for c, env in enumerate(envs)}
        self._U = len(envs)
        self.max_actions = self._U

        # --- layout ------------------------------------------------------
        # Server/client state lives in ARRAY fields (uniformly strided) so
        # the vectorized step bodies can address them with traced indices:
        # one traced handler per message family, vmapped over the family's
        # parameter table, instead of one unrolled trace per envelope code
        # (which produced 20k-equation jaxprs and minute-scale XLA compiles).
        b = LayoutBuilder()
        b.array("bal", S, _bits(self.NB - 1))
        b.array("prop", S, _bits(C))
        b.array("acc", S, _bits(self.NA - 1))
        b.array("dec", S, 1)
        b.array("pp", S * S, 1)  # prepares presence, index s*S + key
        b.array("pv", S * S, _bits(self.NA - 1))  # prepares accepted-codes
        b.array("ac", S * S, 1)  # accepts bitset, index s*S + voter
        self._client_layout(b)
        b.array("net", self._U, 1)
        hist_values = [None] + self.values
        code_bits = _bits(len(hist_values))
        self._hist = BoundedHistory(
            b,
            thread_ids=[Id(S + k) for k in range(C)],
            max_ops=2,
            op_bits=code_bits,
            ret_bits=code_bits,
        )
        self._layout = b.finish()
        self._hist.bind(self._layout)
        self.state_words = self._layout.words

        codecs = reg.history_codecs(hist_values)
        self._op_code, self._code_op, self._ret_code, self._code_ret = codecs

        self._families = self._build_families()

    def _peers(self, x: int):
        return [j for j in range(self.S) if j != x]

    def _build_families(self):
        """Per-family uint32 parameter tables (one column per static
        handler input, send-base columns per peer); see
        PackedClientsMixin._group_families/packed_step."""
        C = self.C

        def acc_base(l: int, r: int) -> int:
            return 1 + ((r - 1) * self.NL + self.lidx[l]) * C

        def params_for(kind: str, params) -> list:
            if kind == "put":
                k, d = params
                return [k, d, self.lidx[d]] + [
                    self._base_prepare[(d, pd)] for pd in self._peers(d)
                ]
            if kind == "putok":
                (p,) = params
                return [p, self._code_get[p]]
            if kind == "get":
                k, d = params
                return [d, self._base_getok[k]]
            if kind == "getok":
                k, p = params
                # ReadOk(values[p]) ret code under [None]+values indexing.
                return [k, 2 + p]
            if kind == "prepare":
                l, r, d = params
                return [
                    self._ballot_code((r, Id(l))),
                    d,
                    self._base_prepared[(l, r, d)],
                ]
            if kind == "prepared":
                l, r, s, la = params
                return [
                    self._ballot_code((r, Id(l))),
                    l,
                    s,
                    la,
                    acc_base(l, r),
                ] + [self._base_accept[(l, r, pd)] for pd in self._peers(l)]
            if kind == "accept":
                l, r, d, p = params
                return [
                    self._ballot_code((r, Id(l))),
                    d,
                    acc_base(l, r) + p,
                    self._code_accepted_env[(l, r, d)],
                ]
            if kind == "accepted":
                l, r, s = params
                return [
                    self._ballot_code((r, Id(l))),
                    l,
                    s,
                    self._base_putok[l],
                ] + [self._base_decided[(l, r, pd)] for pd in self._peers(l)]
            # "decided"
            l, r, d, p = params
            return [self._ballot_code((r, Id(l))), d, acc_base(l, r) + p]

        return self._group_families(params_for)

    def _proposal(self, p: int):
        return (self.S + p, Id(self.S + p), self.values[p])

    def _ballot_code(self, ballot) -> int:
        try:
            return self._ballots.index(ballot)
        except ValueError:
            raise self._OverflowError32(f"ballot outside universe: {ballot!r}")

    def _acc_code(self, opt) -> int:
        try:
            return self._acc_opts.index(opt)
        except ValueError:
            raise self._OverflowError32(f"accepted option outside universe: {opt!r}")

    # --- codec -------------------------------------------------------------

    def pack(self, state):
        import numpy as np

        S, C = self.S, self.C
        fields: dict = {
            "bal": [0] * S,
            "prop": [0] * S,
            "acc": [0] * S,
            "dec": [0] * S,
            "pp": [0] * (S * S),
            "pv": [0] * (S * S),
            "ac": [0] * (S * S),
        }
        for s in range(S):
            a: PaxosState = state.actor_states[s]
            fields["bal"][s] = self._ballot_code(a.ballot)
            if a.proposal is not None:
                p = int(a.proposal[1]) - S
                if not 0 <= p < C or a.proposal != self._proposal(p):
                    raise self._OverflowError32(
                        f"proposal outside universe: {a.proposal!r}"
                    )
                fields["prop"][s] = 1 + p
            fields["acc"][s] = self._acc_code(a.accepted)
            fields["dec"][s] = 1 if a.is_decided else 0
            for key, val in a.prepares:
                j = int(key)
                if not 0 <= j < S:
                    raise self._OverflowError32(f"prepares key {key!r} not a server")
                fields["pp"][s * S + j] = 1
                fields["pv"][s * S + j] = self._acc_code(val)
            for j in a.accepts:
                fields["ac"][s * S + int(j)] = 1
        self._pack_clients(fields, state)
        self._pack_presence_net(fields, state)
        fields.update(
            self._hist.from_tester(state.history, self._op_code, self._ret_code)
        )
        return self._layout.pack(**fields)

    def unpack(self, words):
        from ..actor.model_state import ActorModelState
        from ..actor.network import UnorderedNonDuplicatingNetwork
        from ..actor.timers import Timers
        from ..semantics import LinearizabilityTester
        from ..semantics.register import Register

        f = self._layout.unpack(words)
        S, C = self.S, self.C
        actor_states = []
        for s in range(S):
            prop_code = f["prop"][s]
            prepares = frozenset(
                (Id(j), self._acc_opts[f["pv"][s * S + j]])
                for j in range(S)
                if f["pp"][s * S + j]
            )
            accepts = frozenset(Id(j) for j in range(S) if f["ac"][s * S + j])
            actor_states.append(
                PaxosState(
                    ballot=self._ballots[f["bal"][s]],
                    proposal=None if prop_code == 0 else self._proposal(prop_code - 1),
                    prepares=prepares,
                    accepts=accepts,
                    accepted=self._acc_opts[f["acc"][s]],
                    is_decided=bool(f["dec"][s]),
                )
            )
        self._unpack_clients(f, actor_states)
        counts = {
            self._envs[code]: count for code, count in enumerate(f["net"]) if count
        }
        history = self._hist.to_tester(
            f,
            lambda: LinearizabilityTester(Register(None)),
            self._code_op,
            self._code_ret,
        )
        return ActorModelState(
            actor_states=tuple(actor_states),
            network=UnorderedNonDuplicatingNetwork(counts),
            timers_set=tuple(Timers() for _ in range(S + C)),
            history=history,
        )

    # --- device kernels -----------------------------------------------------

    # --- vectorized per-family delivery bodies -----------------------------
    # Each takes (words[W], e, prm[cols]) with traced envelope code and
    # parameter row; returns (words'[W], valid, overflow). Pre-state reads
    # come from ``words``; updates accumulate on ``w``.

    def _body_put(self, words, e, prm):
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        k, d, lidx_d = prm[0], prm[1], prm[2]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", d) == 0) & (L.get(words, "prop", d) == 0)
        bc = L.get(words, "bal", d)
        r = jnp.where(bc == 0, u32(0), (bc - u32(1)) // u32(self.NL) + u32(1))
        o = ok & (r >= u32(self.R))  # next round would leave the universe
        w = L.set(w, "bal", u32(1) + r * u32(self.NL) + lidx_d, d)
        w = L.set(w, "prop", k + u32(1), d)
        acc_d = L.get(words, "acc", d)
        for j in range(S):  # prepares := {d: accepted}, accepts := {}
            w = L.set(w, "pp", 0, d * S + j)
            w = L.set(w, "pv", 0, d * S + j)
            w = L.set(w, "ac", 0, d * S + j)
        w = L.set(w, "pp", 1, d * S + d)
        w = L.set(w, "pv", acc_d, d * S + d)
        for j in range(S - 1):
            # Prepare codes are contiguous in round: base + (new_round-1).
            w, dup = self._net_send(w, prm[3 + j] + r)
            o = o | dup
        return w, ok, ok & o

    def _body_get(self, words, e, prm):
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        d, getok_base = prm[0], prm[1]
        deliv, w = self._net_take(words, e)
        # Undecided servers ignore Gets (paxos.rs:139-151).
        ok = deliv & (L.get(words, "dec", d) != 0)
        acc_d = L.get(words, "acc", d)
        p = (acc_d - u32(1)) % u32(self.C)  # proposal index of the accepted value
        w, dup = self._net_send(w, getok_base + p)
        # A decided server always has an accepted value (the ref
        # destructures it, paxos.rs:147); acc==0 here is a codec bug.
        return w, ok, ok & (dup | (acc_d == 0))

    def _body_prepare(self, words, e, prm):
        L = self._layout
        bc, d, prepared_base = prm[0], prm[1], prm[2]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", d) == 0) & (L.get(words, "bal", d) < bc)
        w = L.set(w, "bal", bc, d)
        # Prepared(b, accepted) back to the leader: codes contiguous in the
        # accepted option.
        w, dup = self._net_send(w, prepared_base + L.get(words, "acc", d))
        return w, ok, ok & dup

    def _body_prepared(self, words, e, prm):
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        bc, l, s, la, acc_base = prm[0], prm[1], prm[2], prm[3], prm[4]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", l) == 0) & (L.get(words, "bal", l) == bc)
        w = L.set(w, "pp", 1, l * S + s)
        w = L.set(w, "pv", la, l * S + s)
        count = u32(0)
        best = u32(0)
        for j in range(S):
            mine = s == u32(j)
            pj = jnp.where(mine, u32(1), L.get(words, "pp", l * S + j))
            vj = jnp.where(mine, la, L.get(words, "pv", l * S + j))
            count = count + pj
            best = jnp.maximum(best, jnp.where(pj != 0, vj, u32(0)))
        quorum = count == u32(self.majority)
        prop_cur = L.get(words, "prop", l)
        # Drive the best previously-accepted proposal, else our own
        # (paxos.rs:192-204). Accepted codes are monotone in the model's
        # max_by(_accepted_order), so max-of-codes is max-of-options;
        # (code-1) % C recovers the proposal index.
        p_driven = jnp.where(
            best != 0, (best - u32(1)) % u32(self.C), prop_cur - u32(1)
        )
        o = quorum & (best == 0) & (prop_cur == 0)  # ref asserts (paxos.rs:199)
        w2 = L.set(w, "prop", p_driven + u32(1), l)
        w2 = L.set(w2, "acc", acc_base + p_driven, l)
        for j in range(S):  # accepts := {l}
            w2 = L.set(w2, "ac", 0, l * S + j)
        w2 = L.set(w2, "ac", 1, l * S + l)
        for j in range(S - 1):
            w2, dup = self._net_send(w2, prm[5 + j] + p_driven)
            o = o | (quorum & dup)
        w = jnp.where(quorum, w2, w)
        return w, ok, ok & o

    def _body_accept(self, words, e, prm):
        L = self._layout
        bc, d, acc_code, accepted_code = prm[0], prm[1], prm[2], prm[3]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", d) == 0) & (L.get(words, "bal", d) <= bc)
        w = L.set(w, "bal", bc, d)
        w = L.set(w, "acc", acc_code, d)
        w, dup = self._net_send(w, accepted_code)
        return w, ok, ok & dup

    def _body_accepted(self, words, e, prm):
        import jax.numpy as jnp

        L, S, u32 = self._layout, self.S, jnp.uint32
        bc, l, s, putok_base = prm[0], prm[1], prm[2], prm[3]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", l) == 0) & (L.get(words, "bal", l) == bc)
        w = L.set(w, "ac", 1, l * S + s)
        count = u32(0)
        for j in range(S):
            count = count + jnp.where(
                s == u32(j), u32(1), L.get(words, "ac", l * S + j)
            )
        quorum = count == u32(self.majority)
        prop_cur = L.get(words, "prop", l)
        o = quorum & (prop_cur == 0)  # ref asserts (paxos.rs:232)
        p = prop_cur - u32(1)
        w2 = L.set(w, "dec", 1, l)
        for j in range(S - 1):
            w2, dup = self._net_send(w2, prm[4 + j] + p)
            o = o | (quorum & dup)
        # PutOk to the requester of the decided proposal (paxos.rs:236):
        # codes contiguous in proposal for this leader.
        w2, dup = self._net_send(w2, putok_base + p)
        o = o | (quorum & dup)
        w = jnp.where(quorum, w2, w)
        return w, ok, ok & o

    def _body_decided(self, words, e, prm):
        # Learn the decision unconditionally (paxos.rs:239-244).
        L = self._layout
        bc, d, acc_code = prm[0], prm[1], prm[2]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "dec", d) == 0)
        w = L.set(w, "bal", bc, d)
        w = L.set(w, "acc", acc_code, d)
        w = L.set(w, "dec", 1, d)
        return w, ok, ok & ~ok  # never overflows

    def packed_properties(self, words):
        """[linearizable, value chosen] — order of ``properties()``. The
        first is the EXACT on-device linearizability check
        (``device_linearizable_register``). The second mirrors
        ``value_chosen_condition``: a deliverable GetOk with a real value —
        Paxos GetOks always carry one."""
        import jax.numpy as jnp

        L = self._layout
        lin = self.device_linearizable_register(words)

        chosen = jnp.bool_(False)
        for k in range(self.C):
            for p in range(self.C):
                chosen = chosen | (L.get(words, "net", self._base_getok[k] + p) != 0)
        return jnp.stack([lin, chosen])


def main(argv=None) -> None:
    """CLI mirroring paxos.rs:348-461: ``check``/``explore``/``spawn``."""
    import sys

    from ..report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    orig_args = list(args)
    cmd = args.pop(0) if args else None
    if cmd in ("check", "check-xla"):
        # ``check`` runs the device (XLA) engine — the reference's check
        # likewise runs its fastest checker. A custom NETWORK falls back to
        # the host oracle (the packed codec models the default network).
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        if network is None:
            from ..backend import guarded_main

            guarded_main("stateright_tpu.models.paxos", orig_args)
            print(
                f"Model checking Single Decree Paxos with {client_count} "
                "clients on XLA."
            )
            (
                PackedPaxos(client_count, 3)
                .checker()
                .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 16)
                .report(WriteReporter())
            )
        else:
            print(
                f"Model checking Single Decree Paxos with {client_count} "
                "clients."
            )
            (
                paxos_model(client_count, 3, network)
                .checker()
                .spawn_dfs()
                .report(WriteReporter())
            )
    elif cmd == "check-host":
        client_count = int(args.pop(0)) if args else 2
        network = Network.from_name(args.pop(0)) if args else None
        print(f"Model checking Single Decree Paxos with {client_count} clients.")
        (
            paxos_model(client_count, 3, network)
            .checker()
            .spawn_dfs()
            .report(WriteReporter())
        )
    elif cmd == "explore":
        client_count = int(args.pop(0)) if args else 2
        address = args.pop(0) if args else "localhost:3000"
        network = Network.from_name(args.pop(0)) if args else None
        print(
            f"Exploring state space for Single Decree Paxos with "
            f"{client_count} clients on {address}."
        )
        paxos_model(client_count, 3, network).checker().serve(address)
    elif cmd == "spawn":
        from ..actor.spawn import json_codec, spawn

        port = 3000
        ids = [Id.from_addr("127.0.0.1", port + i) for i in range(3)]
        serialize, deserialize = json_codec(
            reg.Put, reg.Get, reg.PutOk, reg.GetOk, reg.Internal,
            Prepare, Prepared, Accept, Accepted, Decided,
        )
        print("  A Single Decree Paxos cluster of three servers.")
        print("  You can interact using netcat:")
        print(f"$ nc -u localhost {port}")
        print(serialize(reg.Put(1, "X")).decode())
        print(serialize(reg.Get(2)).decode())
        spawn(
            serialize,
            deserialize,
            [
                (ids[i], PaxosActor([x for x in ids if x != ids[i]]))
                for i in range(3)
            ],
        )
    else:
        print("USAGE:")
        print("  paxos check [CLIENT_COUNT] [NETWORK]  (device/XLA engine)")
        print("  paxos check-host [CLIENT_COUNT] [NETWORK]  (sequential host oracle)")
        print("  paxos check-xla [CLIENT_COUNT]  (alias of check)")
        print("  paxos explore [CLIENT_COUNT] [ADDRESS] [NETWORK]")
        print("  paxos spawn")
        print(f"NETWORK: {' | '.join(Network.names())}")


if __name__ == "__main__":
    main()
