"""Multiplexed superstep: K independent jobs in ONE device program.

The roofline (BASELINE.md) says the engine is per-level fixed-cost-bound
(~260 ms/level on chip), so under interactive fleet traffic — many *small*
jobs at rm<=4 — every tenant pays the full sort + dispatch fixed cost
alone. :class:`MuxChecker` stacks K same-shape-class jobs under one
leading lane axis and drives them through a single fused device program:

- Each lane is a full :class:`~stateright_tpu.xla.XlaChecker` over the
  SAME model instance (shared compile caches, shared capacity hints) —
  the lane checkers remain the source of truth for per-lane state,
  bookkeeping, checkpoints, and metrics; the mux layer only batches the
  device calls.
- The device program is ``jax.vmap`` of the engine's single-level
  superstep wrapped in a mux-owned ``lax.while_loop``: per-lane
  ``f_count``/termination masks (a finished lane rides with a zero-width
  frontier and a per-lane commit mask, so its frontier, table, and counts
  stay bit-identical), per-lane dedup against per-lane tables (the
  vmapped table-scale sort lowers to ONE batched sort serving all K
  lanes), and per-lane exact counts/discoveries split back out at
  quiescent boundaries.
- Any active lane's overflow (table/frontier/candidate) leaves that
  iteration uncommitted for every lane — the host grows ALL lanes
  uniformly (keeping the stack rectangular; capacities affect cost, never
  counts) and re-enters, exactly the solo engine's retry discipline.

Exactness: counts are bucket-independent (pinned by the engine tests), a
superstep fed ``f_count=0`` is a fixed point, and uncommitted iterations
recompute deterministically — so every lane's generated/unique/discovery
results are bit-identical to its solo run (pinned by tests/test_mux.py).

Exclusions (typed :class:`MuxError`): host-verified properties (their
per-superstep host confirmation would serialize the lanes), the delta
dedup structure (its flush is a host-invoked maintain program), and
visitors. The service's batching scheduler (service/core.py) only groups
specs from the statically mux-eligible families
(service/registry.py:MUX_FAMILIES).

Telemetry: each lane's ``level_log`` rows gain ``lanes``/``lanes_active``,
the mux ``dispatch_log`` records ``(run_cap, committed, lanes,
lanes_active)`` per device call (each lane's own log keeps the pinned
2-tuple schema), and :meth:`MuxChecker.metrics` reports ``mux_lanes`` /
``mux_dispatches_saved`` (the dispatches the batch avoided vs solo runs,
summed as ``lanes_active - 1`` per device call).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Tuple

import numpy as np

from .xla import XlaChecker

INT32_MAX = 2**31 - 1


class MuxError(ValueError):
    """A lane set the multiplexed engine cannot batch (typed so the
    service's batching scheduler and callers can fall back to solo
    dispatch deliberately)."""


def _check_lanes(lanes: List[XlaChecker]) -> None:
    if not lanes:
        raise MuxError("mux needs at least one lane")
    for ln in lanes:
        if type(ln) is not XlaChecker:
            raise MuxError(
                f"mux lanes must be XlaChecker instances, got {type(ln).__name__}"
            )
    if len(set(map(id, lanes))) != len(lanes):
        raise MuxError("mux lanes must be distinct checker instances")
    first = lanes[0]
    for ln in lanes[1:]:
        if ln._model is not first._model:
            raise MuxError(
                "mux lanes must share ONE model instance (same shape class "
                "AND shared compile caches); resolve the spec once and "
                "build every lane from it"
            )
    if first._hv_idx:
        raise MuxError(
            "host-verified properties cannot be multiplexed (their "
            "per-superstep host confirmation would serialize the lanes)"
        )
    if first._dedup == "delta":
        raise MuxError(
            "the delta dedup structure cannot be multiplexed (its flush "
            "is a host-invoked maintain program)"
        )
    for ln in lanes:
        if ln._visitor is not None:
            raise MuxError("visitors cannot be multiplexed")
    for attr in ("_dedup", "_compaction", "_sym_tag", "_max_probes", "_soa"):
        vals = {getattr(ln, attr) for ln in lanes}
        if len(vals) != 1:
            raise MuxError(
                f"mux lanes disagree on {attr.lstrip('_')}: {sorted(map(str, vals))}"
            )
    caps = {(ln._frontier_capacity, ln._table.capacity) for ln in lanes}
    if len(caps) != 1:
        raise MuxError(
            "mux lanes must start at identical frontier/table capacities "
            f"(got {sorted(caps)}); pass the same spawn capacities to every lane"
        )


class MuxChecker:
    """Drive K lane checkers through one batched fused device program.

    The constructor takes fully-spawned lanes (``spawn_xla`` each lane
    with identical capacities over one shared model instance — per-lane
    ``checkpoint_to=``/``metrics_to=``/resume all work unchanged, since
    the lanes hold real state). ``MuxChecker`` then replaces the lanes'
    own dispatch loops: call :meth:`_run_block` until :meth:`is_done`.
    """

    def __init__(self, lanes: List[XlaChecker]):
        _check_lanes(lanes)
        self.lanes = list(lanes)
        self.k = len(self.lanes)
        lead = self.lanes[0]
        self._model = lead._model
        self._jax = lead._jax
        self._levels_per_dispatch = lead._levels_per_dispatch
        # Shared observability: the mux layer owns the dispatch spans and
        # heartbeat (one device call serves every lane); the lanes keep
        # their per-lane checkpoint/metrics hooks.
        self._tracer = lead._tracer
        self._heartbeat = lead._heartbeat
        # Dispatch-phase profiler: inherited from the lead lane (one
        # device call serves every lane, so the mux layer owns the split
        # the same way it owns the dispatch span).
        self._phases = lead._phases
        #: One phase-split dict per device call (see XlaChecker.phase_log).
        self.phase_log: List[Dict[str, Any]] = []
        #: One ``(run_cap, committed, lanes, lanes_active)`` per device
        #: call (the lane-axis extension of the engine's pinned 2-tuple).
        self.dispatch_log: List[Tuple[int, int, int, int]] = []
        self._dispatches_saved = 0

    PHASE_NAMES = XlaChecker.PHASE_NAMES
    _log_phases = XlaChecker._log_phases

    # --- program cache ----------------------------------------------------

    def _mux_key(self, f_cap: int, cand_cap: int):
        lead = self.lanes[0]
        return (
            "mux", self.k, f_cap, cand_cap, self._levels_per_dispatch,
            lead._sym_tag, lead._max_probes, lead._dedup, lead._compaction,
        )

    def _mux_fused_for(self, run_cap: int, cand_cap: int):
        import jax

        cache = self._model.__dict__.setdefault("_xla_mux_cache", {})
        key = self._mux_key(run_cap, cand_cap)
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(self._build_mux_fused(run_cap, cand_cap))
            cache[key] = fn
        return fn, key

    def _build_mux_fused(self, f_cap: int, cand_cap: int):
        """The batched fused program: ``vmap`` of the single-level
        superstep inside a mux-owned ``lax.while_loop``. Per-lane commit
        masks replace the solo fused loop's scalar commit; any active
        lane's overflow leaves the whole iteration uncommitted (the host
        grows uniformly and re-enters)."""
        import jax
        import jax.numpy as jnp

        K = self.k
        L = self._levels_per_dispatch
        P = self.lanes[0]._P
        vstep = jax.vmap(self.lanes[0]._build_superstep(f_cap, cand_cap))

        def mux_fused(frontier, ebits, fcount, table, dfound, dfp,
                      budget, remaining, lane_budget):
            def active_of(fc, tot, taken, df):
                a = (fc > 0) & (tot < remaining) & (taken < lane_budget)
                if P > 0:
                    a = a & ~jnp.all(df, axis=1)
                return a

            def body(carry):
                (fr, eb, fc, tb, df, dp, tot_s, tot_u, taken, committed,
                 _go, _ovf, lv_act, lv_fr, lv_st, lv_un) = carry
                active = active_of(fc, tot_s, taken, df)
                eff = jnp.where(active, fc, jnp.int32(0))
                (nf, ne, ncount, ntb, ndf, ndp, d_s, d_u,
                 t_o, f_o, c_o, cc_o, _hw, _hf, _hc) = vstep(
                    fr, eb, eff, tb, df, dp)
                t_ovf = jnp.any(t_o & active)
                f_ovf = jnp.any(f_o & active)
                c_ovf = jnp.any(c_o & active)
                cc_ovf = jnp.any(cc_o & active)
                ok = ~(t_ovf | f_ovf | c_ovf | cc_ovf)
                cm = active & ok

                def sel(new, old):
                    m = cm.reshape((K,) + (1,) * (new.ndim - 1))
                    return jnp.where(m, new, old)

                fr = sel(nf, fr)
                eb = sel(ne, eb)
                tb = jax.tree_util.tree_map(sel, ntb, tb)
                df = sel(ndf, df)
                dp = sel(ndp, dp)
                slot = jnp.where(ok, committed, jnp.int32(L))
                cmi = cm.astype(jnp.int32)
                lv_act = lv_act.at[slot].set(cm, mode="drop")
                lv_fr = lv_fr.at[slot].set(ncount * cmi, mode="drop")
                lv_st = lv_st.at[slot].set(d_s * cmi, mode="drop")
                lv_un = lv_un.at[slot].set(d_u * cmi, mode="drop")
                fc = jnp.where(cm, ncount, fc)
                tot_s = tot_s + d_s * cmi
                tot_u = tot_u + d_u * cmi
                taken = taken + cmi
                committed = committed + ok.astype(jnp.int32)
                ovf = jnp.stack([t_ovf, f_ovf, c_ovf, cc_ovf])
                go = ok & (committed < budget) & jnp.any(
                    active_of(fc, tot_s, taken, df)
                )
                return (fr, eb, fc, tb, df, dp, tot_s, tot_u, taken,
                        committed, go, ovf, lv_act, lv_fr, lv_st, lv_un)

            z_k = jnp.zeros((K,), jnp.int32)
            carry0 = (
                frontier, ebits, fcount, table, dfound, dfp,
                z_k, z_k, z_k, jnp.int32(0),
                jnp.any(active_of(fcount, z_k, z_k, dfound)) & (budget > 0),
                jnp.zeros((4,), jnp.bool_),
                jnp.zeros((L, K), jnp.bool_),
                jnp.zeros((L, K), jnp.int32),
                jnp.zeros((L, K), jnp.int32),
                jnp.zeros((L, K), jnp.int32),
            )
            out = jax.lax.while_loop(lambda c: c[10], body, carry0)
            (fr, eb, fc, tb, df, dp, tot_s, tot_u, _taken, committed,
             _go, ovf, lv_act, lv_fr, lv_st, lv_un) = out
            return (committed, fr, eb, fc, tb, df, dp, tot_s, tot_u, ovf,
                    lv_act, lv_fr, lv_st, lv_un)

        return mux_fused

    # --- host loop --------------------------------------------------------

    def _stack(self, run_cap: int):
        """Stack the K lanes' device state under a leading lane axis."""
        import jax
        import jax.numpy as jnp

        fs, es = zip(*(ln._bucket_inputs(run_cap) for ln in self.lanes))
        frontier = jnp.stack(fs)
        ebits = jnp.stack(es)
        fcount = jnp.asarray(
            [ln._frontier_count for ln in self.lanes], jnp.int32
        )
        table = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *(ln._table for ln in self.lanes)
        )
        dfound = jnp.stack([ln._disc_found for ln in self.lanes])
        dfp = jnp.stack([ln._disc_fp for ln in self.lanes])
        return frontier, ebits, fcount, table, dfound, dfp

    def _grow_tables(self) -> None:
        for ln in self.lanes:
            ln._grow_table()

    def _grow_frontiers(self, run_cap: int) -> int:
        """Uniform frontier growth: the lead lane's ladder decides the
        next bucket; past the top every lane's capacity ceiling doubles
        together (the stack must stay rectangular)."""
        new_cap = self.lanes[0]._grow_frontier(run_cap)
        for ln in self.lanes[1:]:
            ln._counters.inc("frontier_grows")
            if ln._frontier_capacity < self.lanes[0]._frontier_capacity:
                ln._frontier_capacity = self.lanes[0]._frontier_capacity
        return new_cap

    def _maybe_grow_loaded(self) -> bool:
        """The solo engine's proactive load rule, over the whole stack:
        grow every lane while the BUSIEST lane crosses the ceiling."""
        lead = self.lanes[0]
        num, den = (
            (lead.MAX_LOAD_NUM, lead.MAX_LOAD_DEN)
            if lead._dedup == "hash"
            else (lead.SORTED_LOAD_NUM, lead.SORTED_LOAD_DEN)
        )
        grew = False
        while (
            max(ln._unique_count for ln in self.lanes) * den
            > self.lanes[0]._table.capacity * num
        ):
            self._grow_tables()
            grew = True
        return grew

    def _run_block(self, max_count: int = 1500) -> None:
        """Up to ``levels_per_dispatch`` BFS levels for every active lane
        in ONE device call per iteration (the mux analogue of the solo
        ``_run_block_fused``)."""
        import jax.numpy as jnp

        host_active = [ln._entry_checks() for ln in self.lanes]
        if not any(host_active):
            return
        lead = self.lanes[0]
        K = self.k

        budget_left = self._levels_per_dispatch
        run_cap = lead._run_cap_for(
            max(ln._frontier_count for ln, a in zip(self.lanes, host_active) if a)
        )
        retry = False
        while budget_left > 0:
            kmax = max(1, INT32_MAX // max(run_cap * lead._A, 1))
            budget = min(budget_left, kmax)
            remaining = np.full(K, INT32_MAX, dtype=np.int32)
            lane_budget = np.zeros(K, dtype=np.int32)
            for i, ln in enumerate(self.lanes):
                if not host_active[i]:
                    continue
                lane_budget[i] = budget
                if ln._target_max_depth is not None:
                    lane_budget[i] = max(
                        0, min(budget, ln._target_max_depth - ln._depth)
                    )
                if ln._target_state_count is not None:
                    remaining[i] = max(
                        1,
                        min(
                            INT32_MAX,
                            ln._target_state_count - ln._state_count,
                        ),
                    )
            if not lane_budget.any():
                break
            cand_cap = lead._cand_cap_for(run_cap)
            fn, key = self._mux_fused_for(run_cap, cand_cap)
            fresh = lead._mark_dispatch_shape(key)
            lanes_entry = int(sum(lane_budget > 0))
            if self._heartbeat is not None:
                self._heartbeat.beat(
                    "dispatch", compile=fresh, bucket=run_cap,
                    lanes=K, lanes_active=lanes_entry,
                )
            with self._tracer.span(
                "dispatch", flavor="mux", bucket=run_cap, cand=cand_cap,
                lanes=K, lanes_active=lanes_entry, compile=fresh,
                retry=retry, dedup=lead._dedup, compaction=lead._compaction,
            ) as _sp:
                _pt0 = time.monotonic() if self._phases else 0.0
                args = self._stack(run_cap) + (
                    jnp.int32(budget),
                    jnp.asarray(remaining),
                    jnp.asarray(lane_budget),
                )
                _pt1 = time.monotonic() if self._phases else 0.0
                (committed, nf, ne, ncount, table, dfound, dfp,
                 tot_s, tot_u, ovf, lv_act, lv_fr, lv_st, lv_un) = fn(*args)
                if self._phases:
                    _pt2 = time.monotonic()
                    self._jax.block_until_ready(committed)
                    _pt3 = time.monotonic()
                committed = int(committed)
                _sp.set(committed=committed)
                _pt4 = time.monotonic() if self._phases else 0.0
            self.dispatch_log.append((run_cap, committed, K, lanes_entry))
            if self._phases:
                self._log_phases(
                    _sp, flavor="mux", bucket=run_cap, fresh=fresh,
                    committed=committed,
                    stamps=(_pt0, _pt1, _pt2, _pt3, _pt4),
                )
            self._dispatches_saved += max(0, lanes_entry - 1)
            retry = False

            ncount = np.asarray(ncount)
            tot_s = np.asarray(tot_s)
            tot_u = np.asarray(tot_u)
            lv_act = np.asarray(lv_act)
            lv_fr = np.asarray(lv_fr)
            lv_st = np.asarray(lv_st)
            lv_un = np.asarray(lv_un)

            import jax

            for i, ln in enumerate(self.lanes):
                if not host_active[i]:
                    continue
                ln._frontier = nf[i]
                ln._frontier_ebits = ne[i]
                ln._frontier_count = int(ncount[i])
                ln._table = jax.tree_util.tree_map(lambda a, i=i: a[i], table)
                ln._disc_found = dfound[i]
                ln._disc_fp = dfp[i]
                ln._state_count += int(tot_s[i])
                ln._unique_count += int(tot_u[i])
                lane_committed = int(lv_act[:committed, i].sum()) if committed else 0
                ln.dispatch_log.append((run_cap, lane_committed))
                if lane_committed:
                    depth = ln._depth
                    for lvl in range(committed):
                        if not lv_act[lvl, i]:
                            continue
                        ln.level_log.append(
                            {
                                "depth": depth,
                                "frontier": int(lv_fr[lvl, i]),
                                "generated": int(lv_st[lvl, i]),
                                "unique": int(lv_un[lvl, i]),
                                "bucket": run_cap,
                                "cand_cap": cand_cap,
                                "lane_words": ln._level_lane_words(
                                    run_cap, cand_cap
                                ),
                                "lanes": K,
                                "lanes_active": int(lv_act[lvl].sum()),
                            }
                        )
                        depth += 1
                    ln._depth = depth
                    ln._max_depth = max(ln._max_depth, ln._depth - 1)
            if self._heartbeat is not None:
                self._heartbeat.commit(
                    depth=max(ln._depth for ln in self.lanes),
                    states=sum(ln._state_count for ln in self.lanes),
                )
            budget_left -= committed
            grew_proactively = self._maybe_grow_loaded()
            for i, ln in enumerate(self.lanes):
                if not host_active[i]:
                    continue
                ln._pin_found_names()
                if (
                    ln._target_state_count is not None
                    and ln._state_count >= ln._target_state_count
                ):
                    ln._target_reached = True
                ln._maybe_checkpoint()
                ln._maybe_record()

            t_ovf, f_ovf, c_ovf, cc_ovf = (bool(x) for x in np.asarray(ovf))
            if c_ovf:
                lead._raise_codec_overflow()
            if t_ovf:
                if not grew_proactively:
                    self._grow_tables()
                retry = True
                continue
            if f_ovf:
                run_cap = self._grow_frontiers(run_cap)
                retry = True
                continue
            if cc_ovf:
                lead._grow_cand_cap(run_cap)
                # Outgrown mux programs are dead weight (this mux always
                # looks up the grown cap; lane caps are lead-shared).
                cache = self._model.__dict__.get("_xla_mux_cache", {})
                cache.pop(self._mux_key(run_cap, cand_cap), None)
                retry = True
                continue
            if committed == 0:
                break
            host_active = [
                a and ln._entry_checks()
                for a, ln in zip(host_active, self.lanes)
            ]
            if not any(host_active):
                break

    # --- Checker-ish API --------------------------------------------------

    def is_done(self) -> bool:
        return all(ln.is_done() for ln in self.lanes)

    def run_to_completion(self) -> None:
        while not self.is_done():
            before = [
                (ln._depth, ln._state_count, ln.is_done()) for ln in self.lanes
            ]
            self._run_block()
            after = [
                (ln._depth, ln._state_count, ln.is_done()) for ln in self.lanes
            ]
            if before == after:  # pragma: no cover - livelock guard
                raise RuntimeError("mux dispatch made no progress")

    def state_count(self) -> int:
        return sum(ln.state_count() for ln in self.lanes)

    def unique_state_count(self) -> int:
        return sum(ln.unique_state_count() for ln in self.lanes)

    def max_depth(self) -> int:
        return max(ln.max_depth() for ln in self.lanes)

    def metrics(self) -> Dict[str, Any]:
        """The mux layer's own snapshot (each lane's ``metrics()`` stays
        the pinned per-engine schema; docs/observability.md "Lane
        telemetry")."""
        return {
            "engine": "xla-mux",
            "backend": self._jax.default_backend(),
            "mux_lanes": self.k,
            "mux_lanes_active": sum(1 for ln in self.lanes if not ln.is_done()),
            "mux_dispatches_saved": self._dispatches_saved,
            "dispatches": len(self.dispatch_log),
            "levels_committed": sum(c for _, c, _, _ in self.dispatch_log),
            "state_count": self.state_count(),
            "unique_state_count": self.unique_state_count(),
            "max_depth": max(ln.max_depth() for ln in self.lanes),
        }
