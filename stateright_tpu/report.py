"""Progress reporting. Mirrors ``/root/reference/src/report.rs``.

``WriteReporter``'s exact output format is part of the reference's test
contract (checker.rs:684-757): ``Checking. states=…`` progress lines, a
``Done. states=…, sec=…`` summary, then one ``Discovered "name" …`` block per
discovery.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Dict, TextIO


@dataclass
class ReportData:
    """The data sent during a report event (report.rs:9-20)."""

    total_states: int
    unique_states: int
    max_depth: int
    duration: float  # seconds
    done: bool


@dataclass
class ReportDiscovery:
    """A discovery found during checking (report.rs:23-31)."""

    path: "Path"
    classification: str  # "example" | "counterexample"


class Reporter:
    """A reporter for progress during model checking (report.rs:34-47)."""

    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        """Seconds between progress reports."""
        return 1.0


class WriteReporter(Reporter):
    """Writes the reference's exact text format (report.rs:49-96)."""

    def __init__(self, writer: TextIO = None):
        self.writer = writer if writer is not None else sys.stdout

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self.writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration)}\n"
            )
        else:
            self.writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, discoveries: Dict[str, ReportDiscovery]) -> None:
        # BTreeMap iteration order in the reference == sorted by name.
        for name in sorted(discoveries):
            d = discoveries[name]
            self.writer.write(f'Discovered "{name}" {d.classification} {d.path}')
