"""Guarded accelerator-backend selection for the CLIs.

The deployment reality this package targets (SURVEY §2.8; CLAUDE.md): the
TPU can sit behind a tunnel that WEDGES — ``jax.devices()`` blocks forever
instead of failing — and the container's sitecustomize registers the
accelerator plugin at *config* level, so merely importing jax in a CLI
would hang the process when the tunnel is down. ``bench.py`` probes in a
watchdog subprocess for exactly this reason; this module gives the example
CLIs the same protection without duplicating it seven times.

Library code does NOT call this: engines run on whatever backend the
embedding application configured. Only the ``main()`` entry points (a
human at a shell, expecting an answer, not a hang) pay the probe.
"""

from __future__ import annotations

import os
import subprocess
import sys


def ensure_live_backend(timeout_s: int = 45) -> str:
    """Probe the default jax backend in a watchdog subprocess; pin this
    process to CPU if the accelerator is unreachable or wedges.

    Returns the platform name the process will use ("tpu", "cpu", ...).
    Must be called BEFORE the first jax backend use in this process.

    The probe subprocess pays the full plugin initialization; a healthy
    accelerator answers in a few seconds, a wedged tunnel burns the
    timeout once, and either way the CLI never hangs.

    **Residual hang window (TOCTOU, ADVICE r4):** on probe success the
    CLI initializes the accelerator plugin *itself* with no watchdog — a
    tunnel that wedges between the probe and that first real backend use
    still hangs the process. Accepted for the CLIs: the window is
    seconds wide and a wedge there would have hung the probe moments
    later anyway on the next level dispatch, which no in-process guard
    can prevent (only whole-run subprocess watchdogs can — bench.py's
    pattern; use it for anything unattended).
    """
    probe = (
        "import jax; ds = jax.devices(); print('PLATFORM', ds[0].platform)"
    )
    platform = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        for line in proc.stdout.splitlines():
            if line.startswith("PLATFORM "):
                platform = line.split(" ", 1)[1].strip()
                break
        else:
            proc = None
    except (subprocess.TimeoutExpired, OSError):
        proc = None
    if proc is None or platform == "cpu":
        print(
            "accelerator unreachable (or CPU-only build); running on CPU",
            file=sys.stderr,
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    return platform
