"""Guarded accelerator-backend selection for the CLIs.

The deployment reality this package targets (SURVEY §2.8; CLAUDE.md): the
TPU can sit behind a tunnel that WEDGES — ``jax.devices()`` blocks forever
instead of failing — and the container's sitecustomize registers the
accelerator plugin at *config* level, so merely importing jax in a CLI
would hang the process when the tunnel is down. ``bench.py`` probes in a
watchdog subprocess for exactly this reason; this module gives the example
CLIs the same protection without duplicating it seven times.

Two tiers of protection:

- :func:`ensure_live_backend` — probe-then-proceed. Cheap, but leaves the
  **TOCTOU residual** (ADVICE r4): a tunnel that wedges between the probe
  and this process's own first backend use still hangs the process.
- :func:`guarded_main` — the same supervised-subprocess pattern the
  service (``stateright_tpu/service``) runs its jobs under, closing that
  window: when the probe resolves an accelerator, the CLI re-execs
  *itself* as a heartbeat-supervised worker (``supervise.run_worker``
  injects ``STPU_HEARTBEAT``; the engines beat it around every dispatch),
  so a wedge anywhere — plugin init, first compile, any later dispatch —
  draws a kill verdict instead of hanging a human's shell, and the CLI
  gracefully re-runs on the CPU backend. The model ``main()``s route
  their ``check`` commands through this.

Library code does NOT call this: engines run on whatever backend the
embedding application configured. Only the ``main()`` entry points (a
human at a shell, expecting an answer, not a hang) pay the probe.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Sequence

#: The supervised re-exec marker: set (to the probed platform) in the
#: worker child's environment so the re-entered CLI proceeds in-process
#: instead of recursing into another probe + re-exec.
_CLI_WORKER_ENV = "STPU_CLI_SUPERVISED"


def _probe_platform(timeout_s: int) -> Optional[str]:
    """The default platform per a throwaway probe subprocess (which pays
    the full plugin initialization), or None when the probe wedged/died —
    this process's jax stays untouched either way."""
    probe = (
        "import jax; ds = jax.devices(); print('PLATFORM', ds[0].platform)"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM "):
            return line.split(" ", 1)[1].strip()
    return None


def _pin_cpu() -> None:
    # JAX_PLATFORMS env alone cannot override the sitecustomize's
    # config-level pin; the config update can.
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_live_backend(timeout_s: int = 45) -> str:
    """Probe the default jax backend in a watchdog subprocess; pin this
    process to CPU if the accelerator is unreachable or wedges.

    Returns the platform name the process will use ("tpu", "cpu", ...).
    Must be called BEFORE the first jax backend use in this process.

    **Residual hang window (TOCTOU, ADVICE r4):** on probe success the
    CLI initializes the accelerator plugin *itself* with no watchdog — a
    tunnel that wedges between the probe and that first real backend use
    still hangs the process. :data:`RESIDUAL_HANG_WINDOW` names it;
    :func:`guarded_main` (the model CLIs' ``check`` path) closes it by
    running the whole CLI as a heartbeat-supervised worker."""
    platform = _probe_platform(timeout_s)
    if platform is None or platform == "cpu":
        print(
            "accelerator unreachable (or CPU-only build); running on CPU",
            file=sys.stderr,
        )
        _pin_cpu()
        return "cpu"
    return platform


#: The TOCTOU residual of :func:`ensure_live_backend`, spelled out for
#: callers that accept probe-then-proceed: "probe success to this
#: process's first backend use" is unwatched — use :func:`guarded_main`
#: (or any whole-run subprocess watchdog: bench.py, the service) for
#: anything that must never hang.
RESIDUAL_HANG_WINDOW = (
    "between ensure_live_backend()'s probe and this process's own first "
    "backend use, a tunnel wedge hangs the process"
)


def guarded_main(
    module: str,
    cli_args: Optional[Sequence[str]] = None,
    timeout_s: int = 45,
    *,
    stall_s: float = 300.0,
    startup_grace_s: float = 900.0,
) -> str:
    """Wedge-proof CLI bring-up: the supervised-subprocess pattern the
    service uses, for ``main()`` entry points.

    ``module`` is the CLI's own module path (re-exec runs ``python -m
    module`` — the CLIs use relative imports, so file-path re-exec would
    not import); ``cli_args`` the original CLI arguments (default
    ``sys.argv[1:]``). Returns the platform this process should proceed
    on — the caller just continues its check. Three paths:

    - Probe resolves CPU (or the probe itself wedges): pin CPU, return
      ``"cpu"`` — identical to :func:`ensure_live_backend`.
    - Probe resolves an accelerator: re-exec this CLI as a
      heartbeat-supervised worker — the child sees :data:`_CLI_WORKER_ENV`
      and proceeds in-process on the accelerator, beating the injected
      ``STPU_HEARTBEAT`` around every dispatch. On a clean child exit the
      parent exits with its code (``SystemExit``). On a wedge verdict —
      bring-up OR any later dispatch, the window :func:`ensure_live_backend`
      cannot cover — the child's process group is killed and the parent
      falls back: pins CPU and returns ``"cpu"``, so the CLI re-runs the
      check on the host backend instead of hanging.
    - Already the supervised child: return the probed platform from the
      env marker and proceed.

    ``stall_s`` is the mid-dispatch heartbeat leash (CLI-sized: minutes,
    not bench.py's 20 — interactive shapes dispatch far more often than a
    32-level fused soak block); compile-carrying beats get the standard
    3x."""
    inherited = os.environ.get(_CLI_WORKER_ENV)
    if inherited:
        return inherited
    platform = _probe_platform(timeout_s)
    if platform is None or platform == "cpu":
        print(
            "accelerator unreachable (or CPU-only build); running on CPU",
            file=sys.stderr,
        )
        _pin_cpu()
        return "cpu"

    from . import supervise as sup

    env = dict(os.environ, **{_CLI_WORKER_ENV: platform})
    hb = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"stpu_cli_hb_{os.getpid()}.json"
    )
    args = list(sys.argv[1:] if cli_args is None else cli_args)
    holder = {}
    try:
        res = sup.run_worker(
            [sys.executable, "-m", module] + args,
            heartbeat=hb,
            stall_s=stall_s,
            startup_grace_s=startup_grace_s,
            env=env,
            poll_s=2.0,
            on_spawn=lambda p: holder.update(proc=p),
            # stdout_path=None: the child inherits this terminal — the
            # supervised run IS the CLI's output.
        )
    except KeyboardInterrupt:
        # The child runs in its own session, so terminal SIGINT reaches
        # only this parent — take the worker's whole group down with us
        # or an orphan keeps the accelerator (and the terminal).
        if holder.get("proc") is not None:
            sup._kill_group(holder["proc"])
        raise SystemExit(130) from None
    if res.killed is None and res.rc is not None and res.rc >= 0:
        raise SystemExit(res.rc)
    reason = res.killed or f"worker died by signal (rc={res.rc})"
    print(
        f"accelerator run aborted ({reason}); re-running on CPU",
        file=sys.stderr,
    )
    _pin_cpu()
    return "cpu"
