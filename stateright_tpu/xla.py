"""The TPU/XLA frontier-expansion checker: ``spawn_xla()``.

This is the engine the framework exists for.  Where the reference explores
the state graph one state at a time across CPU worker threads with a
work-stealing job market (``/root/reference/src/checker/bfs.rs:89-211``), the
XLA checker is *level-synchronous*: the entire BFS frontier is expanded in
one fused device program per super-step —

1. evaluate all property predicates over the frontier (fused, mirroring the
   per-state checks of bfs.rs:279-325),
2. expand every state's full action grid with a vmapped bit-packed
   transition kernel (the traced form of ``actions``+``next_state``,
   bfs.rs:332-333),
3. fingerprint all candidates (two uint32 murmur lanes, the device analogue
   of lib.rs:332),
4. deduplicate against a device-resident open-addressing hash set storing
   predecessor fingerprints (replacing the DashMap of bfs.rs:29-31),
5. detect terminal states for eventually-property counterexamples
   (bfs.rs:374-381), and
6. stream-compact the surviving states into the next frontier.

Only a handful of scalars (frontier count, discovery flags, overflow flags)
cross back to the host per super-step; witness paths are reconstructed from
the device parent table only on demand, by forward re-execution (the TLC
technique the reference uses, path.rs:20-97).

Work distribution needs no job market: the frontier array IS the work queue,
and every core processes it data-parallel.  Multi-chip scaling shards the
frontier and hash set by fingerprint ownership over a ``jax.sharding.Mesh``
(see ``stateright_tpu/parallel``).

## PackedModel protocol

A model checkable by this engine exposes its transition system as fixed-width
kernels over bit-packed uint32 state words:

- ``state_words: int`` — W, uint32 lanes per state.
- ``max_actions: int`` — A, static action-slot count.
- ``packed_init() -> np.ndarray[N0, W]`` — packed initial states.
- ``packed_step(words[W]) -> (next[A, W], valid[A])`` — the full action
  fan-out of one state; jnp-traceable.  ``valid=False`` covers disabled
  actions, ``next_state -> None`` no-ops, and boundary exclusion
  (bfs.rs:333-336 collapse into one mask).
- ``packed_properties(words[W]) -> bool[P]`` — property conditions, ordered
  as ``properties()``.
- ``pack(state) / unpack(words)`` — host codec between object states and
  packed words (used for witness reconstruction and the Explorer).
- ``packed_representative(words[W]) -> words[W]`` — optional, for symmetry
  reduction: the device form of ``Representative`` (representative.rs:65).
- ``host_verified_properties: frozenset[str]`` — optional. Properties whose
  exact condition cannot run on device (the linearizability testers'
  backtracking search, linearizability.rs:197-284). For these the
  ``packed_properties`` entry is a *conservative* predicate — it may be
  False (a candidate violation for ``always`` / candidate example for
  ``sometimes``... the polarity of "suspicious") only when the exact
  answer might disagree with the safe default, and must be exact in the
  other direction. The engine compacts candidate states into a small
  buffer per super-step and re-evaluates them on the host with the
  property's exact object-level condition (memoized serializer) before
  recording a discovery — SURVEY §7 M4 variant (a).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import obs
from .checker.base import Checker
from .checker.path import Path
from .core import Expectation, Model
from .ops import deltaset, fphash, hashset, sortedset

#: Counter names every engine seeds (stable ``metrics()`` key set across
#: dedup structures and runs that never grow; docs/observability.md).
ENGINE_COUNTERS = (
    "table_grows",
    "frontier_grows",
    "cand_grows",
    "delta_flushes",
    "shrink_exits",
    "ladder_jumps",
    "checkpoints_written",
)


#: The PackedModel protocol surface (module docstring above).
PACKED_ATTRS = (
    "state_words",
    "max_actions",
    "packed_init",
    "packed_step",
    "packed_properties",
)


def is_packed(model: Model) -> bool:
    """Whether ``model`` implements the PackedModel protocol (and so can
    run on the device engines)."""
    return all(hasattr(model, attr) for attr in PACKED_ATTRS)


def _require_packed(model: Model) -> None:
    missing = [
        attr
        for attr in PACKED_ATTRS
        if not hasattr(model, attr)
    ]
    if missing:
        raise TypeError(
            f"spawn_xla() requires the PackedModel protocol; {type(model).__name__} "
            f"is missing {missing}. See stateright_tpu.xla for the contract."
        )


def accel_auto_compaction(state_words: int) -> str:
    """The planes-compaction mode the ACCELERATOR auto-policy resolves
    for a model width (the round-5 on-chip verdict: sort-family
    compaction wins at narrow W; a wide-W sort compaction is a W+3
    operand ``lax.sort`` whose XLA:TPU compile stalls). ONE definition —
    ``XlaChecker.__init__`` resolves through it, and stpu-lint
    (``analysis/surfaces.py``) traces the program it names so STPU003
    checks the sort widths the chip actually runs; a threshold change
    here re-aims both."""
    return "gather" if state_words > 8 else "sort"


# --- the ladder/rung planner, as shared pure functions ----------------------
#
# The compile-shape schedule — which run buckets the ladder can land on,
# how big each bucket's candidate buffer starts, and which sub-width rungs
# a fused program specialises — used to live only inside XlaChecker
# methods, readable by nothing but a live checker. These module-level
# functions are the ONE definition: the engine delegates to them
# (``_run_cap_for`` / ``_default_cand_cap`` / ``_cand_rungs``), and
# stpu-lint's compile-plan census (``analysis/census.py``, STPU007)
# enumerates them statically, the same way ``accel_auto_compaction``
# already re-aims both the engine and STPU003. A planner change here
# re-aims the census, the warm-cache set, and the engine together.

#: The bucket ladder's floor (see ``_run_cap_for``'s docstring: the
#: round-3 deep-narrow finding — ABD never widens past 54 rows, so a
#: 1024-row floor paid a ~1000x action-grid padding tax per level).
RUN_BUCKET_FLOOR = 64

#: In-program candidate-ladder rung floor: sub-widths below this gain
#: nothing (buckets <= 256 run full-grid candidate buffers and their
#: sorts are batch-trivial) while every rung is a full superstep traced
#: into the fused program — compile cost, not savings.
CAND_RUNG_FLOOR = 256

#: The in-program candidate-ladder depth "auto" resolves to on the
#: planes engine (``XlaChecker.__init__``; the rows/hash engine has no
#: candidate-scale sorts to snug and stays at 1).
CAND_LADDER_AUTO_K = 3


def auto_dedup(backend: str) -> str:
    """The visited-set structure "auto" resolves to per backend (the
    round-5 cost model: scatter-election hash insert is the TPU
    bottleneck, sort-merge wins there; hash + scatter wins on CPU).
    Shared with the census so the warm set prices the structure the
    engine will actually run."""
    return "hash" if backend == "cpu" else "sorted"


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 1).bit_length()


def ladder_buckets(frontier_capacity: int) -> List[int]:
    """Every run bucket the ladder can land on under a frontier-capacity
    ceiling: powers of four from ``RUN_BUCKET_FLOOR``, with the ceiling
    itself as the (possibly non-power-of-four) top rung — exactly the
    values ``_run_cap_for``/``_grow_frontier`` can return before a
    growth event doubles the ceiling. Each distinct bucket is a separate
    XLA compilation; ``len(ladder_buckets(F))`` is therefore the
    compile-shape count a run plan commits to (the STPU007 budget's
    subject)."""
    out = [min(RUN_BUCKET_FLOOR, frontier_capacity)]
    while out[-1] < frontier_capacity:
        out.append(min(out[-1] * 4, frontier_capacity))
    return out


def default_cand_cap(
    run_cap: int,
    max_actions: int,
    backend: str,
    env: Optional[Dict[str, str]] = None,
) -> int:
    """The candidate-buffer capacity a so-far-unseen bucket starts at
    (before any cc_ovf growth): the full action grid for small buckets,
    a power-of-two fraction of it above (CPU m/4, accelerators m/16 —
    per-level cost there scales with sorted lane-words, round-5
    profile). ``env`` defaults to ``os.environ`` (the STPU_CAND_FRAC A/B
    knob); pass ``{}`` for the hermetic census."""
    e = os.environ if env is None else env
    m = run_cap * max_actions
    if run_cap <= 256:
        # Small buckets take the FULL grid: compaction saves nothing at
        # this scale, and an undersized buffer costs a cc_ovf -> grow ->
        # fresh-XLA-compile round per growth.
        cap = _next_pow2(m)
    else:
        den = int(e.get("STPU_CAND_FRAC", "4" if backend == "cpu" else "16"))
        cap = max(1024, _next_pow2(max(m // den, 1)))
    return min(cap, _next_pow2(m))


def cand_rungs(
    f_cap: int,
    cand_cap_of: Callable[[int], int],
    k: int,
    floor: int = CAND_RUNG_FLOOR,
) -> List[Tuple[int, int]]:
    """The in-program candidate ladder for a fused dispatch at bucket
    ``f_cap``: ascending ``[(F_k, C_k)]`` sub-width shapes, last = the
    full bucket. ``cand_cap_of`` maps a bucket to its candidate cap (a
    live checker passes its learned-cap lookup; the census passes
    :func:`default_cand_cap`)."""
    full = (f_cap, cand_cap_of(f_cap))
    if k <= 1:
        return [full]
    rungs = [full]
    Fk = f_cap
    while len(rungs) < k:
        Fk //= 4
        if Fk < floor:
            break
        # Monotone envelope: a cc_ovf growth at a SMALL bucket (its own
        # host dispatches) can push that bucket's learned cap past a
        # bigger bucket's — unclamped, the "snug" rung would then sort a
        # WIDER candidate buffer than the branch above it, inverting the
        # ladder's savings while the telemetry reports the inflated cap
        # as snug. Clamp each rung to the next rung up; an undersized
        # clamp only costs the in-program fall-through, never a dropped
        # candidate.
        rungs.append((Fk, min(cand_cap_of(Fk), rungs[-1][1])))
    rungs.reverse()
    return rungs


def capacity_hints(model: Model) -> Dict[str, int]:
    """Capacities learned from growth events in earlier single-chip checks
    of ``model`` (empty if none grew). Hints auto-apply only to DEFAULT
    capacities; a caller that passes explicit capacities may merge these in
    to pre-size a fresh run — but note that repeated runs that want the
    COMPILE cache warm should pass identical capacities instead, replaying
    the first run's (shape, bucket) schedule (every grown capacity is a new
    array shape, i.e. a recompile; bench.py's warm/measured passes)."""
    out: Dict[str, int] = {}
    table_hints = [
        v
        for k, v in model.__dict__.items()
        if k.startswith("_xla_table_cap_hint_")
    ]
    if table_hints:
        out["table_capacity"] = max(table_hints)
    if "_xla_frontier_cap_hint" in model.__dict__:
        out["frontier_capacity"] = model.__dict__["_xla_frontier_cap_hint"]
    return out


class XlaChecker(Checker):
    """Level-synchronous BFS on an accelerator. One ``_run_block`` = one
    frontier super-step (one BFS level)."""

    def __init__(
        self,
        builder,
        *,
        frontier_capacity: Optional[int] = None,
        table_capacity: Optional[int] = None,
        max_probes: int = 32,
        host_verified_cap: int = 128,
        visit_cap: int = 4096,
        levels_per_dispatch: int = 32,
        checkpoint: Optional[str] = None,
        checkpoint_to: Optional[str] = None,
        checkpoint_every: Any = None,
        checkpoint_keep: Optional[int] = None,
        dedup: str = "auto",
        compaction: str = "auto",
        ladder: str = "auto",
        shrink_exit: str = "auto",
        cand_ladder: Any = "auto",
        symmetry: Any = None,
        trace: Any = None,
        heartbeat: Any = None,
        metrics_to: Any = None,
        metrics_every: Any = None,
        metrics_keep: Optional[int] = None,
        phases: Any = None,
    ):
        import jax

        model = builder._model
        _require_packed(model)
        self._model = model
        self._jax = jax
        # Observability (stateright_tpu/obs, docs/observability.md): a
        # span tracer (NULL_TRACER when off — no clocks, no I/O), a
        # heartbeat writer (None when off), a metrics time-series
        # recorder (None when off — sampled only at quiescent superstep
        # boundaries), and the event-counter half of metrics(). All
        # host-side; never a device sync.
        self._tracer = obs.resolve_tracer(trace)
        self._heartbeat = obs.resolve_heartbeat(heartbeat)
        self._recorder = obs.resolve_recorder(
            metrics_to, metrics_every, metrics_keep
        )
        self._counters = obs.Counters(ENGINE_COUNTERS)
        # Dispatch-phase profiler (docs/observability.md "Distributed
        # tracing"): split every device call into host_prep / enqueue /
        # device_compute / readback sub-spans. The split needs ONE extra
        # host-side wait (block_until_ready on work already enqueued —
        # never a new device sync beyond the commit read the loop pays
        # anyway), so it is off by default and requires a live tracer;
        # the off path is byte-identical to pre-profiler dispatch.
        if phases is None:
            phases = os.environ.get("STPU_PHASES") or None
        if isinstance(phases, str):
            low = phases.strip().lower()
            if low in ("1", "on", "true", "yes"):
                phases = True
            elif low in ("0", "off", "false", "no", ""):
                phases = False
            else:
                raise ValueError(
                    f"phases must be on/off (STPU_PHASES), got {phases!r}"
                )
        self._phases = bool(phases) and self._tracer.enabled
        #: One dict per device call (aligned with ``dispatch_log``):
        #: bucket/flavor/compile/committed + the four phase durations in
        #: seconds. Populated only when the profiler is on.
        self.phase_log: List[Dict[str, Any]] = []
        # Recovery surface (stateright_tpu/checkpoint.py): in-loop
        # auto-checkpointing at superstep boundaries (the quiescent
        # points), plus the resume-provenance gauges metrics() reports.
        from .checkpoint import AutoCheckpointer

        self._autockpt = AutoCheckpointer.resolve(
            checkpoint_to, checkpoint_every, checkpoint_keep
        )
        self._last_checkpoint: Optional[Dict[str, Any]] = None
        self._resumed_from: Optional[str] = checkpoint
        # Symmetry reduction (stateright_tpu/sym, docs/symmetry.md):
        # resolve the spawn_xla(symmetry=) / STPU_SYMMETRY knob against
        # the builder request and the model's capability. When on, the
        # frontier canonicalizes through either the spec-compiled
        # scatter-free kernel (tag "spec:<hash>") or the model's
        # hand-written packed_representative; unsupported paths raise
        # SymmetryUnsupported instead of silently exploring full-space.
        from .sym import SymmetryUnsupported, resolve_symmetry

        _sym = resolve_symmetry(
            symmetry, builder._symmetry is not None, model, engine="xla"
        )
        self._symmetry = _sym.enabled
        self._sym_tag = _sym.tag
        self._sym_canon = _sym.device_canon
        self._sym_canon_host = _sym.host_canon
        if self._symmetry and getattr(model, "host_verified_properties", ()):
            # The hv fallback re-runs exact host predicates on CONCRETE
            # candidate states; a symmetry-reduced frontier only surfaces
            # one member per class, so an asymmetric hv property could
            # silently miss its witness. Typed refusal, not silent
            # wrongness (ISSUE 19 satellite).
            raise SymmetryUnsupported(
                "xla",
                f"{type(model).__name__} declares host_verified_properties; "
                f"the host-verified fallback evaluates concrete states and "
                f"cannot honor a symmetry-reduced frontier",
            )
        self._target_state_count: Optional[int] = builder._target_state_count
        self._target_max_depth: Optional[int] = builder._target_max_depth
        self._visitor = builder._visitor
        self._properties = model.properties()
        self._prop_names = [p.name for p in self._properties]
        # Eventually-property bit assignment: position among the eventually
        # subset (checker.rs:540-547).
        self._ebit_of_prop: Dict[int, int] = {}
        for i, p in enumerate(self._properties):
            if p.expectation == Expectation.EVENTUALLY:
                self._ebit_of_prop[i] = len(self._ebit_of_prop)
        self._ebits0 = (1 << len(self._ebit_of_prop)) - 1

        # Visited-set structure. The on-chip cost model (BASELINE.md) showed
        # the scatter-election hash insert is the TPU bottleneck (0.24 M
        # ins/s at 2^22) while sort runs at ~1.3 G keys/s, and that stream
        # compaction inverts the same way (gather 3x over scatter) — so
        # accelerators default to the sort-merge set + gather compaction
        # (ops/sortedset.py) and CPUs keep the hash set + scatter compaction
        # that wins there.
        # A planes-only compaction request (explicit arg or the
        # STPU_COMPACTION env A/B knob behind "auto") re-aims the dedup
        # auto: "bsearch"/"pallas" exist only in the plane-major engine,
        # and resolving dedup to hash-on-CPU first would reject the
        # combination the caller asked for (the r5e watcher's CPU
        # fallback died exactly there).
        requested_compaction = (
            os.environ.get("STPU_COMPACTION") or "auto"
            if compaction == "auto"
            else compaction
        )
        if dedup == "auto":
            dedup = (
                "sorted"
                if requested_compaction in ("bsearch", "pallas")
                else auto_dedup(jax.default_backend())
            )
        if dedup not in ("hash", "sorted", "delta"):
            raise ValueError(
                f"dedup must be 'auto', 'hash', 'sorted', or 'delta': {dedup!r}"
            )
        self._dedup = dedup
        self._ds = {"hash": hashset, "sorted": sortedset, "delta": deltaset}[dedup]
        # Structure-of-arrays state layout rides with the sorted (accelerator)
        # structure: XLA:TPU tiles the minor two dims of every buffer to
        # (8, 128), so a [N, W] row-major frontier with W=2 pads 2 lanes to
        # 128 — a ~64x memory-traffic blowup on every elementwise op and
        # gather over packed states. Plane-major [W, N] buffers keep N on
        # the 128-lane axis. The planes superstep preserves the rows
        # superstep's semantics bit-for-bit (candidates are restored to
        # state-major order before the insert's winner election).
        self._soa = dedup != "hash"
        # Planes-compaction lowering: "gather" computes the permutation
        # once (one small sort) and gathers every plane by it; "sort"
        # carries the planes as sort payload operands — no random gathers,
        # more sorted bytes; "bsearch" replaces the permutation sort with
        # cumsum + rank binary-search + ascending gathers. The round-5
        # on-chip A/Bs settled the hardware question per shape class:
        #   - narrow-W (2pc W=2, rm=8): sort 8.8s vs gather 15.6s vs
        #     bsearch 29.0s measured — random gathers at table scale are
        #     the dominant per-level cost and sort payload wins;
        #   - wide-W (paxos W=25): the sort-mode grid compaction becomes a
        #     W+3 = 28-operand lax.sort whose XLA:TPU *compile* stalls for
        #     tens of minutes (two bench workers in a row), while gather
        #     compiles in ~2 min and measures fastest (3.2s vs bsearch
        #     4.6s);
        #   - 1-core CPU: gather wins everywhere (round-3 model).
        # So "auto" resolves per backend AND per model width: sort-family
        # compaction only where its operand count stays small.
        # STPU_COMPACTION still makes the A/B a process restart.
        if compaction == "auto":
            compaction = os.environ.get("STPU_COMPACTION") or (
                "gather"
                if jax.default_backend() == "cpu"
                else accel_auto_compaction(model.state_words)
            )
        # "pallas": the state-major layout of "bsearch" with the
        # compaction itself as a sequential-grid pallas streaming kernel
        # (ops/pallas_compact.py) — O(n) data movement instead of the
        # sort's O(n log^2 n). Opt-in until chip-proven; small shapes
        # (bucket below the kernel block) fall back to the stable sort
        # inside compact_1d, bit-identically.
        if compaction not in ("gather", "sort", "bsearch", "pallas"):
            raise ValueError(
                "compaction must be 'auto', 'gather', 'sort', "
                f"'bsearch', or 'pallas': {compaction!r}"
            )
        if compaction in ("bsearch", "pallas") and not self._soa:
            # (bsearch included: the rows superstep never consults the
            # compaction knob, and silently measuring the hash engine
            # under an STPU_COMPACTION=bsearch A/B would mislabel the
            # banked numbers.)
            raise ValueError(
                f"compaction={compaction!r} runs in the plane-major "
                "engine: pass dedup='sorted' or 'delta' (the hash "
                "engine is the rows path)"
            )
        self._compaction = compaction
        # Bucket-ladder policy. "ramp" steps one power-of-four rung per
        # frontier overflow — for a space that widens to 2^19 that is 8
        # separate XLA compiles of the full superstep program, and compile
        # time is dominated by program complexity, not bucket size (~10 s
        # each on 1-core CPU, ~1 min over the TPU tunnel), so the ramp IS
        # the warm-pass cost for ramping spaces (round-4 finding: paxos
        # warm 47 s, 4 buckets). "jump" extrapolates the observed level
        # growth to skip rungs (see _grow_frontier) and prefers an
        # already-compiled bucket over compiling a snug one
        # (_run_cap_for); padding a level costs milliseconds, a fresh
        # compile costs ~a minute on the tunnel. Counts are
        # bucket-independent; STPU_LADDER makes the A/B a process restart.
        if ladder == "auto":
            ladder = os.environ.get("STPU_LADDER", "jump")
        if ladder not in ("jump", "ramp"):
            raise ValueError(f"ladder must be 'auto', 'jump', or 'ramp': {ladder!r}")
        self._ladder = ladder
        # Tail shrink-exit policy. The downshift is a pure host-side
        # dispatch decision — the threshold rides into the compiled
        # program as a runtime scalar — so this knob never costs a
        # compile. "auto": on for CPU, off for accelerators. Each tail
        # downshift is an extra host round-trip, and on the
        # tunnel-attached TPU the rm=8 A/B (2026-08-02) measured the
        # ~7 tail round-trips at ~1.1 s against ~0.15 s of grid-sort
        # savings (2.13 M -> 1.88 M gen/s, same schedule, same counts);
        # on 1-core CPU dispatch is ~free and the snug tail sorts won
        # (rm=6 ramp tail 16384 -> 4096 -> 1024 -> 256). A
        # locally-attached TPU with sub-ms dispatch may want
        # shrink_exit="on" — hence a knob, not a hard-coding.
        # STPU_SHRINK_EXIT makes the A/B a process restart.
        if shrink_exit == "auto":
            shrink_exit = os.environ.get("STPU_SHRINK_EXIT") or (
                "on" if jax.default_backend() == "cpu" else "off"
            )
        if shrink_exit not in ("on", "off"):
            raise ValueError(
                f"shrink_exit must be 'auto', 'on', or 'off': {shrink_exit!r}"
            )
        self._shrink_exit = shrink_exit == "on"
        # In-program candidate-width ladder (attack #2 of the BASELINE
        # roadmap, delivered IN-PROGRAM per the shrink-exit chip lesson:
        # any scheme that adds host dispatches to the tail pays ~150 ms
        # per round-trip over the tunnel, so snug candidate sorts must
        # ride inside the fused ``lax.while_loop``). Fused dispatches
        # branch via ``lax.switch`` over up to K sub-width supersteps —
        # each rung is the (frontier rows, candidate cap) shape a smaller
        # host bucket would run, specialised into the peak program — so a
        # narrow level's candidate-scale sorts (the [table ‖ cand] insert
        # merge, the frontier compaction) and its grid-scale compaction
        # all run snug with ZERO added host round-trips. Branch selection
        # is on-device (see _build_fused); an underestimate falls through
        # to the full-width branch in-program, never dropping candidates.
        # "auto" = STPU_CAND_LADDER or 3 (on for CPU and accelerators —
        # the savings are in-program, so there is no RTT trade); 1
        # disables (one branch = the pre-ladder program, byte-for-byte).
        # Each rung is a full superstep trace, so K bounds the fused
        # program's compile cost (~11 s/bucket baseline on 1-core CPU,
        # ROUND5.md item 6). Planes engine only: the rows/hash superstep
        # has no candidate-scale sorts to snug.
        explicit_cand_ladder = cand_ladder != "auto"
        env_cand_ladder = bool(os.environ.get("STPU_CAND_LADDER"))
        if cand_ladder == "auto":
            cand_ladder = os.environ.get("STPU_CAND_LADDER") or str(
                CAND_LADDER_AUTO_K
            )
        try:
            ladder_k = int(cand_ladder)
        except (TypeError, ValueError):
            raise ValueError(
                f"cand_ladder must be 'auto' or an int in 1..3: {cand_ladder!r}"
            ) from None
        if not 1 <= ladder_k <= 3:
            raise ValueError(f"cand_ladder must be in 1..3: {ladder_k}")
        if ladder_k > 1 and not self._soa:
            if explicit_cand_ladder:
                raise ValueError(
                    "cand_ladder runs in the plane-major engine: pass "
                    "dedup='sorted' or 'delta' (the hash engine's rows "
                    "superstep has no candidate-scale sorts to snug)"
                )
            if env_cand_ladder:
                # Only an explicit env A/B request warns; the default
                # auto→3 resolving to 1 on the hash engine is the normal
                # CPU configuration, not a misconfiguration.
                import warnings

                warnings.warn(
                    "STPU_CAND_LADDER has no effect with dedup='hash' "
                    "(rows-major superstep); the knob applies to the "
                    "sorted/delta planes engine only",
                    RuntimeWarning,
                    stacklevel=3,
                )
            ladder_k = 1
        self._cand_ladder_k = ladder_k
        #: In-program fall-throughs (snug branch overflowed, level re-ran
        #: at full width inside the same dispatch) — the ladder's only
        #: waste case, observable for tests and the A/B harness.
        self.cand_retries = 0
        # Expand-stage layout (attack 2 of the BASELINE roadmap; A/B knob
        # for the chip window). "rows" materializes the [F, A, W] grid the
        # vmap naturally produces, then transposes to [W, A*F] planes —
        # the intermediate has W=2 on the minor axis, i.e. the (8,128)
        # tiling tax on its full traffic. "planes" asks the vmap to emit
        # [A, W, F] directly (out_axes=2), keeping F minor throughout —
        # no padded intermediate. NOT default anywhere: a transpose fused
        # INTO a vmapped kernel is the exact shape XLA:CPU (jax 0.9.0)
        # miscompiles (_build_superstep_planes docstring), so "planes" is
        # for accelerator A/Bs guarded by count_ok + the table audit.
        expand_layout = os.environ.get("STPU_EXPAND_LAYOUT", "rows")
        if expand_layout not in ("rows", "planes"):
            raise ValueError(
                f"STPU_EXPAND_LAYOUT must be 'rows' or 'planes': {expand_layout!r}"
            )
        if expand_layout == "planes" and not self._soa:
            # The knob only exists in the planes superstep; an A/B run on
            # the rows-major (hash-dedup) builder would silently measure
            # two identical programs.
            import warnings

            warnings.warn(
                "STPU_EXPAND_LAYOUT=planes has no effect with dedup='hash' "
                "(rows-major superstep); the knob applies to the "
                "sorted/delta planes engine only",
                RuntimeWarning,
                stacklevel=3,
            )
        self._expand_layout = expand_layout

        self._max_probes = max_probes
        self._W = model.state_words
        self._A = model.max_actions
        self._P = len(self._properties)
        # Host-verified properties: device flags candidates, host confirms
        # with the exact object-level condition (see module docstring).
        hv_names = frozenset(getattr(model, "host_verified_properties", ()))
        unknown = hv_names - {p.name for p in self._properties}
        if unknown:
            raise ValueError(f"host_verified_properties not in properties(): {unknown}")
        self._hv_idx = [
            i for i, p in enumerate(self._properties) if p.name in hv_names
        ]
        for i in self._hv_idx:
            if self._properties[i].expectation == Expectation.EVENTUALLY:
                raise ValueError(
                    "host-verified eventually-properties are not supported"
                )
        # Candidate rows per super-step per host-verified property;
        # spawn_xla(host_verified_cap=...) raises it for models whose
        # conservative predicates flag wide swaths of the frontier.
        self._hv_cap = host_verified_cap
        # Per-level ceiling on host-side visitor path reconstruction.
        self._visit_cap = visit_cap
        # BFS levels fused into one device dispatch. Each host round-trip
        # costs real latency (the axon TPU sits behind a tunnel), so the
        # level loop runs *on device* in a ``lax.while_loop`` that exits
        # early on frontier exhaustion, overflow, discovery resolution, or
        # a state-count target — semantically identical to dispatching one
        # level at a time, at level granularity. Visitors force 1 (they
        # need the host between levels).
        self._levels_per_dispatch = (
            1 if self._visitor is not None else max(1, levels_per_dispatch)
        )

        # --- device state ------------------------------------------------
        import jax.numpy as jnp

        self._disc_found = jnp.zeros(self._P, jnp.bool_)
        self._disc_fp = jnp.zeros((self._P, 2), jnp.uint32)
        self._found_names: Dict[str, int] = {}  # name -> fp64, pinned on first find
        self._target_reached = False
        # Compiled supersteps are a property of the MODEL (its kernels and
        # properties), not of one checker run — cache on the model instance
        # so repeated checks (bench warm/measure passes, retries) reuse
        # compilations instead of paying a fresh XLA compile per bucket.
        self._superstep_cache: Dict[Any, Any] = model.__dict__.setdefault(
            "_xla_superstep_cache", {}
        )

        # Candidate-cap sizing is PER-CHECKER state seeded from per-model
        # hints: the old model-level dict let two live checkers over one
        # model object resize each other's candidate buffers mid-run
        # (latent aliasing — a cc_ovf growth in checker A silently changed
        # checker B's bucket shapes and evicted its compiled programs).
        # Growths still write back to the model hint dict, so a FRESH
        # checker (the bench measured pass) inherits learned caps and
        # replays the warm pass's shapes instead of re-paying cc_ovf
        # growth compiles.
        self._cand_caps: Dict[int, int] = dict(
            model.__dict__.get("_xla_cand_cap_hints", {})
        )
        # Live-checker registry (weakrefs): _grow_cand_cap consults it so
        # a growth in this checker never evicts shared compiled programs
        # a live sibling still sizes at the old cap.
        import weakref

        live = model.__dict__.setdefault("_xla_live_checkers", [])
        live[:] = [r for r in live if r() is not None]
        live.append(weakref.ref(self))

        # Capacities learned by earlier checkers of this model (growth
        # events) — starting there skips the rehash-and-rerun the previous
        # run already paid (bench warm pass learns, measured pass reuses).
        # Hints apply only when the caller took the defaults: an explicit
        # capacity — even a smaller one, e.g. to exercise the growth path —
        # must win over cross-checker state.
        self._table_hint_key = f"_xla_table_cap_hint_{dedup}"
        if table_capacity is None:
            table_capacity = max(
                1 << 20, model.__dict__.get(self._table_hint_key, 0)
            )
        if frontier_capacity is None:
            frontier_capacity = max(
                1 << 15, model.__dict__.get("_xla_frontier_cap_hint", 0)
            )

        # Per-level telemetry ({depth, frontier, generated, unique} per
        # committed BFS level) — populated by both dispatch paths so fused
        # dispatch does not cost consumers (bench_detail.json) the
        # per-level breakdown.
        self.level_log: List[Dict[str, int]] = []
        # Dispatch telemetry — ONE shape for every engine and dispatch
        # flavor (pinned by tests/test_obs.py, documented in
        # docs/observability.md): one ``(run_cap, committed_levels)``
        # tuple per device call, where ``committed_levels`` is the number
        # of BFS levels that call committed. The one-level path therefore
        # records 0 or 1 (0 = an overflow retry of the same level); a
        # fused block records 0..levels_per_dispatch. Invariant on both:
        # ``sum(committed for _, committed in dispatch_log) ==
        # len(level_log)``. Makes the bucket ladder's choices (jump
        # rungs, tail shrink-exits, lpd=1 snug picks) observable to
        # tests, metrics(), and the superstep profiler.
        self.dispatch_log: List[Tuple[int, int]] = []
        # Host-verified-path telemetry (the sampled-predicate cliff,
        # VERDICT r4 weak #6): how much the conservative device predicate
        # over-flags and what the exact host confirmations cost.
        #   flagged      rows the device pass could not clear (sum of
        #                per-superstep candidate counts, pre-cap)
        #   host_checked rows the host serializer actually re-checked
        #   cleared      checked rows that proved serializable (= the
        #                predicate's false alarms, pure overhead)
        #   confirmed    checked rows that confirmed a discovery
        #   host_sec     wall-clock spent in exact host confirmation
        self.hv_stats: Dict[str, float] = {
            "flagged": 0, "host_checked": 0, "cleared": 0,
            "confirmed": 0, "host_sec": 0.0,
        }

        if checkpoint is not None:
            # Skip init seeding entirely; _restore builds the whole state.
            self._frontier_capacity = max(frontier_capacity, 16)
            self._table = self._ds.make(table_capacity, jnp)
            self._restore(checkpoint)
            if self._autockpt is not None:
                self._autockpt.arm(self._depth)
            if self._recorder is not None:
                self._recorder.arm(self._depth)
            return

        init_packed = np.asarray(model.packed_init(), dtype=np.uint32)
        # Boundary filter on init states (bfs.rs:52-56) is the model's
        # responsibility at packed_init time; the object-level default
        # applies it here for safety.
        keep = [model.within_boundary(model.unpack(row)) for row in init_packed]
        init_packed = init_packed[keep]
        n_init = len(init_packed)

        self._frontier_capacity = max(frontier_capacity, 1 << max(n_init.bit_length(), 4))
        self._table = self._ds.make(table_capacity, jnp)
        # Insert init fingerprints with a zero parent (the "no predecessor"
        # marker, like the None predecessor of bfs.rs:59-65).
        dedup_init = self._dedup_words_host(init_packed)
        ihi, ilo = fphash.fingerprint_words(dedup_init, np)
        self._table, is_new, ovf = self._ds.insert(
            self._table,
            jnp.asarray(ihi),
            jnp.asarray(ilo),
            jnp.zeros(n_init, jnp.uint32),
            jnp.zeros(n_init, jnp.uint32),
            jnp.ones(n_init, jnp.bool_),
            max_probes=self._max_probes,
        )
        if bool(np.any(np.asarray(ovf))):  # pragma: no cover - tiny tables only
            raise RuntimeError("visited-set overflow while inserting init states")
        n_unique_init = int(np.sum(np.asarray(is_new)))

        self._frontier = self._pad_rows(init_packed, self._frontier_capacity)
        self._frontier_ebits = jnp.where(
            jnp.arange(self._frontier_capacity) < n_init, jnp.uint32(self._ebits0), jnp.uint32(0)
        )
        self._frontier_count = n_init
        self._depth = 1  # depth of states in the current frontier (bfs.rs:83)
        self._max_depth = 0
        self._state_count = n_init
        self._unique_count = n_unique_init
        self._exhausted = n_init == 0
        if self._autockpt is not None:
            self._autockpt.arm(self._depth)
        if self._recorder is not None:
            self._recorder.arm(self._depth)

    # --- checkpoint/resume (stateright_tpu/checkpoint.py) ------------------

    def save_checkpoint(self, path: str, keep: int = 1) -> None:
        """Atomic (+ rotating, with ``keep > 1``) checkpoint of the current
        search state; also the sink of the in-loop auto-checkpointer, so
        the obs span, the ``checkpoints_written`` counter, and the
        ``last_checkpoint`` gauge live here for manual and automatic saves
        alike."""
        from .checkpoint import _normalize, save_checkpoint

        with self._tracer.span(
            "checkpoint", path=path, depth=self._depth, keep=keep
        ):
            save_checkpoint(self, path, keep=keep)
        self._counters.inc("checkpoints_written")
        self._last_checkpoint = {
            "path": _normalize(path),
            "depth": self._depth,
            "states": self._state_count,
            "unique": self._unique_count,
            "unix_ts": time.time(),
        }

    def _maybe_checkpoint(self) -> None:
        """In-loop auto-checkpoint hook, called at every quiescent point
        (between supersteps, after commit bookkeeping) by both dispatch
        paths. No-op unless ``spawn_xla(checkpoint_to=...)`` /
        ``STPU_CHECKPOINT_TO`` armed a cadence."""
        if self._autockpt is not None:
            self._autockpt.maybe(self)

    def _maybe_record(self) -> None:
        """Metrics time-series hook, called at the same quiescent points
        as :meth:`_maybe_checkpoint` — ``metrics()`` is pure host-side
        reads there, so a sample never adds a device sync. No-op unless
        ``spawn_xla(metrics_to=...)`` / ``STPU_METRICS_TO`` armed a
        recorder (docs/observability.md "Time series")."""
        if self._recorder is not None:
            self._recorder.maybe(self)

    def _restore(self, path: str) -> None:
        """Replaces the freshly-initialized search state with a checkpoint's
        (the table is rebuilt by insertion, so capacities may differ)."""
        import jax
        import jax.numpy as jnp

        from .checkpoint import load_checkpoint, validate_model, validate_symmetry

        ck = load_checkpoint(path)
        validate_model(ck["meta"], self._model, self._prop_names)
        validate_symmetry(ck["meta"], self._sym_tag)

        n_entries = len(ck["key_hi"])
        # Power-of-two growth base: the delta structure's .capacity includes
        # its delta tier (not a power of two); its main tier is the base.
        cap = getattr(self._table, "main_capacity", self._table.capacity)
        while cap < 2 * n_entries:
            cap *= 2
        if self._dedup in ("sorted", "delta"):
            self._table = self._ds.from_entries(
                ck["key_hi"], ck["key_lo"], ck["val_hi"], ck["val_lo"], cap, jnp
            )
        else:
            self._table = hashset.make(cap, jnp)
            while True:
                table, _, ovf = jax.jit(hashset.insert, static_argnames="max_probes")(
                    self._table,
                    jnp.asarray(ck["key_hi"]),
                    jnp.asarray(ck["key_lo"]),
                    jnp.asarray(ck["val_hi"]),
                    jnp.asarray(ck["val_lo"]),
                    jnp.ones(n_entries, jnp.bool_),
                    max_probes=self._max_probes,
                )
                if not bool(np.any(np.asarray(ovf))):
                    self._table = table
                    break
                self._table = hashset.make(self._table.capacity * 2, jnp)

        rows = np.asarray(ck["frontier"], dtype=np.uint32)
        n = len(rows)
        while self._frontier_capacity < n:
            self._frontier_capacity *= 2
        self._frontier = self._pad_rows(rows, self._frontier_capacity)
        ebits = np.zeros(self._frontier_capacity, dtype=np.uint32)
        ebits[:n] = np.asarray(ck["frontier_ebits"], dtype=np.uint32)
        self._frontier_ebits = jnp.asarray(ebits)
        self._frontier_count = n

        meta = ck["meta"]
        self._depth = meta["depth"]
        self._max_depth = meta["max_depth"]
        self._state_count = meta["state_count"]
        self._unique_count = meta["unique_count"]
        self._found_names = dict(meta["found_names"])
        self._exhausted = meta["exhausted"]
        self._target_reached = meta["target_reached"]
        disc_found = np.zeros(self._P, dtype=bool)
        disc_fp = np.zeros((self._P, 2), dtype=np.uint32)
        for i, name in enumerate(self._prop_names):
            if name in self._found_names:
                fp64 = self._found_names[name]
                disc_found[i] = True
                disc_fp[i, 0] = fp64 >> 32
                disc_fp[i, 1] = fp64 & 0xFFFFFFFF
        self._disc_found = jnp.asarray(disc_found)
        self._disc_fp = jnp.asarray(disc_fp)

    # --- helpers ----------------------------------------------------------

    def _pad_rows(self, rows: np.ndarray, cap: int):
        import jax.numpy as jnp

        out = np.zeros((cap, self._W), dtype=np.uint32)
        out[: len(rows)] = rows
        return jnp.asarray(out)

    def _frontier_rows_host(self) -> np.ndarray:
        """The live frontier as host-side ``[n, W]`` rows (checkpointing,
        visitors, and the on-demand pool consume rows)."""
        return np.asarray(self._frontier)[: self._frontier_count]

    def _store_frontier_rows(self, rows: np.ndarray) -> None:
        """Replace the device frontier with these host rows; the caller
        maintains ``_frontier_count``/capacity."""
        import jax.numpy as jnp

        self._frontier = jnp.asarray(np.asarray(rows, dtype=np.uint32))

    def _dedup_words_host(self, rows: np.ndarray) -> np.ndarray:
        """Host-side dedup-key transform: representative packing when
        symmetry is on (the packed analogue of dfs.rs:357-362)."""
        if not self._symmetry:
            return rows
        if self._sym_canon_host is not None:
            # Spec path: the bit-exact numpy twin of the device kernel —
            # no object round-trip, and exact agreement with device
            # fingerprints even when the object representative() is a
            # different (partial) canonicalization.
            canon = self._sym_canon_host
            reps = [canon(np.asarray(row, dtype=np.uint32)) for row in rows]
        else:
            reps = [
                self._model.pack(self._model.unpack(row).representative())
                for row in rows
            ]
        return np.stack(reps) if reps else rows

    def _packed_fp64(self, state: Any) -> int:
        """Host fingerprint of an object state, through the packed codec —
        must agree with device fingerprints (differentially tested)."""
        words = np.asarray(self._model.pack(state), dtype=np.uint32)[None, :]
        words = self._dedup_words_host(words)
        return fphash.fingerprint_u64(words[0], np)

    # --- the fused super-step ---------------------------------------------

    def _build_superstep(self, f_cap: int, cand_cap: int):
        if self._soa:
            return self._build_superstep_planes(f_cap, cand_cap)
        return self._build_superstep_rows(f_cap, cand_cap)

    def _checking_blocks(self):
        """The checking semantics shared verbatim by the rows and planes
        supersteps: fused property evaluation (with host-verified candidate
        collection injected as ``hv_compact``) and terminal detection for
        eventually counterexamples (bfs.rs:279-325, 374-381). One
        implementation so the two layout engines cannot drift."""
        prop_specs = [(i, p.expectation) for i, p in enumerate(self._properties)]
        ebit_of_prop = dict(self._ebit_of_prop)
        hv_idx = list(self._hv_idx)
        hv_cap = self._hv_cap
        W = self._W

        def pin(viol, fhi, flo, i, disc_found, disc_fp, jnp):
            """First-witness election for property ``i`` (races in the
            reference are benign, bfs.rs:291-306; here 'first' is exact)."""
            has = jnp.any(viol)
            first = jnp.argmax(viol)
            take = has & ~disc_found[i]
            disc_fp = disc_fp.at[i, 0].set(jnp.where(take, fhi[first], disc_fp[i, 0]))
            disc_fp = disc_fp.at[i, 1].set(jnp.where(take, flo[first], disc_fp[i, 1]))
            disc_found = disc_found.at[i].set(disc_found[i] | has)
            return disc_found, disc_fp

        def eval_properties(
            props, f_valid, f_ebits, fhi, flo, disc_found, disc_fp, hv_compact, jnp
        ):
            hv_words_out = []
            hv_fp_out = []
            hv_count_out = []
            for i, expectation in prop_specs:
                if expectation == Expectation.EVENTUALLY:
                    bit = jnp.uint32(1 << ebit_of_prop[i])
                    sat = props[:, i] & f_valid
                    f_ebits = jnp.where(sat, f_ebits & ~bit, f_ebits)
                    continue
                if expectation == Expectation.ALWAYS:
                    viol = ~props[:, i] & f_valid
                else:  # SOMETIMES: an example is a "discovery" too
                    viol = props[:, i] & f_valid
                if i in hv_idx:
                    # Candidates only — the host confirms with the exact
                    # condition before anything becomes a discovery.
                    cw, cf, n_viol = hv_compact(viol)
                    hv_words_out.append(cw)
                    hv_fp_out.append(cf)
                    hv_count_out.append(n_viol)
                    continue
                disc_found, disc_fp = pin(viol, fhi, flo, i, disc_found, disc_fp, jnp)
            if hv_idx:
                hv = (
                    jnp.stack(hv_words_out),
                    jnp.stack(hv_fp_out),
                    jnp.stack(hv_count_out),
                )
            else:
                hv = (
                    jnp.zeros((0, hv_cap, W), jnp.uint32),
                    jnp.zeros((0, hv_cap, 2), jnp.uint32),
                    jnp.zeros((0,), jnp.int32),
                )
            return f_ebits, disc_found, disc_fp, hv

        def terminal_pass(terminal, f_ebits, fhi, flo, disc_found, disc_fp, jnp):
            for i, expectation in prop_specs:
                if expectation != Expectation.EVENTUALLY:
                    continue
                bit = jnp.uint32(1 << ebit_of_prop[i])
                viol = terminal & ((f_ebits & bit) != 0)
                disc_found, disc_fp = pin(viol, fhi, flo, i, disc_found, disc_fp, jnp)
            return disc_found, disc_fp

        return eval_properties, terminal_pass

    def _build_superstep_rows(self, f_cap: int, cand_cap: int):
        import jax
        import jax.numpy as jnp

        model = self._model
        symmetry = self._symmetry
        sym_canon = self._sym_canon
        A, W = self._A, self._W
        max_probes = self._max_probes
        hv_cap = self._hv_cap

        def dedup_words(words):
            return sym_canon(words) if symmetry else words

        ds = self._ds
        gather_compact = self._dedup == "sorted"

        def compact(mask, cap, arrays):
            """Stream-compact rows where ``mask`` holds into ``cap``-row
            buffers (stable: original order preserved); rows beyond ``cap``
            are truncated. Returns ``(compacted arrays, count)`` where
            ``count`` is the TOTAL mask population — count > cap means
            truncation (the caller's overflow signal).

            Two lowerings with identical results: cumsum + scatter (wins on
            XLA:CPU) and stable argsort + gather (3x cheaper on TPU, where
            XLA serializes the scatter — BASELINE.md cost model)."""
            if gather_compact:
                # cap may exceed the mask length (cand_cap = next_pow2 can
                # round up past the grid; frontier caps can exceed cand
                # caps for small action counts) — gather what exists, pad
                # the rest with zeros.
                take = min(cap, mask.shape[0])
                order = jnp.argsort(~mask, stable=True)[:take]
                smask = mask[order]
                outs = []
                for a in arrays:
                    out = jnp.where(
                        smask.reshape((take,) + (1,) * (a.ndim - 1)),
                        a[order],
                        jnp.zeros((), a.dtype),
                    )
                    if take < cap:
                        out = jnp.concatenate(
                            [out, jnp.zeros((cap - take,) + a.shape[1:], a.dtype)]
                        )
                    outs.append(out)
                return outs, jnp.sum(mask, dtype=jnp.int32)
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            idx = jnp.where(mask & (pos < cap), pos, cap)
            outs = [
                jnp.zeros((cap,) + a.shape[1:], a.dtype).at[idx].set(a, mode="drop")
                for a in arrays
            ]
            return outs, jnp.sum(mask, dtype=jnp.int32)

        eval_properties, terminal_pass = self._checking_blocks()

        def hv_compact_rows(frontier, fhi, flo):
            def hv_compact(viol):
                (cw, cf), n_viol = compact(
                    viol, hv_cap, [frontier, jnp.stack([fhi, flo], axis=1)]
                )
                return cw, cf, n_viol

            return hv_compact

        def superstep(frontier, f_ebits, f_count, table, disc_found, disc_fp):
            f_valid = jnp.arange(f_cap) < f_count
            dw = jax.vmap(dedup_words)(frontier)
            fhi, flo = fphash.fingerprint_words(dw, jnp)

            # 1. fused property evaluation over the frontier.
            props = jax.vmap(model.packed_properties)(frontier)  # [F, P]
            f_ebits, disc_found, disc_fp, (hv_words, hv_fps, hv_counts) = (
                eval_properties(
                    props, f_valid, f_ebits, fhi, flo, disc_found, disc_fp,
                    hv_compact_rows(frontier, fhi, flo), jnp,
                )
            )

            # 2. full action-grid expansion. A model may return a third
            #    per-action overflow mask: "this successor exists but does
            #    not fit my codec" — the packed analogue of the reference's
            #    capacity panics, surfaced loudly instead of silently
            #    pruning the transition (SURVEY §7 hard part 2).
            stepped = jax.vmap(model.packed_step)(frontier)  # [F,A,W], [F,A][, [F,A]]
            if len(stepped) == 3:
                nxt, valid, step_ovf = stepped
                codec_overflow = jnp.any(step_ovf & f_valid[:, None])
            else:
                nxt, valid = stepped
                codec_overflow = jnp.bool_(False)
            valid = valid & f_valid[:, None]
            step_states = jnp.sum(valid, dtype=jnp.int32)

            # 3. compact valid candidates (typically a minority of the F*A
            #    grid — disabled slots are padding) into a tight buffer, so
            #    canonicalization, fingerprinting, and the hash insert all
            #    scale with real candidates instead of grid lanes.
            cand = nxt.reshape(f_cap * A, W)
            vmask = valid.reshape(-1)
            par_hi = jnp.broadcast_to(fhi[:, None], (f_cap, A)).reshape(-1)
            par_lo = jnp.broadcast_to(flo[:, None], (f_cap, A)).reshape(-1)
            child_ebits = jnp.broadcast_to(f_ebits[:, None], (f_cap, A)).reshape(-1)
            (ccand, cpar_hi, cpar_lo, cebits), n_valid = compact(
                vmask, cand_cap, [cand, par_hi, par_lo, child_ebits]
            )
            cvalid = jnp.arange(cand_cap) < n_valid
            cand_overflow = n_valid > cand_cap
            cdw = jax.vmap(dedup_words)(ccand)
            chi, clo = fphash.fingerprint_words(cdw, jnp)

            # 4. dedup against the visited set. Compaction preserves lane
            #    order, so the insert's lowest-index winner election picks
            #    the same candidate it would have picked uncompacted. Both
            #    structures share the same contract (is_new in batch order,
            #    lowest-index winner, parent values stored).
            table, is_new, ovf = ds.insert(
                table, chi, clo, cpar_hi, cpar_lo, cvalid, max_probes=max_probes
            )
            step_unique = jnp.sum(is_new, dtype=jnp.int32)
            table_overflow = jnp.any(ovf)

            # 5. terminal detection for eventually counterexamples
            #    (bfs.rs:374-381; duplicates count as successors).
            terminal = f_valid & ~jnp.any(valid, axis=1)
            disc_found, disc_fp = terminal_pass(
                terminal, f_ebits, fhi, flo, disc_found, disc_fp, jnp
            )

            # 6. stream-compact survivors into the next frontier.
            (new_frontier, new_ebits), new_count = compact(
                is_new, f_cap, [ccand, cebits]
            )
            frontier_overflow = new_count > f_cap

            return (
                new_frontier,
                new_ebits,
                new_count,
                table,
                disc_found,
                disc_fp,
                step_states,
                step_unique,
                table_overflow,
                frontier_overflow,
                codec_overflow,
                cand_overflow,
                hv_words,
                hv_fps,
                hv_counts,
            )

        return superstep

    def _build_superstep_planes(
        self, f_cap: int, cand_cap: int, out_cap: Optional[int] = None
    ):
        """The superstep with plane-major (structure-of-arrays) bulk
        buffers: the action grid and the candidate set live as ``[W, M]``
        planes so every sort, gather, and elementwise pass over them runs
        on 128-lane-friendly 1-D arrays (see the layout note in
        ``__init__``).  The frontier itself stays ``[F, W]`` rows: it is
        the kernel-facing boundary (vmapped model kernels take ``[W]``
        rows) and two engine-measured facts pin this shape — (a) frontier
        buffers are a factor A*W smaller than the grid, so their layout is
        off the critical path, and (b) XLA:CPU (jax 0.9.0) MIScompiles a
        transpose fused INTO a vmapped kernel (a scalar-cond ``jnp.where``
        inside the kernel returns the wrong branch for batches >= 64;
        eager and jit disagree) — rows-in/transpose-out is the safe fusion
        direction, planes-in/vmap is not.

        Semantics are bit-identical to the rows superstep: the grid
        flattens a-major (``j = a*F + f``, the tiling-friendly order) and
        the candidate compaction sorts by the state-major rank ``f*A + a``,
        so the insert's lowest-index winner election, the stored parents,
        and the next frontier's order all match the rows engine (and the
        host oracle's "for each state, for each action" enumeration)
        exactly.

        ``out_cap`` (default ``f_cap``) sizes the NEXT-frontier buffers
        independently of the expanded width: a candidate-ladder branch
        expands only ``f_cap = F_k`` rows but must hand back carry-shaped
        ``[out_cap, W]`` buffers (the fused loop's full bucket), so
        survivors compact into ``out_cap`` rows and frontier overflow is
        measured against it."""
        import jax
        import jax.numpy as jnp

        if out_cap is None:
            out_cap = f_cap

        model = self._model
        symmetry = self._symmetry
        sym_canon = self._sym_canon
        A, W = self._A, self._W
        max_probes = self._max_probes
        hv_cap = self._hv_cap
        ds = self._ds

        def dedup_words(words):
            return sym_canon(words) if symmetry else words

        def step3(words):
            out = model.packed_step(words)
            if len(out) == 3:
                return out
            nxt, valid = out
            return nxt, valid, jnp.zeros_like(valid)

        compaction = self._compaction
        sort_compact = compaction == "sort"
        # Pallas-lowering knobs, resolved at build time: the kernel block
        # (grid sequential-step granularity; smaller engages the kernel
        # at smaller shapes — tests use this) and interpret mode (the
        # kernel has no CPU lowering; the interpreter is the CPU
        # reference semantics).
        # Default 512: the r5e ring-targeted kernel holds a [B, 2B] f32
        # one-hot plus a [B, B] triangular operand in VMEM — ~3 MB at
        # B=512 vs ~12 MB at B=1024, which crowds the ~16 MB/core budget
        # before the stage ring and lane blocks.
        pallas_block = int(os.environ.get("STPU_PALLAS_BLOCK", "512"))
        pallas_interp = jax.default_backend() == "cpu"

        def compact_1d(mask, cap, arrays, prio=None, rows_out=()):
            """Stream-compact lanes where ``mask`` holds into ``cap`` slots.
            ``arrays`` are 1-D lanes or [W, M] planes (compacted along M);
            indices in ``rows_out`` mark plane entries to emit as [cap, W]
            rows instead (the kernel/host-facing shape). With ``prio``
            survivors come out in ascending prio order (the semantic-order
            restoration); otherwise stable in array order.

            Three lowerings with identical results (``spawn_xla(compaction=)``,
            see ``__init__``): "gather" computes the permutation once and
            gathers every plane; "sort" carries the planes as payload
            operands of the permutation sort — no random gathers; "bsearch"
            (stable/no-prio paths only) avoids the permutation sort
            entirely — cumsum of the mask + a branchless binary search of
            each output rank over it + ascending gathers, so the whole
            compaction is scan/gather-class work. The round-5 on-chip
            profile motivates it: at rm=8 shapes the grid-compaction sort
            over 2^24 lanes is the largest per-level sort in the program."""
            m = mask.shape[0]
            # One fused int32 key: invalid lanes get a high bit above every
            # priority (prio < m <= 2^30 here).
            assert m < (1 << 30)
            if prio is None:
                key = jnp.where(mask, jnp.int32(0), jnp.int32(1))
            else:
                key = jnp.where(mask, prio, prio + jnp.int32(1 << 30))
            take = min(cap, m)
            z32 = jnp.uint32(0)
            n_valid = jnp.sum(mask, dtype=jnp.int32)

            # Flatten the inputs into 1-D lanes (planes of 2-D entries).
            lanes = []
            shapes = []  # (kind, W) per array: "1d" | "planes" | "rows"
            for pos, a in enumerate(arrays):
                if a.ndim == 1:
                    lanes.append(a)
                    shapes.append(("1d", None))
                else:
                    for w in range(a.shape[0]):
                        lanes.append(a[w])
                    shapes.append(
                        ("rows" if pos in rows_out else "planes", a.shape[0])
                    )

            pallas_ok = (
                compaction == "pallas"
                and prio is None
                and m % pallas_block == 0
                and cap % pallas_block == 0
                and m >= pallas_block
                and cap >= pallas_block
                and all(lane.dtype == jnp.uint32 for lane in lanes)
            )
            if pallas_ok:
                # Sequential-grid streaming kernel: O(n) data movement,
                # aligned chunk DMAs, no scatters (ops/pallas_compact.py).
                # Lanes pass as separate refs — no stacked copy of the
                # grid. Shapes below the kernel block fall to the sort
                # branch.
                from .ops.pallas_compact import compact_pallas_staged

                kout = compact_pallas_staged(
                    mask, lanes, cap, block=pallas_block,
                    interpret=pallas_interp,
                )
                smask = jnp.arange(take) < n_valid
                slanes = [kout[i][:take] for i in range(len(lanes))]
            elif compaction == "bsearch" and prio is None:
                # Rank i's source lane = first j with cumsum(mask)[j] == i+1:
                # one scan + log2(m) gather rounds + one ascending gather per
                # lane. No sort, no scatter.
                cs = jnp.cumsum(mask.astype(jnp.int32))
                pos_idx = jnp.searchsorted(
                    cs, jnp.arange(1, take + 1, dtype=jnp.int32), side="left"
                )
                pos_idx = jnp.minimum(pos_idx, m - 1)
                smask = jnp.arange(take) < n_valid
                slanes = [lane[pos_idx] for lane in lanes]
            elif sort_compact or compaction in ("bsearch", "pallas"):
                # ("bsearch" with a prio falls back to the sort lowering —
                # the engine's bsearch grid build emits state-major order,
                # so no prio path stays hot under it; "pallas" lands here
                # for shapes below its kernel block.)
                sorted_all = jax.lax.sort(
                    (key, *lanes), num_keys=1, is_stable=True
                )
                skey = sorted_all[0][:take]
                smask = (
                    skey == 0 if prio is None else skey < jnp.int32(1 << 30)
                )
                slanes = [s[:take] for s in sorted_all[1:]]
            else:
                iota = jnp.arange(m, dtype=jnp.int32)
                _, order = jax.lax.sort((key, iota), num_keys=1)
                order = order[:take]
                smask = mask[order]
                slanes = [lane[order] for lane in lanes]

            def pad(out, pad_shape, dtype, axis=0):
                if take < cap:
                    out = jnp.concatenate(
                        [out, jnp.zeros(pad_shape, dtype)], axis=axis
                    )
                return out

            outs = []
            k = 0
            for kind, Wn in shapes:
                if kind == "1d":
                    lane = slanes[k]
                    k += 1
                    out = pad(
                        jnp.where(smask, lane, jnp.zeros((), lane.dtype)),
                        (cap - take,),
                        lane.dtype,
                    )
                elif kind == "rows":
                    rows = [
                        jnp.where(smask, slanes[k + w], z32) for w in range(Wn)
                    ]
                    k += Wn
                    out = pad(
                        jnp.stack(rows, axis=1), (cap - take, Wn), rows[0].dtype
                    )
                else:
                    planes = [
                        jnp.where(smask, slanes[k + w], z32) for w in range(Wn)
                    ]
                    k += Wn
                    out = pad(
                        jnp.stack(planes),
                        (Wn, cap - take),
                        planes[0].dtype,
                        axis=1,
                    )
                outs.append(out)
            return outs, n_valid

        eval_properties, terminal_pass = self._checking_blocks()

        def hv_compact_planes(frontier, fhi, flo):
            def hv_compact(viol):
                (cw, cfh, cfl), n_viol = compact_1d(
                    viol, hv_cap, [frontier.T, fhi, flo], rows_out=(0,)
                )
                return cw, jnp.stack([cfh, cfl], axis=1), n_viol

            return hv_compact

        def superstep(frontier, f_ebits, f_count, table, disc_found, disc_fp):
            # frontier: [F, W] rows (kernel-facing boundary).
            f_valid = jnp.arange(f_cap) < f_count
            dw = jax.vmap(dedup_words)(frontier)
            fhi, flo = fphash.fingerprint_words(dw, jnp)

            # 1. fused property evaluation over the frontier.
            props = jax.vmap(model.packed_properties)(frontier)  # [F, P]
            f_ebits, disc_found, disc_fp, (hv_words, hv_fps, hv_counts) = (
                eval_properties(
                    props, f_valid, f_ebits, fhi, flo, disc_found, disc_fp,
                    hv_compact_planes(frontier, fhi, flo), jnp,
                )
            )

            # 2. action-grid expansion; codec overflow folded in as in
            #    rows mode. Layout per the STPU_EXPAND_LAYOUT knob (see
            #    __init__): "rows" = [F, A, W] + materialized transpose,
            #    "planes" = the vmap emits [A, W, F] with F minor.
            if self._expand_layout == "planes":
                nxt, valid, step_ovf = jax.vmap(step3, out_axes=(2, 0, 0))(frontier)
            else:
                nxt, valid, step_ovf = jax.vmap(step3)(frontier)
            codec_overflow = jnp.any(step_ovf & f_valid[:, None])
            valid = valid & f_valid[:, None]
            step_states = jnp.sum(valid, dtype=jnp.int32)

            # 3. flatten the grid into [W, A*F] planes and compact in
            #    state-major rank order. Under the sort/gather compactions
            #    the flatten is a-major (F stays on the 128-lane axis — the
            #    tiling-friendly transpose) and a prio key restores the
            #    semantic order inside the compaction sort. Under "bsearch"
            #    and "pallas" the flatten is state-major (k = f*A + a) so
            #    array order IS semantic order and the compaction needs no
            #    sort at all; the [.., F, A] intermediate's minor-axis
            #    padding is fused away into the reshape consumer.
            if compaction in ("bsearch", "pallas"):
                if self._expand_layout == "planes":
                    grid = jnp.transpose(nxt, (1, 2, 0)).reshape(W, f_cap * A)
                else:
                    grid = jnp.transpose(nxt, (2, 0, 1)).reshape(W, f_cap * A)
                vmask = valid.reshape(f_cap * A)
                par_hi = jnp.broadcast_to(fhi[:, None], (f_cap, A)).reshape(-1)
                par_lo = jnp.broadcast_to(flo[:, None], (f_cap, A)).reshape(-1)
                child_ebits = jnp.broadcast_to(
                    f_ebits[:, None], (f_cap, A)
                ).reshape(-1)
                prio = None
            else:
                if self._expand_layout == "planes":
                    # [A, W, F] -> [W, A, F] moves whole F-contiguous lanes:
                    # tiling-friendly, no (8,128)-padded intermediate.
                    grid = jnp.transpose(nxt, (1, 0, 2)).reshape(W, A * f_cap)
                else:
                    grid = jnp.transpose(nxt, (2, 1, 0)).reshape(W, A * f_cap)
                vmask = valid.T.reshape(A * f_cap)
                par_hi = jnp.broadcast_to(fhi[None, :], (A, f_cap)).reshape(-1)
                par_lo = jnp.broadcast_to(flo[None, :], (A, f_cap)).reshape(-1)
                child_ebits = jnp.broadcast_to(
                    f_ebits[None, :], (A, f_cap)
                ).reshape(-1)
                j = jnp.arange(A * f_cap, dtype=jnp.int32)
                prio = (j % f_cap) * A + (j // f_cap)  # semantic rank f*A + a
            if compaction == "sort":
                # The grid sort is the engine's largest per-level op (A*F
                # lanes; ~60% of the sorted lane-words at rm=8 shapes), and
                # the parent-fp/ebits payloads are pure functions of the
                # winning priority key (state-major rank k -> parent row
                # k // A) — so sort ONLY key + state planes and recover
                # parents/ebits by [cand_cap]-sized gathers from the
                # [F]-sized frontier arrays afterwards. Bit-identical to
                # carrying them as payload; removes 3 of the W+4 operands
                # from the dominant sort.
                m_grid = A * f_cap
                gkey = jnp.where(vmask, prio, prio + jnp.int32(1 << 30))
                take = min(cand_cap, m_grid)
                sorted_all = jax.lax.sort(
                    (gkey, *[grid[w] for w in range(W)]),
                    num_keys=1, is_stable=True,
                )
                skey = sorted_all[0][:take]
                smask = skey < jnp.int32(1 << 30)
                k_rank = (skey & jnp.int32((1 << 30) - 1)) // jnp.int32(A)
                f_row = jnp.clip(k_rank, 0, f_cap - 1)
                z32 = jnp.uint32(0)

                def pad_lane(lane):
                    lane = jnp.where(smask, lane, z32)
                    if take < cand_cap:
                        lane = jnp.concatenate(
                            [lane, jnp.zeros((cand_cap - take,), lane.dtype)]
                        )
                    return lane

                ccand = jnp.stack(
                    [pad_lane(s[:take]) for s in sorted_all[1:]]
                )
                cpar_hi = pad_lane(fhi[f_row])
                cpar_lo = pad_lane(flo[f_row])
                cebits = pad_lane(f_ebits[f_row])
                n_valid = jnp.sum(vmask, dtype=jnp.int32)
            else:
                (ccand, cpar_hi, cpar_lo, cebits), n_valid = compact_1d(
                    vmask, cand_cap, [grid, par_hi, par_lo, child_ebits],
                    prio=prio,
                )
            cvalid = jnp.arange(cand_cap) < n_valid
            cand_overflow = n_valid > cand_cap
            if symmetry:
                # The representative kernel needs [W] rows; gather candidate
                # rows once (symmetry models only — the common case keeps
                # candidates pure plane-major).
                crows = jnp.stack([ccand[w] for w in range(W)], axis=1)
                cdw = jax.vmap(dedup_words)(crows)
                chi, clo = fphash.fingerprint_words(cdw, jnp)
            else:
                chi, clo = fphash.fingerprint_planes(ccand, jnp)

            # 4. dedup (candidates are in state-major order, so the insert's
            #    default arange ticket IS the semantic winner election).
            table, is_new, ovf = ds.insert(
                table, chi, clo, cpar_hi, cpar_lo, cvalid, max_probes=max_probes
            )
            step_unique = jnp.sum(is_new, dtype=jnp.int32)
            table_overflow = jnp.any(ovf)

            # 5. terminal detection for eventually counterexamples.
            terminal = f_valid & ~jnp.any(valid, axis=1)
            disc_found, disc_fp = terminal_pass(
                terminal, f_ebits, fhi, flo, disc_found, disc_fp, jnp
            )

            # 6. survivors -> next frontier rows (stable: semantic order).
            (new_frontier, new_ebits), new_count = compact_1d(
                is_new, out_cap, [ccand, cebits], rows_out=(0,)
            )
            frontier_overflow = new_count > out_cap

            return (
                new_frontier,
                new_ebits,
                new_count,
                table,
                disc_found,
                disc_fp,
                step_states,
                step_unique,
                table_overflow,
                frontier_overflow,
                codec_overflow,
                cand_overflow,
                hv_words,
                hv_fps,
                hv_counts,
            )

        return superstep


    def _build_fused(self, f_cap: int, rungs):
        """The level loop as a device program: a ``lax.while_loop`` around
        the superstep that commits one BFS level per iteration and exits on
        (a) the level budget, (b) frontier exhaustion, (c) any overflow —
        the overflowing level is NOT committed, so the host can grow and
        re-enter, (d) every property resolved (found on device, already
        confirmed on host, or — for host-verified properties — at least one
        candidate collected for the host to confirm), or (e) a state-count
        target. Exit conditions are evaluated at level granularity, exactly
        like the one-level-per-dispatch path; only the host round-trips
        differ.

        ``rungs`` is the in-program candidate ladder (``_cand_rungs``):
        ascending ``[(F_k, C_k)]`` sub-width shapes, last = the full
        bucket. With K > 1 each iteration picks a branch ON DEVICE via
        ``lax.switch`` — every branch is a complete superstep at its own
        static shapes, returning identical carry-shaped outputs — so a
        narrow level's grid compaction sorts ``A*F_k`` lanes and its
        insert merges ``[table ‖ C_k]`` instead of the peak shapes, with
        zero added host dispatches (the shrink-exit chip lesson,
        BASELINE.md 2026-08-02). Selection per level:

        - the frontier side is EXACT: branch k needs ``F_k >= f_count``
          (known before expansion), so no state is ever left unexpanded;
        - the candidate side uses ``min(f_count*A, margin * prev_gen *
          clamped_growth)`` — the jump ladder's growth extrapolation run
          device-side. ``f_count*A`` is an exact bound, so when the full
          sub-grid fits the rung the choice is safe by construction; the
          estimate only ever picks a SNUGGER rung than the bound.
        - an UNDERESTIMATE (the chosen rung's candidate buffer
          overflows) is never host-visible and never drops candidates:
          the level is not committed, a carry flag forces the next
          iteration to the full-width branch, and the identical frontier
          re-runs — the structural fall-through. Counts stay exact by
          construction (a committed snug level is bit-identical to the
          full-width level: same candidate order, same winner election).

        TPU caveat, pinned for the chip A/B: registry #4
        (docs/backend_pathologies.md) faulted on a ``lax.cond`` carrying
        a main-capacity sort, and a ladder branch carries the [table ‖
        cand] merge sort — the TPU-target lowering pre-flights clean
        (tests/test_cand_ladder.py), but the runtime verdict needs the
        tunnel (tools/cand_ab.py, staged in the r5e watcher)."""
        import jax
        import jax.numpy as jnp

        K = len(rungs)
        if self._soa:
            steps = [
                self._build_superstep_planes(Fk, Ck, out_cap=f_cap)
                for Fk, Ck in rungs
            ]
        else:
            steps = [self._build_superstep_rows(f_cap, Ck) for _, Ck in rungs]

        def make_branch(step, Fk):
            if Fk == f_cap:
                return step

            def branch(frontier, f_ebits, f_count, table, disc_found, disc_fp):
                # Static prefix slice: selection guarantees
                # f_count <= F_k, so rows beyond the slice are pads.
                return step(
                    jax.lax.slice_in_dim(frontier, 0, Fk),
                    jax.lax.slice_in_dim(f_ebits, 0, Fk),
                    f_count,
                    table,
                    disc_found,
                    disc_fp,
                )

            return branch

        branches = [make_branch(s, Fk) for s, (Fk, _) in zip(steps, rungs)]
        A = self._A
        growth_clamp = self.LADDER_GROWTH_CLAMP
        cand_margin = self.CAND_EST_MARGIN
        W = self._W
        n_hv = len(self._hv_idx)
        hv_cap = self._hv_cap
        # Map property index -> (is_hv, hv position) for the resolution mask.
        hv_pos = {i: j for j, i in enumerate(self._hv_idx)}
        P = self._P
        # Per-level telemetry slots (frontier width / generated / unique per
        # committed level) — fused dispatch must not cost the bench its
        # per-level breakdown. Static bound: the dispatch level budget.
        L = self._levels_per_dispatch

        def fused(frontier, f_ebits, f_count, table, disc_found, disc_fp,
                  budget, remaining, host_found, shrink_below,
                  prev_gen0, prev2_gen0):
            F_rungs = jnp.asarray([r[0] for r in rungs], jnp.int32)
            C_rungs = jnp.asarray([r[1] for r in rungs], jnp.int32)

            def resolved(disc_found, hv_cnt_acc):
                if P == 0:
                    return jnp.bool_(False)
                per_prop = [
                    host_found[i]
                    | (hv_cnt_acc[hv_pos[i]] > 0 if i in hv_pos else disc_found[i])
                    for i in range(P)
                ]
                return jnp.all(jnp.stack(per_prop))

            def hv_pending(hv_cnt_acc):
                """Any *unconfirmed* host-verified property with collected
                candidates: the host must confirm before exploring further,
                and exiting here keeps the candidate buffer to one level's
                worth — the same ``hv_cap`` budget the one-level path has."""
                if not n_hv:
                    return jnp.bool_(False)
                flags = [
                    (hv_cnt_acc[j] > 0) & ~host_found[i] for i, j in hv_pos.items()
                ]
                return jnp.any(jnp.stack(flags))

            def cond(carry):
                (committed, frontier, f_ebits, f_count, table, disc_found,
                 disc_fp, tot_states, tot_unique, ovf, hv_w, hv_f, hv_c,
                 lvl_frontier, lvl_states, lvl_unique, lvl_bucket, lvl_cand,
                 prev_gen, prev2_gen, force_full, retries) = carry
                # The budget bounds COMMITTED levels (the block's semantic
                # unit): a ladder fall-through retry is a non-committing
                # iteration that must not shrink the block the host asked
                # for. Total iterations stay bounded — every non-commit
                # either sets an overflow flag (exit) or force_full, and a
                # forced full-width level commits or overflows.
                return (
                    (committed < budget)
                    & (f_count > 0)
                    # Shrink-exit: once the frontier collapses below the
                    # host-chosen threshold (derived from smaller buckets
                    # that already hold compiled programs — 0 disables),
                    # hand control back so the tail levels re-dispatch at
                    # a snug bucket instead of paying this bucket's full
                    # A*F-lane grid compaction per level. The committed==0
                    # bypass guarantees one committed level per entry: a
                    # frontier-overflow grow can land here with f_count
                    # already at or below the outgrown bucket's threshold,
                    # and exiting at level 0 would stall the checker in a
                    # grow/stall/re-enter cycle forever.
                    & ((committed == 0) | (f_count > shrink_below))
                    & ~jnp.any(ovf)
                    & ~resolved(disc_found, hv_c)
                    & ~hv_pending(hv_c)
                    & (tot_states < remaining)
                )

            def body(carry):
                (committed, frontier, f_ebits, f_count, table, disc_found,
                 disc_fp, tot_states, tot_unique, ovf, hv_w, hv_f, hv_c,
                 lvl_frontier, lvl_states, lvl_unique, lvl_bucket, lvl_cand,
                 prev_gen, prev2_gen, force_full, retries) = carry
                hv_w0, hv_f0 = hv_w, hv_f
                if K == 1:
                    k = jnp.int32(0)
                    out = branches[0](
                        frontier, f_ebits, f_count, table, disc_found, disc_fp
                    )
                else:
                    # Branch selection. ``bound`` is the exact candidate
                    # ceiling (every grid slot valid); the extrapolated
                    # estimate may pick a snugger rung, and the frontier
                    # constraint F_k >= f_count is always exact.
                    bound = f_count * jnp.int32(A)
                    growth = jnp.clip(
                        prev_gen.astype(jnp.float32)
                        / jnp.maximum(prev2_gen, 1).astype(jnp.float32),
                        1.0,
                        growth_clamp,
                    )
                    est = prev_gen.astype(jnp.float32) * growth * cand_margin
                    est_i = jnp.minimum(est, jnp.float32(2**30)).astype(
                        jnp.int32
                    )
                    need = jnp.where(
                        prev_gen > 0, jnp.minimum(bound, est_i), bound
                    )
                    k = jnp.int32(K - 1)
                    for j in range(K - 2, -1, -1):
                        ok = (f_count <= F_rungs[j]) & (need <= C_rungs[j])
                        k = jnp.where(ok, jnp.int32(j), k)
                    k = jnp.where(force_full, jnp.int32(K - 1), k)
                    out = jax.lax.switch(
                        k, branches, frontier, f_ebits, f_count, table,
                        disc_found, disc_fp,
                    )
                (nf, ne, ncount, ntable, ndfound, ndfp, d_states, d_unique,
                 t_ovf, f_ovf, c_ovf, cc_ovf, lw, lf, lc) = out
                # A snug branch's candidate overflow is the ladder's
                # fall-through, not a host event: the level is simply not
                # committed and the next iteration is forced full-width.
                # Only the full-width branch's overflow is the real
                # cc_ovf the host grows on.
                sub_ovf = cc_ovf & (k < K - 1)
                real_cc = cc_ovf & (k == K - 1)
                any_ovf = t_ovf | f_ovf | c_ovf | real_cc
                commit = ~any_ovf & ~sub_ovf
                sel = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(commit, a, b), new, old
                )
                # Telemetry for this level, recorded only when committed
                # (an uncommitted level is retried after growth): slot index
                # L drops the write.
                slot = jnp.where(commit, committed, L)
                lvl_frontier = lvl_frontier.at[slot].set(f_count, mode="drop")
                lvl_states = lvl_states.at[slot].set(d_states, mode="drop")
                lvl_unique = lvl_unique.at[slot].set(d_unique, mode="drop")
                lvl_bucket = lvl_bucket.at[slot].set(F_rungs[k], mode="drop")
                lvl_cand = lvl_cand.at[slot].set(C_rungs[k], mode="drop")
                # Append this level's host-verified candidates to the block
                # accumulator (frontier order within a level, level order
                # across the block — the confirmation order the one-level
                # path uses).
                if n_hv:
                    rows = jnp.arange(hv_cap)
                    for j in range(n_hv):
                        dst = hv_c[j] + rows
                        ok = (rows < lc[j]) & (dst < hv_cap)
                        tgt = jnp.where(ok, dst, hv_cap)
                        hv_w = hv_w.at[j].set(hv_w[j].at[tgt].set(lw[j], mode="drop"))
                        hv_f = hv_f.at[j].set(hv_f[j].at[tgt].set(lf[j], mode="drop"))
                    hv_c = sel(hv_c + lc, hv_c)
                    hv_w = sel(hv_w, hv_w0)
                    hv_f = sel(hv_f, hv_f0)
                return (
                    committed + commit.astype(jnp.int32),
                    sel(nf, frontier),
                    sel(ne, f_ebits),
                    sel(ncount, f_count),
                    sel(ntable, table),
                    sel(ndfound, disc_found),
                    sel(ndfp, disc_fp),
                    tot_states + jnp.where(commit, d_states, 0),
                    tot_unique + jnp.where(commit, d_unique, 0),
                    jnp.stack([t_ovf, f_ovf, c_ovf, real_cc]),
                    hv_w,
                    hv_f,
                    hv_c,
                    lvl_frontier,
                    lvl_states,
                    lvl_unique,
                    lvl_bucket,
                    lvl_cand,
                    jnp.where(commit, d_states, prev_gen),
                    jnp.where(commit, prev_gen, prev2_gen),
                    jnp.where(commit, jnp.bool_(False), force_full | sub_ovf),
                    # Count only fall-throughs that actually re-run
                    # in-program: a snug cc_ovf coinciding with a REAL
                    # overflow exits the loop instead (the host resolves
                    # it and the level re-runs on the next dispatch).
                    retries + (sub_ovf & ~any_ovf).astype(jnp.int32),
                )

            carry0 = (
                jnp.int32(0),
                frontier,
                f_ebits,
                f_count,
                table,
                disc_found,
                disc_fp,
                jnp.int32(0),
                jnp.int32(0),
                jnp.zeros((4,), jnp.bool_),
                jnp.zeros((n_hv, hv_cap, W), jnp.uint32),
                jnp.zeros((n_hv, hv_cap, 2), jnp.uint32),
                jnp.zeros((n_hv,), jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.zeros((L,), jnp.int32),
                prev_gen0,
                prev2_gen0,
                jnp.bool_(False),
                jnp.int32(0),
            )
            return jax.lax.while_loop(cond, body, carry0)

        return fused

    def _cand_cap_for(self, run_cap: int) -> int:
        """Candidate-buffer capacity for a run bucket: a quarter of the
        action grid (valid slots are typically a minority), power-of-four
        bucketed, grown on overflow. Cached per CHECKER (so two live
        checkers over one model can't resize each other's buffers
        mid-run), seeded from and written back to per-model hints so a
        fresh checker still inherits learned growths (see __init__)."""
        caps = self._cand_caps
        cap = caps.get(run_cap)
        if cap is None:
            caps[run_cap] = cap = self._default_cand_cap(run_cap)
        return cap

    def _default_cand_cap(self, run_cap: int) -> int:
        """The cap :meth:`_cand_cap_for` would size a so-far-unseen bucket
        at — split out non-mutating so the sibling eviction guard in
        :meth:`_grow_cand_cap` can probe another live checker's would-be
        sizing without inserting entries into its cap dict. The sizing
        policy itself (full grid small, power-of-two fraction big,
        STPU_CAND_FRAC A/B) is the shared module-level
        :func:`default_cand_cap` so the compile-plan census enumerates
        the caps the engine actually starts at."""
        return default_cand_cap(
            run_cap, self._A, self._jax.default_backend()
        )

    @staticmethod
    def _next_pow2(n: int) -> int:
        return _next_pow2(n)

    def _grow_cand_cap(self, run_cap: int) -> None:
        self._counters.inc("cand_grows")
        m = run_cap * self._A
        old = self._cand_cap_for(run_cap)
        new = min(old * 4, self._next_pow2(m))
        self._cand_caps[run_cap] = new
        hints = self._model.__dict__.setdefault("_xla_cand_cap_hints", {})
        hints[run_cap] = max(hints.get(run_cap, 0), new)
        # Evict outgrown compiled programs — THIS checker's lookups always
        # use the grown cap, and a fresh checker seeds from the (just
        # raised) hints, so the old-cap programs are dead weight holding
        # full XLA executables — UNLESS a live, still-RUNNING sibling
        # checker sizes this bucket at the old cap (caps are per-checker,
        # the cache is model-shared): evicting under it would force it to
        # re-pay a compile for a program that is still current for it. A
        # finished sibling never dispatches again, so a lingering
        # reference to one doesn't pin its outgrown executables.
        # A fused program is stale only when its rung tuple actually
        # CHANGES under the grown caps: an outgrown sub-rung whose cap
        # was already clamped by the monotone envelope recomputes
        # identically, and evicting it would force a byte-identical
        # recompile (~11 s/bucket on this box, ~1 min on the tunnel).
        pinning = [
            (s._sym_tag, s._max_probes, s._dedup, s._compaction)
            for s in self._siblings()
            if not s.is_done()
            and s._cand_caps.get(run_cap, s._default_cand_cap(run_cap)) == old
        ]
        for key in [
            k
            for k in self._superstep_cache
            if (
                (k[0] == run_cap and k[1] == old)
                or (
                    k[0] == "fused"
                    and any(F == run_cap and c == old for F, c in k[2])
                    and tuple(self._cand_rungs(k[1])) != k[2]
                )
            )
            # Per-key pinning: a sibling protects only keys its own
            # engine config can look up (dedup/compaction are part of
            # the key — a hash sibling can never reach a sorted key).
            and (k[3:] if k[0] == "fused" else k[2:]) not in pinning
        ]:
            del self._superstep_cache[key]

    def _siblings(self) -> List["XlaChecker"]:
        """Other live checkers over this model (weakrefs registered in
        ``__init__``; dead refs are pruned on the way out)."""
        live = self._model.__dict__.get("_xla_live_checkers", [])
        live[:] = [r for r in live if r() is not None]
        return [c for r in live if (c := r()) is not None and c is not self]

    #: In-program candidate-ladder rung floor (the shared planner's
    #: constant, re-exported on the class for the A/B harnesses that
    #: already read it here).
    CAND_RUNG_FLOOR = CAND_RUNG_FLOOR
    #: Headroom multiplier on the device-side candidate estimate. An
    #: underestimate costs one wasted snug superstep (the in-program
    #: fall-through re-runs the level full-width), so the estimate is
    #: doubled before picking a rung; the exact ``f_count * A`` bound
    #: still wins whenever the whole sub-grid fits a rung.
    CAND_EST_MARGIN = 2.0

    def _cand_rungs(self, f_cap: int) -> List[Tuple[int, int]]:
        """The in-program candidate ladder for a fused dispatch at bucket
        ``f_cap``: ascending ``[(F_k, C_k)]`` sub-width shapes, last = the
        full bucket. Each rung is exactly the (rows, candidate-cap) shape
        the host ladder would run at bucket ``F_k``, specialised into the
        peak program — so a branch's committed level is bit-identical to
        what a host re-dispatch at that bucket would have produced,
        without the re-dispatch."""
        k = self._cand_ladder_k if self._soa else 1
        return cand_rungs(f_cap, self._cand_cap_for, k)

    def _level_lane_words(self, bucket: int, cand_w: int) -> int:
        """32-bit words carried through ``lax.sort`` operands by ONE
        committed level at these dispatch shapes — the x-axis of the
        round-5 cost law (per-level time ~ sorted lane-words x log^2 n,
        BASELINE.md). Computed from the actual static sort shapes the
        compiled program runs (grid compaction + visited-set insert +
        frontier compaction at engine scale; the hv_cap- and
        symmetry-only side sorts are bounded and not counted), so the
        candidate-ladder A/B is engine-measured, not hand-derived. The
        rows/hash engine sorts nothing (cumsum + scatter compaction)."""
        if not self._soa:
            return 0
        W = self._W
        grid = bucket * self._A
        total = 0
        if self._compaction == "sort":
            # Grid: key + W state planes; frontier: key + W rows + ebits.
            total += grid * (1 + W) + cand_w * (2 + W)
        elif self._compaction == "gather":
            # Permutation sorts only (key + iota); payloads move by gather.
            total += grid * 2 + cand_w * 2
        # bsearch/pallas compactions are scan/kernel lowerings: no sorted
        # lanes at engine scale (their sub-block sort fallbacks are not
        # modeled — both modes are opt-in A/Bs).
        total += self._ds.insert_lane_words(self._table, cand_w)
        return total

    def _mark_dispatch_shape(self, program_key) -> bool:
        """Whether THIS dispatch will trace + compile: true the first
        time a given (program key, table capacity) pair is dispatched in
        this process. A bare program-cache-miss check is not enough —
        the jit cache keys on input avals, and the table capacity is the
        one dispatch input whose SHAPE changes under a fixed program key
        (an overflow-growth retry re-enters the same cached wrapper
        with a doubled table, recompiling for minutes over the tunnel)
        — and both consumers of the flag need it right: the heartbeat
        watchdog's compile leash and roofline --measured's
        compile-vs-steady stage split. Keyed on the program cache key's
        CONTENT (not ``id(fn)`` — eviction by _grow_cand_cap can recycle
        an address and mislabel a real compile) and tracked
        model-shared, like the program cache itself."""
        seen = self._model.__dict__.setdefault("_xla_dispatched_shapes", set())
        key = (program_key, self._table.capacity)
        fresh = key not in seen
        seen.add(key)
        return fresh

    def _superstep_key(self, f_cap: int):
        return (
            f_cap, self._cand_cap_for(f_cap), self._sym_tag,
            self._max_probes, self._dedup, self._compaction,
        )

    def _superstep_for(self, f_cap: int):
        import jax

        key = self._superstep_key(f_cap)
        fn = self._superstep_cache.get(key)
        if fn is None:
            fn = jax.jit(self._build_superstep(f_cap, key[1]))
            self._superstep_cache[key] = fn
        return fn

    def _fused_key(self, f_cap: int):
        return (
            "fused", f_cap, tuple(self._cand_rungs(f_cap)), self._sym_tag,
            self._max_probes, self._dedup, self._compaction,
        )

    def _fused_for(self, f_cap: int):
        import jax

        key = self._fused_key(f_cap)
        fn = self._superstep_cache.get(key)
        if fn is None:
            fn = jax.jit(self._build_fused(f_cap, key[2]))
            self._superstep_cache[key] = fn
        return fn

    #: Proactive-growth trigger for the HASH structure: keep the
    #: open-addressing table at or below this load factor. Probe-chain
    #: length (the dominant insert cost — see BASELINE.md's cost model)
    #: grows superlinearly with load; growing at 1/4 load bounds probe
    #: rounds at a 4x memory cost over the uniques.
    MAX_LOAD_NUM, MAX_LOAD_DEN = 1, 4
    #: For the SORTED structure the trade inverts: per-level cost is the
    #: sort of [capacity + candidates], so headroom costs sort bandwidth,
    #: not probe rounds — run it denser and grow late.
    SORTED_LOAD_NUM, SORTED_LOAD_DEN = 3, 4

    def _grow_table_if_loaded(self) -> None:
        """Double the table whenever the committed unique count crosses the
        structure's load ceiling — BEFORE inserts start paying (hash: long
        probe chains; sorted: an overflow-retry round trip). For the delta
        structure, additionally flush the delta tier proactively at 3/4
        occupancy — a flush at a dispatch boundary costs nothing extra,
        while one discovered mid-level costs the overflow-retry of that
        level."""
        num, den = (
            (self.MAX_LOAD_NUM, self.MAX_LOAD_DEN)
            if self._dedup == "hash"
            else (self.SORTED_LOAD_NUM, self.SORTED_LOAD_DEN)
        )
        while self._unique_count * den > self._table.capacity * num:
            self._grow_table()
        if self._dedup == "delta":
            ds = self._table
            if int(ds.n_delta) * 4 > ds.delta_capacity * 3:
                with self._tracer.span("delta_flush", proactive=True):
                    flushed, ovf = deltaset.maintain_jit(ds)
                    ovf = bool(ovf)
                self._counters.inc("delta_flushes")
                if ovf:  # pragma: no cover - load rule fires first
                    self._grow_table()
                else:
                    self._table = flushed

    def _resolve_table_overflow(self) -> None:
        """A table overflow from the structure: for the delta set a
        non-empty delta tier means FLUSH (``deltaset.maintain``) — the
        amortized big merge, host-invoked so no ``lax.cond`` ever carries
        a main-capacity sort (that conditional shape faults the XLA:TPU
        runtime; see deltaset.insert) — and only an empty-delta overflow
        or a flush that cannot fit main grows capacity."""
        if self._dedup == "delta" and int(self._table.n_delta) > 0:
            with self._tracer.span("delta_flush", proactive=False):
                flushed, ovf = deltaset.maintain_jit(self._table)
                ovf = bool(ovf)
            self._counters.inc("delta_flushes")
            if not ovf:
                self._table = flushed
                return
        self._grow_table()

    def _grow_table(self) -> None:
        """Double the visited-set capacity: a rehash for the hash table, a
        plain plane copy for the sorted set (its invariant is
        capacity-independent)."""
        import jax
        import jax.numpy as jnp

        old = self._table
        with self._tracer.span(
            "grow_table", dedup=self._dedup, capacity=old.capacity * 2
        ):
            if self._dedup == "delta":
                # Growth folds the delta into a doubled main tier
                # (host-side rebuild; rare by the load rule).
                self._table = deltaset.grow(old, old.main_capacity * 2, jnp)
            elif self._dedup == "sorted":
                self._table = sortedset.grow(old, old.capacity * 2, jnp)
            else:
                occupied = (old.key_hi != 0) | (old.key_lo != 0)
                bigger = hashset.make(old.capacity * 2, jnp)
                bigger, _, ovf = jax.jit(
                    hashset.insert, static_argnames="max_probes"
                )(
                    bigger,
                    old.key_hi,
                    old.key_lo,
                    old.val_hi,
                    old.val_lo,
                    occupied,
                    max_probes=self._max_probes,
                )
                if bool(np.any(np.asarray(ovf))):  # pragma: no cover
                    raise RuntimeError(
                        "rehash overflow — pathological fingerprint "
                        "distribution"
                    )
                self._table = bigger
        self._counters.inc("table_grows")
        self._model.__dict__[self._table_hint_key] = self._table.capacity

    def _raise_codec_overflow(self) -> None:
        raise RuntimeError(
            f"{type(self._model).__name__}: packed-codec capacity "
            "overflow — a reachable successor does not fit the "
            "model's declared field widths/slot counts. Raise the "
            "model's capacity bounds (this is the loud failure the "
            "packed toolkit guarantees; see stateright_tpu.packing)."
        )

    #: Reuse-first bound for the "jump" ladder: an already-compiled bucket
    #: up to this factor over the snug one is preferred to a fresh XLA
    #: compile. Bounded so a deep-narrow tail (width ~20 for thousands of
    #: levels) can never get pinned to a huge bucket — the round-4
    #: floor-64 pathology in new clothes.
    LADDER_REUSE_BOUND = 64
    #: Growth-factor clamp for the jump extrapolation: the first levels of
    #: a fanning space show the raw out-degree (17x for 2pc rm=8), which
    #: would extrapolate straight past every useful rung.
    LADDER_GROWTH_CLAMP = 16.0

    def _compiled_run_caps(self) -> set:
        """Run buckets holding a live compiled program for the dispatch
        flavor and engine config this checker would actually invoke."""
        fused = self._levels_per_dispatch > 1
        tail_want = (self._sym_tag, self._max_probes, self._dedup, self._compaction)
        caps = set()
        for k in self._superstep_cache:
            if fused != (k[0] == "fused"):
                continue
            if fused:
                f_cap, tail = k[1], k[3:]
                current = k[2] == tuple(self._cand_rungs(f_cap))
            else:
                f_cap, tail = k[0], k[2:]
                current = k[1] == self._cand_cap_for(f_cap)
            if tail == tail_want and current:
                caps.add(f_cap)
        return caps

    def _recent_growth(self) -> Optional[float]:
        """Frontier growth factor across the last two committed levels, or
        None when there is no (positive-growth) signal yet."""
        if len(self.level_log) < 2:
            return None
        a = self.level_log[-2]["frontier"]
        b = self.level_log[-1]["frontier"]
        if a <= 0 or b <= a:
            return None
        return b / a

    def _grow_frontier(self, run_cap: int) -> int:
        """Next bucket after a frontier-compaction overflow: one
        power-of-four rung ("ramp"), or a growth-extrapolated jump over
        several rungs ("jump"), or — past the top bucket — a doubled
        frontier-capacity ceiling. Returns the new run capacity.

        The jump estimate: the overflowed width is at least ``run_cap``;
        with the frontier growing by observed factor ``g`` per level and
        growth factors decaying as the peak nears, ``run_cap * g^2`` is a
        usable peak forecast — undershoot costs one more overflow round
        (exactly what ramp would have paid anyway), overshoot costs
        bounded padding. Measured on 2pc rm=8 widths this lands 3 compiled
        buckets instead of 8."""
        self._counters.inc("frontier_grows")
        if run_cap < self._frontier_capacity:
            buckets = ladder_buckets(self._frontier_capacity)
            ramp = next(b for b in buckets if b > run_cap)
            nxt = ramp
            if self._ladder == "jump":
                g = self._recent_growth()
                if g is not None and g >= 2.0:
                    est_peak = run_cap * min(g, self.LADDER_GROWTH_CLAMP) ** 2
                    jump = next(
                        (b for b in buckets if b >= 4 * est_peak), buckets[-1]
                    )
                    nxt = max(nxt, jump)
            if nxt > ramp:
                self._counters.inc("ladder_jumps")
            return nxt
        self._frontier_capacity *= 2
        self._model.__dict__["_xla_frontier_cap_hint"] = self._frontier_capacity
        return self._frontier_capacity

    def _run_cap_for(self, n: int) -> int:
        """Smallest power-of-FOUR run capacity with ~4x expansion headroom
        over the live frontier, clamped to [64, frontier_capacity].
        Powers of four keep the compiled-bucket count low (each distinct
        run capacity is a separate XLA compilation).

        The 64-row floor matters for the deep-narrow spaces the
        consistency testers produce (round-3 on-chip finding: ABD 2c/2s
        never widens past 54 rows, so a 1024-row floor paid a ~1000x
        action-grid padding tax per level — measured 66x end-to-end on
        CPU). Wide spaces ramp through at most two extra small buckets
        (64, 256), each a far cheaper XLA compile than the big ones and
        persistent-cache-amortized across runs.

        Under the "jump" ladder, an already-compiled bucket within
        ``LADDER_REUSE_BOUND`` of the snug one is preferred: re-entering
        mid-space (bench measured pass, target-bounded runs) must ride
        the warm pass's compilations, not pay fresh ones."""
        want = max(4 * max(n, 1), RUN_BUCKET_FLOOR)
        buckets = ladder_buckets(self._frontier_capacity)
        cap = next((b for b in buckets if b >= want), buckets[-1])
        if self._ladder == "jump":
            reusable = [
                c
                for c in self._compiled_run_caps()
                if cap <= c <= cap * self.LADDER_REUSE_BOUND
            ]
            if reusable:
                return min(reusable)
        return cap

    def _bucket_inputs(self, run_cap: int):
        """Pad or slice the stored frontier to this dispatch's bucket."""
        import jax
        import jax.numpy as jnp

        stored = self._frontier.shape[0]
        if stored < run_cap:
            f_in = jnp.concatenate(
                [self._frontier, jnp.zeros((run_cap - stored, self._W), jnp.uint32)]
            )
            e_in = jnp.concatenate(
                [self._frontier_ebits, jnp.zeros((run_cap - stored,), jnp.uint32)]
            )
        elif stored > run_cap:
            f_in = jax.lax.slice_in_dim(self._frontier, 0, run_cap)
            e_in = jax.lax.slice_in_dim(self._frontier_ebits, 0, run_cap)
        else:
            f_in, e_in = self._frontier, self._frontier_ebits
        return f_in, e_in

    def _pin_found_names(self) -> None:
        """Records first-found witness fingerprints by property name."""
        found = np.asarray(self._disc_found)
        fps = np.asarray(self._disc_fp)
        for i, name in enumerate(self._prop_names):
            if found[i] and name not in self._found_names:
                self._found_names[name] = (int(fps[i, 0]) << 32) | int(fps[i, 1])

    def _run_block(self, max_count: int = 1500) -> None:
        """One dispatch per call: one BFS level (``levels_per_dispatch=1``)
        or an on-device block of up to that many levels."""
        if self._levels_per_dispatch > 1:
            return self._run_block_fused()
        return self._run_block_single()

    def _entry_checks(self) -> bool:
        """Shared dispatch preamble; returns False when nothing to run.
        Mirrors the dequeue-time depth bookkeeping (bfs.rs:257-272): a
        frontier at the target depth is counted in max_depth but skipped."""
        if self._target_reached or self._exhausted:
            return False
        if self._P > 0 and all(n in self._found_names for n in self._prop_names):
            return False
        if self._frontier_count == 0:
            self._exhausted = True
            return False
        self._max_depth = max(self._max_depth, self._depth)
        if self._target_max_depth is not None and self._depth >= self._target_max_depth:
            self._frontier_count = 0
            self._exhausted = True
            return False
        return True

    def _run_block_fused(self) -> None:
        """Up to ``levels_per_dispatch`` BFS levels in one device call (see
        ``_build_fused``). Overflow exits commit every level before the
        overflowing one, grow, and re-enter with the remaining budget."""
        import jax.numpy as jnp

        if not self._entry_checks():
            return

        budget_left = self._levels_per_dispatch
        if self._target_max_depth is not None:
            budget_left = min(budget_left, self._target_max_depth - self._depth)
        run_cap = self._run_cap_for(self._frontier_count)
        retry = False  # re-entering after an overflow recovery
        while budget_left > 0:
            # Keep the block's int32 generated-state accumulator safe.
            kmax = max(1, (2**31 - 1) // max(run_cap * self._A, 1))
            budget = min(budget_left, kmax)
            remaining = 2**31 - 1
            if self._target_state_count is not None:
                remaining = max(
                    1, min(remaining, self._target_state_count - self._state_count)
                )
            host_found = np.array(
                [name in self._found_names for name in self._prop_names], dtype=bool
            )
            f_in, e_in = self._bucket_inputs(run_cap)
            fn = self._fused_for(run_cap)
            # Will THIS call trace + compile? The dispatch span and the
            # heartbeat phase carry the flag so watchdogs can tell a
            # long compile from a wedge.
            fresh = self._mark_dispatch_shape(self._fused_key(run_cap))
            # Shrink-exit threshold: the tail of a space collapses while
            # the fused loop is pinned to the peak bucket, paying the full
            # grid-compaction sort per level. If a smaller bucket already
            # holds a live compiled program, ask the device to exit once
            # the frontier fits it with 4x headroom — the re-dispatch then
            # reuses that program, so this can never trigger a compile.
            # Tiny buckets aren't worth the extra host round-trip.
            shrink_below = 0
            if self._shrink_exit and run_cap > 256:
                smaller = [c for c in self._compiled_run_caps() if c < run_cap]
                if smaller:
                    shrink_below = max(smaller) // 4
            # Seed the device-side candidate estimate with the last two
            # committed levels' generated counts (the host's level_log is
            # the cross-dispatch memory; runtime scalars, zero compiles).
            prev_gen = self.level_log[-1]["generated"] if self.level_log else 0
            prev2_gen = (
                self.level_log[-2]["generated"] if len(self.level_log) > 1 else 0
            )
            if self._heartbeat is not None:
                self._heartbeat.beat(
                    "dispatch", compile=fresh, bucket=run_cap,
                    depth=self._depth, states=self._state_count,
                )
            with self._tracer.span(
                "dispatch", flavor="fused", bucket=run_cap,
                # Attr expressions are evaluated even when the null span
                # discards them — keep the off path allocation-free by
                # gating the rung-list build on the tracer being live.
                cand=(
                    [list(r) for r in self._cand_rungs(run_cap)]
                    if self._tracer.enabled
                    else None
                ),
                compile=fresh, retry=retry, dedup=self._dedup,
                compaction=self._compaction, shrink_below=shrink_below,
            ) as _sp:
                _pt0 = time.monotonic() if self._phases else 0.0
                _args = (
                    f_in,
                    e_in,
                    self._frontier_count,
                    self._table,
                    self._disc_found,
                    self._disc_fp,
                    jnp.int32(budget),
                    jnp.int32(remaining),
                    jnp.asarray(host_found),
                    jnp.int32(shrink_below),
                    jnp.int32(min(prev_gen, 2**31 - 1)),
                    jnp.int32(min(prev2_gen, 2**31 - 1)),
                )
                _pt1 = time.monotonic() if self._phases else 0.0
                (
                    committed,
                    nf,
                    ne,
                    ncount,
                    table,
                    dfound,
                    dfp,
                    tot_states,
                    tot_unique,
                    ovf,
                    hv_w,
                    hv_f,
                    hv_c,
                    lvl_frontier,
                    lvl_states,
                    lvl_unique,
                    lvl_bucket,
                    lvl_cand,
                    _prev_gen,
                    _prev2_gen,
                    _force_full,
                    n_retries,
                ) = fn(*_args)
                if self._phases:
                    # fn() returned at enqueue; one output leaf becoming
                    # ready means the one fused program finished — a wait
                    # on work already in flight, not an added sync.
                    _pt2 = time.monotonic()
                    self._jax.block_until_ready(committed)
                    _pt3 = time.monotonic()
                # Commit the non-overflowing prefix of the block. The
                # int() blocks until the device program finishes, so the
                # span covers the whole round-trip — and reuses a sync
                # the commit below needs anyway.
                committed = int(committed)
                _sp.set(committed=committed)
                _pt4 = time.monotonic() if self._phases else 0.0
            self.dispatch_log.append((run_cap, committed))
            if self._phases:
                self._log_phases(
                    _sp, flavor="fused", bucket=run_cap, fresh=fresh,
                    committed=committed,
                    stamps=(_pt0, _pt1, _pt2, _pt3, _pt4),
                )
            if self._heartbeat is not None:
                self._heartbeat.commit(
                    depth=self._depth + committed,
                    states=self._state_count + int(tot_states),
                )
            retry = False
            self._frontier, self._frontier_ebits, self._table = nf, ne, table
            self._frontier_count = int(ncount)
            self._disc_found, self._disc_fp = dfound, dfp
            self._state_count += int(tot_states)
            self._unique_count += int(tot_unique)
            self.cand_retries += int(n_retries)
            if committed:
                lvf = np.asarray(lvl_frontier)
                lvs = np.asarray(lvl_states)
                lvu = np.asarray(lvl_unique)
                lvb = np.asarray(lvl_bucket)
                lvc = np.asarray(lvl_cand)
                self.level_log.extend(
                    {
                        "depth": self._depth + i,
                        "frontier": int(lvf[i]),
                        "generated": int(lvs[i]),
                        "unique": int(lvu[i]),
                        "sym": self._sym_tag,
                        # Dispatch-shape telemetry: the (rows, cand)
                        # sub-widths this level actually ran at and the
                        # cost-law lane-words they imply (the ladder A/B's
                        # engine-measured evidence).
                        "bucket": int(lvb[i]),
                        "cand_cap": int(lvc[i]),
                        "lane_words": self._level_lane_words(
                            int(lvb[i]), int(lvc[i])
                        ),
                    }
                    for i in range(committed)
                )
            self._depth += committed
            if committed:
                self._max_depth = max(self._max_depth, self._depth - 1)
            budget_left -= committed
            cap_before = self._table.capacity
            self._grow_table_if_loaded()
            grew_proactively = self._table.capacity > cap_before
            if self._hv_idx:
                self._confirm_hv_candidates(hv_w, hv_f, hv_c)
            self._pin_found_names()
            # Quiescent point: the committed prefix is fully reflected in
            # host-visible state (even when this iteration ended on an
            # overflow — the overflowing level was not committed).
            self._maybe_checkpoint()
            self._maybe_record()
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                self._target_reached = True
                return
            t_ovf, f_ovf, c_ovf, cc_ovf = (bool(x) for x in np.asarray(ovf))
            if c_ovf:
                self._raise_codec_overflow()
            if t_ovf:
                # The proactive pass above may already have doubled past
                # the blockage; only resolve again if it did not (every
                # extra doubling is 2x memory AND a fresh shape compile).
                if not grew_proactively:
                    self._resolve_table_overflow()
                retry = True
                continue
            if f_ovf:
                run_cap = self._grow_frontier(run_cap)
                retry = True
                continue
            if cc_ovf:
                self._grow_cand_cap(run_cap)
                retry = True
                continue
            if self._frontier_count == 0 or committed == 0:
                break
            if self._P > 0 and all(
                name in self._found_names for name in self._prop_names
            ):
                break
            # A shrink-exit (committed block, no overflow, live frontier
            # at or below the threshold): drop to the snuggest compiled
            # bucket that still has 4x expansion headroom.
            if shrink_below and self._frontier_count <= shrink_below:
                snug = [
                    c
                    for c in self._compiled_run_caps()
                    if c < run_cap and self._frontier_count <= c // 4
                ]
                if snug:
                    run_cap = min(snug)
                    self._counters.inc("shrink_exits")

    def _run_block_single(self) -> None:
        """One BFS level per call (level-synchronous super-step)."""
        import jax
        import jax.numpy as jnp

        if not self._entry_checks():
            return

        if self._visitor is not None:
            self._visit_frontier()

        # Adaptive run capacity: BFS levels ramp up and down, but a fixed
        # [frontier_capacity, A] expansion pays full freight on padding
        # lanes every level. Run each level at the smallest compiled bucket
        # with ~4x headroom over the live frontier; a frontier overflow
        # retries at the next bucket (safe — the pre-step table is a
        # functional value, untouched until we commit). The stored frontier
        # keeps whatever row count the last level ran at (always >=
        # frontier_count — every consumer slices [:frontier_count]); it is
        # padded or sliced lazily to this level's bucket, so per-level cost
        # is O(run_cap), not O(frontier_capacity).
        run_cap = self._run_cap_for(self._frontier_count)
        retry = False  # re-running the level after an overflow recovery
        while True:  # retried only on capacity growth
            f_in, e_in = self._bucket_inputs(run_cap)
            fn = self._superstep_for(run_cap)
            fresh = self._mark_dispatch_shape(self._superstep_key(run_cap))
            if self._heartbeat is not None:
                self._heartbeat.beat(
                    "dispatch", compile=fresh, bucket=run_cap,
                    depth=self._depth, states=self._state_count,
                )
            with self._tracer.span(
                "dispatch", flavor="single", bucket=run_cap,
                cand=self._cand_cap_for(run_cap), compile=fresh,
                retry=retry, dedup=self._dedup,
                compaction=self._compaction,
            ) as _sp:
                _pt0 = time.monotonic() if self._phases else 0.0
                _args = (
                    f_in,
                    e_in,
                    self._frontier_count,
                    self._table,
                    self._disc_found,
                    self._disc_fp,
                )
                _pt1 = time.monotonic() if self._phases else 0.0
                out = fn(*_args)
                if self._phases:
                    _pt2 = time.monotonic()
                    self._jax.block_until_ready(out)
                    _pt3 = time.monotonic()
                (
                    nf,
                    ne,
                    ncount,
                    table,
                    dfound,
                    dfp,
                    d_states,
                    d_unique,
                    t_ovf,
                    f_ovf,
                    c_ovf,
                    cc_ovf,
                    hv_words,
                    hv_fps,
                    hv_counts,
                ) = out
                # The bool() reads block until the device program
                # finishes — the span covers the full round-trip using
                # syncs the commit logic pays anyway.
                committed = not (bool(t_ovf) or bool(f_ovf) or bool(cc_ovf))
                _sp.set(committed=int(committed))
                _pt4 = time.monotonic() if self._phases else 0.0
            self.dispatch_log.append((run_cap, int(committed)))
            if self._phases:
                self._log_phases(
                    _sp, flavor="single", bucket=run_cap, fresh=fresh,
                    committed=int(committed),
                    stamps=(_pt0, _pt1, _pt2, _pt3, _pt4),
                )
            if self._heartbeat is not None:
                self._heartbeat.commit(
                    depth=self._depth, states=self._state_count
                )
            if bool(c_ovf):
                self._raise_codec_overflow()
            if bool(t_ovf):
                # Functional arrays: the pre-step table is untouched;
                # flush (delta) or grow, then re-run the same level.
                self._resolve_table_overflow()
                retry = True
                continue
            if bool(f_ovf):
                run_cap = self._grow_frontier(run_cap)
                retry = True
                continue
            if bool(cc_ovf):
                self._grow_cand_cap(run_cap)
                retry = True
                continue
            break

        self.level_log.append(
            {
                "depth": self._depth,
                "frontier": self._frontier_count,
                "generated": int(d_states),
                "unique": int(d_unique),
                "sym": self._sym_tag,
                # The one-level path picks its snug bucket host-side, so
                # its dispatch-shape telemetry is the run bucket itself
                # (the in-program ladder applies to fused dispatch only).
                "bucket": run_cap,
                "cand_cap": self._cand_cap_for(run_cap),
                "lane_words": self._level_lane_words(
                    run_cap, self._cand_cap_for(run_cap)
                ),
            }
        )
        self._frontier, self._frontier_ebits, self._table = nf, ne, table
        self._frontier_count = int(ncount)
        self._disc_found, self._disc_fp = dfound, dfp
        self._state_count += int(d_states)
        self._unique_count += int(d_unique)
        self._depth += 1
        self._grow_table_if_loaded()
        if self._hv_idx:
            self._confirm_hv_candidates(hv_words, hv_fps, hv_counts)
        self._pin_found_names()
        self._maybe_checkpoint()
        self._maybe_record()
        if (
            self._target_state_count is not None
            and self._state_count >= self._target_state_count
        ):
            self._target_reached = True

    #: Phase names in stamp order — the profiler's contiguous split of
    #: one dispatch round-trip (docs/observability.md).
    PHASE_NAMES = ("host_prep", "enqueue", "device_compute", "readback")

    def _log_phases(
        self, sp, *, flavor: str, bucket: int, fresh: bool,
        committed: int, stamps: Tuple[float, ...],
    ) -> None:
        """Record one device call's phase split: a ``phase_log`` row
        (dispatch_log-adjacent telemetry) plus four ``phase:*`` sub-spans
        parented to the just-closed dispatch span. Called only with the
        profiler on; the stamps are contiguous, so the phases partition
        the dispatch span's interior exactly."""
        row: Dict[str, Any] = {
            "bucket": bucket, "flavor": flavor, "compile": fresh,
            "committed": committed,
        }
        for i, name in enumerate(self.PHASE_NAMES):
            dur = stamps[i + 1] - stamps[i]
            row[name] = dur
            self._tracer.emit(
                f"phase:{name}", t0=stamps[i], dur=dur,
                attrs={"bucket": bucket}, parent_id=sp.span_id,
            )
        self.phase_log.append(row)

    def _confirm_hv_candidates(self, hv_words, hv_fps, hv_counts) -> None:
        """Exact host-side re-check of device-flagged candidate states for
        host-verified properties (SURVEY §7 M4a): the first candidate (in
        frontier order) whose exact condition confirms the violation/example
        becomes the discovery. Conditions like the linearizability testers
        memoize per distinct history, so repeat candidates are cheap."""
        counts = np.asarray(hv_counts)
        words = fps = None
        _sp = self._tracer.span("host_verify")
        _checked0 = self.hv_stats["host_checked"]
        _conf0 = self.hv_stats["confirmed"]
        t0 = time.monotonic()
        with _sp:
            try:
                for j, i in enumerate(self._hv_idx):
                    prop = self._properties[i]
                    if prop.name in self._found_names:
                        continue
                    n = int(counts[j])
                    if n == 0:
                        continue
                    self.hv_stats["flagged"] += n
                    if words is None:
                        words = np.asarray(hv_words)
                        fps = np.asarray(hv_fps)
                    confirmed = False
                    for r in range(min(n, self._hv_cap)):
                        state = self._model.unpack(words[j, r])
                        holds = bool(prop.condition(self._model, state))
                        viol = (
                            (not holds)
                            if prop.expectation == Expectation.ALWAYS
                            else holds
                        )
                        self.hv_stats["host_checked"] += 1
                        if viol:
                            fp64 = (int(fps[j, r, 0]) << 32) | int(fps[j, r, 1])
                            self._found_names[prop.name] = fp64
                            confirmed = True
                            self.hv_stats["confirmed"] += 1
                            break
                        self.hv_stats["cleared"] += 1
                    if not confirmed and n > self._hv_cap:
                        raise RuntimeError(
                            f"{n} candidate states for host-verified property "
                            f"{prop.name!r} in one super-step, none of the "
                            f"first {self._hv_cap} confirmed — tighten the "
                            "conservative device predicate or raise the "
                            "candidate cap."
                        )
            finally:
                # Inner finally: attrs land before the span's __exit__
                # emits the line, including on the over-cap raise path.
                _sp.set(
                    checked=self.hv_stats["host_checked"] - _checked0,
                    confirmed=self.hv_stats["confirmed"] - _conf0,
                )
                self.hv_stats["host_sec"] += time.monotonic() - t0

    def _visit_frontier(self) -> None:
        """Applies the visitor to every frontier state's path (the XLA
        analogue of bfs.rs:274-276). Host-side path reconstruction re-executes
        the object model per state and would appear to hang on big frontiers,
        so levels wider than ``spawn_xla(visit_cap=...)`` are truncated with
        a loud warning — visitors are a debug/recording surface, not part of
        checking semantics."""
        n = self._frontier_count
        if n > self._visit_cap:
            import warnings

            warnings.warn(
                f"visitor: frontier has {n} states at depth {self._depth}; "
                f"visiting only the first {self._visit_cap} (host-side path "
                "reconstruction per state does not scale — use visitors on "
                "small runs, or raise spawn_xla(visit_cap=...))",
                RuntimeWarning,
                stacklevel=2,
            )
        rows = self._frontier_rows_host()[: min(n, self._visit_cap)]
        parents = self._parent_map()
        for row in rows:
            fp = fphash.fingerprint_u64(self._dedup_words_host(row[None, :])[0], np)
            self._visitor.visit(self._model, self._path_for(fp, parents))

    # --- Checker API -------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def metrics(self) -> Dict[str, Any]:
        """One unified snapshot of the engine's telemetry (the registry
        half of stateright_tpu/obs): configuration gauges, live search
        gauges, and the event counters that used to live scattered across
        ``cand_retries`` / ``dispatch_log`` / ad-hoc logs. Pure host-side
        reads — safe to poll mid-run (the Explorer's ``/.status`` does).
        Key set is stable across dedup structures (pinned by
        tests/test_obs.py); schema in docs/observability.md."""
        cap = self._table.capacity
        job = (
            {"job_id": self._service_job_id}
            if self._service_job_id is not None
            else {}
        )
        return {
            **job,
            "engine": "xla",
            "backend": self._jax.default_backend(),
            # -- configuration gauges ---------------------------------
            "dedup": self._dedup,
            "compaction": self._compaction,
            "symmetry": self._sym_tag,
            "ladder": self._ladder,
            "cand_ladder_k": self._cand_ladder_k,
            "shrink_exit": self._shrink_exit,
            "levels_per_dispatch": self._levels_per_dispatch,
            "checkpoint_to": self._autockpt.path if self._autockpt else None,
            "metrics_to": self._recorder.path if self._recorder else None,
            # -- recovery gauges (docs/observability.md "Recovery") ----
            "resumed_from": self._resumed_from,
            "last_checkpoint_level": (
                self._last_checkpoint["depth"] if self._last_checkpoint else None
            ),
            # -- live search gauges -----------------------------------
            "state_count": self._state_count,
            "unique_state_count": self._unique_count,
            "depth": self._depth,
            "max_depth": self._max_depth,
            "frontier_count": self._frontier_count,
            "frontier_capacity": self._frontier_capacity,
            "table_capacity": cap,
            "table_occupancy": self._unique_count / max(cap, 1),
            "dispatches": len(self.dispatch_log),
            "levels_committed": sum(c for _, c in self.dispatch_log),
            "cand_retries": self.cand_retries,
            "hv": dict(self.hv_stats),
            # -- event counters (obs.Counters, pre-seeded) ------------
            **self._counters.snapshot(),
        }

    def is_done(self) -> bool:
        if self._exhausted or self._target_reached:
            return True
        if self._P > 0 and all(n in self._found_names for n in self._prop_names):
            return True
        return self._frontier_count == 0 and self._state_count > 0

    def discoveries(self) -> Dict[str, Path]:
        parents = self._parent_map()
        return {
            name: self._path_for(fp64, parents)
            for name, fp64 in self._found_names.items()
        }

    def _parent_map(self):
        """Pulls the device table once and indexes fp64 -> parent fp64
        (C++ open-addressing index when the native toolchain is present —
        building a Python dict over millions of slots is the host hot spot
        of witness reconstruction; see stateright_tpu/native)."""
        from .native import ParentMap

        return ParentMap(
            np.asarray(self._table.key_hi),
            np.asarray(self._table.key_lo),
            np.asarray(self._table.val_hi),
            np.asarray(self._table.val_lo),
        )

    def _path_for(self, fp64: int, parents) -> Path:
        """Walks parent fingerprints back to an init state, then re-executes
        the object model forward (bfs.rs:430-459 + path.rs:20-97, with the
        packed fingerprint as the digest). ``parents`` is a
        ``native.ParentMap``; the whole walk is one native call."""
        try:
            chain: List[int] = parents.chain(fp64)
        except KeyError as e:
            raise RuntimeError(
                f"{e.args[0]} during path reconstruction; packed model "
                "host/device codecs disagree"
            ) from None
        chain.reverse()

        model = self._model
        last_state = None
        for s in model.init_states():
            if self._packed_fp64(s) == chain[0]:
                last_state = s
                break
        if last_state is None:
            raise RuntimeError(
                "No init state matches the first fingerprint of a discovery "
                "path. The packed codec (pack/packed_init) and the object "
                "model disagree, or packed_step diverges from next_state."
            )
        pairs = []
        for next_fp in chain[1:]:
            found = None
            for action, state in model.next_steps(last_state):
                if self._packed_fp64(state) == next_fp:
                    found = (action, state)
                    break
            if found is None:
                raise RuntimeError(
                    f"No successor of {last_state!r} matches fingerprint "
                    f"{next_fp:#x}: packed_step and next_state disagree."
                )
            pairs.append((last_state, found[0]))
            last_state = found[1]
        pairs.append((last_state, None))
        return Path(pairs)
