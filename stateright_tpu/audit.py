"""Host-side integrity audit of a device checker's visited set.

Motivation (round-3 finding, BASELINE.md): the one on-chip paxos 2c/3s run
recorded 17,198 unique states where the pinned oracle says 16,668 — on a
revision whose CPU run reproduces the oracle exactly. Exact state counts
are this framework's correctness contract (the reference asserts them in
its example tests, e.g. /root/reference/examples/paxos.rs:321), so a count
drift on one platform must be attributable. The audit answers the sharpest
question on the table: **does the visited set hold the same fingerprint
twice?** A duplicate entry means the device insert admitted a key that was
already present (each admission increments ``unique_count`` and re-expands
the state, inflating both counters) — the signature of a backend miscompile
of the insert program rather than a model nondeterminism.

The audit deliberately runs on the HOST in NumPy over a pulled copy of the
table planes: an audit computed by the suspect device program would prove
nothing.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


def audit_table(checker) -> Dict[str, Any]:
    """Pulls the checker's visited-set key planes and cross-checks them
    against the committed ``unique_state_count()``.

    Works on any engine whose table exposes ``key_hi``/``key_lo`` planes
    (hash, sorted and delta structures on both the single-chip and sharded
    engines; the sharded engine's non-addressable shards are read through
    its ``_host_read``).

    Returns::

        {
          "entries":        occupied slots across all planes,
          "distinct_keys":  distinct 64-bit fingerprints among them,
          "duplicate_keys": entries - distinct_keys  (MUST be 0),
          "unique_count":   the checker's committed unique_state_count(),
          "ok":             duplicate_keys == 0 and entries == unique_count,
        }

    ``entries != unique_count`` with zero duplicates would instead indicate
    lost entries (growth/rehash dropping keys) or a counter bug — a
    different failure signature, also caught here.
    """
    read = getattr(checker, "_host_read", np.asarray)
    table = checker._table
    kh = np.asarray(read(table.key_hi), dtype=np.uint64)
    kl = np.asarray(read(table.key_lo), dtype=np.uint64)
    keys = (kh << np.uint64(32)) | kl
    occupied = keys != 0  # EMPTY is key == (0, 0); fphash never emits it
    live = keys[occupied]
    entries = int(live.size)
    distinct = int(np.unique(live).size)
    unique = int(checker.unique_state_count())
    return {
        "entries": entries,
        "distinct_keys": distinct,
        "duplicate_keys": entries - distinct,
        "unique_count": unique,
        "ok": (entries == distinct) and (entries == unique),
    }
