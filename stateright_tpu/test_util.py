"""Checker-correctness fixture models.

Ports of the reference's test fixtures (``/root/reference/src/test_util.rs``):
tiny closed-form models whose exact state counts, visit orders, and discovery
paths are oracles for every engine (host BFS/DFS and the XLA engine alike).
"""

from __future__ import annotations

from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Set

from .core import Model, Property


class _NamedEnum(Enum):
    """Enum whose str/repr is the bare variant name, to match the display of
    Rust enum variants in reporter-format parity tests."""

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return str(self.value)

    def __str__(self) -> str:
        return str(self.value)


# --- binary clock (test_util.rs:4-47) ------------------------------------


class BinaryClockAction(_NamedEnum):
    GO_LOW = "GoLow"
    GO_HIGH = "GoHigh"


class BinaryClock(Model):
    """A machine that cycles between two states."""

    def init_states(self) -> List[int]:
        return [0, 1]

    def actions(self, state: int, actions: List[Any]) -> None:
        if state == 0:
            actions.append(BinaryClockAction.GO_HIGH)
        else:
            actions.append(BinaryClockAction.GO_LOW)

    def next_state(self, state: int, action: Any) -> Optional[int]:
        return 1 if action == BinaryClockAction.GO_HIGH else 0

    def properties(self) -> List[Property]:
        return [Property.always("in [0, 1]", lambda _, state: 0 <= state <= 1)]


# --- directed graph (test_util.rs:50-118) ---------------------------------


class DGraph(Model):
    """A directed graph over u8 nodes, specified via paths from inits.

    Used to unit-test checker semantics (notably eventually-properties)
    against explicit edge lists.
    """

    def __init__(
        self,
        inits: Optional[Set[int]] = None,
        edges: Optional[Dict[int, Set[int]]] = None,
        property: Optional[Property] = None,
    ):
        self.inits: Set[int] = set(inits or ())
        self.edges: Dict[int, Set[int]] = {k: set(v) for k, v in (edges or {}).items()}
        self._property = property

    @staticmethod
    def with_property(property: Property) -> "DGraph":
        return DGraph(property=property)

    def with_path(self, path: List[int]) -> "DGraph":
        new = DGraph(self.inits, self.edges, self._property)
        src = path[0]
        new.inits.add(src)
        for dst in path[1:]:
            new.edges.setdefault(src, set()).add(dst)
            src = dst
        return new

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self) -> List[int]:
        return sorted(self.inits)

    def actions(self, state: int, actions: List[Any]) -> None:
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, state: int, action: int) -> Optional[int]:
        return action

    def properties(self) -> List[Property]:
        return [self._property] if self._property is not None else []


# --- function model (test_util.rs:121-139) --------------------------------


class PackedDGraph(DGraph):
    """A :class:`DGraph` that also implements the PackedModel protocol.

    States are node ids in one uint32 word; the successor grid and property
    predicate values are baked into dense device tables at construction.
    This is the primary semantics fixture for the XLA engine: every
    checker-semantics test over explicit edge lists runs identically on the
    device engine.
    """

    state_words = 1

    def __init__(self, graph: DGraph):
        super().__init__(graph.inits, graph.edges, graph._property)
        import numpy as np

        n_nodes = 256
        self.max_actions = max(
            (len(dsts) for dsts in self.edges.values()), default=1
        )
        succ = np.zeros((n_nodes, self.max_actions), dtype=np.uint32)
        valid = np.zeros((n_nodes, self.max_actions), dtype=bool)
        for src, dsts in self.edges.items():
            for k, dst in enumerate(sorted(dsts)):
                succ[src, k] = dst
                valid[src, k] = True
        self._succ = succ
        self._valid = valid
        props = self.properties()
        prop_table = np.zeros((n_nodes, len(props)), dtype=bool)
        for node in range(n_nodes):
            for j, p in enumerate(props):
                prop_table[node, j] = bool(p.condition(self, node))
        self._prop_table = prop_table

    def pack(self, state: int):
        import numpy as np

        return np.array([state], dtype=np.uint32)

    def unpack(self, words) -> int:
        return int(words[0])

    def packed_init(self):
        import numpy as np

        return np.stack([self.pack(s) for s in self.init_states()])

    def packed_step(self, words):
        import jax.numpy as jnp

        node = words[0].astype(jnp.int32)
        succ = jnp.asarray(self._succ)[node]  # [A]
        valid = jnp.asarray(self._valid)[node]  # [A]
        return succ[:, None], valid

    def packed_properties(self, words):
        import jax.numpy as jnp

        return jnp.asarray(self._prop_table)[words[0].astype(jnp.int32)]


class FnModel(Model):
    """A model defined by one function ``f(prev_or_None, out_actions)``.

    With ``prev=None`` the function emits init states; otherwise it emits the
    successors of ``prev`` (next_state is the identity on actions).
    """

    def __init__(self, fn: Callable[[Optional[Any], List[Any]], None]):
        self._fn = fn

    def init_states(self) -> List[Any]:
        out: List[Any] = []
        self._fn(None, out)
        return out

    def actions(self, state: Any, actions: List[Any]) -> None:
        self._fn(state, actions)

    def next_state(self, state: Any, action: Any) -> Optional[Any]:
        return action


# --- linear equation solver (test_util.rs:142-194) ------------------------


class Guess(_NamedEnum):
    INCREASE_X = "IncreaseX"
    INCREASE_Y = "IncreaseY"


class LinearEquation(Model):
    """Finds u8 ``x``,``y`` with ``a*x + b*y == c`` (wrapping arithmetic).

    State space is exactly 256*256 when fully enumerated.
    """

    def __init__(self, a: int, b: int, c: int):
        self.a, self.b, self.c = a, b, c

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions: List[Any]) -> None:
        actions.append(Guess.INCREASE_X)
        actions.append(Guess.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action == Guess.INCREASE_X:
            return ((x + 1) & 0xFF, y)
        return (x, (y + 1) & 0xFF)

    def properties(self) -> List[Property]:
        def solvable(model, solution) -> bool:
            x, y = solution
            return (model.a * x + model.b * y) & 0xFF == model.c

        return [Property.sometimes("solvable", solvable)]
