// Stateright-TPU Explorer — original single-page app.
//
// Speaks the Explorer HTTP contract (see stateright_tpu/checker/explorer.py):
//   GET  /.status               -> {done, model, state_count, unique_state_count,
//                                   max_depth, properties, recent_path}
//   GET  /.states/<fp>/<fp>/... -> [{action?, outcome?, state?, fingerprint?,
//                                   properties, svg?}, ...]
//   POST /.runtocompletion
// Properties are [expectation, name, encodedDiscoveryPathOrNull] triples with
// expectation one of "Always" | "Sometimes" | "Eventually".
//
// Routing: #/steps/<fp>/<fp>...?offset=<n> — the fingerprint path of the
// states walked so far, plus the selected row.

"use strict";

const stateCache = new Map(); // fp-path string -> states JSON

async function fetchStates(fpPath) {
  if (stateCache.has(fpPath)) return stateCache.get(fpPath);
  const res = await fetch("/.states" + (fpPath ? "/" + fpPath : "/"));
  if (!res.ok) throw new Error(await res.text());
  const states = await res.json();
  stateCache.set(fpPath, states);
  return states;
}

function propertyIcon(p, pathWithLeadingSlash) {
  // Mirror of the reference UI's per-state iconography: at the discovery
  // state show the verdict, before it show "deeper", after it show "above".
  const [expectation, _name, discoveryPath] = p;
  if (discoveryPath) {
    // Prefix tests honor "/" segment boundaries so fingerprint "12" is not
    // treated as an ancestor of "123/...".
    const dp = "/" + discoveryPath;
    const ancestorOfDiscovery = dp === pathWithLeadingSlash || dp.startsWith(pathWithLeadingSlash + "/");
    const descendantOfDiscovery = pathWithLeadingSlash.startsWith(dp + "/");
    if (ancestorOfDiscovery || descendantOfDiscovery) {
      if (dp.length > pathWithLeadingSlash.length) return "⬇️";
      if (dp.length < pathWithLeadingSlash.length) return "⬆️";
      return expectation === "Sometimes" ? "✅" : "⚠️";
    }
    return expectation === "Sometimes" ? "✅" : "⚠️";
  }
  return expectation === "Sometimes" ? "⚠️" : "✅";
}

function propertySummary(p, done) {
  const [expectation, name, discoveryPath] = p;
  let text;
  if (discoveryPath) {
    text = expectation === "Sometimes" ? "✅ example found" : "⚠️ counterexample found";
  } else if (!done) {
    text = "🔎 searching";
  } else {
    text =
      expectation === "Sometimes" ? "⚠️ example not found"
      : expectation === "Always" ? "✅ safety holds"
      : "✅ liveness holds";
  }
  return `${text}: ${expectation} “${name}”`;
}

// --- routing ---------------------------------------------------------------

function parseHash() {
  const h = location.hash || "#/steps";
  const m = h.match(/^#\/steps\/?([^?]*)(?:\?offset=(\d+))?$/);
  if (!m) return { fps: [], offset: 0 };
  const fps = m[1] ? m[1].split("/").filter((s) => s.length) : [];
  return { fps, offset: m[2] ? parseInt(m[2], 10) : 0 };
}

function navigate(fps, offset) {
  const path = fps.length ? "/" + fps.join("/") : "";
  location.hash = `#/steps${path}${offset ? "?offset=" + offset : ""}`;
}

// --- rendering -------------------------------------------------------------

const el = (id) => document.getElementById(id);

let current = { fps: [], offset: 0, steps: [] };

async function render() {
  const { fps, offset } = parseHash();
  const fpPath = fps.join("/");
  let steps;
  try {
    steps = await fetchStates(fpPath);
  } catch (err) {
    el("steps").innerHTML = `<div class="empty">${escapeHtml(err.message)}</div>`;
    return;
  }
  // A slow fetch may resolve after the user navigated away; the newer
  // render owns the DOM.
  const now = parseHash();
  if (now.fps.join("/") !== fpPath || now.offset !== offset) return;
  current = { fps, offset, steps };

  // Breadcrumbs: root plus one crumb per walked fingerprint.
  const crumbs = [`<a href="#/steps">init</a>`];
  for (let i = 0; i < fps.length; i++) {
    const prefix = fps.slice(0, i + 1).join("/");
    crumbs.push(`<a href="#/steps/${prefix}">${fps[i]}</a>`);
  }
  el("breadcrumbs").innerHTML = crumbs.join('<span class="sep">/</span>');

  // Step list.
  const stepsEl = el("steps");
  stepsEl.innerHTML = "";
  if (!steps.length) {
    stepsEl.innerHTML = '<div class="empty">No next steps — terminal state.</div>';
  }
  steps.forEach((s, i) => {
    const div = document.createElement("div");
    const ignored = !("fingerprint" in s);
    div.className = "step" + (ignored ? " ignored" : "") + (i === offset ? " selected" : "");
    const childPath = "/" + fps.concat(s.fingerprint || []).join("/");
    const icons = ignored
      ? ""
      : (s.properties || []).map((p) => propertyIcon(p, childPath)).join(" ");
    div.innerHTML =
      `<span class="icons">${icons}</span>` +
      `<div class="action">${s.action ? escapeHtml(s.action) : "init state " + i}</div>` +
      (ignored
        ? '<div class="outcome">action ignored (no-op)</div>'
        : `<div class="outcome">${escapeHtml(s.outcome || s.state || "")}</div>` +
          `<div class="fp">fp ${s.fingerprint}</div>`);
    if (!ignored) {
      div.addEventListener("click", () => {
        if (i === offset) descend();
        else navigate(fps, i);
      });
    }
    stepsEl.appendChild(div);
  });

  // Detail pane for the selected step.
  const sel = steps[offset];
  el("state-detail").textContent = sel && sel.state ? sel.state : "";
  el("svg-pane").innerHTML = sel && sel.svg ? sel.svg : "";
}

function escapeHtml(s) {
  return String(s).replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

function descend() {
  const { fps, offset, steps } = current;
  const sel = steps[offset];
  if (sel && sel.fingerprint) navigate(fps.concat(sel.fingerprint), 0);
}

function ascend() {
  const { fps } = current;
  if (fps.length) navigate(fps.slice(0, -1), 0);
}

function move(delta) {
  const { fps, offset, steps } = current;
  if (!steps.length) return;
  const next = Math.min(Math.max(offset + delta, 0), steps.length - 1);
  if (next !== offset) navigate(fps, next);
}

// --- status pane -----------------------------------------------------------

async function pollStatus() {
  try {
    const res = await fetch("/.status");
    if (!res.ok) return;
    const s = await res.json();
    el("model-name").textContent = s.model;
    el("done-indicator").textContent = s.done ? "✅ done" : "🔎 searching";
    el("state-count").textContent = s.state_count.toLocaleString();
    el("unique-count").textContent = s.unique_state_count.toLocaleString();
    el("max-depth").textContent = s.max_depth;
    el("run-to-completion").disabled = s.done;
    const list = el("property-list");
    list.innerHTML = "";
    for (const p of s.properties) {
      const li = document.createElement("li");
      li.textContent = propertySummary(p, s.done);
      if (p[2]) {
        const a = document.createElement("a");
        a.href = "#/steps/" + p[2];
        a.textContent = " ↪ view path";
        li.appendChild(a);
      }
      list.appendChild(li);
    }
    el("recent-path").textContent = s.recent_path || "";
    // Discoveries and counts can change which icons apply; drop the cache
    // when the run finishes so the next render reflects final verdicts.
    if (s.done && !pollStatus._wasDone) {
      stateCache.clear();
      render();
    }
    pollStatus._wasDone = s.done;
  } catch (_err) {
    /* server restarting; keep polling */
  }
}

// --- wiring ----------------------------------------------------------------

window.addEventListener("hashchange", render);
window.addEventListener("keydown", (e) => {
  if (e.key === "j" || e.key === "ArrowDown") { move(1); e.preventDefault(); }
  else if (e.key === "k" || e.key === "ArrowUp") { move(-1); e.preventDefault(); }
  else if (e.key === "Enter" || e.key === "ArrowRight") { descend(); e.preventDefault(); }
  else if (e.key === "ArrowLeft" || e.key === "h") { ascend(); e.preventDefault(); }
});
el("run-to-completion").addEventListener("click", async () => {
  await fetch("/.runtocompletion", { method: "POST" });
});

render();
pollStatus();
setInterval(pollStatus, 5000);
