// Stateright-TPU pool dashboard — vanilla SPA, no build step (same style
// as app.js). Polls:
//   GET /.pool                      -> pool gauges + per-job snapshots
//   GET /.jobs/<id>/metrics.json?n= -> windowed metrics time-series rows
//   GET /.status                    -> fallback when no service is attached
// Renders stat tiles + single-series SVG sparklines (frontier size, gen/s
// derived from consecutive state_count deltas, queue depth from the poll
// ring). Status verdicts (breaker, heartbeat staleness) always carry a
// text label next to the colored dot — never color alone.

"use strict";

const POLL_MS = 2000;
const SERIES_N = 120;
const el = (id) => document.getElementById(id);

function escapeHtml(s) {
  return String(s).replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

function fmt(v) {
  if (v === null || v === undefined) return "–";
  if (typeof v !== "number") return String(v);
  if (Math.abs(v) >= 1e6) return (v / 1e6).toFixed(2) + "M";
  if (Math.abs(v) >= 1e4) return (v / 1e3).toFixed(1) + "k";
  if (Number.isInteger(v)) return v.toLocaleString();
  return v.toFixed(2);
}

function ageLabel(s) {
  if (s === null || s === undefined) return "–";
  return s < 90 ? `${Math.round(s)}s ago` : `${Math.round(s / 60)}m ago`;
}

// --- sparkline -------------------------------------------------------------

// Single-series sparkline (no legend — the row's name labels it): 2px
// line in the series hue, a 3px end-dot, and a hover layer that snaps to
// the nearest sample and shows its value in the readout span.
function sparkline(container, values, fmtVal) {
  const W = 170, H = 36, PAD = 3;
  fmtVal = fmtVal || fmt;
  const svgNS = "http://www.w3.org/2000/svg";
  container.innerHTML = "";
  const svg = document.createElementNS(svgNS, "svg");
  svg.setAttribute("width", W);
  svg.setAttribute("height", H);
  const readout = container.parentElement.querySelector(".val");
  if (!values.length) {
    if (readout) readout.textContent = "–";
    container.appendChild(svg);
    return;
  }
  const lo = Math.min(...values), hi = Math.max(...values);
  const span = hi - lo || 1;
  const x = (i) => values.length === 1
    ? W / 2 : PAD + (i * (W - 2 * PAD)) / (values.length - 1);
  const y = (v) => H - PAD - ((v - lo) * (H - 2 * PAD)) / span;
  const line = document.createElementNS(svgNS, "polyline");
  line.setAttribute("points", values.map((v, i) => `${x(i)},${y(v)}`).join(" "));
  line.setAttribute("fill", "none");
  line.setAttribute("stroke", "var(--series-1)");
  line.setAttribute("stroke-width", "2");
  line.setAttribute("stroke-linejoin", "round");
  svg.appendChild(line);
  const dot = document.createElementNS(svgNS, "circle");
  dot.setAttribute("r", "3");
  dot.setAttribute("fill", "var(--series-1)");
  dot.setAttribute("cx", x(values.length - 1));
  dot.setAttribute("cy", y(values[values.length - 1]));
  svg.appendChild(dot);
  const last = values[values.length - 1];
  if (readout) readout.textContent = fmtVal(last);
  // Hover layer: nearest-sample readout (reverts to the latest value on
  // leave); the whole svg is the hit target, larger than any mark.
  svg.addEventListener("mousemove", (e) => {
    const rect = svg.getBoundingClientRect();
    const i = Math.max(0, Math.min(values.length - 1,
      Math.round(((e.clientX - rect.left - PAD) / (W - 2 * PAD)) * (values.length - 1))));
    dot.setAttribute("cx", x(i));
    dot.setAttribute("cy", y(values[i]));
    if (readout) readout.textContent = fmtVal(values[i]);
  });
  svg.addEventListener("mouseleave", () => {
    dot.setAttribute("cx", x(values.length - 1));
    dot.setAttribute("cy", y(last));
    if (readout) readout.textContent = fmtVal(last);
  });
  container.appendChild(svg);
}

function sparkRow(name) {
  const row = document.createElement("div");
  row.className = "spark";
  row.innerHTML = `<span class="name">${escapeHtml(name)}</span>` +
    `<span class="plot"></span><span class="val mono"></span>`;
  return row;
}

// --- pool header -----------------------------------------------------------

const queueRing = [];   // {t, queued, running} from each poll

function breakerBadge(b) {
  if (!b) return "";
  const open = b.state === "open";
  const cls = open ? "serious" : "good";
  const label = open
    ? `breaker OPEN (${b.consecutive_wedges}/${b.k} wedges)`
    : "breaker closed";
  return `<span class="badge ${cls}"><span class="dot"></span>${label}</span>`;
}

function hbBadge(age) {
  if (age === null || age === undefined)
    return `<span class="badge"><span class="dot"></span>no heartbeat</span>`;
  const cls = age < 30 ? "good" : age < 120 ? "warning" : "serious";
  const word = age < 30 ? "beating" : age < 120 ? "quiet" : "stale";
  return `<span class="badge ${cls}"><span class="dot"></span>heartbeat ${word} · ${ageLabel(age)}</span>`;
}

// QoS class tiles (docs/service.md "QoS & overload"): one tile per
// priority class with live queue/run occupancy and the fair-share
// weight, plus shed/quota counters when the overload tier has acted.
function qosTiles(pool) {
  const qos = pool.qos;
  if (!qos || !qos.classes) return "";
  let html = Object.keys(qos.classes).map((cls) => {
    const c = qos.classes[cls];
    return `<div class="tile"><div class="v">${fmt(c.queued)}+${fmt(c.running)}</div>` +
      `<div class="k">${escapeHtml(cls)} (w=${fmt(c.weight)})</div></div>`;
  }).join("");
  if (pool.sheds || pool.quota_rejects) {
    html += `<div class="tile"><div class="v">${fmt(pool.sheds || 0)}</div>` +
      `<div class="k">shed (${fmt(pool.quota_rejects || 0)} quota)</div></div>`;
  }
  return html;
}

function renderPool(pool) {
  const tiles = [
    ["queued", pool.queued], ["in flight", pool.running],
    ["quarantined", pool.quarantined], ["sessions", pool.interactive],
    ["done", pool.jobs_done], ["failed", pool.jobs_failed],
    ["wedges", pool.wedge_verdicts], ["requeues", pool.requeues],
  ];
  el("pool-tiles").innerHTML = tiles.map(([k, v]) =>
    `<div class="tile"><div class="v">${fmt(v)}</div><div class="k">${k}</div></div>`
  ).join("") + `<div class="tile"><div class="v">${breakerBadge(pool.breaker)}</div>` +
    `<div class="k">${pool.fleet ? "fleet" : "device"}</div></div>` +
    (pool.fleet ? `<div class="tile"><div class="v">${fmt(pool.migrations)}</div>` +
      `<div class="k">migrations</div></div>` : "") +
    // Lane occupancy (batched scheduling; docs/service.md): mean lanes
    // per mux group plus the device calls the batching avoided.
    (pool.mux_groups ? `<div class="tile"><div class="v">` +
      `${(pool.mux_lanes / pool.mux_groups).toFixed(1)}×</div>` +
      `<div class="k">lane occupancy (${fmt(pool.mux_groups)} batches · ` +
      `${fmt(pool.mux_dispatches_saved)} dispatches saved)</div></div>` : "") +
    (pool.journal ? `<div class="tile"><div class="v">${fmt(pool.journal.records)}</div>` +
      `<div class="k">journal records</div></div>` : "") +
    qosTiles(pool);

  queueRing.push({ queued: (pool.queued || 0) + (pool.quarantined || 0),
                   running: pool.running || 0 });
  if (queueRing.length > SERIES_N) queueRing.shift();
  let sparks = el("pool-sparks");
  if (!sparks.dataset.built) {
    sparks.dataset.built = "1";
    for (const name of ["queue depth", "in flight"]) {
      sparks.appendChild(sparkRow(name));
    }
  }
  const rows = sparks.querySelectorAll(".spark");
  sparkline(rows[0].querySelector(".plot"), queueRing.map((r) => r.queued));
  sparkline(rows[1].querySelector(".plot"), queueRing.map((r) => r.running));
}

// --- devices (fleet pools; service/fleet.py) -------------------------------

function deviceBadge(dev) {
  if (dev.lost)
    return `<span class="badge serious"><span class="dot"></span>LOST</span>`;
  // Elastic pools (docs/service.md "QoS & overload"): a quiesced pool
  // is healthy but parked — it wakes on queue pressure.
  if (dev.quiesced)
    return `<span class="badge"><span class="dot"></span>quiesced</span>`;
  const open = dev.breaker && dev.breaker.state === "open";
  return open
    ? `<span class="badge warning"><span class="dot"></span>breaker open</span>`
    : `<span class="badge good"><span class="dot"></span>healthy</span>`;
}

function renderDevices(devices) {
  const holder = el("devices");
  if (!holder) return;
  if (!devices) { holder.innerHTML = ""; return; }
  holder.innerHTML = Object.keys(devices).map((name) => {
    const d = devices[name];
    return `<div class="tile device"><h3><span class="mono">${escapeHtml(name)}</span>` +
      `${deviceBadge(d)}</h3>` +
      `<div class="meta mono">run ${fmt(d.running)} · queue ${fmt((d.queued || 0) + (d.quarantined || 0))}` +
      ` · done ${fmt(d.jobs_done)}` +
      (d.jobs_evacuated ? ` · evac ${fmt(d.jobs_evacuated)}` : "") +
      (d.wedge_verdicts ? ` · wedges ${fmt(d.wedge_verdicts)}` : "") +
      `</div></div>`;
  }).join("");
}

// --- jobs ------------------------------------------------------------------

function statusBadge(job) {
  const cls = job.status === "done" ? "good"
    : job.status === "failed" ? "serious"
    : job.status === "quarantined" ? "warning" : "";
  return `<span class="badge ${cls}"><span class="dot"></span>${escapeHtml(job.status)}</span>`;
}

function jobCard(id, job) {
  const div = document.createElement("div");
  div.className = "job";
  div.id = `job-${id}`;
  const engine = job.degraded ? `${job.engine} (degraded)` : job.engine;
  div.innerHTML =
    `<h3><span class="mono">${escapeHtml(id)}</span>${statusBadge(job)}</h3>` +
    `<div class="meta">${escapeHtml(job.spec || "")} · ${escapeHtml(engine || "")}` +
    ` · ${escapeHtml(job.kind || "batch")}` +
    // QoS identity: priority class (+ tenant when not the default).
    (job.priority && job.priority !== "batch" ? ` · ${escapeHtml(job.priority)}` : "") +
    (job.tenant && job.tenant !== "default" ? ` · ${escapeHtml(job.tenant)}` : "") +
    // Mux membership: the lane this member rode (rates on this card are
    // the LANE's own — the batch total lives in the pool tiles).
    (job.mux ? ` · lane ${(job.mux.lane || 0) + 1}/${job.mux.lanes}` +
      ` of ${escapeHtml(job.mux.group || "")}` : "") +
    (job.wedges ? ` · ${job.wedges} wedge${job.wedges > 1 ? "s" : ""}` : "") +
    (job.requeues ? ` · ${job.requeues} requeue${job.requeues > 1 ? "s" : ""}` : "") +
    `</div>` +
    `<div class="meta">${hbBadge(job.heartbeat_age_s)} ` +
    `<span class="badge"><span class="dot"></span>checkpoint ${ageLabel(job.checkpoint_age_s)}</span></div>` +
    (job.result ? `<div class="meta mono">generated ${fmt(job.result.generated)} · ` +
      `unique ${fmt(job.result.unique)} · depth ${fmt(job.result.max_depth)} · ` +
      `${fmt(job.result.seconds)}s</div>` : "") +
    (job.error ? `<div class="err">${escapeHtml(job.error)}</div>` : "") +
    `<div class="series"></div>`;
  return div;
}

async function renderJobSeries(id, card) {
  let doc;
  try {
    const res = await fetch(`/.jobs/${encodeURIComponent(id)}/metrics.json?n=${SERIES_N}`);
    if (!res.ok) return;  // host-engine job or swept artifacts: no series
    doc = await res.json();
  } catch (_err) { return; }
  const rows = (doc.rows || []).map((r) => r.metrics).filter(Boolean);
  if (!rows.length) return;
  const holder = card.querySelector(".series");
  if (!holder.dataset.built) {
    holder.dataset.built = "1";
    for (const name of ["frontier", "gen/s", "table occupancy"]) {
      holder.appendChild(sparkRow(name));
    }
  }
  const sparkEls = holder.querySelectorAll(".spark");
  sparkline(sparkEls[0].querySelector(".plot"), rows.map((m) => m.frontier_count || 0));
  // gen/s between consecutive samples: Δ generated / Δ wall-clock.
  const rates = [];
  const raw = doc.rows || [];
  for (let i = 1; i < raw.length; i++) {
    const ds = (raw[i].metrics.state_count || 0) - (raw[i - 1].metrics.state_count || 0);
    const dt = (raw[i].unix_ts || 0) - (raw[i - 1].unix_ts || 0);
    if (dt > 0 && ds >= 0) rates.push(ds / dt);
  }
  sparkline(sparkEls[1].querySelector(".plot"), rates);
  sparkline(sparkEls[2].querySelector(".plot"),
    rows.map((m) => m.table_occupancy || 0), (v) => (100 * v).toFixed(1) + "%");
}

function renderJobs(jobs) {
  const holder = el("jobs");
  const ids = Object.keys(jobs);
  if (!ids.length) {
    holder.innerHTML = '<div class="empty">No jobs in the pool yet.</div>';
    return;
  }
  if (holder.querySelector(".empty")) holder.innerHTML = "";
  for (const id of ids) {
    const job = jobs[id];
    const fresh = jobCard(id, job);
    const existing = el(`job-${id}`);
    if (existing) {
      // Preserve the built sparkline sub-tree across re-renders (its
      // hover state and data-built flag live in the DOM).
      const series = existing.querySelector(".series");
      fresh.querySelector(".series").replaceWith(series);
      existing.replaceWith(fresh);
    } else {
      holder.appendChild(fresh);
    }
    if (job.status === "running" || job.status === "done" ||
        job.kind === "interactive") {
      renderJobSeries(id, fresh);
    }
  }
}

// --- polling ---------------------------------------------------------------

async function poll() {
  try {
    const res = await fetch("/.pool");
    if (res.ok) {
      const pool = await res.json();
      el("pool-error").textContent = "";
      renderPool(pool);
      renderDevices(pool.devices || null);
      renderJobs(pool.jobs || {});
      return;
    }
    // No service attached: degrade to a single interactive card fed by
    // /.status + the live series ring.
    const st = await fetch("/.status");
    if (!st.ok) throw new Error(`status ${st.status}`);
    const s = await st.json();
    el("pool-tiles").innerHTML =
      `<div class="tile"><div class="v">${fmt(s.state_count)}</div><div class="k">states</div></div>` +
      `<div class="tile"><div class="v">${fmt(s.unique_state_count)}</div><div class="k">unique</div></div>` +
      `<div class="tile"><div class="v">${fmt(s.max_depth)}</div><div class="k">depth</div></div>`;
    renderJobs({ interactive: {
      kind: "interactive", spec: s.model, status: s.done ? "done" : "running",
      engine: (s.metrics || {}).engine, heartbeat_age_s: s.heartbeat_age_s,
      checkpoint_age_s: null,
    }});
  } catch (_err) {
    el("pool-error").textContent = "server unreachable — retrying";
  }
}

poll();
setInterval(poll, POLL_MS);
