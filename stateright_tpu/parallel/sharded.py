"""Fingerprint-sharded frontier expansion over a ``jax.sharding.Mesh``.

One super-step per BFS level, run as a single ``shard_map``-ped program:

1. each shard evaluates properties over its local frontier rows and expands
   its local action grid (same fused kernels as the single-chip engine);
2. candidates are fingerprinted and assigned an **owner shard** from the
   fingerprint bits;
3. one ``all_to_all`` routes every candidate (state words + fingerprint +
   parent fingerprint + eventually-bits) to its owner;
4. the owner inserts into its local partition of the visited hash set —
   dedup is lock-free because exactly one shard can ever see a given
   fingerprint (vs. the insert-if-vacant race of bfs.rs:349-363);
5. newly-inserted states *are* the owner's next local frontier (children
   live where their fingerprint lives, so no return routing is needed);
6. counters and discovery flags combine with ``psum``/max.

Capacities (frontier rows per shard, table slots per shard, routing slots
per destination) are static per compiled program; overflow of any of them
sets a flag and the host grows the overflowing buffer and re-runs the same
level — safe because the step is functional.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .. import obs
from ..checker.base import Checker
from ..core import Expectation, Model
from ..ops import deltaset, fphash, hashset, sortedset
from ..xla import ENGINE_COUNTERS, XlaChecker, _require_packed

# Owner mix constants: decorrelated from both the fingerprint lanes and the
# hash-set slot mix (ops/hashset.py:76) so shard choice, slot choice, and
# identity are pairwise independent.
_OWNER_MULT = 0x7FEB352D


def _owner_bits(fp_hi, fp_lo, n_shards: int, xp):
    u = xp.uint32
    mixed = (fp_lo ^ (fp_hi * u(_OWNER_MULT))) >> u(5)
    return (mixed % u(n_shards)).astype(xp.int32)


def default_mesh(n_devices: Optional[int] = None):
    """A 1-D ``Mesh`` over the first ``n_devices`` devices (all by default),
    with the axis name the engine expects."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("shards",))


class ShardedXlaChecker(Checker):
    """Level-synchronous BFS sharded over a device mesh.

    Spawn via ``model.checker().spawn_xla(mesh=mesh)``; with a 1-device mesh
    (or none) ``spawn_xla`` falls back to the single-chip engine.
    """

    def __init__(
        self,
        builder,
        mesh,
        *,
        frontier_capacity: Optional[int] = None,
        table_capacity: Optional[int] = None,
        route_capacity: Optional[int] = None,
        max_probes: int = 32,
        visit_cap: int = 4096,
        levels_per_dispatch: int = 32,
        checkpoint: Optional[str] = None,
        checkpoint_to: Optional[str] = None,
        checkpoint_every: Any = None,
        checkpoint_keep: Optional[int] = None,
        dedup: str = "auto",
        symmetry=None,
        host_verified_cap: int = 128,
        trace=None,
        heartbeat=None,
        metrics_to=None,
        metrics_every=None,
        metrics_keep: Optional[int] = None,
    ):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        model = builder._model
        _require_packed(model)
        self._model = model
        self._mesh = mesh
        self._D = mesh.devices.size
        if self._D & (self._D - 1):
            raise ValueError(f"mesh size must be a power of two, got {self._D}")
        # Symmetry reduction (stateright_tpu/sym, docs/symmetry.md): the
        # same resolution as the single-chip engine — shard ROUTING hashes
        # the canonical form too (owner bits come from the representative
        # fingerprint), so one class never splits across shards.
        from ..sym import SymmetryUnsupported, resolve_symmetry

        _sym = resolve_symmetry(
            symmetry, builder._symmetry is not None, model, engine="xla-mesh"
        )
        self._symmetry = _sym.enabled
        self._sym_tag = _sym.tag
        self._sym_canon = _sym.device_canon
        self._sym_canon_host = _sym.host_canon
        if self._symmetry and getattr(model, "host_verified_properties", ()):
            raise SymmetryUnsupported(
                "xla-mesh",
                f"{type(model).__name__} declares host_verified_properties; "
                f"the host-verified fallback evaluates concrete states and "
                f"cannot honor a symmetry-reduced frontier",
            )
        self._target_state_count = builder._target_state_count
        self._target_max_depth = builder._target_max_depth
        self._visitor = builder._visitor
        self._visit_cap = visit_cap
        # Same contract as the single-chip engine: the level loop runs on
        # device, up to this many levels per dispatch (visitors force 1).
        self._levels_per_dispatch = (
            1 if self._visitor is not None else max(1, levels_per_dispatch)
        )
        self._properties = model.properties()
        self._prop_names = [p.name for p in self._properties]
        self._ebit_of_prop: Dict[int, int] = {}
        for i, p in enumerate(self._properties):
            if p.expectation == Expectation.EVENTUALLY:
                self._ebit_of_prop[i] = len(self._ebit_of_prop)
        self._ebits0 = (1 << len(self._ebit_of_prop)) - 1

        self._max_probes = max_probes
        self._W = model.state_words
        self._A = model.max_actions
        self._P = len(self._properties)
        # Host-verified properties on the mesh (the single-chip contract,
        # xla.py: device flags candidate states with a conservative
        # predicate, the host confirms with the exact object-level
        # condition). Each shard compacts up to ``host_verified_cap``
        # candidate rows per super-step; the buffers stay sharded on device
        # and are only materialized host-side (``_host_read`` — an
        # allgather under ``jax.distributed``) when a level actually
        # flagged something.
        hv_names = frozenset(getattr(model, "host_verified_properties", ()))
        unknown = hv_names - {p.name for p in self._properties}
        if unknown:
            raise ValueError(f"host_verified_properties not in properties(): {unknown}")
        self._hv_idx = [
            i for i, p in enumerate(self._properties) if p.name in hv_names
        ]
        for i in self._hv_idx:
            if self._properties[i].expectation == Expectation.EVENTUALLY:
                raise ValueError(
                    "host-verified eventually-properties are not supported"
                )
        self._hv_cap = host_verified_cap

        # Per-shard visited-set structure + bulk-buffer layout, mirroring
        # the single-chip engine (xla.py): accelerators get the sort-merge
        # set, plane-major grid/payload buffers, and gather-based packing
        # and compaction; CPUs keep the hash set + scatter lowerings that
        # win there. Each shard's table partition is an independent
        # instance of the structure (ownership routing makes cross-shard
        # dedup races impossible either way).
        if dedup == "auto":
            dedup = "hash" if jax.default_backend() == "cpu" else "sorted"
        if dedup not in ("hash", "sorted", "delta"):
            raise ValueError(
                f"dedup must be 'auto', 'hash', 'sorted', or 'delta': {dedup!r}"
            )
        self._dedup = dedup
        self._ds = {"hash": hashset, "sorted": sortedset, "delta": deltaset}[dedup]

        D = self._D
        # Capacities learned by earlier checkers of this model over a
        # same-size mesh (growth events) — start there instead of repeating
        # the growth.
        # Same hint policy as the single-chip engine: hints may only raise
        # DEFAULT capacities — an explicit request (even a smaller one, e.g.
        # to exercise the growth path) wins over cross-checker state.
        hints = model.__dict__.get("_xla_sharded_cap_hints", {}).get(D, {})
        if frontier_capacity is None:
            frontier_capacity = max(1 << 15, hints.get("frontier", 0))
        if table_capacity is None:
            table_capacity = max(1 << 20, hints.get("table", 0))
        self._Fl = max(frontier_capacity // D, 16)  # frontier rows per shard
        self._Cl = max(table_capacity // D, 64)  # table slots per shard
        if self._Cl & (self._Cl - 1):
            raise ValueError("table_capacity/D must be a power of two")
        # Routing slots per (src, dst) pair. Hash uniformity spreads each
        # shard's candidates evenly over destinations; 4x slack + retry on
        # overflow covers skew.
        local_cand = self._Fl * self._A
        if route_capacity is not None:
            if route_capacity < 1:
                # K=0 could never grow out of route overflow (growth doubles).
                raise ValueError(f"route_capacity must be >= 1, got {route_capacity}")
            self._K = route_capacity  # explicit request wins over the hint
        else:
            self._K = min(local_cand, max(64, (local_cand // D) * 4))
            self._K = max(self._K, hints.get("route", 0))

        self._row_spec = P("shards", None)
        self._plane_spec = P("shards")
        self._row_sharding = NamedSharding(mesh, self._row_spec)
        self._plane_sharding = NamedSharding(mesh, self._plane_spec)
        self._rep_sharding = NamedSharding(mesh, P())

        self._found_names: Dict[str, int] = {}
        self._target_reached = False
        self._step_cache: Dict[Any, Any] = {}
        # Observability (stateright_tpu/obs): same contract as the
        # single-chip engine — spans/heartbeat around every SPMD dispatch,
        # the unified dispatch_log shape ((run_rows, committed_levels) per
        # device call, global rows here), and metrics() counters. The mesh
        # engine adds a route-buffer growth counter to the shared seed.
        self._tracer = obs.resolve_tracer(trace)
        self._heartbeat = obs.resolve_heartbeat(heartbeat)
        # Recorder gated to process 0, like save_checkpoint: under
        # jax.distributed every rank reaches the same quiescent point
        # with the same gauges, so rank 0's rows ARE the series — and
        # concurrent appenders on one base path would double-count rows
        # and double-shift the rotation chain out from under each other.
        self._recorder = (
            obs.resolve_recorder(metrics_to, metrics_every, metrics_keep)
            if jax.process_index() == 0
            else None
        )
        self._counters = obs.Counters(ENGINE_COUNTERS + ("route_grows",))
        self.dispatch_log = []
        # Recovery surface — same contract as the single-chip engine
        # (stateright_tpu/checkpoint.py): in-loop auto-checkpointing at
        # superstep boundaries plus resume-provenance gauges.
        from ..checkpoint import AutoCheckpointer

        self._autockpt = AutoCheckpointer.resolve(
            checkpoint_to, checkpoint_every, checkpoint_keep
        )
        self._last_checkpoint: Optional[Dict[str, Any]] = None
        self._resumed_from: Optional[str] = checkpoint

        if checkpoint is not None:
            # Skip init seeding entirely; _restore builds the whole state.
            self._restore(checkpoint)
            if self._autockpt is not None:
                self._autockpt.arm(self._depth)
            if self._recorder is not None:
                self._recorder.arm(self._depth)
            return

        # --- initial device state ----------------------------------------
        init_packed = np.asarray(model.packed_init(), dtype=np.uint32)
        keep = [model.within_boundary(model.unpack(row)) for row in init_packed]
        init_packed = init_packed[keep]
        n_init = len(init_packed)

        # Route init states to their owner shard host-side.
        frontier, fhi, flo, ebits, counts = self._route_frontier_host(
            init_packed, np.full(n_init, self._ebits0, dtype=np.uint32)
        )
        self._frontier = jax.device_put(
            frontier.reshape(D * self._Fl, self._W), self._row_sharding
        )
        self._frontier_ebits = jax.device_put(
            ebits.reshape(D * self._Fl), self._plane_sharding
        )
        self._counts = jax.device_put(counts, self._plane_sharding)

        self._table = self._make_table()
        # Insert init fingerprints (shard-local batches, zero parents).
        zeros = np.zeros_like(fhi)
        n_unique_init = self._bulk_insert(fhi, flo, zeros, zeros, counts)
        self._disc_found = jax.device_put(
            jnp.zeros(self._P, jnp.bool_), self._rep_sharding
        )
        self._disc_fp = jax.device_put(
            jnp.zeros((self._P, 2), jnp.uint32), self._rep_sharding
        )

        self._depth = 1
        self._max_depth = 0
        self._state_count = n_init
        self._unique_count = int(n_unique_init)
        self._frontier_total_cache = n_init
        self._exhausted = n_init == 0
        if self._autockpt is not None:
            self._autockpt.arm(self._depth)
        if self._recorder is not None:
            self._recorder.arm(self._depth)

    # --- checkpoint/resume (stateright_tpu/checkpoint.py) ------------------

    def save_checkpoint(self, path: str, keep: int = 1) -> None:
        """The single-chip implementation (atomic + rotating save, obs
        span, ``checkpoints_written`` counter, ``last_checkpoint`` gauge),
        gated to process 0: under ``jax.distributed`` every rank reaches
        the same quiescent point with the same allgathered payload
        (``_host_read``), so rank 0's write IS the complete checkpoint —
        and concurrent writers on one base path would sweep each other's
        temp files and double-shift the rotation chain."""
        import jax

        if jax.process_index() != 0:
            return
        XlaChecker.save_checkpoint(self, path, keep)

    # The in-loop auto-checkpoint hook routes through save_checkpoint
    # above, so the process-0 gate covers automatic writes too. The
    # metrics time-series hook samples at the same quiescent points
    # (metrics() here is host-side cached reads — no device dispatch, so
    # multi-process SPMD program order is safe).
    _maybe_checkpoint = XlaChecker._maybe_checkpoint
    _maybe_record = XlaChecker._maybe_record

    def _restore(self, path: str) -> None:
        """Loads a checkpoint, re-routing frontier rows and table entries to
        their owner shards — the checkpoint is layout-agnostic, so one
        written by the single-chip engine (or a different mesh size) loads
        here."""
        import jax
        import jax.numpy as jnp

        from ..checkpoint import load_checkpoint, validate_model, validate_symmetry

        ck = load_checkpoint(path)
        validate_model(ck["meta"], self._model, self._prop_names)
        validate_symmetry(ck["meta"], self._sym_tag)
        D = self._D

        # Visited set: distribute entries by owner, then bulk-insert.
        kh = np.asarray(ck["key_hi"], dtype=np.uint32)
        kl = np.asarray(ck["key_lo"], dtype=np.uint32)
        vh = np.asarray(ck["val_hi"], dtype=np.uint32)
        vl = np.asarray(ck["val_lo"], dtype=np.uint32)
        owners = _owner_bits(kh, kl, D, np)
        counts, order, pos = self._shard_positions(owners, D)
        B = max(16, int(counts.max()))
        while self._Cl < 2 * B:
            self._Cl *= 2
        self._table = self._make_table()
        blocks = [np.zeros((D, B), dtype=np.uint32) for _ in range(4)]
        shard = owners[order]
        for block, lane in zip(blocks, (kh, kl, vh, vl)):
            block[shard, pos] = lane[order]
        self._bulk_insert(*blocks, counts)

        # Frontier: re-route rows to their owners.
        rows = np.asarray(ck["frontier"], dtype=np.uint32)
        frontier, _fhi, _flo, ebits, fcounts = self._route_frontier_host(
            rows, np.asarray(ck["frontier_ebits"], dtype=np.uint32)
        )
        Fl = self._Fl
        self._frontier = jax.device_put(
            frontier.reshape(D * Fl, self._W), self._row_sharding
        )
        self._frontier_ebits = jax.device_put(
            ebits.reshape(D * Fl), self._plane_sharding
        )
        self._counts = jax.device_put(fcounts, self._plane_sharding)
        self._frontier_total_cache = int(fcounts.sum())

        meta = ck["meta"]
        self._depth = meta["depth"]
        self._max_depth = meta["max_depth"]
        self._state_count = meta["state_count"]
        self._unique_count = meta["unique_count"]
        self._found_names = dict(meta["found_names"])
        self._exhausted = meta["exhausted"]
        self._target_reached = meta["target_reached"]
        disc_found = np.zeros(self._P, dtype=bool)
        disc_fp = np.zeros((self._P, 2), dtype=np.uint32)
        for i, name in enumerate(self._prop_names):
            if name in self._found_names:
                fp64 = self._found_names[name]
                disc_found[i] = True
                disc_fp[i, 0] = fp64 >> 32
                disc_fp[i, 1] = fp64 & 0xFFFFFFFF
        self._disc_found = jax.device_put(
            jnp.asarray(disc_found), self._rep_sharding
        )
        self._disc_fp = jax.device_put(jnp.asarray(disc_fp), self._rep_sharding)

    # --- host helpers (shared semantics with the single-chip engine) ------

    _dedup_words_host = XlaChecker._dedup_words_host
    _packed_fp64 = XlaChecker._packed_fp64
    _path_for = XlaChecker._path_for
    # _parent_map is overridden below: it must gather table planes across
    # processes before indexing them.

    # --- table representation ----------------------------------------------
    #
    # The sharded table is the single-chip structure per shard, stored as
    # GLOBAL planes sharded over the mesh. hash: 4 uint32 planes [D*Cl].
    # sorted: the same 4 planes plus a [D] int32 plane of per-shard occupied
    # prefix lengths (SortedSet.n, one scalar per shard). Both reprs keep
    # the key_hi/key_lo/val_hi/val_lo attribute names and the zero-pad
    # layout contract, so checkpointing and the native ParentMap consume
    # either unchanged.

    def _delta_cap(self) -> int:
        """Per-shard delta-tier rows for dedup="delta"."""
        return deltaset._delta_cap(self._Cl)

    def _make_table(self):
        import jax
        import jax.numpy as jnp

        D = self._D
        z = jnp.zeros((D * self._Cl,), jnp.uint32)
        planes = [jax.device_put(z, self._plane_sharding) for _ in range(4)]
        if self._dedup == "delta":
            zd = jnp.zeros((D * self._delta_cap(),), jnp.uint32)
            dplanes = [jax.device_put(zd, self._plane_sharding) for _ in range(4)]
            nz = lambda: jax.device_put(
                jnp.zeros((D,), jnp.int32), self._plane_sharding
            )
            return deltaset.DeltaSet(*planes, *dplanes, nz(), nz())
        if self._dedup == "sorted":
            n = jax.device_put(jnp.zeros((D,), jnp.int32), self._plane_sharding)
            return sortedset.SortedSet(*planes, n)
        return hashset.HashSet(*planes)

    def _table_len(self) -> int:
        return {"hash": 4, "sorted": 5, "delta": 10}[self._dedup]

    def _local_table(self, table):
        """Per-shard structure from the shard-local plane blocks (inside
        shard_map: planes are [Cl] (+ [dc] delta tiers), n planes [1])."""
        if self._dedup == "delta":
            return deltaset.DeltaSet(*table[:8], table[8][0], table[9][0])
        if self._dedup == "sorted":
            return sortedset.SortedSet(
                table[0], table[1], table[2], table[3], table[4][0]
            )
        return hashset.HashSet(*table)

    @staticmethod
    def _local_table_out(new_table):
        """Back to the tuple-of-blocks form (rank-1 n so it shards)."""
        if isinstance(new_table, deltaset.DeltaSet):
            return tuple(new_table[:8]) + (
                new_table.n_main[None],
                new_table.n_delta[None],
            )
        if isinstance(new_table, sortedset.SortedSet):
            return (
                new_table.key_hi,
                new_table.key_lo,
                new_table.val_hi,
                new_table.val_lo,
                new_table.n[None],
            )
        return tuple(new_table)

    # --- device programs ---------------------------------------------------

    def _shard_map(self, fn, in_specs, out_specs):
        import jax

        if hasattr(jax, "shard_map"):  # jax >= 0.8
            smap = jax.shard_map(
                fn,
                mesh=self._mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )
        else:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

            smap = shard_map(
                fn,
                mesh=self._mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=False,
            )
        return jax.jit(smap)

    @staticmethod
    def _shard_positions(owners: np.ndarray, D: int):
        """Vectorized bucket placement: for each element, its shard and its
        position within that shard (stable order). Returns
        ``(counts[D], sorted_order, pos_in_shard)``."""
        counts = np.bincount(owners, minlength=D).astype(np.int32)
        order = np.argsort(owners, kind="stable")
        offsets = np.zeros(D, dtype=np.int64)
        np.cumsum(counts[:-1], out=offsets[1:])
        pos = np.arange(len(owners), dtype=np.int64) - np.repeat(offsets, counts)
        return counts, order, pos

    def _route_frontier_host(self, rows: np.ndarray, ebits_values: np.ndarray):
        """Distribute packed rows to their owner shard (host-side,
        vectorized; used for init seeding and checkpoint restore). Grows
        ``Fl`` (and rescales the routing capacity) to fit. Returns
        ``(frontier[D,Fl,W], fhi[D,Fl], flo[D,Fl], ebits[D,Fl], counts[D])``.
        """
        D, W = self._D, self._W
        n = len(rows)
        if n:
            dedup = self._dedup_words_host(rows)
            ihi, ilo = fphash.fingerprint_words(dedup, np)
            owners = _owner_bits(ihi, ilo, D, np)
            counts, order, pos = self._shard_positions(owners, D)
            grew = False
            while self._Fl < int(counts.max()):
                self._Fl *= 2
                grew = True
            if grew:
                # Keep the routing buffers scaled with the frontier, as
                # _grow_frontier does — otherwise the first superstep would
                # churn through route-overflow recompiles.
                local_cand = self._Fl * self._A
                self._K = min(local_cand, max(self._K, (local_cand // D) * 4))
        Fl = self._Fl
        frontier = np.zeros((D, Fl, W), dtype=np.uint32)
        fhi = np.zeros((D, Fl), dtype=np.uint32)
        flo = np.zeros((D, Fl), dtype=np.uint32)
        ebits = np.zeros((D, Fl), dtype=np.uint32)
        out_counts = np.zeros((D,), dtype=np.int32)
        if n:
            shard = owners[order]
            frontier[shard, pos] = rows[order]
            fhi[shard, pos] = ihi[order]
            flo[shard, pos] = ilo[order]
            ebits[shard, pos] = np.asarray(ebits_values, dtype=np.uint32)[order]
            out_counts = counts
        return frontier, fhi, flo, ebits, out_counts

    def _bulk_insert(
        self,
        fhi: np.ndarray,
        flo: np.ndarray,
        vhi: np.ndarray,
        vlo: np.ndarray,
        counts: np.ndarray,
    ) -> int:
        """Insert per-shard blocks ``[D, B]`` of (fingerprint, value) pairs
        into the sharded table; grows the table and retries on overflow.
        Returns the number of new entries (psum over shards)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        D, B = fhi.shape
        max_probes = self._max_probes
        ds = self._ds
        TL = self._table_len()
        local_table = self._local_table
        local_table_out = self._local_table_out

        def build():
            def body(table, fh, fl, vh, vl, count):
                active = jnp.arange(B) < count[0]
                table, is_new, ovf = ds.insert(
                    local_table(table), fh, fl, vh, vl, active,
                    max_probes=max_probes,
                )
                unique = jax.lax.psum(jnp.sum(is_new, dtype=jnp.int32), "shards")
                any_ovf = jax.lax.pmax(jnp.any(ovf).astype(jnp.uint32), "shards")
                return local_table_out(table), unique, any_ovf

            return self._shard_map(
                body,
                in_specs=(
                    (P("shards"),) * TL,
                    P("shards"), P("shards"), P("shards"), P("shards"), P("shards"),
                ),
                out_specs=((P("shards"),) * TL, P(), P()),
            )

        cache = self.__dict__.setdefault("_bulk_insert_cache", {})
        put = lambda a: jax.device_put(a.reshape(-1), self._plane_sharding)
        while True:
            # Re-key per attempt: _grow_table changes the plane shapes.
            key = ("bulk", B, self._Cl)
            fn = cache.get(key)
            if fn is None:
                fn = cache[key] = build()
            planes, unique, ovf = fn(
                tuple(self._table),
                put(fhi), put(flo), put(vhi), put(vlo),
                jax.device_put(counts, self._plane_sharding),
            )
            if bool(np.asarray(ovf)):
                self._grow_table()
                continue
            self._table = self._global_table(planes)
            return int(np.asarray(unique))

    def _global_table(self, planes):
        cls = {
            "hash": hashset.HashSet,
            "sorted": sortedset.SortedSet,
            "delta": deltaset.DeltaSet,
        }[self._dedup]
        return cls(*planes)

    def _make_local_step(self, Fl: int, Cl: int, K: int):
        """The per-shard superstep body (one BFS level), without the
        ``shard_map`` wrapper — shared by the one-level and fused
        programs."""
        import jax
        import jax.numpy as jnp

        model = self._model
        prop_specs = [(i, p.expectation) for i, p in enumerate(self._properties)]
        ebit_of_prop = dict(self._ebit_of_prop)
        symmetry = self._symmetry
        A, W, D = self._A, self._W, self._D
        P_count = self._P
        max_probes = self._max_probes
        hv_idx = list(self._hv_idx)
        n_hv = len(hv_idx)
        hv_cap = self._hv_cap
        LANES = W + 5  # state words + fp_hi, fp_lo, par_hi, par_lo, ebits
        ds = self._ds
        sorted_mode = self._dedup != "hash"  # planes/gather lowering family
        local_table = self._local_table
        local_table_out = self._local_table_out

        sym_canon = self._sym_canon

        def dedup_words(words):
            return sym_canon(words) if symmetry else words

        def pick_discovery(disc_found, disc_fp, i, viol, fhi, flo):
            """Elect one witness fingerprint across shards: the local first
            match, combined by pmax (the reference lets threads race here,
            bfs.rs:291-306; pmax is simply a deterministic tiebreak)."""
            has_local = jnp.any(viol)
            first = jnp.argmax(viol)
            cand_hi = jnp.where(has_local, fhi[first], jnp.uint32(0))
            cand_lo = jnp.where(has_local, flo[first], jnp.uint32(0))
            g_hi = jax.lax.pmax(cand_hi, "shards")
            is_max_shard = cand_hi == g_hi
            g_lo = jax.lax.pmax(
                jnp.where(is_max_shard, cand_lo, jnp.uint32(0)), "shards"
            )
            has = jax.lax.pmax(has_local.astype(jnp.uint32), "shards") > 0
            take = has & ~disc_found[i]
            disc_fp = disc_fp.at[i, 0].set(jnp.where(take, g_hi, disc_fp[i, 0]))
            disc_fp = disc_fp.at[i, 1].set(jnp.where(take, g_lo, disc_fp[i, 1]))
            disc_found = disc_found.at[i].set(disc_found[i] | has)
            return disc_found, disc_fp

        def superstep(frontier, f_ebits, count, table, disc_found, disc_fp):
            # Local block shapes: frontier [Fl, W], f_ebits [Fl], count [1],
            # table planes [Cl], disc_* replicated.
            f_valid = jnp.arange(Fl) < count[0]
            dw = jax.vmap(dedup_words)(frontier)
            fhi, flo = fphash.fingerprint_words(dw, jnp)

            # 1. property evaluation over the local frontier. Host-verified
            #    properties compact up to ``hv_cap`` shard-local candidate
            #    rows instead of pinning a discovery — the host confirms
            #    with the exact condition (xla.py ``_checking_blocks``).
            #    Zero-padded rows carry fp (0, 0), which a real state never
            #    has, so the host needs no per-shard layout bookkeeping.
            def hv_compact(viol):
                k = min(hv_cap, Fl)
                order = jnp.argsort(~viol, stable=True)[:k]
                m = viol[order]
                cw = jnp.where(m[:, None], frontier[order], jnp.uint32(0))
                cf = jnp.where(
                    m[:, None],
                    jnp.stack([fhi[order], flo[order]], axis=1),
                    jnp.uint32(0),
                )
                if k < hv_cap:
                    cw = jnp.concatenate(
                        [cw, jnp.zeros((hv_cap - k, W), jnp.uint32)]
                    )
                    cf = jnp.concatenate(
                        [cf, jnp.zeros((hv_cap - k, 2), jnp.uint32)]
                    )
                return cw, cf, jnp.sum(viol, dtype=jnp.int32)

            hv_w_out, hv_f_out, hv_c_out = [], [], []
            props = jax.vmap(model.packed_properties)(frontier)  # [Fl, P]
            for i, expectation in prop_specs:
                if expectation == Expectation.EVENTUALLY:
                    bit = jnp.uint32(1 << ebit_of_prop[i])
                    sat = props[:, i] & f_valid
                    f_ebits = jnp.where(sat, f_ebits & ~bit, f_ebits)
                    continue
                if expectation == Expectation.ALWAYS:
                    viol = ~props[:, i] & f_valid
                else:
                    viol = props[:, i] & f_valid
                if i in hv_idx:
                    cw, cf, n_viol = hv_compact(viol)
                    hv_w_out.append(cw)
                    hv_f_out.append(cf)
                    hv_c_out.append(n_viol)
                    continue
                disc_found, disc_fp = pick_discovery(
                    disc_found, disc_fp, i, viol, fhi, flo
                )
            if n_hv:
                hv_w = jnp.stack(hv_w_out)  # [n_hv, hv_cap, W]
                hv_f = jnp.stack(hv_f_out)  # [n_hv, hv_cap, 2]
                hv_c = jnp.stack(hv_c_out)[:, None]  # [n_hv, 1]
            else:
                hv_w = jnp.zeros((0, hv_cap, W), jnp.uint32)
                hv_f = jnp.zeros((0, hv_cap, 2), jnp.uint32)
                hv_c = jnp.zeros((0, 1), jnp.int32)

            # 2. local action-grid expansion. An optional third output is
            #    the per-action codec-overflow mask (see xla.py superstep
            #    step 2): psum'd across shards and surfaced loudly.
            stepped = jax.vmap(model.packed_step)(frontier)  # [Fl,A,W],[Fl,A]
            if len(stepped) == 3:
                nxt, valid, step_ovf = stepped
                codec_ovf = (
                    jax.lax.pmax(
                        jnp.any(step_ovf & f_valid[:, None]).astype(jnp.uint32),
                        "shards",
                    )
                    > 0
                )
            else:
                nxt, valid = stepped
                codec_ovf = jnp.bool_(False)
            valid = valid & f_valid[:, None]
            step_states = jax.lax.psum(jnp.sum(valid, dtype=jnp.int32), "shards")

            # 3. terminal detection (bfs.rs:374-381) before routing — it
            #    needs the parent-side successor mask.
            terminal = f_valid & ~jnp.any(valid, axis=1)
            for i, expectation in prop_specs:
                if expectation != Expectation.EVENTUALLY:
                    continue
                bit = jnp.uint32(1 << ebit_of_prop[i])
                viol = terminal & ((f_ebits & bit) != 0)
                disc_found, disc_fp = pick_discovery(
                    disc_found, disc_fp, i, viol, fhi, flo
                )

            # 4-6. fingerprint candidates, assign owner shards, pack
            #    per-destination routing buffers, all_to_all. Each candidate
            #    has exactly one destination, so the pack is one
            #    O(Fl*A log) sort pass regardless of mesh size; candidates
            #    stay in state-major (frontier) order within each
            #    destination, so the receiver's insert elects the same
            #    winners as the single-chip engine. Inactive slots stay
            #    all-zero; (0,0) fingerprints mark them empty downstream.
            #
            #    Two lowerings (same results): the sorted/accelerator path
            #    keeps the grid plane-major ([W, A*Fl], lane-axis Fl — see
            #    the xla.py layout note) and GATHERS destination slots from
            #    the owner-sorted order; the hash/CPU path keeps row-major
            #    buffers and a scatter pack.
            n_cand = Fl * A
            if sorted_mode:
                grid = jnp.transpose(nxt, (2, 1, 0)).reshape(W, n_cand)
                vflat = valid.T.reshape(-1)
                if symmetry:
                    crows = jnp.stack([grid[w] for w in range(W)], axis=1)
                    cdw = jax.vmap(dedup_words)(crows)
                    chi, clo = fphash.fingerprint_words(cdw, jnp)
                else:
                    chi, clo = fphash.fingerprint_planes(grid, jnp)
                owner = _owner_bits(chi, clo, D, jnp)
                par_hi = jnp.broadcast_to(fhi[None, :], (A, Fl)).reshape(-1)
                par_lo = jnp.broadcast_to(flo[None, :], (A, Fl)).reshape(-1)
                ceb = jnp.broadcast_to(f_ebits[None, :], (A, Fl)).reshape(-1)
                j = jnp.arange(n_cand, dtype=jnp.int32)
                prio = (j % Fl) * A + (j // Fl)  # state-major rank f*A + a
                owner_eff = jnp.where(vflat, owner, D)
                if (D + 1) * n_cand < (1 << 31):
                    # Fused int32 key (owner, state-major rank): one key
                    # operand instead of two on the routing sort.
                    key = owner_eff * jnp.int32(n_cand) + prio
                    key_s, order = jax.lax.sort((key, j), num_keys=1)
                    so = key_s // jnp.int32(n_cand)
                else:  # pragma: no cover - needs a >2^31 global grid
                    so, _, order = jax.lax.sort((owner_eff, prio, j), num_keys=2)
                starts = jnp.searchsorted(so, jnp.arange(D + 1))
                cnt = starts[1:] - starts[:-1]
                route_ovf = jnp.any(cnt > K)
                src = jnp.clip(
                    starts[:-1][:, None] + jnp.arange(K)[None, :], 0, n_cand - 1
                )
                idx = order[src]  # [D, K] payload lanes per destination
                mask = jnp.arange(K)[None, :] < cnt[:, None]
                planes = [grid[w] for w in range(W)] + [chi, clo, par_hi, par_lo, ceb]
                buf = jnp.stack(
                    [jnp.where(mask, p[idx], jnp.uint32(0)) for p in planes]
                )  # [LANES, D, K]
                route_ovf = jax.lax.pmax(route_ovf.astype(jnp.uint32), "shards") > 0
                recv = jax.lax.all_to_all(
                    buf, "shards", split_axis=1, concat_axis=1, tiled=False
                ).reshape(LANES, D * K)
                r_state = recv[:W]  # [W, D*K] planes
                r_hi, r_lo = recv[W], recv[W + 1]
                r_par_hi, r_par_lo = recv[W + 2], recv[W + 3]
                r_ebits = recv[W + 4]
            else:
                cand = nxt.reshape(n_cand, W)
                cdw = jax.vmap(dedup_words)(cand)
                chi, clo = fphash.fingerprint_words(cdw, jnp)
                vflat = valid.reshape(-1)
                owner = _owner_bits(chi, clo, D, jnp)
                payload = jnp.concatenate(
                    [
                        cand,
                        chi[:, None],
                        clo[:, None],
                        jnp.broadcast_to(fhi[:, None], (Fl, A)).reshape(-1)[:, None],
                        jnp.broadcast_to(flo[:, None], (Fl, A)).reshape(-1)[:, None],
                        jnp.broadcast_to(f_ebits[:, None], (Fl, A)).reshape(-1)[:, None],
                    ],
                    axis=1,
                )  # [Fl*A, LANES]
                owner_eff = jnp.where(vflat, owner.astype(jnp.int32), D)
                order = jnp.argsort(owner_eff, stable=True)
                sorted_owner = owner_eff[order]
                starts = jnp.searchsorted(sorted_owner, jnp.arange(D + 1))
                route_ovf = jnp.any(starts[1:] - starts[:-1] > K)
                slot = jnp.arange(n_cand) - starts[jnp.clip(sorted_owner, 0, D - 1)]
                keep = (sorted_owner < D) & (slot < K)
                buf = (
                    jnp.zeros((D, K, LANES), jnp.uint32)
                    .at[
                        jnp.where(keep, sorted_owner, D),
                        jnp.where(keep, slot, K),
                        :,
                    ]
                    .set(jnp.where(keep[:, None], payload[order], 0), mode="drop")
                )
                route_ovf = jax.lax.pmax(route_ovf.astype(jnp.uint32), "shards") > 0
                recv = jax.lax.all_to_all(
                    buf, "shards", split_axis=0, concat_axis=0, tiled=False
                ).reshape(D * K, LANES)
                r_state = recv[:, :W]  # [D*K, W] rows
                r_hi, r_lo = recv[:, W], recv[:, W + 1]
                r_par_hi, r_par_lo = recv[:, W + 2], recv[:, W + 3]
                r_ebits = recv[:, W + 4]
            r_active = (r_hi != 0) | (r_lo != 0)

            # 7. owner-local dedup insert (no cross-shard races possible;
            #    both structures share the insert contract).
            new_table, is_new, ovf = ds.insert(
                local_table(table),
                r_hi,
                r_lo,
                r_par_hi,
                r_par_lo,
                r_active,
                max_probes=max_probes,
            )
            step_unique = jax.lax.psum(jnp.sum(is_new, dtype=jnp.int32), "shards")
            table_ovf = jax.lax.pmax(jnp.any(ovf).astype(jnp.uint32), "shards") > 0

            # 8. compact the owner's new states into its next local
            #    frontier (gather lowering for sorted/accelerator, scatter
            #    for hash/CPU; identical results — receiver lane order).
            new_count = jnp.sum(is_new, dtype=jnp.int32)
            frontier_ovf = (
                jax.lax.pmax((new_count > Fl).astype(jnp.uint32), "shards") > 0
            )
            if sorted_mode:
                order2 = jnp.argsort(~is_new, stable=True)[:Fl]
                sm = is_new[order2]
                new_frontier = jnp.stack(
                    [
                        jnp.where(sm, r_state[w][order2], jnp.uint32(0))
                        for w in range(W)
                    ],
                    axis=1,
                )  # [Fl, W] rows (the kernel-facing boundary)
                new_ebits = jnp.where(sm, r_ebits[order2], jnp.uint32(0))
            else:
                pos = jnp.cumsum(is_new.astype(jnp.int32)) - 1
                idx2 = jnp.where(is_new & (pos < Fl), pos, Fl)
                new_frontier = (
                    jnp.zeros((Fl, W), jnp.uint32).at[idx2].set(r_state, mode="drop")
                )
                new_ebits = (
                    jnp.zeros((Fl,), jnp.uint32).at[idx2].set(r_ebits, mode="drop")
                )

            return (
                new_frontier,
                new_ebits,
                new_count[None],
                local_table_out(new_table),
                disc_found,
                disc_fp,
                step_states,
                step_unique,
                table_ovf,
                frontier_ovf,
                route_ovf,
                codec_ovf,
                hv_w,
                hv_f,
                hv_c,
            )

        return superstep

    def _build_superstep(self, Fl: int, Cl: int, K: int):
        from jax.sharding import PartitionSpec as P

        TL = self._table_len()
        spec_rows = P("shards", None)
        spec_plane = P("shards")
        spec_rep = P()
        return self._shard_map(
            self._make_local_step(Fl, Cl, K),
            in_specs=(
                spec_rows,
                spec_plane,
                spec_plane,
                (spec_plane,) * TL,
                spec_rep,
                spec_rep,
            ),
            out_specs=(
                spec_rows,
                spec_plane,
                spec_plane,
                (spec_plane,) * TL,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                P(None, "shards", None),  # hv candidate words
                P(None, "shards", None),  # hv candidate fingerprints
                P(None, "shards"),  # hv per-shard counts
            ),
        )

    def _build_fused(self, Fl: int, Cl: int, K: int):
        """The level loop as one SPMD program: a ``lax.while_loop`` (with
        the cross-shard collectives inside its body) around the local
        superstep. Every shard computes the exit condition from replicated
        values, so the loop stays in lockstep. Exit conditions mirror the
        single-chip fused block (xla.py ``_build_fused``): level budget,
        global frontier exhaustion, any overflow (the overflowing level is
        NOT committed), every property found, or a state-count target."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        local_step = self._make_local_step(Fl, Cl, K)
        P_count = self._P
        W = self._W
        n_hv = len(self._hv_idx)
        hv_cap = self._hv_cap
        hv_idx = list(self._hv_idx)  # slot j <-> property hv_idx[j]
        hv_pos = {i: j for j, i in enumerate(self._hv_idx)}

        def fused(frontier, f_ebits, count, table, disc_found, disc_fp,
                  budget, remaining, host_found):
            def resolved(df, g_hv_c):
                """Every property found on device, already confirmed on
                host, or — host-verified — with candidates collected
                somewhere on the mesh (global counts, so all shards agree)."""
                if P_count == 0:
                    return jnp.bool_(False)
                per_prop = [
                    host_found[i]
                    | (g_hv_c[hv_pos[i]] > 0 if i in hv_pos else df[i])
                    for i in range(P_count)
                ]
                return jnp.all(jnp.stack(per_prop))

            def hv_pending(g_hv_c):
                """Any *unconfirmed* host-verified property with collected
                candidates anywhere on the mesh: exit so the host can
                confirm — the same one-level candidate budget as the
                single-chip fused block (xla.py)."""
                if not n_hv:
                    return jnp.bool_(False)
                flags = [
                    (g_hv_c[j] > 0) & ~host_found[i] for i, j in hv_pos.items()
                ]
                return jnp.any(jnp.stack(flags))

            def cond(carry):
                (lvl, committed, fr, eb, cnt, tab, df, dfp, ts, tu, ovf,
                 gcount, hv_w, hv_f, hv_c, g_hv_c) = carry
                return (
                    (lvl < budget)
                    & (gcount > 0)
                    & ~jnp.any(ovf)
                    & ~resolved(df, g_hv_c)
                    & ~hv_pending(g_hv_c)
                    & (ts < remaining)
                )

            def body(carry):
                (lvl, committed, fr, eb, cnt, tab, df, dfp, ts, tu, ovf,
                 gcount, hv_w, hv_f, hv_c, g_hv_c) = carry
                (nf, ne, ncnt, ntab, ndf, ndfp, ds, du, t_ovf, f_ovf,
                 r_ovf, c_ovf, lw, lf, lc) = local_step(fr, eb, cnt, tab, df, dfp)
                commit = ~(t_ovf | f_ovf | r_ovf | c_ovf)
                sel = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(commit, a, b), new, old
                )
                # Append this level's shard-local candidates to the block
                # accumulators (level order across the block, shard-local
                # frontier order within a level).
                if n_hv:
                    rows = jnp.arange(hv_cap)
                    new_w, new_f = hv_w, hv_f
                    # A property the host already confirmed collects
                    # nothing: without this mask the accumulators keep
                    # growing for confirmed properties and rows past
                    # hv_cap are dropped silently — harmless only while
                    # _confirm_hv_candidates skips confirmed props, a
                    # coupling no future consumer should inherit
                    # (ADVICE r4).
                    lc = lc * jnp.stack(
                        [(~host_found[i]).astype(lc.dtype) for i in hv_idx]
                    )[:, None]
                    for j in range(n_hv):
                        dst = hv_c[j, 0] + rows
                        ok = (rows < lc[j, 0]) & (dst < hv_cap)
                        tgt = jnp.where(ok, dst, hv_cap)
                        new_w = new_w.at[j].set(
                            new_w[j].at[tgt].set(lw[j], mode="drop")
                        )
                        new_f = new_f.at[j].set(
                            new_f[j].at[tgt].set(lf[j], mode="drop")
                        )
                    hv_w = sel(new_w, hv_w)
                    hv_f = sel(new_f, hv_f)
                    hv_c = sel(hv_c + lc, hv_c)
                    g_hv_c = sel(
                        g_hv_c + jax.lax.psum(lc[:, 0], "shards"), g_hv_c
                    )
                return (
                    lvl + 1,
                    committed + commit.astype(jnp.int32),
                    sel(nf, fr),
                    sel(ne, eb),
                    sel(ncnt, cnt),
                    sel(ntab, tab),
                    sel(ndf, df),
                    sel(ndfp, dfp),
                    ts + jnp.where(commit, ds, 0),
                    tu + jnp.where(commit, du, 0),
                    jnp.stack([t_ovf, f_ovf, r_ovf, c_ovf]),
                    jnp.where(commit, jax.lax.psum(ncnt[0], "shards"), gcount),
                    hv_w,
                    hv_f,
                    hv_c,
                    g_hv_c,
                )

            carry0 = (
                jnp.int32(0),
                jnp.int32(0),
                frontier,
                f_ebits,
                count,
                table,
                disc_found,
                disc_fp,
                jnp.int32(0),
                jnp.int32(0),
                jnp.zeros((4,), jnp.bool_),
                jax.lax.psum(count[0], "shards"),
                jnp.zeros((n_hv, hv_cap, W), jnp.uint32),
                jnp.zeros((n_hv, hv_cap, 2), jnp.uint32),
                jnp.zeros((n_hv, 1), jnp.int32),
                jnp.zeros((n_hv,), jnp.int32),
            )
            out = jax.lax.while_loop(cond, body, carry0)
            # Drop the level counter, the global count and the replicated
            # hv count (the host reads the per-shard counts plane).
            return out[1:11] + out[12:15]

        TL = self._table_len()
        spec_rows = P("shards", None)
        spec_plane = P("shards")
        spec_rep = P()
        return self._shard_map(
            fused,
            in_specs=(
                spec_rows,
                spec_plane,
                spec_plane,
                (spec_plane,) * TL,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
            ),
            out_specs=(
                spec_rep,
                spec_rows,
                spec_plane,
                spec_plane,
                (spec_plane,) * TL,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                spec_rep,
                P(None, "shards", None),  # hv candidate words
                P(None, "shards", None),  # hv candidate fingerprints
                P(None, "shards"),  # hv per-shard counts ([n_hv, D])
            ),
        )

    def _superstep(self):
        key = (self._Fl, self._Cl, self._K)
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._build_superstep(*key)
            self._step_cache[key] = fn
        return fn

    def _fused(self):
        key = ("fused", self._Fl, self._Cl, self._K)
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._build_fused(self._Fl, self._Cl, self._K)
            self._step_cache[key] = fn
        return fn

    # --- host materialization ----------------------------------------------

    def _host_read(self, arr) -> np.ndarray:
        """Materialize a (possibly cross-process) sharded device array on
        every host. Single-process: a plain transfer. Multi-process (the
        ``jax.distributed`` DCN path): an allgather of addressable shards —
        ``np.asarray`` alone raises on arrays spanning non-addressable
        devices."""
        import jax

        if jax.process_count() == 1:
            return np.asarray(arr)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))

    def _counts_total(self) -> int:
        """Global frontier size: device-side psum, replicated output, so no
        host ever touches the sharded counts plane directly. The result is
        cached host-side (``_frontier_total_cache``) for passive readers —
        ``metrics()`` must never enqueue device work (a poll from one
        process of a multi-process mesh would desync SPMD program order)."""
        import jax
        import jax.numpy as jnp

        fn = self.__dict__.get("_counts_total_fn")
        if fn is None:
            fn = jax.jit(
                lambda c: jnp.sum(c, dtype=jnp.int32),
                out_shardings=self._rep_sharding,
            )
            self.__dict__["_counts_total_fn"] = fn
        total = int(np.asarray(fn(self._counts)))
        self._frontier_total_cache = total
        return total

    def _parent_map(self):
        """The single-chip walk over a gathered copy of the table planes
        (multi-process safe via ``_host_read``)."""
        from ..native import ParentMap

        return ParentMap(
            self._host_read(self._table.key_hi),
            self._host_read(self._table.key_lo),
            self._host_read(self._table.val_hi),
            self._host_read(self._table.val_lo),
        )

    # --- growth -----------------------------------------------------------

    def _grow_table_if_loaded(self) -> None:
        """Same proactive-growth policy as the single-chip engine
        (xla.py MAX_LOAD_* / SORTED_LOAD_*): hash partitions stay at or
        below 1/4 load so inserts never pay long probe chains; sorted
        partitions run denser (3/4) because their per-level cost is the
        sort of [capacity + batch], not probe rounds. Uniform fingerprint
        ownership keeps per-shard load within noise of the global figure."""
        from ..xla import XlaChecker

        if self._dedup == "hash":
            num, den = XlaChecker.MAX_LOAD_NUM, XlaChecker.MAX_LOAD_DEN
        else:
            # Both sort-based structures take the dense (3/4) rule, and the
            # capacity term mirrors xla.py's ``self._table.capacity``: for
            # the delta structure that includes the delta tier.
            num, den = XlaChecker.SORTED_LOAD_NUM, XlaChecker.SORTED_LOAD_DEN
        cap_l = self._Cl + (self._delta_cap() if self._dedup == "delta" else 0)
        while self._unique_count * den > self._D * cap_l * num:
            self._grow_table()
            cap_l = self._Cl + (
                self._delta_cap() if self._dedup == "delta" else 0
            )

    def _grow_table(self) -> None:
        with self._tracer.span(
            "grow_table", dedup=self._dedup, shards=self._D,
            capacity=self._D * self._Cl * 2,
        ):
            self._grow_table_impl()
        self._counters.inc("table_grows")

    def _grow_table_impl(self) -> None:
        """Double every shard's table partition (ownership is capacity-
        independent, so growth stays shard-local: a plane copy for the
        sorted structure, a rehash for the hash table)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        D, Cl = self._D, self._Cl
        old = self._table
        new_Cl = Cl * 2
        max_probes = self._max_probes

        if self._dedup == "delta":
            dc = self._delta_cap()
            # The minimum delta tier (1024) can out-hold a tiny main
            # partition: the doubled main must fit main + delta.
            new_Cl = 2 * max(Cl, dc)
            new_dc = deltaset._delta_cap(new_Cl)

            def grow_delta_local(planes):
                # Fold delta into a doubled main, shard-locally: one sort
                # of [Cl + dc] (tiers are disjoint, so merged keys are
                # unique); the delta tier resets at its rescaled size.
                mkh, mkl, mvh, mvl, dkh, dkl, dvh, dvl, nm, nd = planes
                full = jnp.uint32(0xFFFFFFFF)
                m_valid = jnp.arange(Cl) < nm[0]
                d_valid = jnp.arange(dc) < nd[0]
                kh = jnp.concatenate(
                    [jnp.where(m_valid, mkh, full), jnp.where(d_valid, dkh, full)]
                )
                kl = jnp.concatenate(
                    [jnp.where(m_valid, mkl, full), jnp.where(d_valid, dkl, full)]
                )
                vh = jnp.concatenate([mvh, dvh])
                vl = jnp.concatenate([mvl, dvl])
                skh, skl, svh, svl = jax.lax.sort((kh, kl, vh, vl), num_keys=2)
                n_new = nm[0] + nd[0]
                row_ok = jnp.arange(Cl + dc) < n_new
                z = jnp.uint32(0)
                pad = jnp.zeros((new_Cl - Cl - dc,), jnp.uint32)
                out = lambda a: jnp.concatenate([jnp.where(row_ok, a, z), pad])
                zd = jnp.zeros((new_dc,), jnp.uint32)
                return (
                    out(skh), out(skl), out(svh), out(svl),
                    zd, zd, zd, zd,
                    n_new[None], jnp.zeros((1,), jnp.int32),
                )

            fn = self._shard_map(
                grow_delta_local,
                in_specs=((P("shards"),) * 10,),
                out_specs=(P("shards"),) * 10,
            )
            planes = fn(tuple(self._table))
            self._table = deltaset.DeltaSet(
                *planes[:8], *(p.reshape(-1) for p in planes[8:])
            )
            self._Cl = new_Cl
            self._cap_hints()["table"] = D * new_Cl
            return

        if self._dedup == "sorted":

            def grow_local(planes):
                kh, kl, vh, vl, n = planes
                pad = jnp.zeros((Cl,), jnp.uint32)
                return (
                    jnp.concatenate([kh, pad]),
                    jnp.concatenate([kl, pad]),
                    jnp.concatenate([vh, pad]),
                    jnp.concatenate([vl, pad]),
                    n,
                )

            fn = self._shard_map(
                grow_local,
                in_specs=((P("shards"),) * 5,),
                out_specs=(P("shards"),) * 5,
            )
            self._table = sortedset.SortedSet(*fn(tuple(old)))
            self._Cl = new_Cl
            self._cap_hints()["table"] = D * new_Cl
            return

        def rehash(old_planes):
            kh, kl, vh, vl = old_planes
            occupied = (kh != 0) | (kl != 0)
            bigger = hashset.make(new_Cl, jnp)
            bigger, _, ovf = hashset.insert(
                bigger, kh, kl, vh, vl, occupied, max_probes=max_probes
            )
            # rank-1 so the per-shard scalar shards over the axis.
            return tuple(bigger), jnp.any(ovf)[None]

        fn = self._shard_map(
            rehash,
            in_specs=((P("shards"),) * 4,),
            out_specs=((P("shards"),) * 4, P("shards")),
        )
        planes, ovf = fn(tuple(old))
        if bool(np.any(self._host_read(ovf))):  # pragma: no cover
            raise RuntimeError("rehash overflow — pathological fingerprint distribution")
        self._table = hashset.HashSet(*planes)
        self._Cl = new_Cl
        self._cap_hints()["table"] = D * new_Cl

    def _grow_route(self) -> None:
        self._counters.inc("route_grows")
        self._K = min(self._Fl * self._A, self._K * 2)
        self._cap_hints()["route"] = self._K

    def _cap_hints(self) -> dict:
        return self._model.__dict__.setdefault(
            "_xla_sharded_cap_hints", {}
        ).setdefault(self._D, {})

    def _grow_frontier(self) -> None:
        self._counters.inc("frontier_grows")
        with self._tracer.span(
            "grow_frontier", shards=self._D, rows=self._D * self._Fl * 2
        ):
            self._grow_frontier_impl()

    def _grow_frontier_impl(self) -> None:
        """Double every shard's frontier rows, shard-locally on device (a
        host round-trip here would stall every growth event at scale)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        Fl, W = self._Fl, self._W
        new_Fl = Fl * 2

        def grow(rows, ebits):
            # Local blocks [Fl, W] / [Fl]: append zero rows per shard.
            return (
                jnp.concatenate([rows, jnp.zeros((Fl, W), jnp.uint32)]),
                jnp.concatenate([ebits, jnp.zeros((Fl,), jnp.uint32)]),
            )

        fn = self._shard_map(
            grow,
            in_specs=(P("shards", None), P("shards")),
            out_specs=(P("shards", None), P("shards")),
        )
        self._frontier, self._frontier_ebits = fn(
            self._frontier, self._frontier_ebits
        )
        self._Fl = new_Fl
        self._cap_hints()["frontier"] = self._D * new_Fl
        local_cand = self._Fl * self._A
        self._K = min(local_cand, max(self._K, (local_cand // self._D) * 4))

    # --- engine ------------------------------------------------------------

    def _run_block(self, max_count: int = 1500) -> None:
        if self._levels_per_dispatch > 1:
            return self._run_block_fused()
        return self._run_block_single()

    def _entry_checks(self) -> bool:
        """Shared dispatch preamble; returns False when nothing to run."""
        import numpy as np

        if self._target_reached or self._exhausted:
            return False
        if self._P > 0 and all(n in self._found_names for n in self._prop_names):
            return False
        if self._counts_total() == 0:
            self._exhausted = True
            return False
        self._max_depth = max(self._max_depth, self._depth)
        if self._target_max_depth is not None and self._depth >= self._target_max_depth:
            # Mirror the single-chip engine: a depth-halted checker reads as
            # frontier-empty to counters and checkpoint consumers alike.
            import jax.numpy as jnp

            self._counts = jnp.zeros_like(self._counts)
            self._exhausted = True
            return False
        return True

    def _raise_codec_overflow(self) -> None:
        raise RuntimeError(
            f"{type(self._model).__name__}: packed-codec capacity "
            "overflow — a reachable successor does not fit the "
            "model's declared field widths/slot counts (see "
            "stateright_tpu.packing)."
        )

    def _pin_found_names(self) -> None:
        found = np.asarray(self._disc_found)
        fps = np.asarray(self._disc_fp)
        for i, name in enumerate(self._prop_names):
            if found[i] and name not in self._found_names:
                self._found_names[name] = (int(fps[i, 0]) << 32) | int(fps[i, 1])

    def _confirm_hv_candidates(self, hv_w, hv_f, hv_c) -> None:
        with self._tracer.span("host_verify"):
            self._confirm_hv_impl(hv_w, hv_f, hv_c)

    def _confirm_hv_impl(self, hv_w, hv_f, hv_c) -> None:
        """Exact host-side re-check of device-flagged candidate states for
        host-verified properties — the single-chip contract
        (xla.py ``_confirm_hv_candidates``) over the mesh's allgathered
        candidate buffers. Confirmation order is shard-major (owner shard
        0's rows first): deterministic, but a different witness tiebreak
        than the single-chip engine's frontier order — the same documented
        divergence as ``pick_discovery``'s pmax election. Zero-fingerprint
        rows are padding (a real state never fingerprints to (0, 0))."""
        counts = self._host_read(hv_c)  # [n_hv, D]
        words = fps = None
        for j, i in enumerate(self._hv_idx):
            prop = self._properties[i]
            if prop.name in self._found_names:
                continue
            total = int(counts[j].sum())
            if total == 0:
                continue
            if words is None:
                words = self._host_read(hv_w)  # [n_hv, D*hv_cap, W]
                fps = self._host_read(hv_f)  # [n_hv, D*hv_cap, 2]
            confirmed = False
            collected = 0
            for r in range(words.shape[1]):
                fp_hi, fp_lo = int(fps[j, r, 0]), int(fps[j, r, 1])
                if fp_hi == 0 and fp_lo == 0:
                    continue
                collected += 1
                state = self._model.unpack(words[j, r])
                holds = bool(prop.condition(self._model, state))
                viol = (not holds) if prop.expectation == Expectation.ALWAYS else holds
                if viol:
                    self._found_names[prop.name] = (fp_hi << 32) | fp_lo
                    confirmed = True
                    break
            if not confirmed and total > collected:
                raise RuntimeError(
                    f"{total} candidate states for host-verified property "
                    f"{prop.name!r} in one super-step, none of the "
                    f"{collected} collected confirmed — tighten the "
                    "conservative device predicate or raise "
                    "spawn_xla(host_verified_cap=...)."
                )

    def _run_block_fused(self) -> None:
        """Up to ``levels_per_dispatch`` BFS levels in one SPMD dispatch
        (see ``_build_fused``); overflow exits commit the non-overflowing
        prefix, grow the overflowing buffer, and re-enter."""
        import jax.numpy as jnp

        if not self._entry_checks():
            return
        budget_left = self._levels_per_dispatch
        if self._target_max_depth is not None:
            budget_left = min(budget_left, self._target_max_depth - self._depth)
        retry = False  # re-entering after an overflow recovery
        while budget_left > 0:
            # Keep the block's int32 generated-state accumulator safe:
            # global candidates per level = D * Fl * A.
            kmax = max(1, (2**31 - 1) // max(self._D * self._Fl * self._A, 1))
            budget = min(budget_left, kmax)
            remaining = 2**31 - 1
            if self._target_state_count is not None:
                remaining = max(
                    1, min(remaining, self._target_state_count - self._state_count)
                )
            host_found = np.array(
                [n in self._found_names for n in self._prop_names], dtype=bool
            )
            n_cached = len(self._step_cache)
            fn = self._fused()
            fresh = len(self._step_cache) > n_cached
            run_rows = self._D * self._Fl
            if self._heartbeat is not None:
                self._heartbeat.beat(
                    "dispatch", compile=fresh, bucket=run_rows,
                    depth=self._depth, states=self._state_count,
                )
            with self._tracer.span(
                "dispatch", flavor="fused", bucket=run_rows,
                cand=self._D * self._K, compile=fresh, retry=retry,
                dedup=self._dedup, compaction="mesh", shards=self._D,
            ) as _sp:
                (
                    committed,
                    nf,
                    ne,
                    ncounts,
                    table,
                    dfound,
                    dfp,
                    tot_states,
                    tot_unique,
                    ovf,
                    hv_w,
                    hv_f,
                    hv_c,
                ) = fn(
                    self._frontier,
                    self._frontier_ebits,
                    self._counts,
                    tuple(self._table),
                    self._disc_found,
                    self._disc_fp,
                    jnp.int32(budget),
                    jnp.int32(remaining),
                    jnp.asarray(host_found),
                )
                committed = int(np.asarray(committed))
                _sp.set(committed=committed)
            self.dispatch_log.append((run_rows, committed))
            retry = False
            self._frontier, self._frontier_ebits = nf, ne
            self._counts = ncounts
            self._table = self._global_table(table)
            self._disc_found, self._disc_fp = dfound, dfp
            self._state_count += int(np.asarray(tot_states))
            self._unique_count += int(np.asarray(tot_unique))
            if self._heartbeat is not None:
                self._heartbeat.commit(
                    depth=self._depth + committed, states=self._state_count
                )
            self._depth += committed
            if committed:
                self._max_depth = max(self._max_depth, self._depth - 1)
            budget_left -= committed
            Cl_before = self._Cl
            self._grow_table_if_loaded()
            grew_proactively = self._Cl > Cl_before
            self._pin_found_names()
            if self._hv_idx:
                self._confirm_hv_candidates(hv_w, hv_f, hv_c)
            # Quiescent point: the committed prefix is fully reflected in
            # host-visible state.
            self._maybe_checkpoint()
            self._maybe_record()
            if (
                self._target_state_count is not None
                and self._state_count >= self._target_state_count
            ):
                self._target_reached = True
                return
            t_ovf, f_ovf, r_ovf, c_ovf = (bool(x) for x in np.asarray(ovf))
            if c_ovf:
                self._raise_codec_overflow()
            if t_ovf:
                # Only grow again if the proactive pass above did not just
                # double past the blockage (see xla.py).
                if not grew_proactively:
                    self._grow_table()
                retry = True
                continue
            if f_ovf:
                self._grow_frontier()
                retry = True
                continue
            if r_ovf:
                self._grow_route()
                retry = True
                continue
            if committed == 0:
                break
            if self._counts_total() == 0:
                break
            if self._P > 0 and all(
                n in self._found_names for n in self._prop_names
            ):
                break

    def _run_block_single(self) -> None:
        import numpy as np

        if not self._entry_checks():
            return
        if self._visitor is not None:
            self._visit_frontier()

        retry = False  # re-running the level after an overflow recovery
        while True:
            n_cached = len(self._step_cache)
            fn = self._superstep()
            fresh = len(self._step_cache) > n_cached
            run_rows = self._D * self._Fl
            if self._heartbeat is not None:
                self._heartbeat.beat(
                    "dispatch", compile=fresh, bucket=run_rows,
                    depth=self._depth, states=self._state_count,
                )
            with self._tracer.span(
                "dispatch", flavor="single", bucket=run_rows,
                cand=self._D * self._K, compile=fresh, retry=retry,
                dedup=self._dedup, compaction="mesh", shards=self._D,
            ) as _sp:
                out = fn(
                    self._frontier,
                    self._frontier_ebits,
                    self._counts,
                    tuple(self._table),
                    self._disc_found,
                    self._disc_fp,
                )
                (nf, ne, ncounts, table, dfound, dfp, d_states, d_unique,
                 t_ovf, f_ovf, r_ovf, c_ovf, hv_w, hv_f, hv_c) = out
                committed = not (
                    bool(np.asarray(t_ovf))
                    or bool(np.asarray(f_ovf))
                    or bool(np.asarray(r_ovf))
                )
                _sp.set(committed=int(committed))
            self.dispatch_log.append((run_rows, int(committed)))
            if self._heartbeat is not None:
                self._heartbeat.commit(
                    depth=self._depth, states=self._state_count
                )
            if bool(np.asarray(c_ovf)):
                self._raise_codec_overflow()
            if bool(np.asarray(t_ovf)):
                self._grow_table()
                retry = True
                continue
            if bool(np.asarray(f_ovf)):
                self._grow_frontier()
                retry = True
                continue
            if bool(np.asarray(r_ovf)):
                self._grow_route()
                retry = True
                continue
            break

        self._frontier, self._frontier_ebits = nf, ne
        self._counts = ncounts
        self._table = self._global_table(table)
        self._disc_found, self._disc_fp = dfound, dfp
        self._state_count += int(np.asarray(d_states))
        self._unique_count += int(np.asarray(d_unique))
        self._depth += 1
        self._grow_table_if_loaded()
        self._pin_found_names()
        if self._hv_idx:
            self._confirm_hv_candidates(hv_w, hv_f, hv_c)
        self._maybe_checkpoint()
        self._maybe_record()
        if (
            self._target_state_count is not None
            and self._state_count >= self._target_state_count
        ):
            self._target_reached = True

    def _visit_frontier(self) -> None:
        """Same visitor truncation contract as the single-chip engine: at
        most ``spawn_xla(visit_cap=...)`` states per level, loud warning."""
        rows = self._host_read(self._frontier).reshape(self._D, self._Fl, self._W)
        counts = self._host_read(self._counts)
        total = int(counts.sum())
        if total > self._visit_cap:
            import warnings

            warnings.warn(
                f"visitor: frontier has {total} states at depth {self._depth};"
                f" visiting only the first {self._visit_cap} (host-side path "
                "reconstruction per state does not scale — use visitors on "
                "small runs, or raise spawn_xla(visit_cap=...))",
                RuntimeWarning,
                stacklevel=2,
            )
        parents = self._parent_map()
        budget = self._visit_cap
        for d in range(self._D):
            for row in rows[d, : counts[d]]:
                if budget <= 0:
                    return
                budget -= 1
                fp = fphash.fingerprint_u64(
                    self._dedup_words_host(row[None, :])[0], np
                )
                self._visitor.visit(self._model, self._path_for(fp, parents))

    # --- Checker API -------------------------------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique_count

    def max_depth(self) -> int:
        return self._max_depth

    def metrics(self) -> Dict[str, Any]:
        """The mesh engine's unified telemetry snapshot — same contract
        as the single-chip ``XlaChecker.metrics()`` (stable key superset;
        docs/observability.md) plus mesh gauges (``shards``, per-shard
        capacities, route slots). Host-side reads only — frontier_count
        is the cached total from the last engine-driven reduction, never
        a fresh device dispatch (a poll from one process of a
        multi-process mesh would desync SPMD program order)."""
        import jax

        cap = self._D * (
            self._Cl + (self._delta_cap() if self._dedup == "delta" else 0)
        )
        return {
            "engine": "xla-sharded",
            "backend": jax.default_backend(),
            # -- configuration gauges ---------------------------------
            "dedup": self._dedup,
            "compaction": "mesh",
            "symmetry": self._sym_tag,
            "ladder": "none",
            "cand_ladder_k": 1,
            "shrink_exit": False,
            "levels_per_dispatch": self._levels_per_dispatch,
            "checkpoint_to": self._autockpt.path if self._autockpt else None,
            "metrics_to": self._recorder.path if self._recorder else None,
            # -- recovery gauges (docs/observability.md "Recovery") ----
            "resumed_from": self._resumed_from,
            "last_checkpoint_level": (
                self._last_checkpoint["depth"] if self._last_checkpoint else None
            ),
            "shards": self._D,
            "frontier_rows_per_shard": self._Fl,
            "table_slots_per_shard": self._Cl,
            "route_slots": self._K,
            # -- live search gauges -----------------------------------
            "state_count": self._state_count,
            "unique_state_count": self._unique_count,
            "depth": self._depth,
            "max_depth": self._max_depth,
            "frontier_count": self._frontier_total_cache,
            "frontier_capacity": self._D * self._Fl,
            "table_capacity": cap,
            "table_occupancy": self._unique_count / max(cap, 1),
            "dispatches": len(self.dispatch_log),
            "levels_committed": sum(c for _, c in self.dispatch_log),
            "cand_retries": 0,
            "hv": {},
            # -- event counters (obs.Counters, pre-seeded) ------------
            **self._counters.snapshot(),
        }

    def is_done(self) -> bool:
        if self._exhausted or self._target_reached:
            return True
        if self._P > 0 and all(n in self._found_names for n in self._prop_names):
            return True
        return self._counts_total() == 0 and self._state_count > 0

    def discoveries(self):
        parents = self._parent_map()
        return {
            name: self._path_for(fp64, parents)
            for name, fp64 in self._found_names.items()
        }
