"""Multi-chip scale-out for the XLA checker.

The reference scales with worker threads sharing a ``DashMap`` visited set
(``/root/reference/src/checker/bfs.rs:29-31, 89-211``). On a TPU slice the
equivalents are collectives over the ICI mesh (SURVEY.md §2.8):

- the **frontier** is sharded over the mesh's one axis,
- the **visited hash set** is sharded by *fingerprint ownership* — every
  64-bit fingerprint has exactly one owner shard, so dedup needs no locks
  and no replication,
- candidate states are routed to their owner with one ``all_to_all`` per
  super-step, and
- counters/discovery flags combine with ``psum`` (the analogue of the
  reference's shared atomics, bfs.rs:27-28).

Because children live wherever their fingerprint lands, frontier load
balances itself by hash uniformity — the data-parallel replacement for the
reference's work-sharing job market.

**Beyond one host**: the engine is expressed entirely as ``shard_map`` over
a one-axis ``Mesh``, so the multi-host path is JAX's standard one — call
``jax.distributed.initialize()`` on every process, build the mesh over
``jax.devices()`` (all hosts' chips), and the same programs run with XLA
routing the ``all_to_all``/``psum`` over ICI within a slice and DCN across
slices. The host-side driver state (counters, found-name pinning, growth
decisions) is derived from replicated scalars, so every controller process
takes identical decisions. Single-host multi-chip is what CI validates (the
8-device virtual CPU mesh in tests/conftest.py and the driver's
``dryrun_multichip``); true multi-host needs hardware this container does
not have.
"""

from .sharded import ShardedXlaChecker, default_mesh

__all__ = ["ShardedXlaChecker", "default_mesh"]
