"""Multi-chip scale-out for the XLA checker.

The reference scales with worker threads sharing a ``DashMap`` visited set
(``/root/reference/src/checker/bfs.rs:29-31, 89-211``). On a TPU slice the
equivalents are collectives over the ICI mesh (SURVEY.md §2.8):

- the **frontier** is sharded over the mesh's one axis,
- the **visited hash set** is sharded by *fingerprint ownership* — every
  64-bit fingerprint has exactly one owner shard, so dedup needs no locks
  and no replication,
- candidate states are routed to their owner with one ``all_to_all`` per
  super-step, and
- counters/discovery flags combine with ``psum`` (the analogue of the
  reference's shared atomics, bfs.rs:27-28).

Because children live wherever their fingerprint lands, frontier load
balances itself by hash uniformity — the data-parallel replacement for the
reference's work-sharing job market.
"""

from .sharded import ShardedXlaChecker, default_mesh

__all__ = ["ShardedXlaChecker", "default_mesh"]
