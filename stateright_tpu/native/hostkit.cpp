// Native host runtime for stateright_tpu.
//
// The device engine keeps the visited set in HBM as four uint32 planes
// (key_hi/key_lo -> parent_hi/parent_lo; see stateright_tpu/ops/hashset.py).
// Witness reconstruction and checkpointing pull those planes to the host,
// where the Python fallback builds a dict over every occupied slot — O(n)
// Python-object churn for tables with millions of entries. This library is
// the C++ equivalent of the reference's native engine surface
// (/root/reference is pure Rust; SURVEY.md section 2): an open-addressing
// index over the raw planes plus chain walking and batch fingerprinting,
// exposed through a C ABI consumed with ctypes (no pybind11 in this image).
//
// Everything here must stay bit-identical with the Python/JAX mirrors:
// fingerprint_words (ops/fphash.py) and the parent chains the checkers
// produce; differential tests enforce it.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Two-lane Zobrist-form fingerprint, the exact mirror of ops/fphash.py:
// per-word position-keyed fmix32 digests, XOR-folded across the width, one
// final avalanche over the seeded fold.
// ---------------------------------------------------------------------------

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

// words: [n, w] row-major uint32; out_hi/out_lo: [n]
void fingerprint_words(const uint32_t* words, int64_t n, int64_t w,
                       uint32_t* out_hi, uint32_t* out_lo) {
    for (int64_t r = 0; r < n; ++r) {
        uint32_t fold_hi = 0;
        uint32_t fold_lo = 0;
        const uint32_t* row = words + r * w;
        for (int64_t i = 0; i < w; ++i) {
            uint32_t word = row[i];
            uint32_t pos = (uint32_t)(i + 1);
            fold_hi ^= fmix32(word * 0x2545F491u + 0x9E3779B9u * pos);
            fold_lo ^= fmix32(word * 0x85157AF5u + 0x61C88647u * pos);
        }
        uint32_t hi = fmix32(fold_hi ^ 0x9E3779B9u);
        uint32_t lo = fmix32(fold_lo ^ 0x517CC1B7u);
        if (hi == 0 && lo == 0) lo = 1;  // reserve EMPTY sentinel
        if (hi == 0xFFFFFFFFu && lo == 0xFFFFFFFFu) lo = 0xFFFFFFFEu;  // reserve sorted-set pad key
        out_hi[r] = hi;
        out_lo[r] = lo;
    }
}

// ---------------------------------------------------------------------------
// Parent map: open-addressing index over the device table planes.
// ---------------------------------------------------------------------------

struct ParentMap {
    int64_t capacity;   // power of two
    uint64_t* keys;     // fp64, 0 == empty
    uint64_t* parents;  // parent fp64
    int64_t count;
};

static inline int64_t pm_slot(uint64_t key, int64_t mask) {
    // splitmix64 finalizer: uncorrelated with the device slot hash.
    uint64_t z = key + 0x9E3779B97F4A7C15ull;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    z ^= z >> 31;
    return (int64_t)(z & (uint64_t)mask);
}

// Build from the four planes; returns NULL only on allocation failure.
// Capacity is sized at 2x occupancy rounded up to a power of two.
ParentMap* parentmap_build(const uint32_t* key_hi, const uint32_t* key_lo,
                           const uint32_t* val_hi, const uint32_t* val_lo,
                           int64_t n_slots) {
    int64_t occupied = 0;
    for (int64_t i = 0; i < n_slots; ++i)
        if (key_hi[i] || key_lo[i]) ++occupied;
    int64_t cap = 64;
    while (cap < occupied * 2) cap <<= 1;

    ParentMap* pm = (ParentMap*)std::malloc(sizeof(ParentMap));
    if (!pm) return nullptr;
    pm->capacity = cap;
    pm->count = occupied;
    pm->keys = (uint64_t*)std::calloc((size_t)cap, sizeof(uint64_t));
    pm->parents = (uint64_t*)std::calloc((size_t)cap, sizeof(uint64_t));
    if (!pm->keys || !pm->parents) {
        std::free(pm->keys);
        std::free(pm->parents);
        std::free(pm);
        return nullptr;
    }
    int64_t mask = cap - 1;
    for (int64_t i = 0; i < n_slots; ++i) {
        if (!(key_hi[i] || key_lo[i])) continue;
        uint64_t key = ((uint64_t)key_hi[i] << 32) | key_lo[i];
        uint64_t par = ((uint64_t)val_hi[i] << 32) | val_lo[i];
        int64_t s = pm_slot(key, mask);
        while (pm->keys[s] != 0 && pm->keys[s] != key) s = (s + 1) & mask;
        pm->keys[s] = key;
        pm->parents[s] = par;
    }
    return pm;
}

void parentmap_free(ParentMap* pm) {
    if (!pm) return;
    std::free(pm->keys);
    std::free(pm->parents);
    std::free(pm);
}

int64_t parentmap_count(const ParentMap* pm) { return pm->count; }

// Look up one fingerprint; returns 1 and writes *parent on hit, 0 on miss.
int parentmap_get(const ParentMap* pm, uint64_t key, uint64_t* parent) {
    int64_t mask = pm->capacity - 1;
    int64_t s = pm_slot(key, mask);
    while (pm->keys[s] != 0) {
        if (pm->keys[s] == key) {
            *parent = pm->parents[s];
            return 1;
        }
        s = (s + 1) & mask;
    }
    return 0;
}

// Walk the parent chain from fp64 back to a zero parent (init marker).
// Writes up to max_len fingerprints (discovery first, init last) into out.
// Returns the chain length, -1 if a fingerprint is missing from the table
// (host/device codec drift), or -2 if the chain exceeds max_len (cycle in
// the parent pointers, which cannot happen for insert-once tables).
int64_t parentmap_chain(const ParentMap* pm, uint64_t fp64, uint64_t* out,
                        int64_t max_len) {
    int64_t len = 0;
    uint64_t cur = fp64;
    while (cur != 0) {
        if (len >= max_len) return -2;
        uint64_t parent;
        if (!parentmap_get(pm, cur, &parent)) return -1;
        out[len++] = cur;
        cur = parent;
    }
    return len;
}

}  // extern "C"
