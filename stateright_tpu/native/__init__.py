"""Native (C++) host runtime: fingerprinting and parent-map indexing.

The shared library builds lazily from ``hostkit.cpp`` on first import (g++,
no external deps; pybind11 is unavailable in this image so the binding is
ctypes over a C ABI). Everything degrades to the pure-Python mirrors when a
toolchain is missing, so the native layer is an accelerator, never a
requirement.

Exposed surface:

- :func:`available` — whether the library loaded.
- :func:`fingerprint_words` — batch two-lane fingerprints, bit-identical
  with ``ops/fphash.py`` (differentially tested).
- :class:`ParentMap` — open-addressing index over the device visited-set
  planes with O(1) lookup and native chain walking; replaces the Python
  dict built by the checkers' ``_parent_map`` for witness reconstruction.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "hostkit.cpp")
_LIB_PATH = os.path.join(_DIR, "libhostkit.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # Compile to a process-unique temp name and rename into place: rename is
    # atomic, so concurrent builders (or an interrupted compile) can never
    # leave a truncated .so behind.
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return False
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception:
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB_PATH) or os.path.getmtime(
            _LIB_PATH
        ) < os.path.getmtime(_SRC):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None

        u32p = ctypes.POINTER(ctypes.c_uint32)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.fingerprint_words.argtypes = [
            u32p,
            ctypes.c_int64,
            ctypes.c_int64,
            u32p,
            u32p,
        ]
        lib.fingerprint_words.restype = None
        lib.parentmap_build.argtypes = [u32p, u32p, u32p, u32p, ctypes.c_int64]
        lib.parentmap_build.restype = ctypes.c_void_p
        lib.parentmap_free.argtypes = [ctypes.c_void_p]
        lib.parentmap_free.restype = None
        lib.parentmap_count.argtypes = [ctypes.c_void_p]
        lib.parentmap_count.restype = ctypes.c_int64
        lib.parentmap_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64, u64p]
        lib.parentmap_get.restype = ctypes.c_int
        lib.parentmap_chain.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            u64p,
            ctypes.c_int64,
        ]
        lib.parentmap_chain.restype = ctypes.c_int64
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def _u32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def fingerprint_words(words: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Native mirror of ``ops/fphash.fingerprint_words`` for 2-D batches.

    Falls back to the numpy implementation when the library is missing.
    """
    words = np.ascontiguousarray(words, dtype=np.uint32)
    if words.ndim != 2:
        raise ValueError(f"expected [n, w] words, got shape {words.shape}")
    lib = _load()
    if lib is None:
        from ..ops import fphash

        return fphash.fingerprint_words(words, np)
    n, w = words.shape
    out_hi = np.empty(n, dtype=np.uint32)
    out_lo = np.empty(n, dtype=np.uint32)
    lib.fingerprint_words(_u32ptr(words), n, w, _u32ptr(out_hi), _u32ptr(out_lo))
    return out_hi, out_lo


class ParentMap:
    """Index over visited-set planes: fp64 -> parent fp64 (native when the
    toolchain allows, dict fallback otherwise).

    The planes are the hash set's ``key_hi/key_lo/val_hi/val_lo`` uint32
    arrays; empty slots are key == (0, 0).
    """

    def __init__(self, key_hi, key_lo, val_hi, val_lo):
        kh = np.ascontiguousarray(key_hi, dtype=np.uint32)
        kl = np.ascontiguousarray(key_lo, dtype=np.uint32)
        vh = np.ascontiguousarray(val_hi, dtype=np.uint32)
        vl = np.ascontiguousarray(val_lo, dtype=np.uint32)
        self._lib = _load()
        self._handle = None
        self._dict = None
        if self._lib is not None:
            handle = self._lib.parentmap_build(
                _u32ptr(kh), _u32ptr(kl), _u32ptr(vh), _u32ptr(vl), len(kh)
            )
            if handle:
                self._handle = handle
                return
        # Fallback: plain dict (the original Python path).
        occ = (kh != 0) | (kl != 0)
        keys = (kh[occ].astype(np.uint64) << np.uint64(32)) | kl[occ].astype(
            np.uint64
        )
        vals = (vh[occ].astype(np.uint64) << np.uint64(32)) | vl[occ].astype(
            np.uint64
        )
        self._dict = {int(k): int(v) for k, v in zip(keys, vals)}

    def __len__(self) -> int:
        if self._handle is not None:
            return int(self._lib.parentmap_count(self._handle))
        return len(self._dict)

    def __contains__(self, fp64: int) -> bool:
        return self.get(fp64) is not None

    def get(self, fp64: int) -> Optional[int]:
        if self._handle is not None:
            out = ctypes.c_uint64()
            hit = self._lib.parentmap_get(
                self._handle, ctypes.c_uint64(fp64), ctypes.byref(out)
            )
            return int(out.value) if hit else None
        return self._dict.get(fp64)

    def __getitem__(self, fp64: int) -> int:
        value = self.get(fp64)
        if value is None:
            raise KeyError(fp64)
        return value

    def chain(self, fp64: int, max_len: int = 1 << 24) -> list:
        """The parent chain [fp64, ..., init_fp]; raises KeyError if a link
        is missing (host/device codec drift) and RuntimeError on a cycle
        (chain longer than ``max_len``)."""
        if self._handle is not None:
            # Geometric buffer growth: chains are usually short (BFS depth).
            size = 1024
            while True:
                out = np.empty(size, dtype=np.uint64)
                n = self._lib.parentmap_chain(
                    self._handle,
                    ctypes.c_uint64(fp64),
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                    size,
                )
                if n == -1:
                    raise KeyError(
                        f"fingerprint {fp64:#x} missing from the visited table"
                    )
                if n == -2:
                    if size >= max_len:
                        raise RuntimeError("parent chain exceeds max_len")
                    size = min(size * 8, max_len)
                    continue
                return [int(x) for x in out[:n]]
        chain = []
        cur = fp64
        while cur != 0:
            if len(chain) >= max_len:
                raise RuntimeError("parent chain exceeds max_len")
            if cur not in self._dict:
                raise KeyError(
                    f"fingerprint {cur:#x} missing from the visited table"
                )
            chain.append(cur)
            cur = self._dict[cur]
        return chain

    def __del__(self):  # pragma: no cover - interpreter teardown
        if getattr(self, "_handle", None) is not None and self._lib is not None:
            try:
                self._lib.parentmap_free(self._handle)
            except Exception:
                pass
