"""stpu-lint rule registry, findings, and the waiver file.

Each rule ID names ONE pinned backend pathology (docs/backend_pathologies.md,
docs/static-analysis.md) that was root-caused on real hardware and is now
enforced mechanically instead of by tribal knowledge:

- STPU001-005 are jaxpr-level invariants checked against the lowered
  representation of every registered kernel surface
  (``stateright_tpu/analysis/surfaces.py``);
- STPU101-103 are AST-level project rules over the package source
  (``stateright_tpu/analysis/astlint.py``).

Findings that are KNOWN-correct exceptions are waived in
``.stpu-lint-waivers.toml`` at the repo root — every waiver carries a
one-line justification and matches findings by rule + glob patterns over
the surface name and file. An unmatched waiver is itself reported (a
stale waiver hides nothing but rots the record).

The waiver file is TOML restricted to ``[[waiver]]`` array-of-tables with
string values (this container runs Python 3.10 — no stdlib ``tomllib`` —
so :func:`_parse_waivers_toml` is a minimal parser for exactly that
subset, loud on anything else).
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Rule:
    id: str
    title: str
    #: Which pass owns it: "jaxpr" or "ast".
    kind: str
    #: The measured failure this rule pins (the "why", shown by
    #: ``--list-rules`` and docs/static-analysis.md).
    history: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            "STPU001",
            "no data-dependent scatter inside a vmapped model kernel",
            "jaxpr",
            "XLA:TPU silently DROPS data-dependent one-element scatters "
            "inside vmapped model kernels at batch >= 4096 (round-3/5 "
            "on-chip paxos count drift; bisected in tools/paxos_diag.py). "
            "Traced-index packed-field writes must lower one-hot via "
            "packing._word_update. Static-index scatters are exempt: XLA "
            "folds them and the pinned drift never reproduced there.",
        ),
        Rule(
            "STPU002",
            "no transpose fused into a vmapped kernel on the CPU path",
            "jaxpr",
            "XLA:CPU (jax 0.9.0 lineage) MIScompiles a transpose fused "
            "into a vmapped kernel: a scalar-cond jnp.where inside the "
            "kernel returns the wrong branch at batch >= 64, eager and "
            "jit disagree (_build_superstep_planes docstring). "
            "Rows-in/transpose-out is the safe fusion direction, so a "
            "kernel-surface jaxpr must not hand its outputs straight out "
            "of a transpose (the vmap out_axes != 0 shape).",
        ),
        Rule(
            "STPU003",
            "lax.sort operand count within the chip-proven width",
            "jaxpr",
            "A wide-W sort-mode grid compaction is a W+3-operand lax.sort "
            "whose XLA:TPU *compile* stalls for tens of minutes (round-5, "
            "paxos W=25: two bench workers lost at 28 operands), while "
            "narrow-W sort-family lowerings are chip-proven. The engine's "
            "auto policy caps sort-family compaction at state_words <= 8 "
            "(<= 12 sort operands); any surface carrying a wider sort "
            "re-introduces the stall shape.",
        ),
        Rule(
            "STPU004",
            "deltaset flush never under a lax.cond branch",
            "jaxpr",
            "A lax.cond carrying the main-capacity flush sort reproducibly "
            "FAULTS the XLA:TPU runtime ('TPU worker crashed - kernel "
            "fault', observed at 2^22 and 2^27 main tiers, round 5). The "
            "flush is the host-invoked maintain program through the "
            "overflow protocol; no cond/switch branch in a delta-dedup "
            "surface may contain a table-scale sort.",
        ),
        Rule(
            "STPU005",
            "Mosaic TC kernel rules + mandatory TPU lowering pre-flight",
            "jaxpr",
            "Mosaic TC kernels have no cumsum lowering, no u32<->f32 "
            "casts, and reject dynamic-offset vector stores (r5e first "
            "silicon; registry #6). Mosaic lowering runs host-side, so "
            "jit(f).trace(...).lower(lowering_platforms=('tpu',)) on CPU "
            "pre-flights every pallas kernel without a tunnel window - "
            "the pre-flight is mandatory for every kernel in ops/, and "
            "this rule also scans kernel jaxprs for the three shapes "
            "the r5e rework banned.",
        ),
        Rule(
            "STPU006",
            "Pallas kernel VMEM footprint within the per-core budget",
            "jaxpr",
            "An oversized block turns into a runtime Mosaic allocation "
            "error ON CHIP — after a tunnel window was already spent "
            "compiling it. The footprint is statically derivable from the "
            "pallas_call BlockSpecs/avals (blocked operands are "
            "double-buffered by the pipeline emitter, VMEM scratch is "
            "resident in full), so the flight-check prices every kernel "
            "across the supported STPU_PALLAS_BLOCK range against the "
            "~16 MiB/core v5e budget before any chip time is booked.",
        ),
        Rule(
            "STPU007",
            "compile-plan shape count within the declared budget",
            "jaxpr",
            "Compile time, not run time, burned the round-4/5 windows "
            "(paxos warm 47 s at 4 buckets on CPU; ~1 min per bucket over "
            "the tunnel; VERDICT item 6 lost a window to first-compile "
            "latency). The (bucket, cand-rung) schedule a run plan commits "
            "to is statically enumerable from the shared ladder planner "
            "(xla.ladder_buckets/cand_rungs), so a plan whose distinct "
            "program count blows the budget is a finding before it is a "
            "burned window. The census doubles as the warm-cache set "
            "(tools/warm_cache.py derives from it).",
        ),
        Rule(
            "STPU008",
            "no pathology-class op in only ONE backend's lowering",
            "jaxpr",
            "Both pinned miscompiles are the same structural class: an op "
            "the two backends lower DIFFERENTLY (TPU drops the vmapped "
            "scatter CPU executes; CPU miscompiles the fused transpose TPU "
            "runs fine). Lowering every kernel surface for both platforms "
            "from this CPU box (the STPU005 pre-flight trick) and diffing "
            "the StableHLO op inventories catches a registry-class op that "
            "appears on one side only — the shape where the backends have "
            "already disagreed twice.",
        ),
        Rule(
            "STPU101",
            "traced-index packed-field writes go through packing",
            "ast",
            "Direct .at[...].set/.add writes in model kernel code are the "
            "exact shape STPU001 exists for, caught at the source level "
            "before anything is traced: route them through "
            "packing.Layout.set / packing._word_update, which owns the "
            "backend-split (scatter on CPU, one-hot on accelerators).",
        ),
        Rule(
            "STPU102",
            "no bare jax.devices()/backend bring-up outside backend.py",
            "ast",
            "The axon TPU tunnel WEDGES instead of failing: jax.devices() "
            "blocks forever when the tunnel is down (CLAUDE.md gotcha #1). "
            "Backend bring-up belongs behind backend.ensure_live_backend / "
            "backend.guarded_main (probe subprocess + supervised re-exec); "
            "a bare call anywhere else re-opens the round-4 hang window.",
        ),
        Rule(
            "STPU103",
            "checkpoint/heartbeat files written atomically",
            "ast",
            "Checkpoints and heartbeats are read by watchdogs and resumed "
            "from after SIGKILL; a plain open(path, 'w') can be observed "
            "torn. checkpoint.py and obs/ own the tmp + os.replace "
            "pattern (payload sha256, rotation); writes to *checkpoint* / "
            "*heartbeat* paths outside them must go through those codecs.",
        ),
    )
}

#: STPU003's chip-proven ceiling: the widest sort-family lowering the
#: round-5 A/Bs measured healthy is the W=8 sort-compaction class
#: (key + W state planes + 3 payload lanes = 12 operands); the pinned
#: compile stall was at 28 (W=25). Conservative midpoint: anything
#: above 16 operands is the stall shape.
MAX_SAFE_SORT_OPERANDS = 16

#: STPU006's per-core VMEM budget: ~16 MiB on the v5e class this project
#: targets (the Pallas guide's memory-hierarchy table). The footprint
#: model charges blocked operands twice (the pipeline emitter
#: double-buffers them) and VMEM scratch in full; SMEM/semaphores/ANY
#: (HBM) operands are free.
VMEM_BUDGET_BYTES = 16 * 2**20

#: STPU007's default compile budget: distinct (bucket, rung-schedule)
#: programs a run plan may commit to. Every shipped plan sits at 3-4
#: buckets; 8 is the "a window will burn on compiles" line (~1 min per
#: bucket over the tunnel). A model may declare its own via an
#: ``xla_compile_budget`` attribute.
MAX_COMPILE_SHAPES = 8

#: STPU008's pathology registry: lowered-op classes a backend has
#: already miscompiled, dropped, or stalled on. An op from this set in
#: only ONE backend's StableHLO lowering of the same program is the
#: structural shape both pinned miscompiles belong to.
PATHOLOGY_LOWERING_OPS = (
    "stablehlo.scatter",          # the STPU001 dropped-write class
    "stablehlo.transpose",        # the STPU002 fused-transpose class
    "stablehlo.sort",             # the STPU003 compile-stall class
    "stablehlo.dynamic_update_slice",  # scatter's one-element sibling
    "stablehlo.select_and_scatter",
)


@dataclass
class Finding:
    rule: str
    #: Which registered surface (jaxpr pass) or file (AST pass) tripped.
    surface: str
    #: Repo-relative path and 1-based line of the best source anchor.
    file: str
    line: int
    message: str
    #: The lowered-op excerpt (jaxpr eqn) or source line that matched.
    excerpt: str
    waived: bool = False
    waiver_reason: str = ""

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "surface": self.surface,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "excerpt": self.excerpt,
            "waived": self.waived,
            "waiver_reason": self.waiver_reason,
        }

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "<no-source>"
        tag = " [waived: %s]" % self.waiver_reason if self.waived else ""
        out = f"{loc}: {self.rule} [{self.surface}] {self.message}{tag}"
        if self.excerpt:
            out += f"\n    | {self.excerpt}"
        return out


@dataclass
class Waiver:
    rule: str
    reason: str
    surface: str = "*"
    file: str = "*"
    #: Optional ``YYYY-MM-DD`` expiry. Past it the waiver STOPS
    #: suppressing (its findings go active) and it is reported like a
    #: stale one — so a chip-A/B-pending waiver cannot rot past its
    #: window. Empty = never expires.
    expires: str = ""
    used: int = field(default=0, compare=False)

    @property
    def expired(self) -> bool:
        if not self.expires:
            return False
        import datetime

        return (
            datetime.date.fromisoformat(self.expires)
            < datetime.date.today()
        )

    def matches(self, f: Finding) -> bool:
        return (
            not self.expired
            and f.rule == self.rule
            and fnmatch.fnmatchcase(f.surface, self.surface)
            and fnmatch.fnmatchcase(f.file, self.file)
        )


class WaiverError(ValueError):
    """Malformed waiver file — typed, so the CLI exits 2 (internal/config
    error), never silently ignoring a waiver that was meant to apply."""


def _parse_waivers_toml(text: str, path: str) -> List[Waiver]:
    """Minimal TOML subset parser: ``[[waiver]]`` tables of
    ``key = "string"`` pairs; comments and blank lines. Loud on anything
    else (Python 3.10 has no tomllib; this file format is ours)."""
    waivers: List[Waiver] = []
    current: Optional[dict] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            if current is not None:
                waivers.append(_finish_waiver(current, path))
            current = {"_line": lineno}
            continue
        if "=" in line and current is not None:
            key, _, val = line.partition("=")
            key = key.strip()
            val = val.strip()
            if key in ("rule", "reason", "surface", "file", "expires") and (
                len(val) >= 2 and val[0] == '"' and val[-1] == '"'
            ):
                current[key] = val[1:-1]
                continue
        raise WaiverError(
            f"{path}:{lineno}: unsupported waiver syntax {raw!r} "
            "(only [[waiver]] tables with rule/reason/surface/file/"
            'expires string keys, e.g. rule = "STPU001")'
        )
    if current is not None:
        waivers.append(_finish_waiver(current, path))
    return waivers


def _finish_waiver(d: dict, path: str) -> Waiver:
    line = d.pop("_line")
    if "rule" not in d or "reason" not in d:
        raise WaiverError(
            f"{path}:{line}: every [[waiver]] needs 'rule' and a "
            "one-line 'reason' justifying it"
        )
    if d["rule"] not in RULES:
        raise WaiverError(
            f"{path}:{line}: unknown rule {d['rule']!r}; "
            f"known: {sorted(RULES)}"
        )
    if not d["reason"].strip():
        raise WaiverError(f"{path}:{line}: empty waiver reason")
    if d.get("expires"):
        import datetime

        try:
            datetime.date.fromisoformat(d["expires"])
        except ValueError:
            raise WaiverError(
                f"{path}:{line}: expires must be YYYY-MM-DD, got "
                f"{d['expires']!r}"
            ) from None
    return Waiver(**d)


def load_waivers(path: Optional[str]) -> List[Waiver]:
    """Waivers from ``path`` (missing file = no waivers)."""
    if path is None or not os.path.exists(path):
        return []
    with open(path) as fh:
        return _parse_waivers_toml(fh.read(), path)


def apply_waivers(
    findings: List[Finding], waivers: List[Waiver]
) -> Tuple[List[Finding], List[Finding], List[Waiver]]:
    """Split findings into (active, waived); also return UNUSED waivers
    (stale entries worth pruning — reported, not fatal)."""
    active: List[Finding] = []
    waived: List[Finding] = []
    for f in findings:
        for w in waivers:
            if w.matches(f):
                f.waived = True
                f.waiver_reason = w.reason
                w.used += 1
                waived.append(f)
                break
        else:
            active.append(f)
    unused = [w for w in waivers if w.used == 0]
    return active, waived, unused
