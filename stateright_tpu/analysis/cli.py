"""stpu-lint orchestration and CLI (``python -m stateright_tpu.analysis``).

Runs entirely on the CPU backend with no device access and no program
execution: the jaxpr pass traces the registered surfaces
(``surfaces.py``), the AST pass parses the package source
(``astlint.py``), and findings are filtered through the waiver file
(``rules.py``). Exit codes for CI:

- 0 — clean (waived findings allowed; they are reported, not counted),
- 1 — unwaived findings,
- 2 — infrastructure error (a surface failed to trace, malformed waiver
  file): the tree was NOT verified.

``--json`` / ``--json-out`` emit the machine-readable report
(``tools/smoke.sh`` writes ``runs/lint.json``; ``bench.py`` records its
verdict as ``lint_ok`` provenance in ``bench_detail.json``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .rules import RULES, Finding, WaiverError, apply_waivers, load_waivers

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_WAIVERS = os.path.join(_REPO, ".stpu-lint-waivers.toml")


def run_lint(
    *,
    trace: bool = True,
    ast_pass: bool = True,
    full: bool = False,
    only: Optional[List[str]] = None,
    rules: Optional[List[str]] = None,
    waivers_path: Optional[str] = DEFAULT_WAIVERS,
    admission: Optional[str] = None,
    use_cache: bool = True,
    cache_dir: Optional[str] = None,
) -> dict:
    """The whole lint as a dict report (the CLI's JSON schema; tests,
    bench, and the service's admission gate consume this directly).
    ``admission`` swaps the sweep for one spec's flight-check subset
    (kernel rules + lowering diff + compile-plan census — see
    ``surfaces.build_admission_sweep``); the AST pass is whole-package
    and is skipped there."""
    t0 = time.monotonic()
    waivers = load_waivers(waivers_path)
    if admission is not None:
        ast_pass = False

    findings: List[Finding] = []
    surfaces = []
    errors: List[str] = []
    # A --rules filter naming only AST rules never needs the (much
    # slower) jaxpr sweep; same for jaxpr-only filters and the AST pass.
    if rules:
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            raise ValueError(
                f"unknown rule(s) {unknown}; known: {sorted(RULES)}"
            )
        kinds = {RULES[r].kind for r in rules}
        trace = trace and "jaxpr" in kinds
        ast_pass = ast_pass and "ast" in kinds
    if trace:
        from .surfaces import run_sweep

        for rep in run_sweep(
            full=full,
            only=only,
            admission_spec=admission,
            use_cache=use_cache,
            cache_dir=cache_dir,
        ):
            surfaces.append(
                {
                    "name": rep.name,
                    "seconds": rep.seconds,
                    "findings": len(rep.findings),
                    "error": rep.error,
                    "skipped": rep.skipped,
                    "cached": rep.cached,
                }
            )
            findings.extend(rep.findings)
            if rep.error:
                errors.append(f"{rep.name}: {rep.error}")
    if ast_pass:
        from .astlint import run_ast_pass

        findings.extend(run_ast_pass())

    if rules:
        keep = set(rules)
        findings = [f for f in findings if f.rule in keep]

    active, waived, unused = apply_waivers(findings, waivers)
    # An EXPIRED waiver stopped suppressing (its findings are active
    # above) and is always reported — unlike merely-stale ones it is
    # actionable on any run, partial or not.
    expired = [w for w in unused if w.expired]
    # A filtered run is PARTIAL: its verdict covers only what it swept.
    # Stale-waiver detection is suppressed (a live waiver's findings may
    # simply never have fired), and the flag rides in the report so
    # provenance consumers (bench.py's lint_ok) never mistake a
    # --only/--rules iteration artifact for a full-tree verdict.
    partial = bool(
        rules or only or admission or not (trace and ast_pass)
    )
    if partial:
        unused = expired
    return {
        "ok": not active and not errors,
        "partial": partial,
        "admission": admission,
        "elapsed_s": round(time.monotonic() - t0, 2),
        "surfaces": surfaces,
        "findings": [f.to_json() for f in active],
        "waived": [f.to_json() for f in waived],
        "unused_waivers": [
            {
                "rule": w.rule,
                "surface": w.surface,
                "file": w.file,
                "reason": w.reason,
                "expires": w.expires,
                "expired": w.expired,
            }
            for w in unused
        ],
        "errors": errors,
        "rules": {r.id: r.title for r in RULES.values()},
    }


def _print_human(report: dict) -> None:
    for f in report["findings"] + report["waived"]:
        print(Finding(**{k: f[k] for k in (
            "rule", "surface", "file", "line", "message", "excerpt",
            "waived", "waiver_reason")}).format())
    for e in report["errors"]:
        print(f"ERROR: {e}")
    for s in report["surfaces"]:
        if s.get("skipped"):
            print(f"skipped {s['name']}: {s['skipped']}")
    for w in report["unused_waivers"]:
        if w.get("expired"):
            print(
                f"EXPIRED waiver (no longer suppressing since "
                f"{w['expires']}): {w['rule']} surface={w['surface']!r} "
                f"file={w['file']!r} — renew with a fresh justification "
                "or fix the finding"
            )
        else:
            print(
                f"stale waiver (matched nothing): {w['rule']} "
                f"surface={w['surface']!r} file={w['file']!r} — prune it"
            )
    n_surf = len(report["surfaces"])
    n_cached = sum(1 for s in report["surfaces"] if s.get("cached"))
    print(
        f"stpu-lint: {n_surf} surfaces ({n_cached} cached), "
        f"{len(report['findings'])} finding(s), "
        f"{len(report['waived'])} waived, "
        f"{len(report['errors'])} error(s) "
        f"in {report['elapsed_s']}s -> "
        + ("OK" if report["ok"] else "FAIL")
    )


def write_sarif(report: dict, path: str) -> None:
    """The report as SARIF 2.1.0 (code-scanning annotations: one result
    per finding, waived ones at ``note`` level)."""
    results = []
    for f, level in [(f, "error") for f in report["findings"]] + [
        (f, "note") for f in report["waived"]
    ]:
        msg = f["message"]
        if f.get("waiver_reason"):
            msg += f" [waived: {f['waiver_reason']}]"
        result = {
            "ruleId": f["rule"],
            "level": level,
            "message": {"text": f"[{f['surface']}] {msg}"},
        }
        if f["file"]:
            result["locations"] = [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f["file"]},
                        "region": {"startLine": max(f["line"], 1)},
                    }
                }
            ]
        results.append(result)
    sarif = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "stpu-lint",
                        "informationUri": "docs/static-analysis.md",
                        "rules": [
                            {
                                "id": r.id,
                                "shortDescription": {"text": r.title},
                                "fullDescription": {"text": r.history},
                            }
                            for r in RULES.values()
                        ],
                    }
                },
                "results": results,
            }
        ],
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(sarif, fh, indent=1)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m stateright_tpu.analysis",
        description=(
            "stpu-lint: mechanically enforce the pinned backend-"
            "miscompile rules over every shipped kernel surface "
            "(docs/static-analysis.md)"
        ),
    )
    p.add_argument("--json", action="store_true", help="JSON report on stdout")
    p.add_argument("--json-out", metavar="PATH", help="also write the JSON report here")
    p.add_argument(
        "--rules",
        metavar="IDS",
        help="comma-separated rule filter, e.g. STPU001,STPU003",
    )
    p.add_argument(
        "--only",
        metavar="SUBSTR",
        action="append",
        help="only surfaces whose name contains SUBSTR (repeatable)",
    )
    p.add_argument(
        "--waivers",
        default=DEFAULT_WAIVERS,
        help="waiver file (default: .stpu-lint-waivers.toml at repo root)",
    )
    p.add_argument(
        "--no-trace", action="store_true", help="skip the jaxpr surface sweep"
    )
    p.add_argument("--no-ast", action="store_true", help="skip the AST pass")
    p.add_argument(
        "--full",
        action="store_true",
        help="full config matrix for every spec (slower; default sweeps "
        "the matrix on one narrow + one wide model)",
    )
    p.add_argument(
        "--admission",
        metavar="SPEC",
        help="one spec's admission flight-check (kernel rules + lowering "
        "diff + compile-plan census) — what CheckerService runs at "
        "submit; implies a partial report",
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help="ignore the content-hash per-surface result cache "
        "(runs/lint_cache) and re-trace everything",
    )
    p.add_argument(
        "--sarif",
        metavar="PATH",
        help="also write the report as SARIF 2.1.0 (code-scanning "
        "annotations)",
    )
    p.add_argument(
        "--census-out",
        metavar="PATH",
        default=os.path.join(_REPO, "runs", "compile_plan.json"),
        help="where a full run writes the STPU007 compile-plan census "
        "(default: runs/compile_plan.json)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule registry and exit"
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id} [{r.kind}] {r.title}\n    {r.history}\n")
        return 0

    rules = None
    if args.rules:
        rules = [s.strip() for s in args.rules.split(",") if s.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {unknown}; known: {sorted(RULES)}", file=sys.stderr)
            return 2

    try:
        report = run_lint(
            trace=not args.no_trace,
            ast_pass=not args.no_ast,
            full=args.full,
            only=args.only,
            rules=rules,
            waivers_path=args.waivers,
            admission=args.admission,
            use_cache=not args.no_cache,
        )
    except WaiverError as e:
        print(f"waiver file error: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        # Typed caller bugs (unknown --admission spec, bad STPU_FAMILIES
        # entry): infrastructure verdict, the tree was not verified.
        # With --json the verdict still goes to stdout as a parseable
        # not-ok report — the service's admission gate must REJECT a
        # spec that cannot even resolve (a spec defect), not fail open
        # as if the lint tool itself had crashed.
        print(f"stpu-lint error: {e}", file=sys.stderr)
        if args.json:
            json.dump(
                {
                    "ok": False,
                    "partial": True,
                    "admission": args.admission,
                    "surfaces": [],
                    "findings": [],
                    "waived": [],
                    "unused_waivers": [],
                    "errors": [f"{type(e).__name__}: {e}"],
                },
                sys.stdout,
                indent=1,
            )
            print()
        return 2

    # A CLEAN full (non-partial, traced) run banks the STPU007 census as
    # the compile-plan artifact — the warm-cache set and bench
    # provenance read it (docs/static-analysis.md). A failing or
    # erroring run banks nothing (the artifact describes a verified
    # tree), and a census-build crash must not eat the lint report or
    # the exit-code contract — the sweep's verdict stands either way.
    if (
        report["ok"]
        and not report["partial"]
        and not args.no_trace
        and args.census_out
    ):
        try:
            from .cache import tree_hash
            from .census import build_census

            census = build_census()
            census["tree"] = tree_hash()[:12]
            census["generated_unix_ts"] = time.time()
            os.makedirs(
                os.path.dirname(os.path.abspath(args.census_out)),
                exist_ok=True,
            )
            with open(args.census_out, "w") as fh:
                json.dump(census, fh, indent=1)
        except Exception as e:
            print(f"census bank failed: {e}", file=sys.stderr)

    if args.json_out:
        os.makedirs(os.path.dirname(os.path.abspath(args.json_out)), exist_ok=True)
        with open(args.json_out, "w") as fh:
            json.dump(report, fh, indent=1)
    if args.sarif:
        write_sarif(report, args.sarif)
    if args.json:
        json.dump(report, sys.stdout, indent=1)
        print()
    else:
        _print_human(report)

    if report["errors"]:
        return 2
    return 0 if report["ok"] else 1
