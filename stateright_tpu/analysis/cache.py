"""Content-hash-keyed per-surface result cache for the lint sweep.

The sweep's cost is tracing (and, for STPU008, dual-platform lowering) —
pure functions of the package source. One ``tree_hash`` over every
``stateright_tpu/**/*.py`` keys the whole cache: any source edit
invalidates everything (conservative but correct — a surface's traced
program can depend on any module), while repeat runs on an unchanged
tree (the common smoke.sh / admission case) replay findings from disk in
milliseconds. The waiver file is deliberately NOT in the hash: waivers
are applied after the sweep, to raw findings, so cached findings stay
valid across waiver edits.

Entries live under ``runs/lint_cache/<tree12>/<slug>.json`` (``runs/``
is gitignored); growth is bounded to the NEWEST ``KEEP_TREES`` tree
dirs (by mtime; ``STPU_LINT_CACHE_KEEP`` overrides) — pruned at lint
startup and on write, so per-commit content-hash dirs never accumulate
while a couple of recent trees (branch switches, A/B edits) stay warm.
``--no-cache`` forces a fresh sweep; surfaces that ERRORED or SKIPPED
are never cached (an environment verdict is not a tree verdict).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import List, Optional

from .rules import Finding

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)
DEFAULT_CACHE_DIR = os.path.join(_REPO, "runs", "lint_cache")

#: Newest tree dirs retained (per-commit content hashes would otherwise
#: accumulate forever on a long-lived box); env STPU_LINT_CACHE_KEEP.
KEEP_TREES = 4

_tree_hash_memo: Optional[str] = None


def tree_hash(root: str = _PKG) -> str:
    """sha256 over every package source file (path + content), memoized
    per process — the key under which cached surface results are valid."""
    global _tree_hash_memo
    if _tree_hash_memo is not None and root == _PKG:
        return _tree_hash_memo
    h = hashlib.sha256()
    # The jaxpr/lowering verdicts are functions of the installed jax
    # too, not just this tree: a jax upgrade must invalidate cached
    # STPU005 pre-flights and STPU008 inventories. (jax is already
    # imported by this container's sitecustomize in every process, so
    # this costs nothing and initializes no backend.)
    try:
        import jax

        h.update(jax.__version__.encode())
    except Exception:  # pragma: no cover - jax-less caller
        pass
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as fh:
                h.update(fh.read())
    digest = h.hexdigest()
    if root == _PKG:
        _tree_hash_memo = digest
    return digest


def _slug(surface: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", surface)


class SurfaceCache:
    """get/put of raw (pre-waiver) surface findings under one tree hash,
    bounded to the newest :data:`KEEP_TREES` tree dirs."""

    def __init__(self, cache_dir: Optional[str] = None,
                 keep_trees: Optional[int] = None):
        self.root = cache_dir or DEFAULT_CACHE_DIR
        self.tree = tree_hash()[:12]
        self.dir = os.path.join(self.root, self.tree)
        if keep_trees is None:
            try:
                keep_trees = int(
                    os.environ.get("STPU_LINT_CACHE_KEEP", KEEP_TREES)
                )
            except ValueError:
                keep_trees = KEEP_TREES
        self.keep_trees = max(1, keep_trees)
        # Prune at startup too, not just on write: a lint run on an
        # unchanged tree (all hits, no puts) must still bound the cache.
        self._prune()

    def _prune(self) -> None:
        """Delete all but the newest ``keep_trees`` tree dirs (by mtime;
        the current tree always counts as newest — a warm hit must never
        prune the entries it is about to read)."""
        try:
            others = sorted(
                (
                    d for d in os.listdir(self.root)
                    if d != self.tree
                    and os.path.isdir(os.path.join(self.root, d))
                ),
                key=lambda d: os.path.getmtime(os.path.join(self.root, d)),
                reverse=True,
            )
        except OSError:  # pragma: no cover - cache is best-effort
            return
        for d in others[self.keep_trees - 1:]:
            shutil.rmtree(os.path.join(self.root, d), ignore_errors=True)

    def get(self, surface: str) -> Optional[List[Finding]]:
        path = os.path.join(self.dir, _slug(surface) + ".json")
        try:
            with open(path) as fh:
                rows = json.load(fh)["findings"]
        except (OSError, json.JSONDecodeError, KeyError):
            return None
        try:
            return [
                Finding(**{k: r[k] for k in (
                    "rule", "surface", "file", "line", "message", "excerpt"
                )})
                for r in rows
            ]
        except (KeyError, TypeError):
            return None

    def put(self, surface: str, findings: List[Finding]) -> None:
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError:  # pragma: no cover - cache is best-effort
            return
        payload = {
            "findings": [
                {k: v for k, v in f.to_json().items()
                 if k not in ("waived", "waiver_reason")}
                for f in findings
            ]
        }
        tmp = os.path.join(self.dir, _slug(surface) + ".json.tmp")
        try:
            with open(tmp, "w") as fh:
                json.dump(payload, fh)
            os.replace(tmp, os.path.join(self.dir, _slug(surface) + ".json"))
        except OSError:  # pragma: no cover - cache is best-effort
            pass
