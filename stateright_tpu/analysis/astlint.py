"""The AST lint pass: STPU101-103 project rules over the package source.

These are source-level rules — cheaper than tracing and catching the
pinned shapes before they ever reach a jaxpr. The pass parses every
``.py`` under ``stateright_tpu/`` (no imports, no execution) and walks
the ASTs once.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Tuple

from .rules import Finding

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)

#: ``.at[...].<method>`` indexed-update methods STPU101 flags in model
#: kernel code.
_AT_METHODS = frozenset(
    {"set", "add", "multiply", "mul", "divide", "min", "max", "apply", "power"}
)

#: Backend bring-up calls STPU102 reserves for backend.py's guarded
#: paths (the wedge-probe rule).
_BRINGUP_ATTRS = frozenset({"devices", "local_devices"})

#: Path-name fragments that mark a write target as a checkpoint or
#: heartbeat artifact for STPU103.
_DURABLE_HINTS = ("heartbeat", "checkpoint", "ckpt", "hb_path", "hb_file")


def iter_sources(root: str = _PKG) -> Iterator[Tuple[str, str]]:
    """``(abs_path, rel_path)`` for every package source file."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                yield p, os.path.relpath(p, _REPO)


def _line_of(src_lines: List[str], node: ast.AST) -> str:
    i = getattr(node, "lineno", 0)
    if 1 <= i <= len(src_lines):
        return src_lines[i - 1].strip()
    return ""


def _is_at_update(node: ast.Call) -> bool:
    """``X.at[IDX].set(...)`` and friends."""
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in _AT_METHODS
        and isinstance(f.value, ast.Subscript)
        and isinstance(f.value.value, ast.Attribute)
        and f.value.value.attr == "at"
    )


def _is_backend_bringup(node: ast.Call) -> bool:
    """``<anything>.devices()`` / ``.local_devices()`` — in this package
    the receiver is always a jax module object (``jax`` or a stored
    ``self._jax``), and no other library in the tree shares the name."""
    f = node.func
    return isinstance(f, ast.Attribute) and f.attr in _BRINGUP_ATTRS


def _open_write_target(node: ast.Call) -> str:
    """For ``open(path, mode)`` calls whose mode writes, the unparsed
    path expression; '' otherwise."""
    f = node.func
    if not (isinstance(f, ast.Name) and f.id == "open"):
        return ""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return ""
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return ""
    if not any(c in mode.value for c in "wa+x"):
        return ""
    if not node.args:
        return ""
    try:
        return ast.unparse(node.args[0])
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return ""


def lint_file(path: str, rel: str) -> List[Finding]:
    with open(path) as fh:
        src = fh.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:  # pragma: no cover - tree is import-clean
        return [
            Finding(
                rule="STPU101",
                surface=f"ast:{rel}",
                file=rel,
                line=e.lineno or 0,
                message=f"source failed to parse: {e.msg}",
                excerpt="",
            )
        ]
    lines = src.splitlines()
    in_models = f"{os.sep}models{os.sep}" in path
    in_backend = os.path.basename(path) == "backend.py"
    in_durable_owner = (
        os.path.basename(path) == "checkpoint.py"
        or f"{os.sep}obs{os.sep}" in path
    )
    in_analysis = f"{os.sep}analysis{os.sep}" in path

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if in_models and _is_at_update(node):
            out.append(
                Finding(
                    rule="STPU101",
                    surface=f"ast:{rel}",
                    file=rel,
                    line=node.lineno,
                    message=(
                        "direct .at[...] indexed write in model kernel "
                        "code: route it through packing.Layout.set / "
                        "packing._word_update (owns the CPU-scatter vs "
                        "accelerator-one-hot split; STPU001's source "
                        "form)"
                    ),
                    excerpt=_line_of(lines, node),
                )
            )
        if not in_backend and not in_analysis and _is_backend_bringup(node):
            out.append(
                Finding(
                    rule="STPU102",
                    surface=f"ast:{rel}",
                    file=rel,
                    line=node.lineno,
                    message=(
                        "bare backend bring-up (jax.devices-class call) "
                        "outside backend.py: the tunnel WEDGES instead "
                        "of failing — use backend.ensure_live_backend / "
                        "backend.guarded_main, or justify a waiver"
                    ),
                    excerpt=_line_of(lines, node),
                )
            )
        if not in_durable_owner and not in_analysis:
            target = _open_write_target(node)
            if target and any(h in target.lower() for h in _DURABLE_HINTS):
                out.append(
                    Finding(
                        rule="STPU103",
                        surface=f"ast:{rel}",
                        file=rel,
                        line=node.lineno,
                        message=(
                            "non-atomic write to a checkpoint/heartbeat "
                            "path outside checkpoint.py/obs/: watchdogs "
                            "and resume can observe a torn file — write "
                            "through the owning codec (tmp + os.replace)"
                        ),
                        excerpt=_line_of(lines, node),
                    )
                )
    return out


def run_ast_pass(root: str = _PKG) -> List[Finding]:
    findings: List[Finding] = []
    for path, rel in iter_sources(root):
        findings.extend(lint_file(path, rel))
    return findings
