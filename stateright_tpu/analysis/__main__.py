import sys

from .cli import main

if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. `... --list-rules | head`
        sys.exit(0)
