"""STPU007: the compile-plan census.

Every distinct (bucket, cand-rung schedule) is a separate XLA
compilation — ~1 min each over the tunnel — and compile latency, not run
time, is what burned the round-4/5 windows (VERDICT item 6). The ladder
planner that decides those shapes is now ONE shared definition
(``xla.ladder_buckets`` / ``default_cand_cap`` / ``cand_rungs`` — the
engine delegates to the same functions), so the exact program shapes a
model's run plan will compile are statically enumerable with no tracing
and no device:

- :func:`plan_for` — one spec's plan on one platform: resolved dedup /
  compaction (the same policy ``XlaChecker.__init__`` applies), the
  bucket ladder for the registry capacities, and each bucket's fused
  rung schedule;
- :func:`build_census` — the full shipped census, keyed by spec; the CLI
  writes it to ``runs/compile_plan.json`` on every full run, and
  ``tools/warm_cache.py`` derives its warm set from it (the warm set is
  DERIVED, not a second hand-maintained shape list — a census/SHIPPED
  drift is a test failure, ``tests/test_analysis.py``);
- :func:`census_findings` — STPU007 proper: a plan whose distinct shape
  count blows its budget (``rules.MAX_COMPILE_SHAPES``, or the model's
  own ``xla_compile_budget`` attribute) is a finding before it is a
  burned window.

The census is hermetic: candidate-cap sizing ignores the caller's
``STPU_CAND_FRAC`` (an empty env is passed through), so the artifact
describes the TREE's plan, not the shell's.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..xla import (
    CAND_LADDER_AUTO_K,
    accel_auto_compaction,
    auto_dedup,
    cand_rungs,
    default_cand_cap,
    ladder_buckets,
)
from .rules import MAX_COMPILE_SHAPES, Finding

#: The platforms a shipped plan is enumerated for: the CPU policy (hash
#: dedup, gather compaction, no cand ladder) and the accelerator policy
#: (sorted dedup, width-resolved compaction, auto-depth cand ladder).
PLATFORMS = ("cpu", "tpu")


def plan_for(
    spec: str,
    platform: str,
    *,
    frontier_capacity: Optional[int] = None,
    table_capacity: Optional[int] = None,
    mux_k: Optional[int] = None,
    symmetry: bool = False,
    _resolved=None,
) -> Dict[str, Any]:
    """The compile plan one spec commits to on one platform, at the
    registry's shipped capacities (override for what-if probes and the
    golden-bad tests). Growth events (frontier/table doubling) are
    excluded: the census prices the DECLARED plan, which is also exactly
    the shape set ``tools/warm_cache.py`` can pre-compile.
    ``_resolved`` lets :func:`build_census` resolve each spec's model
    once instead of once per platform.

    ``mux_k`` adds the multiplexed-superstep shape classes a service
    running with ``STPU_MUX=K`` would additionally compile
    (``xla_mux.py``; docs/service.md "Batched scheduling"): one batched
    program per bucket at lane count K — the mux engine has no in-program
    cand ladder, so its shape class is exactly ``(k, bucket, cand_cap)``.
    Only mux-eligible plans get the sub-dict (family in
    ``registry.MUX_FAMILIES``, non-delta dedup); when present, the mux
    programs count toward the same STPU007 budget — batching is opt-in,
    so the default census (and the banked ``runs/compile_plan.json``)
    stays the solo plan.

    ``symmetry`` adds the symmetry-variant shape classes
    (docs/symmetry.md): every bucket program recompiles under the
    canonicalization tag in its cache key when ``STPU_SYMMETRY=1``, so a
    symmetry-on service doubles the plan. Only models shipping a
    ``symmetry_spec`` (or ``packed_representative``) get the ``sym``
    sub-dict; it counts toward the same STPU007 budget, and — like mux —
    the default census stays the symmetry-off plan."""
    if _resolved is None:
        from ..service.registry import resolve

        _resolved = resolve(spec)
    model, caps = _resolved
    W, A = model.state_words, model.max_actions
    f_cap = frontier_capacity or caps["frontier_capacity"]
    t_cap = table_capacity or caps["table_capacity"]
    # The same policy resolution XlaChecker.__init__ applies (minus env
    # A/B knobs — the census is hermetic): every constant here is the
    # ENGINE's export, so a policy change re-aims the census with it.
    dedup = auto_dedup(platform)
    compaction = "gather" if platform == "cpu" else accel_auto_compaction(W)
    k = 1 if dedup == "hash" else CAND_LADDER_AUTO_K

    def cap_of(rc: int) -> int:
        return default_cand_cap(rc, A, platform, env={})

    shapes: List[Dict[str, Any]] = []
    for bucket in ladder_buckets(f_cap):
        shapes.append(
            {
                "bucket": bucket,
                "cand_cap": cap_of(bucket),
                "rungs": [list(r) for r in cand_rungs(bucket, cap_of, k)],
            }
        )
    plan = {
        "spec": spec,
        "platform": platform,
        "state_words": W,
        "max_actions": A,
        "dedup": dedup,
        "compaction": compaction,
        "frontier_capacity": f_cap,
        "table_capacity": t_cap,
        "shapes": shapes,
        "distinct_programs": len(shapes),
        "budget": int(getattr(model, "xla_compile_budget", MAX_COMPILE_SHAPES)),
    }
    if symmetry:
        spec_obj = getattr(model, "symmetry_spec", None)
        tag = (
            f"spec:{spec_obj.spec_hash()[:12]}"
            if spec_obj is not None
            else (
                "model:packed_representative"
                if hasattr(model, "packed_representative")
                else None
            )
        )
        if tag is not None:
            plan["sym"] = {
                "tag": tag,
                # One symmetry-variant program per solo shape (same
                # buckets/rungs; the canon kernel fuses into each).
                "distinct_programs": len(shapes),
            }
    if mux_k is not None and mux_k > 1:
        from ..service.registry import MUX_FAMILIES, parse

        if parse(spec)[0] in MUX_FAMILIES and dedup != "delta":
            plan["mux"] = {
                "k": mux_k,
                "shapes": [
                    {"bucket": b, "cand_cap": cap_of(b)}
                    for b in ladder_buckets(f_cap)
                ],
            }
            plan["mux"]["distinct_programs"] = len(plan["mux"]["shapes"])
    return plan


def build_census(
    specs: Optional[List[str]] = None,
    mux_k: Optional[int] = None,
    symmetry: bool = False,
) -> Dict[str, Any]:
    """The full census: every shipped spec's plan on both platforms.
    Callers that may touch a fresh jax process (``tools/warm_cache.py``'s
    parent) must ``surfaces.pin_cpu()`` first — model resolution builds
    packed layouts, and the first backend use must never be the axon
    plugin (CLAUDE.md gotcha #1)."""
    from ..service.registry import SHIPPED, resolve

    out: Dict[str, Any] = {"specs": {}}
    for spec in specs if specs is not None else list(SHIPPED):
        resolved = resolve(spec)
        out["specs"][spec] = {
            p: plan_for(
                spec, p, mux_k=mux_k, symmetry=symmetry, _resolved=resolved
            )
            for p in PLATFORMS
        }
    return out


def census_findings(census: Dict[str, Any]) -> List[Finding]:
    """STPU007 over a built census: one finding per (spec, platform)
    plan whose distinct program count exceeds its declared budget."""
    findings: List[Finding] = []
    for spec, plans in census["specs"].items():
        for platform, plan in plans.items():
            # A mux-enabled census prices the TOTAL a batching service
            # compiles: the solo plan plus one batched program per
            # bucket at lane count K.
            n = (
                plan["distinct_programs"]
                + plan.get("mux", {}).get("distinct_programs", 0)
                + plan.get("sym", {}).get("distinct_programs", 0)
            )
            budget = plan["budget"]
            if n <= budget:
                continue
            buckets = [s["bucket"] for s in plan["shapes"]]
            findings.append(
                Finding(
                    rule="STPU007",
                    surface=f"plan:{spec}:{platform}",
                    file="",
                    line=0,
                    message=(
                        f"run plan compiles {n} distinct program shapes "
                        f"(budget {budget}): buckets {buckets} — at ~1 "
                        "min per compile over the tunnel this plan burns "
                        "the window before it measures; lower the "
                        "frontier ceiling or declare a bigger "
                        "xla_compile_budget with a justification"
                    ),
                    excerpt=f"buckets={buckets}",
                )
            )
    return findings


def warm_specs(census: Optional[Dict[str, Any]] = None) -> List[str]:
    """The warm-cache spec list, DERIVED from the census (one entry per
    censused spec, shipped order) — ``tools/warm_cache.py``'s default
    ``--specs``."""
    if census is None:
        census = build_census()
    return list(census["specs"])
