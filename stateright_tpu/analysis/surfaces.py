"""The registered kernel surfaces stpu-lint sweeps.

A *surface* is one traceable device program the repo ships: a packed
model's vmapped transition/property kernels, an engine superstep at a
concrete dedup x compaction configuration, a fused multi-level dispatch,
the multiplexed (K-lane-batched) superstep, one of the standalone ops
programs (deltaset ``maintain``, hashset ``insert``), or a Pallas kernel. Each surface traces to a ``ClosedJaxpr``
on the CPU backend — no device, no execution, no XLA compile — and
declares which rule scans apply:

- kernel surfaces take STPU001/STPU002 (the two pinned vmapped-kernel
  miscompiles) — these must be checked on the STANDALONE vmapped kernel,
  because engine-level programs legitimately contain scatters (the rows
  engine's cumsum+scatter compaction on CPU) that are not the pinned
  shape;
- engine surfaces take STPU003 (sort width, W-dependent) and — for
  delta-dedup programs — STPU004 (no flush under cond);
- Pallas surfaces take the STPU005 static scans plus the mandatory TPU
  lowering pre-flight (Mosaic lowering runs host-side, so
  ``jit(f).trace(...).lower(lowering_platforms=("tpu",))`` pre-flights a
  kernel from this CPU-only box; registry #6).

Kernel tracing forces ``packing.ONE_HOT_WRITES = True`` — the
ACCELERATOR lowering of traced-index field writes — exactly like the old
``tests/test_packing.py`` HLO pin this sweep generalizes: the CPU
backend keeps its (correct, O(1)) scatter writes, and linting that path
would only measure the backend split, not the chip invariant.

The default sweep is sized for the <60 s 1-core CI budget: every shipped
spec's kernel surfaces and policy-resolved sorted-engine superstep, plus
the full config matrix (hash rows engine, delta, bsearch/pallas
compaction, fused programs) on one narrow (2pc:3, W=2) and one wide
(paxos:2,3, W=25) model — engine code is shared across models, so the
config matrix varies by W class, not by model count. ``--full`` sweeps
the whole matrix for every spec.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from .jaxpr_lint import (
    cond_flush_sorts,
    diff_lowering_inventories,
    mosaic_kernel_rules,
    op_inventory,
    output_transposes,
    taint_scatters,
    vmem_budget,
    wide_sorts,
)
from .rules import Finding

#: Batch the kernel surfaces trace at. The pinned scatter drop needs
#: batch >= 4096 at RUNTIME; the jaxpr is structurally batch-independent,
#: but tracing at the dangerous scale keeps the pin honest.
KERNEL_BATCH = 4096

#: Engine-surface trace shapes: small (trace cost only — shapes never
#: run), but divisible by the pallas kernel block so the pallas
#: compaction path engages instead of falling back to the sort.
F_CAP = 1024
CAND_CAP = 1024
TABLE_CAP = 1 << 13
#: Delta-dedup surfaces trace with a bigger main tier: STPU004's
#: "table-scale" threshold is the main capacity C, and the legitimate
#: in-program sorts (the [Dc + batch] delta merge, the A*F_CAP grid
#: compaction inside a fused ladder branch) must sit clearly BELOW it at
#: the trace shapes or they false-positive. C = 2^15 clears the largest
#: legitimate in-cond sort the default sweep traces (2pc fused: 17 *
#: F_CAP = 17408 grid lanes) while the flush shape ([C + Dc] lanes)
#: stays >= C. A fused-delta surface for a model with max_actions *
#: F_CAP >= C would need this raised.
TABLE_CAP_DELTA = 1 << 15

#: The two models the full config matrix runs on by default: one narrow
#: and one wide state (the sort-width classes the compaction policy
#: splits on).
MATRIX_SPECS = ("2pc:3", "paxos:2,3")

#: The STPU_PALLAS_BLOCK values STPU006 prices each pallas kernel at:
#: the shipped default (512) and its supported neighbours. The VMEM
#: footprint scales with the block, so the budget must hold across the
#: whole range an A/B session can select.
SUPPORTED_PALLAS_BLOCKS = (256, 512, 1024)

#: The virtual CPU mesh width the sharded-engine surface traces under —
#: the same 8-device mesh tests/conftest.py forces for the mesh tests.
MESH_DEVICES = 8

#: The lane counts the multiplexed-superstep surfaces trace at
#: (xla_mux.py; docs/service.md "Batched scheduling"): the smallest real
#: batch and a mid-size one. The jaxpr is structurally K-independent —
#: like KERNEL_BATCH, two points keep the pin honest without paying a
#: trace per possible K.
MUX_KS = (2, 4)


class SurfaceSkip(Exception):
    """A surface that cannot run in THIS environment (e.g. the sharded
    surface without the 8-device virtual mesh) — reported with its
    reason, not an error: the environment, not the tree, is the cause,
    exactly like the distributed-mesh tests' probe-and-self-skip."""


@dataclass
class SurfaceReport:
    name: str
    findings: List[Finding] = field(default_factory=list)
    seconds: float = 0.0
    #: Non-empty when the surface failed to TRACE (an infrastructure
    #: failure, not a rule finding — the CLI exits 2 on these: a surface
    #: that cannot be checked is not a pass).
    error: str = ""
    #: Non-empty when the surface self-skipped (environment limitation,
    #: not a failure; the reason is the probe's verdict).
    skipped: str = ""
    #: Whether the findings came from the content-hash result cache
    #: (analysis/cache.py) instead of a fresh trace.
    cached: bool = False


def pin_cpu() -> None:
    """The analyzer never touches a device: pin the CPU backend before
    any jax backend use (env alone cannot override the sitecustomize's
    config-level accelerator pin — CLAUDE.md gotcha #2). Guarded: on a
    jax lineage where a post-init update raises, an already-CPU process
    proceeds; anything else is a real configuration error. Also asks the
    CPU client for the 8-device virtual mesh (read at CPU-client init,
    so it must be set here, before the first backend use) so the sharded
    engine surface can trace — a backend that initialized earlier with
    fewer devices makes that one surface self-skip, never fail."""
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={MESH_DEVICES}"
        ).strip()
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:  # pragma: no cover - backend already initialized
        if jax.default_backend() != "cpu":
            raise


def _jnp():
    import jax

    import jax.numpy as jnp

    return jax, jnp


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _trace(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def _step3(model, jnp):
    def step3(words):
        out = model.packed_step(words)
        if len(out) == 3:
            return out
        nxt, valid = out
        return nxt, valid, jnp.zeros_like(valid)

    return step3


# --- surface builders -------------------------------------------------------


def _kernel_surfaces(spec: str, model) -> List[Tuple[str, Callable[[], List[Finding]]]]:
    jax, jnp = _jnp()
    W = model.state_words
    rows = _sds((KERNEL_BATCH, W), jnp.uint32)

    def scan(name, fn):
        def run():
            jx = _trace(jax.vmap(fn), rows)
            return (
                taint_scatters(jx, name)
                + output_transposes(jx, name)
                + wide_sorts(jx, name)
            )

        return run

    out = [
        (f"kernel:{spec}:packed_step", scan(f"kernel:{spec}:packed_step", model.packed_step)),
        (
            f"kernel:{spec}:packed_properties",
            scan(f"kernel:{spec}:packed_properties", model.packed_properties),
        ),
    ]
    if hasattr(model, "packed_representative"):
        out.append(
            (
                f"kernel:{spec}:packed_representative",
                scan(
                    f"kernel:{spec}:packed_representative",
                    model.packed_representative,
                ),
            )
        )
    if getattr(model, "symmetry_spec", None) is not None:
        # The spec-compiled canonicalization kernel (stateright_tpu/sym;
        # docs/symmetry.md): fingerprinting vmaps it over every frontier
        # row when symmetry is on, so it takes the same vmapped-kernel
        # rules as the model's own transition kernels.
        name = f"kernel:{spec}:sym-canon"

        def run_sym(name=name, spec_obj=model.symmetry_spec):
            from ..sym import compile_canon

            jx = _trace(jax.vmap(compile_canon(spec_obj)), rows)
            return (
                taint_scatters(jx, name)
                + output_transposes(jx, name)
                + wide_sorts(jx, name)
            )

        out.append((name, run_sym))

    # The STPU_EXPAND_LAYOUT=planes A/B variant: vmap emits [A, W, F]
    # directly (out_axes=2) — the transpose-fused-into-vmap shape. Kept
    # in the sweep so STPU002 proves it still exists ONLY behind the
    # accelerator-gated knob (the finding is waived with that
    # justification; losing the waiver match means the shape moved).
    name = f"kernel:{spec}:packed_step:planes-expand"

    def run_planes():
        step3 = _step3(model, jnp)
        jx = _trace(jax.vmap(step3, out_axes=(2, 0, 0)), rows)
        return taint_scatters(jx, name) + output_transposes(jx, name)

    out.append((name, run_planes))
    return out


def _lowering_surface(spec: str, model) -> Tuple[str, Callable[[], List[Finding]]]:
    """STPU008: lower the spec's transition kernel for BOTH platforms
    from this CPU box (no device — the STPU005 pre-flight trick) and
    diff the StableHLO op inventories for pathology-registry ops that
    appear on one side only."""
    name = f"lower:{spec}:packed_step"

    def run():
        jax, jnp = _jnp()
        rows = _sds((KERNEL_BATCH, model.state_words), jnp.uint32)
        fn = jax.vmap(model.packed_step)
        inv = {}
        for platform in ("cpu", "tpu"):
            lowered = jax.jit(fn).trace(rows).lower(
                lowering_platforms=(platform,)
            )
            inv[platform] = op_inventory(lowered.as_text())
        return diff_lowering_inventories(name, inv["cpu"], inv["tpu"])

    return name, run


def _sym_lowering_surface(spec: str, model) -> Tuple[str, Callable[[], List[Finding]]]:
    """STPU008 for the spec-compiled canonicalization kernel: diff its
    cpu/tpu StableHLO op inventories the same way the transition kernel
    is diffed — the canon kernel rides every symmetry-on dispatch, so a
    one-sided pathology op there is the same structural miscompile class."""
    name = f"lower:{spec}:sym-canon"

    def run():
        jax, jnp = _jnp()
        from ..sym import compile_canon

        rows = _sds((KERNEL_BATCH, model.state_words), jnp.uint32)
        fn = jax.vmap(compile_canon(model.symmetry_spec))
        inv = {}
        for platform in ("cpu", "tpu"):
            lowered = jax.jit(fn).trace(rows).lower(
                lowering_platforms=(platform,)
            )
            inv[platform] = op_inventory(lowered.as_text())
        return diff_lowering_inventories(name, inv["cpu"], inv["tpu"])

    return name, run


def _superstep_args(checker, model, f_cap: int):
    _, jnp = _jnp()
    P = len(checker._prop_names)
    return (
        _sds((f_cap, model.state_words), jnp.uint32),
        _sds((f_cap,), jnp.uint32),
        _sds((), jnp.int32),
        checker._table,
        _sds((P,), jnp.bool_),
        _sds((P, 2), jnp.uint32),
    )


def _spawn(spec: str, dedup: str, compaction: str = "auto"):
    from ..service.registry import resolve

    model, _ = resolve(spec)
    checker = model.checker().spawn_xla(
        dedup=dedup,
        compaction=compaction,
        frontier_capacity=F_CAP,
        table_capacity=TABLE_CAP_DELTA if dedup == "delta" else TABLE_CAP,
    )
    return model, checker


def _flush_lanes(checker) -> Optional[int]:
    """STPU004's table-scale threshold: the delta structure's main
    capacity (the flush sort is [C + Dc] lanes, every in-program delta
    sort is [Dc + batch] — strictly below C at the trace shapes)."""
    if checker._dedup != "delta":
        return None
    return checker._table.main_capacity


def _engine_surface(spec: str, dedup: str, compaction: str):
    tag = dedup if compaction in ("auto",) else f"{dedup}-{compaction}"
    name = f"engine:{spec}:superstep:{tag}"

    def run():
        model, checker = _spawn(spec, dedup, compaction)
        step = checker._build_superstep(F_CAP, CAND_CAP)
        jx = _trace(step, *_superstep_args(checker, model, F_CAP))
        return (
            wide_sorts(jx, name)
            + cond_flush_sorts(jx, name, _flush_lanes(checker))
            + mosaic_kernel_rules(jx, name)
        )

    return name, run


def _fused_surface(spec: str, dedup: str):
    name = f"engine:{spec}:fused:{dedup}"

    def run():
        jax, jnp = _jnp()
        model, checker = _spawn(spec, dedup)
        rungs = tuple(checker._cand_rungs(F_CAP))
        fused = checker._build_fused(F_CAP, rungs)
        P = len(checker._prop_names)
        scalars = _sds((), jnp.int32)
        args = _superstep_args(checker, model, F_CAP) + (
            scalars,
            scalars,
            _sds((P,), jnp.bool_),
            scalars,
            scalars,
            scalars,
        )
        jx = _trace(fused, *args)
        return (
            wide_sorts(jx, name)
            + cond_flush_sorts(jx, name, _flush_lanes(checker))
            + mosaic_kernel_rules(jx, name)
        )

    return name, run


def _accel_policy_compaction(model) -> str:
    """The compaction the accelerator auto-policy resolves for this
    model's width (the lint runs on CPU, so 'auto' would resolve the
    CPU answer — the sweep must check the path the CHIP runs). Shared
    with the engine: one definition, no drift."""
    from ..xla import accel_auto_compaction

    return accel_auto_compaction(model.state_words)


def _ops_surfaces() -> List[Tuple[str, Callable[[], List[Finding]]]]:
    jax, jnp = _jnp()

    def maintain_run():
        from ..ops import deltaset

        ds = deltaset.make(TABLE_CAP, jnp)
        jx = _trace(deltaset.maintain, ds)
        name = "ops:deltaset-maintain"
        # The maintain sort IS table-scale — the point is that it is a
        # standalone host-invoked program, so it must carry no cond at
        # all around that sort. flush_lanes = main capacity applies.
        return wide_sorts(jx, name) + cond_flush_sorts(
            jx, name, ds.main_capacity
        )

    def hashset_run():
        from ..ops import hashset

        name = "ops:hashset-insert"
        table = hashset.make(TABLE_CAP, jnp)
        n = 512
        u32 = _sds((n,), jnp.uint32)
        active = _sds((n,), jnp.bool_)

        def insert(table, hi, lo, vh, vl, act):
            return hashset.insert(table, hi, lo, vh, vl, act, max_probes=32)

        jx = _trace(insert, table, u32, u32, u32, u32, active)
        # The open-addressing insert scatters at probed (data-dependent)
        # slots by DESIGN — correct there (not a vmapped model kernel;
        # four rounds of exact counts) and waived in
        # .stpu-lint-waivers.toml. The finding must keep firing so the
        # waiver stays honest.
        return taint_scatters(jx, name)

    return [
        ("ops:deltaset-maintain", maintain_run),
        ("ops:hashset-insert", hashset_run),
    ]


def _pallas_surfaces() -> List[Tuple[str, Callable[[], List[Finding]]]]:
    jax, jnp = _jnp()

    def preflight(name, fn, *args) -> List[Finding]:
        """Registry #6: the TPU lowering pre-flight, as a lint check.
        Mosaic lowering runs host-side; a kernel that cannot lower for
        the TPU target is a finding, not a crash."""
        try:
            jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))
        except Exception as e:
            first = str(e).strip().splitlines()
            return [
                Finding(
                    rule="STPU005",
                    surface=name,
                    file="",
                    line=0,
                    message=(
                        "TPU lowering pre-flight failed "
                        f"({type(e).__name__}) — every ops/ pallas "
                        "kernel must lower for the TPU target from CPU "
                        "(registry #6)"
                    ),
                    excerpt=first[0] if first else type(e).__name__,
                )
            ]
        return []

    def compact_run():
        from ..ops.pallas_compact import compact_pallas_staged

        name = "pallas:compact"
        M, cap, P = 2048, 2048, 4
        mask = _sds((M,), jnp.bool_)
        lanes = [_sds((M,), jnp.uint32) for _ in range(P)]

        def fn(m, *ls):
            return compact_pallas_staged(m, list(ls), cap, block=512)

        jx = _trace(fn, mask, *lanes)
        return (
            mosaic_kernel_rules(jx, name)
            + vmem_budget(jx, name)
            + preflight(name, fn, mask, *lanes)
        )

    def merge_run():
        from ..ops.pallas_merge import merge_insert

        name = "pallas:merge"
        C, m = 2048, 512
        table = _sds((4, C), jnp.uint32)
        batch = _sds((4, m), jnp.uint32)

        def fn(t, b):
            return merge_insert(t, b, block=512)

        jx = _trace(fn, table, batch)
        return (
            mosaic_kernel_rules(jx, name)
            + vmem_budget(jx, name)
            + preflight(name, fn, table, batch)
        )

    def vmem_block_run(block: int):
        """STPU006 across the supported STPU_PALLAS_BLOCK range: both
        kernels re-traced at this block (shapes sized block-divisible)
        and priced against the per-core budget. The full rule scans ride
        the default-block surfaces above; these price the block knob."""

        def run():
            from ..ops.pallas_compact import compact_pallas_staged
            from ..ops.pallas_merge import merge_insert

            name = f"pallas:vmem:block{block}"
            M = 4 * block
            mask = _sds((M,), jnp.bool_)
            lanes = [_sds((M,), jnp.uint32) for _ in range(4)]

            def cfn(m, *ls):
                return compact_pallas_staged(m, list(ls), M, block=block)

            out = vmem_budget(_trace(cfn, mask, *lanes), name)
            table = _sds((4, 4 * block), jnp.uint32)
            batch = _sds((4, block), jnp.uint32)

            def mfn(t, b):
                return merge_insert(t, b, block=block)

            return out + vmem_budget(_trace(mfn, table, batch), name)

        return run

    out = [("pallas:compact", compact_run), ("pallas:merge", merge_run)]
    out += [
        (f"pallas:vmem:block{b}", vmem_block_run(b))
        for b in SUPPORTED_PALLAS_BLOCKS
        if b != 512  # the default block is priced by the surfaces above
    ]
    return out


def _sharded_surfaces() -> List[Tuple[str, Callable[[], List[Finding]]]]:
    """The fingerprint-sharded mesh engine's superstep, traced under the
    same 8-device virtual CPU mesh the distributed tests force — the
    second surface docs/static-analysis.md listed as missing. Both dedup
    configs the mesh runs: hash (the CPU/test config) and sorted (the
    accelerator config STPU003's sort widths apply to)."""

    def make(dedup: str):
        name = f"engine:2pc:3:sharded-superstep:{dedup}"

        def run():
            jax, jnp = _jnp()
            if len(jax.devices()) < MESH_DEVICES:
                raise SurfaceSkip(
                    f"needs the {MESH_DEVICES}-device virtual CPU mesh "
                    f"(backend initialized with {len(jax.devices())} "
                    "devices before the analyzer could request it)"
                )
            from ..parallel import default_mesh
            from ..service.registry import resolve

            model, _ = resolve("2pc:3")
            checker = model.checker().spawn_xla(
                mesh=default_mesh(MESH_DEVICES),
                dedup=dedup,
                frontier_capacity=1 << 10,
                table_capacity=1 << 13,
            )
            step = checker._superstep()
            jx = _trace(
                step,
                checker._frontier,
                checker._frontier_ebits,
                checker._counts,
                tuple(checker._table),
                checker._disc_found,
                checker._disc_fp,
            )
            return wide_sorts(jx, name) + mosaic_kernel_rules(jx, name)

        return name, run

    return [make("hash"), make("sorted")]


def _mux_batched_args(checker, model, k: int):
    """The superstep's argument shapes under a leading ``k`` lane axis —
    exactly what ``MuxChecker._build_mux_fused``'s ``vmap`` of the
    single-level superstep carries (the table pytree batches leaf-wise)."""
    import jax

    return tuple(
        jax.tree_util.tree_map(lambda a: _sds((k,) + a.shape, a.dtype), arg)
        for arg in _superstep_args(checker, model, F_CAP)
    )


def _mux_surfaces() -> List[Tuple[str, Callable[[], List[Finding]]]]:
    """The multiplexed superstep (xla_mux.py): ``jax.vmap`` of the
    engine's single-level superstep under a leading K lane axis — the
    program ``worker.py --mux`` compiles. Three pins per the surface
    taxonomy above:

    - ``kernel:…:mux-packed_step:k{K}`` — STPU001/STPU002 on the
      DOUBLY-vmapped model kernel (vmap-over-lanes of the vmap-over-rows
      transition), the new vmap nesting mux introduces. The batched
      superstep itself legitimately contains engine-level scatters, the
      same exemption the solo engine surfaces get;
    - ``engine:…:mux-superstep:k{K}:{dedup}`` — the engine rules
      (STPU003 sort widths now carry the K batch dimension, STPU005
      statics) over the batched superstep, both mux-supported dedups
      (delta is ``MuxError``-ineligible, so no surface exists to lint);
    - ``lower:…:mux-superstep:k2`` — one STPU008 cross-backend lowering
      diff of the whole batched program (cheap: ~0.6 s both platforms).
    """
    out: List[Tuple[str, Callable[[], List[Finding]]]] = []
    spec = "2pc:3"

    def make_kernel(k: int):
        name = f"kernel:{spec}:mux-packed_step:k{k}"

        def run():
            jax, jnp = _jnp()
            from ..service.registry import resolve

            model, _ = resolve(spec)
            rows = _sds((k, KERNEL_BATCH, model.state_words), jnp.uint32)
            jx = _trace(jax.vmap(jax.vmap(model.packed_step)), rows)
            return (
                taint_scatters(jx, name)
                + output_transposes(jx, name)
                + wide_sorts(jx, name)
            )

        return name, run

    def make_engine(k: int, dedup: str):
        name = f"engine:{spec}:mux-superstep:k{k}:{dedup}"

        def run():
            jax, _ = _jnp()
            model, checker = _spawn(spec, dedup)
            step = checker._build_superstep(F_CAP, CAND_CAP)
            jx = _trace(jax.vmap(step), *_mux_batched_args(checker, model, k))
            return (
                wide_sorts(jx, name)
                + cond_flush_sorts(jx, name, _flush_lanes(checker))
                + mosaic_kernel_rules(jx, name)
            )

        return name, run

    def make_lowering(k: int):
        name = f"lower:{spec}:mux-superstep:k{k}"

        def run():
            jax, _ = _jnp()
            model, checker = _spawn(spec, "sorted")
            step = checker._build_superstep(F_CAP, CAND_CAP)
            args = _mux_batched_args(checker, model, k)
            inv = {}
            for platform in ("cpu", "tpu"):
                lowered = jax.jit(jax.vmap(step)).trace(*args).lower(
                    lowering_platforms=(platform,)
                )
                inv[platform] = op_inventory(lowered.as_text())
            return diff_lowering_inventories(name, inv["cpu"], inv["tpu"])

        return name, run

    for k in MUX_KS:
        out.append(make_kernel(k))
        for dedup in ("sorted", "hash"):
            out.append(make_engine(k, dedup))
    out.append(make_lowering(MUX_KS[0]))
    return out


def _census_surface(
    specs: Optional[List[str]] = None,
) -> Tuple[str, Callable[[], List[Finding]]]:
    """STPU007: the compile-plan census over the shipped specs (or one
    admission spec) — pure planner arithmetic, no tracing."""
    name = "plan:shipped" if specs is None else f"plan:{','.join(specs)}"

    def run():
        from .census import build_census, census_findings

        return census_findings(build_census(specs))

    return name, run


# --- the sweep --------------------------------------------------------------


def build_sweep(full: bool = False) -> List[Tuple[str, Callable[[], List[Finding]]]]:
    """Every (name, runner) in the sweep. Runners trace lazily, so an
    ``--only``-filtered run costs only the surfaces it touches (and a
    ``--rules`` filter naming no jaxpr rule skips the sweep entirely —
    ``cli.run_lint``)."""
    from ..service.registry import SHIPPED, resolve

    out: List[Tuple[str, Callable[[], List[Finding]]]] = []
    for spec in SHIPPED:
        model, _ = resolve(spec)
        out.extend(_kernel_surfaces(spec, model))
        # The accelerator-policy sorted-engine superstep: the program
        # the chip actually runs for this model (W-dependent sort
        # widths — STPU003's subject).
        out.append(_engine_surface(spec, "sorted", _accel_policy_compaction(model)))
        if full or spec in MATRIX_SPECS:
            out.append(_engine_surface(spec, "hash", "auto"))
            out.append(_engine_surface(spec, "delta", "gather"))
            out.append(_engine_surface(spec, "sorted", "bsearch"))
            out.append(_engine_surface(spec, "sorted", "pallas"))
        # STPU008's dual-platform lowering costs real seconds per
        # surface; the default sweep diffs the two width classes (engine
        # programs are W-class-shared; kernels differ per model, so
        # --full widens to every spec). Admission checks always diff the
        # admitted spec (build_admission_sweep).
        if full or spec in MATRIX_SPECS:
            out.append(_lowering_surface(spec, model))
            if getattr(model, "symmetry_spec", None) is not None:
                out.append(_sym_lowering_surface(spec, model))
    # Fused multi-level programs (the lax.switch ladder + while loop):
    # one narrow sorted, one narrow delta (STPU004's switch-carrying
    # delta program), one wide sorted under --full.
    out.append(_fused_surface("2pc:3", "sorted"))
    out.append(_fused_surface("2pc:3", "delta"))
    if full:
        out.append(_fused_surface("paxos:2,3", "sorted"))
    # The multiplexed superstep (worker.py --mux): batched-kernel pins at
    # the MUX_KS lane counts plus one cross-backend lowering diff.
    out.extend(_mux_surfaces())
    out.extend(_sharded_surfaces())
    out.extend(_ops_surfaces())
    out.extend(_pallas_surfaces())
    out.append(_census_surface())
    return out


def build_admission_sweep(
    spec: str,
) -> List[Tuple[str, Callable[[], List[Finding]]]]:
    """The admission-time flight-check for ONE spec (docs/service.md):
    its kernel surfaces (STPU001/002/003), its cross-backend lowering
    diff (STPU008), and its compile-plan census (STPU007) — the subset
    a user-submitted model must pass before the pool schedules it on
    the device. Engine/ops/pallas surfaces are spec-independent and
    stay the full sweep's business."""
    from ..service.registry import resolve

    model, _ = resolve(spec)
    out = _kernel_surfaces(spec, model)
    out.append(_lowering_surface(spec, model))
    if getattr(model, "symmetry_spec", None) is not None:
        out.append(_sym_lowering_surface(spec, model))
    out.append(_census_surface([spec]))
    return out


def run_sweep(
    full: bool = False,
    only: Optional[List[str]] = None,
    *,
    admission_spec: Optional[str] = None,
    use_cache: bool = True,
    cache_dir: Optional[str] = None,
) -> List[SurfaceReport]:
    """Trace and scan every surface (CPU backend, accelerator write
    lowering pinned on). ``only`` filters surface names by substring;
    ``admission_spec`` swaps the sweep for :func:`build_admission_sweep`
    over that one spec.

    The sweep is HERMETIC: every ``STPU_*`` env knob is scrubbed for the
    duration (and restored after). The knobs exist for A/B sessions —
    an exported ``STPU_SORTEDSET_KEYS=packed`` or ``STPU_COMPACTION``
    would otherwise make the lint trace a different program than the
    tree defines (or error outright on x64-requiring variants), turning
    the verdict into a function of the caller's shell. The one
    exemption is ``STPU_FAMILIES`` (service/registry.py's user-family
    hook): it selects WHICH models exist, not how a program lowers, and
    scrubbing it would make the admission check unable to see the very
    spec it was asked to verify.

    ``use_cache`` replays raw findings from the content-hash cache
    (analysis/cache.py) for surfaces whose package tree is unchanged —
    errors and skips are never cached."""
    import os as _os

    # Snapshot BEFORE pin_cpu appends the 8-virtual-device flag for the
    # sharded mesh surface: once the backend is initialized (the flag is
    # only read at CPU-client init) the caller's value is restored in
    # the finally below, so subprocesses an embedding process spawns
    # later never inherit it.
    prev_flags = _os.environ.get("XLA_FLAGS")
    pin_cpu()
    from .. import packing

    cache = None
    if use_cache and admission_spec is not None:
        # A user-submitted family (STPU_FAMILIES) lives OUTSIDE the
        # package tree the cache hashes — serving its surfaces from the
        # tree-keyed cache would replay stale verdicts across user
        # edits. Shipped families stay cacheable.
        from ..service.registry import FAMILIES, parse

        family, _ = parse(admission_spec)
        use_cache = family in FAMILIES
    if use_cache:
        from .cache import SurfaceCache

        cache = SurfaceCache(cache_dir)

    reports: List[SurfaceReport] = []
    prev = packing.ONE_HOT_WRITES
    packing.ONE_HOT_WRITES = True
    scrubbed = {
        k: _os.environ.pop(k)
        for k in list(_os.environ)
        if k.startswith("STPU_") and k != "STPU_FAMILIES"
    }
    try:
        sweep = (
            build_admission_sweep(admission_spec)
            if admission_spec is not None
            else build_sweep(full=full)
        )
        for name, runner in sweep:
            if only and not any(s in name for s in only):
                continue
            t0 = time.monotonic()
            rep = SurfaceReport(name=name)
            hit = cache.get(name) if cache is not None else None
            if hit is not None:
                rep.findings = hit
                rep.cached = True
            else:
                try:
                    rep.findings = runner()
                    if cache is not None:
                        cache.put(name, rep.findings)
                except SurfaceSkip as e:
                    rep.skipped = str(e)
                except Exception as e:  # trace failure: loud, not a pass
                    rep.error = f"{type(e).__name__}: {e}"
            rep.seconds = round(time.monotonic() - t0, 3)
            reports.append(rep)
    finally:
        packing.ONE_HOT_WRITES = prev
        _os.environ.update(scrubbed)
        if prev_flags is None:
            _os.environ.pop("XLA_FLAGS", None)
        else:
            _os.environ["XLA_FLAGS"] = prev_flags
    return reports
