"""The jaxpr invariant pass: STPU001-005 over lowered kernel surfaces.

Everything here operates on already-traced ``ClosedJaxpr``s (tracing is
``surfaces.py``'s job) — no device, no execution, no XLA compile. Rules
are checked against the jaxpr rather than compiled HLO on purpose: the
jaxpr is backend-independent and stable across XLA fusion decisions, so a
finding names the op the PROGRAM asked for, with ``eqn.source_info``
giving the exact repo ``file:line`` that asked. (The one HLO-adjacent
check, the STPU005 Mosaic pre-flight, goes through the real TPU lowering
pipeline in ``surfaces.py`` because Mosaic's verifier IS the checkable
artifact there.)

Shared mechanics:

- :func:`iter_eqns` walks equations recursively through every sub-jaxpr
  (cond/switch branches, while bodies, pjit calls, pallas kernels),
  yielding the primitive path from the root so rules can scope to
  "inside a cond branch" or "inside a pallas kernel".
- :func:`taint_scatters` runs the forward dataflow STPU001 needs:
  a scatter is only the pinned-fatal shape when its *index* operand is
  data-DEPENDENT (derived from the kernel's traced inputs). Static-index
  writes also appear as ``scatter`` eqns in a jaxpr, but XLA folds them
  and the round-5 drift never reproduced there — flagging those would
  bury the real signal in noise (every Layout.set of a static field).
"""

from __future__ import annotations

import os
from typing import Any, Iterator, List, Optional, Tuple

from .rules import (
    MAX_SAFE_SORT_OPERANDS,
    PATHOLOGY_LOWERING_OPS,
    VMEM_BUDGET_BYTES,
    Finding,
)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Primitive families the rules key on.
SCATTER_PRIMS = (
    "scatter",
    "scatter-add",
    "scatter_add",
    "scatter-mul",
    "scatter_mul",
    "scatter-min",
    "scatter_min",
    "scatter-max",
    "scatter_max",
)
CUMULATIVE_PRIMS = ("cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp")
#: Pallas ref-store primitives (a dynamic-offset *vector* store is the
#: Mosaic-rejected shape; DMA copies at dynamic offsets are sanctioned).
STORE_PRIMS = ("swap", "masked_swap", "store")


def _subjaxprs(eqn) -> List[Any]:
    """Raw ``Jaxpr`` children of an equation's params (cond branches,
    while body/cond, pjit jaxpr, pallas kernel jaxpr, ...)."""
    subs = []
    for v in eqn.params.values():
        for x in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(x, "jaxpr"):  # ClosedJaxpr
                subs.append(x.jaxpr)
            elif hasattr(x, "eqns"):  # Jaxpr
                subs.append(x)
    return subs


def iter_eqns(jaxpr, path: Tuple[str, ...] = ()) -> Iterator[Tuple[Any, Tuple[str, ...]]]:
    """Yield ``(eqn, path)`` over ``jaxpr`` and every sub-jaxpr; ``path``
    is the tuple of enclosing primitive names from the root."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        sub_path = path + (eqn.primitive.name,)
        for s in _subjaxprs(eqn):
            yield from iter_eqns(s, sub_path)


def source_of(eqn) -> Tuple[str, int]:
    """Best repo-relative ``(file, line)`` anchor for an equation, from
    jax's per-eqn source info (the deepest user frame inside the repo);
    ``("", 0)`` when the trace carries none."""
    tb = getattr(eqn.source_info, "traceback", None)
    if tb is None:
        return "", 0
    frames = [
        f
        for f in tb.frames
        if f.file_name
        and f.file_name.startswith(_REPO)
        # The lint driver's own frames (this package, the tools/
        # wrapper) are never the anchor: an op inserted by vmap
        # machinery with no user frame must report "<no-source>", not
        # blame the lint entry point.
        and f"{os.sep}analysis{os.sep}" not in f.file_name
        and not f.file_name.endswith(f"tools{os.sep}stpu_lint.py")
    ]
    if not frames:
        return "", 0
    f = frames[0]
    return os.path.relpath(f.file_name, _REPO), f.line_num


def excerpt_of(eqn, limit: int = 160) -> str:
    txt = " ".join(str(eqn).split())
    return txt if len(txt) <= limit else txt[: limit - 3] + "..."


def _is_literal(v) -> bool:
    return hasattr(v, "val")


# --- STPU001 ----------------------------------------------------------------


def taint_scatters(closed, surface: str) -> List[Finding]:
    """STPU001: scatter eqns whose index operand is derived from the
    surface's traced inputs (data-dependent — the shape XLA:TPU drops in
    vmapped kernels at batch >= 4096)."""
    findings: List[Finding] = []

    def walk(jaxpr, taint):
        for eqn in jaxpr.eqns:
            in_taint = [
                (not _is_literal(v)) and id(v) in taint for v in eqn.invars
            ]
            if eqn.primitive.name in SCATTER_PRIMS:
                # Scatter operands: (operand, indices, updates).
                if len(in_taint) > 1 and in_taint[1]:
                    file, line = source_of(eqn)
                    findings.append(
                        Finding(
                            rule="STPU001",
                            surface=surface,
                            file=file,
                            line=line,
                            message=(
                                "data-dependent scatter in a vmapped "
                                "kernel surface: route this traced-index "
                                "write through packing._word_update "
                                "(one-hot) — XLA:TPU drops this scatter "
                                "at batch >= 4096"
                            ),
                            excerpt=excerpt_of(eqn),
                        )
                    )
            # Propagate taint through this eqn and into sub-jaxprs.
            any_taint = any(in_taint)
            for s in _subjaxprs(eqn):
                walk(s, set(map(id, s.invars)) if any_taint else set())
            if any_taint:
                for o in eqn.outvars:
                    taint.add(id(o))
        return findings

    jaxpr = closed.jaxpr
    return walk(jaxpr, set(map(id, jaxpr.invars)))


# --- STPU002 ----------------------------------------------------------------


def output_transposes(closed, surface: str) -> List[Finding]:
    """STPU002: ANY transpose equation inside a kernel-surface jaxpr —
    whether it produces the surface's outputs directly (the
    ``vmap(..., out_axes != 0)`` shape) or sits mid-kernel between ops
    (e.g. a nested ``vmap(..., out_axes != 0)`` whose transpose feeds
    further kernel ops — the documented gap the first cut of this rule
    left open). Either way the transpose is FUSED into the vmapped
    kernel, which is the shape XLA:CPU miscompiles; the engine's safe
    direction materializes rows and transposes as a separate consumer
    (rows-in/transpose-out). Shipped kernels carry zero transposes, so
    the whole-body scan stays noise-free."""
    findings: List[Finding] = []
    jaxpr = closed.jaxpr
    outs = {id(v) for v in jaxpr.outvars if not _is_literal(v)}
    for eqn, _path in iter_eqns(jaxpr):
        if eqn.primitive.name != "transpose":
            continue
        direct = any(id(o) in outs for o in eqn.outvars)
        file, line = source_of(eqn)
        findings.append(
            Finding(
                rule="STPU002",
                surface=surface,
                file=file,
                line=line,
                message=(
                    (
                        "vmapped kernel hands its output straight out of "
                        "a transpose (out_axes != 0)"
                        if direct
                        else "transpose buried mid-kernel between ops in "
                        "a vmapped kernel (e.g. a nested "
                        "vmap(out_axes != 0))"
                    )
                    + ": the transpose-fused-into-vmap shape XLA:CPU "
                    "miscompiles — emit rows (out_axes=0) and transpose "
                    "outside the kernel"
                ),
                excerpt=excerpt_of(eqn),
            )
        )
    return findings


# --- STPU003 ----------------------------------------------------------------


def wide_sorts(
    closed, surface: str, max_operands: int = MAX_SAFE_SORT_OPERANDS
) -> List[Finding]:
    """STPU003: ``lax.sort`` equations carrying more operands than the
    chip-proven width (the wide-W compile-stall shape)."""
    findings: List[Finding] = []
    for eqn, _path in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "sort":
            continue
        n = len(eqn.invars)
        if n > max_operands:
            file, line = source_of(eqn)
            findings.append(
                Finding(
                    rule="STPU003",
                    surface=surface,
                    file=file,
                    line=line,
                    message=(
                        f"{n}-operand lax.sort exceeds the chip-proven "
                        f"width ({max_operands}): the W=25 sort-compaction "
                        "compile stalled XLA:TPU for tens of minutes — "
                        "use gather-family compaction for wide states"
                    ),
                    excerpt=excerpt_of(eqn),
                )
            )
    return findings


# --- STPU004 ----------------------------------------------------------------


def cond_flush_sorts(
    closed, surface: str, flush_lanes: Optional[int]
) -> List[Finding]:
    """STPU004: a sort of table-scale lanes (>= ``flush_lanes``, the
    delta structure's main capacity) inside a cond/switch branch — the
    flush-under-``lax.cond`` shape that faults the XLA:TPU runtime.
    ``flush_lanes=None`` skips the rule (surface has no delta tier)."""
    if flush_lanes is None:
        return []
    findings: List[Finding] = []
    for eqn, path in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "sort" or "cond" not in path:
            continue
        lanes = max(
            (v.aval.shape[0] for v in eqn.invars if v.aval.shape), default=0
        )
        if lanes >= flush_lanes:
            file, line = source_of(eqn)
            findings.append(
                Finding(
                    rule="STPU004",
                    surface=surface,
                    file=file,
                    line=line,
                    message=(
                        f"table-scale sort ({lanes} lanes >= main "
                        f"capacity {flush_lanes}) inside a cond/switch "
                        "branch: the deltaset flush must be the "
                        "host-invoked maintain program through the "
                        "overflow protocol — this shape faults the "
                        "XLA:TPU runtime"
                    ),
                    excerpt=excerpt_of(eqn),
                )
            )
    return findings


# --- STPU006: static VMEM budget for pallas kernels -------------------------


def _vmem_bytes(aval) -> int:
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", None)
    itemsize = getattr(dtype, "itemsize", None)
    if itemsize is None:  # extended dtypes (semaphores) are space-filtered
        itemsize = 4
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(itemsize)


def pallas_vmem_footprint(eqn) -> Tuple[int, List[str]]:
    """Static per-core VMEM bytes of one ``pallas_call`` equation, from
    the kernel jaxpr's ref avals: blocked operands (default memory
    space) count TWICE — the pipeline emitter double-buffers them —
    VMEM scratch counts in full, and ANY (HBM) / SMEM / semaphore refs
    are free. Returns ``(bytes, breakdown)``."""
    total = 0
    breakdown: List[str] = []
    kernel = eqn.params.get("jaxpr")
    if kernel is None:  # not a shape this pass prices
        return 0, []
    for v in kernel.invars:
        aval = v.aval
        space = getattr(aval, "memory_space", None)
        tag = str(getattr(space, "value", space)).lower()
        if space is None:
            b = 2 * _vmem_bytes(aval)  # double-buffered pipeline block
            label = "block x2"
        elif tag == "vmem":
            b = _vmem_bytes(aval)
            label = "scratch"
        else:  # any (HBM), smem, semaphores
            continue
        total += b
        breakdown.append(
            f"{label} {tuple(getattr(aval, 'shape', ()))} = {b}B"
        )
    return total, breakdown


def vmem_budget(
    closed, surface: str, budget: int = VMEM_BUDGET_BYTES
) -> List[Finding]:
    """STPU006: every ``pallas_call`` whose static VMEM footprint
    exceeds the per-core budget (the shape that today surfaces as a
    runtime Mosaic allocation error on chip, after the tunnel window is
    already spent)."""
    findings: List[Finding] = []
    for eqn, _path in iter_eqns(closed.jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        total, breakdown = pallas_vmem_footprint(eqn)
        if total > budget:
            file, line = source_of(eqn)
            findings.append(
                Finding(
                    rule="STPU006",
                    surface=surface,
                    file=file,
                    line=line,
                    message=(
                        f"static VMEM footprint {total} B exceeds the "
                        f"per-core budget {budget} B "
                        f"({', '.join(breakdown)}): shrink the block "
                        "(STPU_PALLAS_BLOCK) or the scratch rings — on "
                        "chip this is a runtime Mosaic allocation error"
                    ),
                    excerpt=excerpt_of(eqn),
                )
            )
    return findings


# --- STPU008: cross-backend lowering diff ------------------------------------

#: Dialects whose ops count as the lowered inventory.
_OP_RE = None


def op_inventory(stablehlo_text: str) -> set:
    """The set of ``stablehlo.*``/``chlo.*``/``mhlo.*`` op names
    appearing in a lowered module's text."""
    import re

    global _OP_RE
    if _OP_RE is None:
        _OP_RE = re.compile(r"\b(?:stablehlo|chlo|mhlo)\.[\w.]+")
    return set(_OP_RE.findall(stablehlo_text))


def diff_lowering_inventories(
    surface: str, cpu_ops: set, tpu_ops: set
) -> List[Finding]:
    """STPU008: pathology-registry ops present in exactly ONE backend's
    lowering of the same program — the structural class both pinned
    miscompiles belong to (TPU drops the scatter CPU executes; CPU
    miscompiles the transpose TPU runs fine)."""
    findings: List[Finding] = []
    for op in PATHOLOGY_LOWERING_OPS:
        in_cpu, in_tpu = op in cpu_ops, op in tpu_ops
        if in_cpu == in_tpu:
            continue
        only, missing = ("cpu", "tpu") if in_cpu else ("tpu", "cpu")
        findings.append(
            Finding(
                rule="STPU008",
                surface=surface,
                file="",
                line=0,
                message=(
                    f"pathology-registry op {op} appears only in the "
                    f"{only} lowering (absent from {missing}): the "
                    "backends lower this program differently in exactly "
                    "the op class they have already disagreed on — "
                    "rewrite the program so both lowerings agree, or "
                    "waive with a chip-verified justification"
                ),
                excerpt=f"{only}-only: {op}",
            )
        )
    return findings


# --- STPU005 (static half; the lowering pre-flight lives in surfaces.py) ----


def _is_u32_f32_cast(eqn) -> bool:
    if eqn.primitive.name != "convert_element_type":
        return False
    new = eqn.params.get("new_dtype")
    old = eqn.invars[0].aval.dtype
    names = {str(old), str(new)}
    return names == {"uint32", "float32"}


def mosaic_kernel_rules(closed, surface: str) -> List[Finding]:
    """STPU005 static scans inside every ``pallas_call`` kernel jaxpr:
    no cumulative-scan primitives (no Mosaic TC lowering), no direct
    u32<->f32 casts (unsupported; use the value-exact i32 hop), and no
    dynamic-offset vector stores (the Mosaic alignment prover rejects
    them; stream through aligned ring buffers + chunk DMAs instead)."""
    findings: List[Finding] = []
    for eqn, path in iter_eqns(closed.jaxpr):
        if "pallas_call" not in path:
            continue
        bad: Optional[str] = None
        if eqn.primitive.name in CUMULATIVE_PRIMS:
            bad = (
                f"{eqn.primitive.name} inside a Mosaic TC kernel has no "
                "lowering: use the MXU lower-triangular one-hot "
                "contraction (ops/pallas_compact.tri_inclusive)"
            )
        elif _is_u32_f32_cast(eqn):
            bad = (
                "direct u32<->f32 cast inside a Mosaic TC kernel is "
                "unsupported: hop through i32 (value-exact for 16-bit "
                "halves — ops/pallas_compact.split16/fuse16)"
            )
        elif eqn.primitive.name in STORE_PRIMS:
            # A store whose ref indexing consumes traced operands and
            # whose stored value is a vector: the dynamic-offset
            # vector-store shape. Static slices carry no index invars.
            idx_vars = [v for v in eqn.invars[2:] if not _is_literal(v)]
            val_aval = eqn.invars[1].aval if len(eqn.invars) > 1 else None
            if idx_vars and val_aval is not None and val_aval.shape:
                bad = (
                    "dynamic-offset vector store inside a Mosaic TC "
                    "kernel: the alignment prover rejects it — place "
                    "survivors via the one-hot ring fold and flush "
                    "with B-aligned chunk DMAs"
                )
        if bad:
            file, line = source_of(eqn)
            findings.append(
                Finding(
                    rule="STPU005",
                    surface=surface,
                    file=file,
                    line=line,
                    message=bad,
                    excerpt=excerpt_of(eqn),
                )
            )
    return findings
