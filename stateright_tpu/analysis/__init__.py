"""stpu-lint: static jaxpr/HLO + AST analysis enforcing the pinned
backend-miscompile rules (docs/static-analysis.md).

CLI: ``python -m stateright_tpu.analysis`` (wrapped by
``tools/stpu_lint.py``); library entry: :func:`run_lint`.
"""

from .rules import (  # noqa: F401
    MAX_SAFE_SORT_OPERANDS,
    RULES,
    Finding,
    Rule,
    Waiver,
    WaiverError,
    apply_waivers,
    load_waivers,
)
from .cli import DEFAULT_WAIVERS, main, run_lint  # noqa: F401
