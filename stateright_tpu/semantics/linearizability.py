"""Linearizability tester (semantics/linearizability.rs:57-312).

Captures a potentially concurrent operation history and decides whether a
total order exists that (a) is valid for the reference object, (b) respects
per-thread program order, and (c) respects *real-time* order: an operation
invoked after another completed (on any thread) may not be serialized before
it.  Real time is enforced by recording, at invocation, the index of the last
completed operation of every other thread (linearizability.rs:114-126) and
rejecting interleavings that would schedule an op while one of those
prerequisite peer ops is still unscheduled.
"""

from __future__ import annotations

from ._backtracking import BacktrackingTester


class LinearizabilityTester(BacktrackingTester):
    _REAL_TIME = True
