"""Write-once register reference semantics
(semantics/write_once_register.rs:9-58): the first write wins; later writes
of a *different* value fail, rewrites of the same value succeed."""

from __future__ import annotations

from typing import Any, Optional

from ..utils.variant import variant
from . import SequentialSpec

Write = variant("Write", ["value"])
Read = variant("Read", [])
WriteOk = variant("WriteOk", [])
WriteFail = variant("WriteFail", [])
ReadOk = variant("ReadOk", ["value"])  # value None while unwritten


class WORegister(SequentialSpec):
    __slots__ = ("value",)

    def __init__(self, value: Optional[Any] = None):
        self.value = value

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Write):
            if self.value is None or self.value == op.value:
                self.value = op.value
                return WriteOk()
            return WriteFail()
        if isinstance(op, Read):
            return ReadOk(self.value)
        raise TypeError(f"unknown WORegister op {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        # Specialized like write_once_register.rs:46-58.
        if isinstance(op, Write):
            if isinstance(ret, WriteOk):
                if self.value is None:
                    self.value = op.value
                    return True
                return self.value == op.value
            if isinstance(ret, WriteFail):
                return self.value is not None and self.value != op.value
            return False
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def clone(self) -> "WORegister":
        return WORegister(self.value)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, WORegister) and self.value == other.value

    def __hash__(self) -> int:
        return hash(("WORegister", self.value))

    def __repr__(self) -> str:
        return f"WORegister({self.value!r})"

    def __fingerprint_key__(self):
        return self.value
