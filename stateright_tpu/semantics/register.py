"""Read/write register reference semantics (semantics/register.rs:9-49)."""

from __future__ import annotations

from typing import Any

from ..utils.variant import variant
from . import SequentialSpec

Write = variant("Write", ["value"])
Read = variant("Read", [])
WriteOk = variant("WriteOk", [])
ReadOk = variant("ReadOk", ["value"])


class Register(SequentialSpec):
    """A register holding a single value; reads observe the latest write."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Write):
            self.value = op.value
            return WriteOk()
        if isinstance(op, Read):
            return ReadOk(self.value)
        raise TypeError(f"unknown register op {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        # Specialized like register.rs:38-49.
        if isinstance(op, Write) and isinstance(ret, WriteOk):
            self.value = op.value
            return True
        if isinstance(op, Read) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def clone(self) -> "Register":
        return Register(self.value)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Register) and self.value == other.value

    def __hash__(self) -> int:
        return hash(("Register", self.value))

    def __repr__(self) -> str:
        return f"Register({self.value!r})"

    def __fingerprint_key__(self):
        return self.value
