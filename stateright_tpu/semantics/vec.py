"""Stack-like vector reference semantics (semantics/vec.rs:22-50)."""

from __future__ import annotations

from typing import Any, Tuple

from ..utils.variant import variant
from . import SequentialSpec

Push = variant("Push", ["value"])
Pop = variant("Pop", [])
Len = variant("Len", [])
PushOk = variant("PushOk", [])
PopOk = variant("PopOk", ["value"])  # value None when empty
LenOk = variant("LenOk", ["length"])


class VecSpec(SequentialSpec):
    """Reference object over a growable vector: push/pop/len.  (Named
    ``Vec`` in the reference, where the spec is implemented directly on
    ``std::vec::Vec``.)"""

    __slots__ = ("items",)

    def __init__(self, items: Tuple[Any, ...] = ()):
        self.items = tuple(items)

    def invoke(self, op: Any) -> Any:
        if isinstance(op, Push):
            self.items = self.items + (op.value,)
            return PushOk()
        if isinstance(op, Pop):
            if not self.items:
                return PopOk(None)
            top, self.items = self.items[-1], self.items[:-1]
            return PopOk(top)
        if isinstance(op, Len):
            return LenOk(len(self.items))
        raise TypeError(f"unknown vec op {op!r}")

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        if isinstance(op, Push) and isinstance(ret, PushOk):
            self.items = self.items + (op.value,)
            return True
        if isinstance(op, Pop) and isinstance(ret, PopOk):
            if not self.items:
                return ret.value is None
            top, rest = self.items[-1], self.items[:-1]
            if ret.value == top:
                self.items = rest
                return True
            return False
        if isinstance(op, Len) and isinstance(ret, LenOk):
            return len(self.items) == ret.length
        return False

    def clone(self) -> "VecSpec":
        return VecSpec(self.items)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, VecSpec) and self.items == other.items

    def __hash__(self) -> int:
        return hash(("VecSpec", self.items))

    def __repr__(self) -> str:
        return f"VecSpec({list(self.items)!r})"

    def __fingerprint_key__(self):
        return self.items
