"""Exact consistency checking ON DEVICE, generalized over thread count,
operation count, and sequential spec.

The host testers (``linearizability.py`` / ``sequential_consistency.py``)
run a backtracking search per history
(``/root/reference/src/semantics/linearizability.rs:197-284``,
``/root/reference/src/semantics/sequential_consistency.rs:53-241``). A
backtracking search cannot be traced into an XLA program — but for the
statically-bounded histories packed models carry
(:class:`~stateright_tpu.packing.BoundedHistory`: T threads, at most M
completed ops plus one in-flight op each), the whole search space is a
*static enumeration*: every admissible serialization is a merge of the
per-thread sequences, i.e. an arrangement of the multiset
``{0^(M+1), ..., (T-1)^(M+1)}``. This module evaluates ALL of them as one
data-parallel expression — patterns become a constant ``[P, L]`` index
table, and each BFS frontier row checks every pattern simultaneously. That
is the TPU-first shape of the problem: no control flow, one fused
gather/where pipeline, ``P`` as a vector lane axis.

Semantics replicated (differentially tested against the host serializer):

- per-thread program order is preserved by construction (a thread's slots
  appear in sequence order in every pattern);
- **linearizability** additionally checks the recorded real-time
  prerequisites: an op invoked after a peer's op completed must be
  serialized after it (linearizability.rs:221-233);
- **sequential consistency** is the same enumeration with the real-time
  constraint dropped (sequential_consistency.rs:118-130 tracks only
  in-order per-thread consumption);
- in-flight ops "need never return" (the testers may exclude them): an
  excluded in-flight op is subsumed by a pattern scheduling it after every
  constrained op, because specs here are *total* (every op is invocable in
  every spec state) and a trailing op constrains nothing — completed-op
  prerequisites reference peer *completed* ops only;
- a poisoned history (``h_valid`` cleared by protocol misuse) is never
  serializable, matching the testers' HistoryError freeze.

Sizing: P = (T·(M+1))! / ((M+1)!)^T — 20 at 2×2, 1 680 at 3×2, 34 650 at
3×3, 369 600 at 4×2. Up to ``MAX_PATTERNS`` the whole enumeration runs as
one ``[P]``-lane pipeline; past it (SURVEY §7 M4 variant (b) widened,
round 4) the pattern axis is CHUNKED under ``lax.scan`` — live memory is
bounded by one ``[chunk]`` block while exactness is preserved — up to
``MAX_PATTERNS_EXACT``. Only beyond that (5 threads × 2 ops = 1.68e8)
should models fall back to the engine's ``host_verified_properties`` path
(a conservative sampled device predicate + exact host confirmation,
xla.py M4 variant (a)).

The pipeline carries per-thread RUNNING counts instead of precomputed
``slot``/``cnt_before`` tables: the only embedded constant is the
``tid[P, L]`` thread schedule (int8), which keeps the 4-thread exact
enumeration's constant footprint at ~4 MB instead of ~90 MB of derived
tables baked into the executable.
"""

from __future__ import annotations

import itertools
from functools import lru_cache
from typing import Tuple

import numpy as np

#: Single-shot lane budget: up to this many interleavings run as one
#: [P]-lane pipeline with no scan overhead.
MAX_PATTERNS = 50_000
#: Exact-enumeration ceiling for the chunked (lax.scan) path. Time-bounded,
#: not memory-bound: each scan step evaluates one PATTERN_CHUNK block.
MAX_PATTERNS_EXACT = 2_000_000
#: Pattern-block width for the scanned path: live intermediates are
#: [batch, PATTERN_CHUNK] lanes.
PATTERN_CHUNK = 8_192


@lru_cache(maxsize=None)
def interleaving_tids(T: int, slots: int, limit: int = None) -> np.ndarray:
    """The ``tid[P, L]`` thread-schedule table for merges of T sequences of
    ``slots`` slots (L = T*slots): the thread scheduled at each step. The
    per-thread slot index and preceding-count tables are derivable by a
    running count and are NOT materialized (see module docstring). With
    ``limit`` (< the full count), a deterministic uniform random sample of
    ``limit`` arrangements is generated directly — the full table is never
    built.
    """
    L = T * slots
    P_full = pattern_count(T, slots - 1)
    if limit is not None and limit < P_full:
        # A one-sided (host-confirmed) pass wants pattern DIVERSITY, and it
        # must NOT materialize the full enumeration (1.7e8 patterns at 5
        # threads x 2 ops): sample arrangements directly — each row is an
        # independent uniform shuffle of the multiset {t^slots}, which is
        # uniform over distinct patterns (duplicates merely waste lanes;
        # negligible while limit << P). Deterministic seed for stable
        # compilation caching.
        rng = np.random.default_rng(0xC0FFEE)
        base = np.repeat(np.arange(T, dtype=np.int32), slots)
        tid = np.asarray(rng.permuted(np.tile(base, (limit, 1)), axis=1))
    else:
        pats: list = []

        def rec(remaining: tuple, t: int, cur: list) -> None:
            if t == T - 1:
                pat = list(cur)
                for pos in remaining:
                    pat[pos] = t
                pats.append(pat)
                return
            for comb in itertools.combinations(remaining, slots):
                taken = set(comb)
                nxt = list(cur)
                for pos in comb:
                    nxt[pos] = t
                rec(tuple(p for p in remaining if p not in taken), t + 1, nxt)

        rec(tuple(range(L)), 0, [0] * L)
        tid = np.asarray(pats, dtype=np.int32)
    return np.ascontiguousarray(tid.astype(np.int8))


@lru_cache(maxsize=None)
def interleaving_tables(
    T: int, slots: int, limit: int = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Back-compat view of :func:`interleaving_tids` with the derived
    tables materialized: ``(tid[P, L], slot[P, L], cnt_before[P, L, T])``.
    The serializer itself no longer consumes the derived tables (it
    carries running counts); this form remains for tests/tooling."""
    tid = interleaving_tids(T, slots, limit).astype(np.int32)
    P, L = tid.shape
    slot = np.zeros((P, L), dtype=np.int32)
    cnt_before = np.zeros((P, L, T), dtype=np.int32)
    running = np.zeros((P, T), dtype=np.int32)
    rows = np.arange(P)
    for l in range(L):
        cnt_before[:, l, :] = running
        slot[:, l] = running[rows, tid[:, l]]
        running[rows, tid[:, l]] += 1
    return tid, slot, cnt_before


def pattern_count(T: int, max_ops: int) -> int:
    """P without building the tables: (T*(M+1))! / ((M+1)!)^T."""
    import math

    slots = max_ops + 1
    return math.factorial(T * slots) // math.factorial(slots) ** T


class DeviceRegister:
    """Device form of :class:`~stateright_tpu.semantics.register.Register`
    under the ``history_codecs`` convention (register.py:102-128): stored
    op codes — ``Read = 1``, ``Write(values[i]) = 2 + i``; stored ret
    codes — ``WriteOk = 1``, ``ReadOk(values[i]) = 2 + i``; running value
    is the ``values`` index (0 = unwritten None)."""

    def init_value(self, jnp, shape):
        return jnp.zeros(shape, jnp.uint32)

    def step(self, jnp, v, o, r, is_comp):
        is_read = o == jnp.uint32(1)
        is_write = o >= jnp.uint32(2)
        sem_ok = jnp.where(
            is_comp,
            jnp.where(is_read, r == v + jnp.uint32(2), r == jnp.uint32(1)),
            True,
        )
        v = jnp.where(is_write, o - jnp.uint32(2), v)
        return sem_ok, v


class DeviceWORegister:
    """Device form of
    :class:`~stateright_tpu.semantics.write_once_register.WORegister`
    (write_once_register.rs:9-58: first write wins, rewrites of the same
    value succeed, different-value writes fail) under ``wo_history_codecs``:
    stored op codes — ``Read = 1``, ``Write(values[i]) = 2 + i``; stored
    ret codes — ``WriteOk = 1``, ``WriteFail = 2``,
    ``ReadOk(values[i]) = 3 + i``."""

    def init_value(self, jnp, shape):
        return jnp.zeros(shape, jnp.uint32)

    def step(self, jnp, v, o, r, is_comp):
        u32 = jnp.uint32
        is_read = o == u32(1)
        is_write = o >= u32(2)
        w_val = o - u32(2)
        accepts = (v == u32(0)) | (v == w_val)  # unwritten or same value
        write_ok = jnp.where(accepts, r == u32(1), r == u32(2))
        sem_ok = jnp.where(
            is_comp,
            jnp.where(is_read, r == v + u32(3), write_ok),
            True,
        )
        v = jnp.where(is_write & accepts, w_val, v)
        return sem_ok, v


def device_serializable(hist, words, spec, *, real_time: bool, pattern_limit=None):
    """True iff the packed history in ``words`` admits a legal serialization
    of ``spec`` — the traced, exact device form of
    ``BacktrackingTester.serialized_history() is not None``
    (real_time=True: linearizability; False: sequential consistency).

    ``hist`` is the model's bound :class:`BoundedHistory`; jnp-traceable per
    state row (vmap over the frontier).

    ``pattern_limit``: evaluate only the first N patterns. The result is
    then one-sided — True still proves serializability, False means
    *unknown* — which is exactly the conservative-predicate contract of the
    engine's ``host_verified_properties`` path: use a limited device pass to
    clear the bulk of the frontier and let the host serializer confirm the
    flagged remainder.
    """
    import jax
    import jax.numpy as jnp

    T = len(hist.thread_ids)
    M = hist.max_ops
    slots = M + 1
    P_full = pattern_count(T, M)
    limit = (
        None
        if pattern_limit is None or pattern_limit >= P_full
        else pattern_limit
    )
    if (P_full if limit is None else limit) > MAX_PATTERNS_EXACT:
        raise NotImplementedError(
            f"{P_full if limit is None else limit} interleavings "
            f"({T} threads x {M}+1 ops"
            f"{'' if limit is None else f', pattern_limit={limit}'}) exceeds "
            f"MAX_PATTERNS_EXACT={MAX_PATTERNS_EXACT}; declare the property "
            "in host_verified_properties instead (conservative device "
            "predicate — this function with a pattern_limit <= "
            f"{MAX_PATTERNS_EXACT} — plus exact host confirmation)."
        )
    L_ = hist.layout
    u32 = jnp.uint32
    tid_np = interleaving_tids(T, slots, limit)  # [P, L] int8
    P = tid_np.shape[0]
    Lsteps = T * slots

    N = jnp.stack([L_.get(words, f"h{t}_n") for t in range(T)])  # [T]
    FL = jnp.stack([L_.get(words, f"h{t}_fl") for t in range(T)])  # [T]
    # Completed-op tables, padded to `slots` so the slot index is always in
    # bounds (the pad row is only gathered when inactive).
    zero = jnp.uint32(0)
    OP = jnp.stack(
        [
            jnp.stack([L_.get(words, f"h{t}_op", j) for j in range(M)] + [zero])
            for t in range(T)
        ]
    )  # [T, slots]
    RET = jnp.stack(
        [
            jnp.stack([L_.get(words, f"h{t}_ret", j) for j in range(M)] + [zero])
            for t in range(T)
        ]
    )  # [T, slots]
    npeer = max(T - 1, 1)
    # Prereqs on absolute thread columns (self column stays 0 = no entry).
    PRE = jnp.zeros((T, slots, T), u32)
    FLPRE = jnp.zeros((T, T), u32)
    for t in range(T):
        for pi, q in enumerate(hist.peers[t]):
            FLPRE = FLPRE.at[t, q].set(L_.get(words, f"h{t}_flpre", pi))
            for j in range(M):
                PRE = PRE.at[t, j, q].set(L_.get(words, f"h{t}_pre", j * npeer + pi))

    thread_lanes = jnp.arange(T, dtype=jnp.int32)

    def eval_block(tid_blk):
        """Serializability of this state's history over one [p, L] block of
        patterns; carries per-thread running counts (see module docstring)."""
        p = tid_blk.shape[0]
        running = jnp.zeros((p, T), u32)
        v = spec.init_value(jnp, (p,))
        ok = jnp.ones((p,), bool)
        for l in range(Lsteps):
            tl = tid_blk[:, l].astype(jnp.int32)  # [p]
            onehot = tl[:, None] == thread_lanes[None, :]  # [p, T]
            # This step's per-thread slot index: how many of tl's slots ran.
            sl = jnp.sum(jnp.where(onehot, running, zero), axis=1)  # [p] u32
            sl_i = sl.astype(jnp.int32)  # < slots by construction
            n_t = N[tl]
            is_comp = sl < n_t
            is_fl = (sl == n_t) & (FL[tl] != 0)
            active = is_comp | is_fl
            o = jnp.where(is_comp, OP[tl, sl_i], jnp.where(is_fl, FL[tl], zero))
            r = jnp.where(is_comp, RET[tl, sl_i], zero)
            if real_time:
                rt = jnp.ones((p,), bool)
                for q in range(T):
                    b = jnp.where(
                        is_comp, PRE[tl, sl_i, q], jnp.where(is_fl, FLPRE[tl, q], zero)
                    )
                    # Peer q's completed ops scheduled so far: its running
                    # count, capped at its completed count (dynamic).
                    sched = jnp.minimum(running[:, q], N[q])
                    # b stores prereq index + 2; 0 = no entry. b >= 2
                    # whenever nonzero, so b - 2 cannot wrap on the checked
                    # branch.
                    rt = rt & ((b == zero) | (b - u32(2) < sched))
            else:
                rt = True
            sem_ok, nv = spec.step(jnp, v, o, r, is_comp)
            # Inactive (padding) steps constrain nothing and change nothing.
            ok = ok & (~active | (rt & sem_ok))
            v = jnp.where(active, nv, v)
            running = running + onehot.astype(u32)
        return ok

    if P <= MAX_PATTERNS:
        any_ok = jnp.any(eval_block(jnp.asarray(tid_np)))
    else:
        # Chunk the pattern axis under lax.scan: exactness at bounded
        # memory. The pad block repeats pattern 0 — duplicates cannot
        # change an any() reduction.
        C = -(-P // PATTERN_CHUNK)
        pad = C * PATTERN_CHUNK - P
        if pad:
            tid_np = np.concatenate([tid_np, np.tile(tid_np[:1], (pad, 1))])
        xs = jnp.asarray(tid_np.reshape(C, PATTERN_CHUNK, Lsteps))

        def body(acc, tid_blk):
            return acc | jnp.any(eval_block(tid_blk)), None

        any_ok, _ = jax.lax.scan(body, jnp.bool_(False), xs)
    return (L_.get(words, "h_valid") != 0) & any_ok
