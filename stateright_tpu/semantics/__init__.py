"""Consistency semantics: reference objects and concurrent-history testers.

Mirrors the reference's ``semantics`` module (``/root/reference/src/semantics.rs``):
correctness of a concurrent system is defined by a sequential "reference
object" (:class:`SequentialSpec`) plus a consistency model that constrains how
concurrent operation histories may be serialized against it:

- :class:`LinearizabilityTester` — real-time order across threads must be
  respected (semantics/linearizability.rs:57).
- :class:`SequentialConsistencyTester` — only per-thread program order must be
  respected (semantics/sequential_consistency.rs:55).

Testers ride inside the checker as auxiliary history state (``ActorModel``'s
``H`` parameter), so they must be cheap to clone, equality-comparable, and
fingerprintable — all provided here.
"""

from __future__ import annotations

from typing import Any, Iterable, Tuple


class SequentialSpec:
    """A sequential reference object (semantics.rs:73-98).

    Subclasses define ``invoke(op) -> ret`` mutating the object, plus
    ``clone``/``__eq__``/``__fingerprint_key__``.  Op/Ret values are
    small NamedTuples (the Python rendering of the reference's enums).
    """

    def invoke(self, op: Any) -> Any:
        raise NotImplementedError

    def clone(self) -> "SequentialSpec":
        raise NotImplementedError

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        """Whether invoking ``op`` *might* return ``ret``.  Default mirrors
        the reference's (semantics.rs:88-90): invoke and compare.  NOTE: like
        the reference, this MUTATES the object (applies the op)."""
        return self.invoke(op) == ret

    def is_valid_history(self, ops: Iterable[Tuple[Any, Any]]) -> bool:
        """Whether a sequential (op, ret) history is valid (semantics.rs:92-97)."""
        return all(self.is_valid_step(op, ret) for op, ret in ops)


class ConsistencyTester:
    """Records operation invocations/returns of a concurrent system and
    decides whether the history satisfies a consistency model
    (semantics/consistency_tester.rs:15-43).

    ``on_invoke``/``on_return`` raise :class:`HistoryError` on protocol
    misuse (second in-flight op for a thread, return without invocation);
    the tester is poisoned thereafter and reports inconsistent.
    """

    def on_invoke(self, thread_id: Any, op: Any) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id: Any, ret: Any) -> "ConsistencyTester":
        raise NotImplementedError

    def is_consistent(self) -> bool:
        raise NotImplementedError

    def on_invret(self, thread_id: Any, op: Any, ret: Any) -> "ConsistencyTester":
        return self.on_invoke(thread_id, op).on_return(thread_id, ret)


class HistoryError(ValueError):
    """An operation history violated the recording protocol."""


from .linearizability import LinearizabilityTester  # noqa: E402
from .sequential_consistency import SequentialConsistencyTester  # noqa: E402
from . import register  # noqa: E402
from . import vec  # noqa: E402
from . import write_once_register  # noqa: E402

__all__ = [
    "ConsistencyTester",
    "HistoryError",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
    "SequentialSpec",
    "register",
    "vec",
    "write_once_register",
]
