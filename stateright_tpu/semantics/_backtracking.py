"""Shared backtracking serializer behind both consistency testers.

The reference implements the search twice (semantics/linearizability.rs:197-284,
semantics/sequential_consistency.rs:127-225); the only semantic difference is
that the linearizability variant records, per operation, the index of the last
operation completed by every *other* thread at invocation time, and rejects
interleavings that would reorder an operation before one of those
prerequisites ("real time" order).  Sequential consistency is the same search
with no prerequisites.  We implement the search once, parameterized by whether
real-time prerequisites are recorded.

Determinism note: the reference iterates threads in ``BTreeMap`` (sorted)
order, which fixes *which* witness serialization is returned; we iterate
sorted thread ids for the same reason, and tests assert identical witnesses.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from . import ConsistencyTester, HistoryError, SequentialSpec


class BacktrackingTester(ConsistencyTester):
    _REAL_TIME = False  # overridden by LinearizabilityTester

    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        # thread_id -> list of completed ops: (prereqs, op, ret) where
        # prereqs maps peer thread -> index of its last completed op at
        # invocation time ({} when real time is not tracked).
        self.history_by_thread: Dict[Any, List[Tuple[dict, Any, Any]]] = {}
        # thread_id -> (prereqs, op) for the at-most-one in-flight op.
        self.in_flight_by_thread: Dict[Any, Tuple[dict, Any]] = {}
        self.is_valid_history = True

    # --- recording (consistency_tester.rs:15-43) --------------------------

    def on_invoke(self, thread_id: Any, op: Any) -> "BacktrackingTester":
        if not self.is_valid_history:
            raise HistoryError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            raise HistoryError(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, op={self.in_flight_by_thread[thread_id][1]!r}"
            )
        if self._REAL_TIME:
            prereqs = {
                tid: len(completed) - 1
                for tid, completed in self.history_by_thread.items()
                if tid != thread_id and completed
            }
        else:
            prereqs = {}
        self.in_flight_by_thread[thread_id] = (prereqs, op)
        self.history_by_thread.setdefault(thread_id, [])
        return self

    def on_return(self, thread_id: Any, ret: Any) -> "BacktrackingTester":
        if not self.is_valid_history:
            raise HistoryError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            raise HistoryError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}"
            )
        prereqs, op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread.setdefault(thread_id, []).append((prereqs, op, ret))
        return self

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    # --- the search (linearizability.rs:197-284) --------------------------

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        """A total order of (op, ret) consistent with the reference object
        and the consistency model, or None.  In-flight operations may —
        but need not — take effect."""
        if not self.is_valid_history:
            return None
        remaining = {
            tid: [(i, entry) for i, entry in enumerate(completed)]
            for tid, completed in self.history_by_thread.items()
        }
        return self._serialize(
            [], self.init_ref_obj, remaining, dict(self.in_flight_by_thread)
        )

    @classmethod
    def _real_time_violation(cls, prereqs: dict, remaining: dict) -> bool:
        """An op may not be scheduled while a peer op it observed as complete
        is still unscheduled (linearizability.rs:221-233)."""
        for peer_id, min_peer_time in prereqs.items():
            peer_ops = remaining.get(peer_id)
            if peer_ops and peer_ops[0][0] <= min_peer_time:
                return True
        return False

    @classmethod
    def _serialize(
        cls,
        valid_history: List[Tuple[Any, Any]],
        ref_obj: SequentialSpec,
        remaining: Dict[Any, List[Tuple[int, Tuple[dict, Any, Any]]]],
        in_flight: Dict[Any, Tuple[dict, Any]],
    ) -> Optional[List[Tuple[Any, Any]]]:
        if all(not ops for ops in remaining.values()):
            return valid_history  # in-flight ops need never return
        for thread_id in sorted(remaining):
            thread_remaining = remaining[thread_id]
            if not thread_remaining:
                # Maybe the thread's in-flight op takes effect here; its
                # return value is chosen by the reference object.
                if thread_id not in in_flight:
                    continue
                prereqs, op = in_flight[thread_id]
                if cls._real_time_violation(prereqs, remaining):
                    continue
                next_ref_obj = ref_obj.clone()
                ret = next_ref_obj.invoke(op)
                next_in_flight = dict(in_flight)
                del next_in_flight[thread_id]
                next_remaining = remaining
            else:
                (idx, (prereqs, op, ret)) = thread_remaining[0]
                next_remaining = dict(remaining)
                next_remaining[thread_id] = thread_remaining[1:]
                if cls._real_time_violation(prereqs, next_remaining):
                    continue
                next_ref_obj = ref_obj.clone()
                if not next_ref_obj.is_valid_step(op, ret):
                    continue
                next_in_flight = in_flight
            result = cls._serialize(
                valid_history + [(op, ret)], next_ref_obj, next_remaining, next_in_flight
            )
            if result is not None:
                return result
        return None

    # --- value semantics (testers ride in fingerprinted history state) ----

    def clone(self) -> "BacktrackingTester":
        dup = type(self)(self.init_ref_obj.clone())
        dup.history_by_thread = {
            tid: list(completed) for tid, completed in self.history_by_thread.items()
        }
        dup.in_flight_by_thread = dict(self.in_flight_by_thread)
        dup.is_valid_history = self.is_valid_history
        return dup

    def _canonical(self):
        return (
            type(self).__name__,
            self.init_ref_obj.__fingerprint_key__(),
            tuple(
                (tid, tuple((tuple(sorted(pr.items())), op, ret) for pr, op, ret in cs))
                for tid, cs in sorted(self.history_by_thread.items())
            ),
            tuple(
                (tid, tuple(sorted(pr.items())), op)
                for tid, (pr, op) in sorted(self.in_flight_by_thread.items())
            ),
            self.is_valid_history,
        )

    def __eq__(self, other: Any) -> bool:
        return type(other) is type(self) and other._canonical() == self._canonical()

    def __hash__(self) -> int:
        return hash(self._canonical())

    def __fingerprint_key__(self):
        return self._canonical()

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(init={self.init_ref_obj!r}, "
            f"history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r})"
        )
