"""Sequential-consistency tester
(semantics/sequential_consistency.rs:55-379): like linearizability but
without the cross-thread real-time constraint — only per-thread program
order and reference-object validity restrict the serialization."""

from __future__ import annotations

from ._backtracking import BacktrackingTester


class SequentialConsistencyTester(BacktrackingTester):
    _REAL_TIME = False
