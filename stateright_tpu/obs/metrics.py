"""Counters registry: the event half of ``checker.metrics()``.

A plain dict of named monotonic counters, pre-seeded so the snapshot's key
set is stable across engines, dedup structures, and runs that never hit a
growth path (consumers diff snapshots; a key that appears only after the
first table growth would read as schema drift). Gauges — occupancy,
capacities, live counts — are NOT registered here: the engines compute
them from live state at ``metrics()`` time, so the registry itself never
touches the hot path (increments happen only at rare host-side events:
growths, flushes, shrink-exits).
"""

from __future__ import annotations

from typing import Dict, Iterable


class Counters:
    """Named monotonic event counters with a stable key set."""

    __slots__ = ("_c",)

    def __init__(self, seed: Iterable[str] = ()):
        self._c: Dict[str, int] = {name: 0 for name in seed}

    def inc(self, name: str, n: int = 1) -> None:
        self._c[name] = self._c.get(name, 0) + n

    def __getitem__(self, name: str) -> int:
        return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        return dict(self._c)
