"""Run-trace observability: spans, counters, heartbeat.

The engine's telemetry grew per-round as ad-hoc lists (``checker.level_log``,
``dispatch_log``, ``cand_retries``, ``hv_stats``) and bench-side logging;
this package is the one structured home for the pieces that need *wall-clock*
and *liveness*:

- :class:`~stateright_tpu.obs.trace.Tracer` — host-side wall-clock spans
  around every host↔device boundary (dispatch, compile-carrying dispatch,
  table growth/rehash, delta flush, host-verify round-trip), appended as
  JSONL (``STPU_TRACE=path`` / ``spawn_xla(trace=...)``) with a Chrome
  trace-event exporter (``export_chrome``) so runs open directly in
  Perfetto (``STPU_TRACE_CHROME=path`` auto-exports at interpreter exit).
- :class:`~stateright_tpu.obs.metrics.Counters` — the counter half of
  ``checker.metrics()``: growth events, shrink-exits, delta flushes.
  Gauges (occupancy, capacities, counts) are computed at snapshot time
  from live engine state, so the registry costs nothing on the hot path.
- :class:`~stateright_tpu.obs.heartbeat.Heartbeat` — a small JSON file the
  engine rewrites around every device dispatch (``STPU_HEARTBEAT=path`` /
  ``spawn_xla(heartbeat=...)``): phase ``"dispatch"`` before entering the
  device (with a ``compile`` flag when this call traces a fresh program),
  phase ``"idle"`` with ``seq`` incremented after it returns. Watchdogs
  (bench.py, tools/tpu_watch.sh) read staleness + phase to distinguish a
  wedged tunnel from a long XLA compile in-band.
- :class:`~stateright_tpu.obs.timeseries.MetricsRecorder` — the snapshot
  layer over time (``STPU_METRICS_TO=path`` / ``spawn_xla(metrics_to=...)``):
  append-only rotating ``metrics.jsonl`` of ``checker.metrics()`` rows
  sampled at quiescent superstep boundaries on a level/wall-clock
  cadence; :func:`~stateright_tpu.obs.timeseries.read_series` reassembles
  the rotation chain.
- :mod:`~stateright_tpu.obs.promexport` — OpenMetrics rendering of any
  snapshot or series tail (``stpu_*`` counter/gauge families with
  ``job``/``engine``/``dedup`` labels), served by the Explorer as
  ``GET /.metrics``; ships the validating parser the tests and smoke
  stage scrape with.

Everything here is OFF by default and adds **no device syncs** when on:
spans only wrap host boundaries and reuse scalars the host already fetches.
With tracing off the engines hold the shared :data:`NULL_TRACER`, whose
``span()`` returns a no-op context — no files, no clocks, no allocation.

Schemas are documented in ``docs/observability.md`` and pinned by
``tests/test_obs.py``.
"""

from __future__ import annotations

import os
from typing import Optional, Union

from . import collect
from .heartbeat import Heartbeat
from .metrics import Counters
from .timeseries import MetricsRecorder, read_series
from .trace import (
    CTX_ENV,
    NULL_TRACER,
    Span,
    Tracer,
    export_chrome,
    format_ctx,
    new_trace_id,
    parse_ctx,
)

__all__ = [
    "CTX_ENV",
    "Counters",
    "Heartbeat",
    "MetricsRecorder",
    "NULL_TRACER",
    "Span",
    "Tracer",
    "collect",
    "export_chrome",
    "format_ctx",
    "new_trace_id",
    "parse_ctx",
    "read_series",
    "resolve_heartbeat",
    "resolve_recorder",
    "resolve_tracer",
]


#: Process-wide live tracers by absolute path: several checkers in one
#: process (bench primary pass + matrix entries) must SHARE one tracer —
#: one epoch, one ``trace_start`` — or the appended file's timestamps
#: restart at zero mid-run and the Chrome/roofline timeline garbles.
_TRACERS: dict = {}


def resolve_tracer(trace: Union[None, str, Tracer] = None):
    """The tracer a checker should hold: an explicit :class:`Tracer`, a
    path (``spawn_xla(trace="...")``), the ``STPU_TRACE`` env default, or
    — the common case — the shared no-op :data:`NULL_TRACER`. Path
    resolution is cached process-wide (one tracer per file).

    ``STPU_TRACE_CHROME`` (env) or ``Tracer(chrome_path=...)`` additionally
    exports the Chrome trace-event form when the tracer closes."""
    if isinstance(trace, Tracer):
        return trace
    if trace is None:
        trace = os.environ.get("STPU_TRACE") or None
    if trace is None:
        return NULL_TRACER
    path = os.path.abspath(trace)
    tracer = _TRACERS.get(path)
    if tracer is None or tracer.closed:
        tracer = Tracer(
            path, chrome_path=os.environ.get("STPU_TRACE_CHROME") or None
        )
        _TRACERS[path] = tracer
    return tracer


def resolve_heartbeat(heartbeat: Union[None, str, Heartbeat] = None) -> Optional[Heartbeat]:
    """The heartbeat a checker should beat, or None (the default — the
    protocol is for watchdog-supervised runs, not every spawn)."""
    if isinstance(heartbeat, Heartbeat):
        return heartbeat
    if heartbeat is None:
        heartbeat = os.environ.get("STPU_HEARTBEAT") or None
    if heartbeat is None:
        return None
    return Heartbeat(heartbeat)


def resolve_recorder(metrics_to=None, metrics_every=None, metrics_keep=None):
    """The metrics recorder a checker should sample into, or None (the
    default — same off-by-default pin discipline as the tracer). Accepts
    a live :class:`MetricsRecorder` (shared-series embedders), a path, or
    the ``STPU_METRICS_{TO,EVERY,KEEP}`` env knobs."""
    if isinstance(metrics_to, MetricsRecorder):
        return metrics_to
    return MetricsRecorder.resolve(metrics_to, metrics_every, metrics_keep)
