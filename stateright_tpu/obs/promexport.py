"""OpenMetrics rendering of metrics snapshots (`GET /.metrics`).

Turns any ``checker.metrics()`` snapshot, ``service.gauges()`` pool
snapshot, or :mod:`~stateright_tpu.obs.timeseries` row into the
OpenMetrics text format Prometheus-shaped scrapers consume, so a running
Explorer/CheckerService is scrapable live (``checker/explorer.py`` serves
the render as ``GET /.metrics``).

Naming is mechanical — snapshot key ``foo`` becomes ``stpu_foo`` (engine
snapshots), ``stpu_pool_foo`` (pool snapshots), or ``stpu_hv_foo`` (the
flattened host-verify stats dict). Monotonic keys (the obs Counters plus
the cumulative search totals) render as OpenMetrics *counters* with the
mandatory ``_total`` suffix; everything else numeric is a *gauge*;
booleans render 0/1; strings and None are skipped (they ride as labels or
not at all). Labels carried per sample: ``job`` (the pool job id),
``engine``, ``dedup`` — the identity triple the ISSUE pins — with absent
values omitted, never empty-stringed. QoS rollups (``gauges()["qos"]``)
additionally ride ``class=`` / ``tenant=`` labels on the
``stpu_pool_qos_*`` families (docs/service.md "QoS & overload").

The module also ships :func:`parse_openmetrics` — a strict-enough parser
(TYPE tracking, label unescaping, the ``# EOF`` terminator) used by the
tests and the smoke stage to validate the endpoint's output and
cross-check every counter against ``checker.metrics()`` exactly. Both
directions are pinned by tests/test_promexport.py; documented in
docs/observability.md "/.metrics".
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: Snapshot keys rendered as OpenMetrics counters (monotonic; the
#: ``_total`` suffix is mandatory in the exposition format). Everything
#: numeric outside this set is a gauge.
COUNTER_KEYS = frozenset(
    {
        # cumulative search totals
        "state_count",
        "unique_state_count",
        "dispatches",
        "levels_committed",
        "cand_retries",
        # the obs.Counters event registry (ENGINE_COUNTERS + mesh extras)
        "table_grows",
        "frontier_grows",
        "cand_grows",
        "delta_flushes",
        "shrink_exits",
        "ladder_jumps",
        "checkpoints_written",
        "route_grows",
        # pool counters (SERVICE_COUNTERS)
        "submitted",
        "admitted",
        "rejected",
        "jobs_done",
        "jobs_failed",
        "wedge_verdicts",
        "crashes",
        "requeues",
        "breaker_trips",
        "breaker_closes",
        "degraded_jobs",
        "device_probes",
        "lint_checks",
        "lint_rejects",
        "lint_errors",
        "idem_dedups",
        "jobs_recovered",
        "orphans_killed",
        "artifacts_swept",
        "jobs_evacuated",
        # QoS tier (docs/service.md "QoS & overload"): shed admissions,
        # tenant-quota rejections, aging-term scheduler picks, and
        # compile-on-admit warm-cache spawns.
        "sheds",
        "quota_rejects",
        "aged_picks",
        "warm_compiles",
        # batched scheduling (xla_mux.py; docs/service.md "Batched
        # scheduling") — mux_groups/mux_lanes count groups/members the
        # pool launched, mux_dispatches_saved the device calls the
        # batching avoided (both the pool's fold of worker summaries and
        # the per-lane engine snapshots carry the latter). mux_lanes and
        # mux_lanes_active on a LIVE MuxChecker snapshot are gauges (the
        # batch's current width), so only the monotonic keys ride here.
        "mux_groups",
        "mux_dispatches_saved",
        # fleet counters (FLEET_COUNTERS; service/fleet.py)
        "routed",
        "migrations",
        "devices_lost",
        "device_flakes",
        "host_last_resort",
        "pools_quiesced",
        "pools_woken",
    }
)

#: The label set every sample may carry (ISSUE 13): absent values are
#: omitted from the sample, never rendered as empty strings.
LABEL_KEYS = ("job", "engine", "dedup")

#: One exposition sample: ``(metric_name, labels, value)``.
Sample = Tuple[str, Dict[str, str], float]


def _numeric(value: Any) -> Optional[float]:
    """The sample value for a snapshot entry, or None to skip it. bools
    are 0/1 (``waiting``, breaker flags); ints/floats pass through;
    strings/None/containers are identity or structure, not samples."""
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        return float(value)
    return None


def _labels_of(snapshot: Dict[str, Any], extra: Optional[Dict[str, Any]] = None) -> Dict[str, str]:
    """The identity labels a snapshot carries: ``job`` from the service
    job id, ``engine``/``dedup`` from the snapshot's own config gauges;
    ``extra`` (caller-known identity, e.g. the pool job id for a finished
    job's recorded snapshot) wins over the snapshot."""
    merged = {"job": snapshot.get("job_id"), "engine": snapshot.get("engine"),
              "dedup": snapshot.get("dedup")}
    if extra:
        merged.update({k: v for k, v in extra.items() if k in LABEL_KEYS})
    return {k: str(v) for k, v in merged.items() if v is not None}


def engine_samples(
    snapshot: Dict[str, Any], labels: Optional[Dict[str, Any]] = None
) -> List[Sample]:
    """Flatten one engine snapshot into ``stpu_*`` samples (the ``hv``
    stats dict flattens to ``stpu_hv_*`` gauges)."""
    lab = _labels_of(snapshot, labels)
    out: List[Sample] = []
    for key, value in snapshot.items():
        if key == "hv" and isinstance(value, dict):
            for hk, hv in value.items():
                v = _numeric(hv)
                if v is not None:
                    out.append((f"stpu_hv_{hk}", lab, v))
            continue
        v = _numeric(value)
        if v is None:
            continue
        name = f"stpu_{key}_total" if key in COUNTER_KEYS else f"stpu_{key}"
        out.append((name, lab, v))
    return out


def pool_samples(
    gauges: Dict[str, Any],
    labels: Optional[Dict[str, Any]] = None,
    prefix: str = "stpu_pool",
) -> List[Sample]:
    """Flatten a ``service.gauges()`` snapshot into ``{prefix}_*``
    samples: occupancy counts, caps, the SERVICE_COUNTERS, breaker state
    (``{prefix}_breaker_open`` 0/1 + consecutive-wedge gauge), and the
    journal position. ``labels`` ride every sample — the Explorer labels
    a fleet's per-device pool rows ``device="device-K"`` — and fleet-
    scoped rows render under ``prefix="stpu_fleet"`` so they never share
    a family with (and double-count against) the per-device pool rows."""
    out: List[Sample] = []
    lab: Dict[str, str] = {
        str(k): str(v) for k, v in (labels or {}).items() if v is not None
    }
    for key, value in gauges.items():
        if key == "breaker" and isinstance(value, dict):
            out.append(
                (f"{prefix}_breaker_open", lab, float(value.get("state") == "open"))
            )
            v = _numeric(value.get("consecutive_wedges"))
            if v is not None:
                out.append((f"{prefix}_breaker_consecutive_wedges", lab, v))
            continue
        if key == "journal" and isinstance(value, dict):
            v = _numeric(value.get("records"))
            if v is not None:
                out.append((f"{prefix}_journal_records_total", lab, v))
            continue
        if key == "qos" and isinstance(value, dict):
            out.extend(_qos_samples(value, lab, prefix))
            continue
        v = _numeric(value)
        if v is None:
            continue
        name = (
            f"{prefix}_{key}_total" if key in COUNTER_KEYS else f"{prefix}_{key}"
        )
        out.append((name, lab, v))
    return out


def _qos_samples(
    qos: Dict[str, Any], lab: Dict[str, str], prefix: str
) -> List[Sample]:
    """Flatten a ``gauges()["qos"]`` dict (docs/service.md "QoS &
    overload"): per-class rows render under ``{prefix}_qos_class_*`` with
    a ``class`` label, per-tenant rows under ``{prefix}_qos_tenant_*``
    with a ``tenant`` label, scalar fields (``aging_s``,
    ``drain_per_s``) as plain gauges. ``served`` is the journaled
    monotonic stride counter, so it renders as an OpenMetrics counter."""
    out: List[Sample] = []
    for key, value in qos.items():
        if key in ("classes", "tenants") and isinstance(value, dict):
            label_key = "class" if key == "classes" else "tenant"
            suffix = "class" if key == "classes" else "tenant"
            for ident, row in value.items():
                if not isinstance(row, dict):
                    continue
                row_lab = dict(lab)
                row_lab[label_key] = str(ident)
                for f, fv in row.items():
                    v = _numeric(fv)
                    if v is None:
                        continue
                    name = (
                        f"{prefix}_qos_{suffix}_{f}_total"
                        if f == "served"
                        else f"{prefix}_qos_{suffix}_{f}"
                    )
                    out.append((name, row_lab, v))
            continue
        v = _numeric(value)
        if v is not None:
            out.append((f"{prefix}_qos_{key}", lab, v))
    return out


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\"", "\\\"").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    # Integral values render without a trailing .0 — exact-count
    # cross-checks (and humans) compare them against ints.
    return str(int(v)) if float(v).is_integer() else repr(float(v))


#: Memoized build-info labels: the package tree hash walks every source
#: file — compute once per process (the tree cannot change under a
#: running service), not once per scrape.
_BUILD_INFO_LABELS: Optional[Dict[str, str]] = None


def build_info_sample(platform: Optional[str] = None) -> Sample:
    """The ``stpu_build_info`` identity gauge (value always 1; the
    standard Prometheus *info*-metric idiom): ``platform`` (the live jax
    backend unless the caller knows better), ``jax`` (version), and
    ``tree`` — the package-tree content hash the stpu-lint cache keys by
    (``analysis/cache.tree_hash``), so a scrape ties metrics to the exact
    source the service is running."""
    global _BUILD_INFO_LABELS
    if _BUILD_INFO_LABELS is None:
        import jax

        try:
            from ..analysis.cache import tree_hash

            tree = tree_hash()[:12]
        except Exception:  # noqa: BLE001 - identity is best-effort
            tree = "unknown"
        _BUILD_INFO_LABELS = {
            "jax": getattr(jax, "__version__", "unknown"),
            "tree": tree,
        }
    if platform is None:
        import jax

        platform = jax.default_backend()
    return (
        "stpu_build_info",
        {"platform": str(platform), **_BUILD_INFO_LABELS},
        1.0,
    )


def render_openmetrics(samples: List[Sample]) -> str:
    """One OpenMetrics exposition of ``samples``: a ``# TYPE`` line per
    family (counter families carry the ``_total``-stripped family name,
    per the spec), samples grouped under it, ``# EOF`` terminated."""
    by_family: Dict[str, List[Sample]] = {}
    order: List[str] = []
    for name, labels, value in samples:
        family = name[: -len("_total")] if name.endswith("_total") else name
        if family not in by_family:
            by_family[family] = []
            order.append(family)
        by_family[family].append((name, labels, value))
    lines: List[str] = []
    for family in order:
        rows = by_family[family]
        kind = "counter" if rows[0][0].endswith("_total") else "gauge"
        lines.append(f"# TYPE {family} {kind}")
        for name, labels, value in rows:
            if labels:
                inner = ",".join(
                    f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
                )
                lines.append(f"{name}{{{inner}}} {_fmt_value(value)}")
            else:
                lines.append(f"{name} {_fmt_value(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> Dict[Tuple[str, frozenset], float]:
    """The validating parser the tests and the smoke stage drive against
    ``GET /.metrics``: returns ``{(name, frozenset(labels.items())):
    value}``. Raises ``ValueError`` on a malformed exposition — missing
    ``# EOF``, a sample line that does not parse, a ``_total`` sample
    under a non-counter family, or a counter family whose samples lack
    the suffix."""
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition not terminated by # EOF")
    out: Dict[Tuple[str, frozenset], float] = {}
    types: Dict[str, str] = {}
    for line in lines[:-1]:
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
                continue
            raise ValueError(f"unexpected comment line: {line!r}")
        name, labels, value = _parse_sample(line)
        family = name[: -len("_total")] if name.endswith("_total") else name
        kind = types.get(family)
        if kind is None:
            raise ValueError(f"sample before its # TYPE line: {line!r}")
        if kind == "counter" and not name.endswith("_total"):
            raise ValueError(f"counter sample without _total: {line!r}")
        if kind != "counter" and name.endswith("_total"):
            raise ValueError(f"_total sample under gauge family: {line!r}")
        key = (name, frozenset(labels.items()))
        if key in out:
            raise ValueError(f"duplicate sample: {line!r}")
        out[key] = value
    return out


def _parse_sample(line: str) -> Tuple[str, Dict[str, str], float]:
    rest = line
    labels: Dict[str, str] = {}
    brace = rest.find("{")
    if brace != -1:
        name = rest[:brace]
        end = rest.rfind("}")
        if end == -1:
            raise ValueError(f"unterminated label set: {line!r}")
        labels = _parse_labels(rest[brace + 1 : end])
        rest = rest[end + 1 :]
    else:
        name, _, rest = rest.partition(" ")
        rest = " " + rest
    if not name or not name.replace("_", "").replace(":", "").isalnum():
        raise ValueError(f"bad metric name in: {line!r}")
    try:
        value = float(rest.strip().split()[0])
    except (ValueError, IndexError):
        raise ValueError(f"bad sample value in: {line!r}") from None
    return name, labels, value


def _parse_labels(body: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.find("=", i)
        if eq == -1 or body[eq + 1] != '"':
            raise ValueError(f"bad label pair in: {body!r}")
        key = body[i:eq]
        j = eq + 2
        value = []
        while j < len(body):
            c = body[j]
            if c == "\\":
                nxt = body[j + 1] if j + 1 < len(body) else ""
                value.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            value.append(c)
            j += 1
        else:
            raise ValueError(f"unterminated label value in: {body!r}")
        labels[key] = "".join(value)
        i = j + 1
        if i < len(body) and body[i] == ",":
            i += 1
    return labels


#: The Content-Type the endpoint serves (the OpenMetrics registration).
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"
