"""Heartbeat protocol: in-band liveness for wedge-prone device runs.

The axon TPU tunnel WEDGES — blocks forever rather than failing — so every
supervisor so far has guessed from the *outside* with one hard ``timeout``
(bench.py's watchdog, the per-round ``tpu_watch`` scripts). The ambiguity
that breaks those guesses: a silent 20-minute worker may be (a) wedged, or
(b) paying a legitimate multi-minute XLA compile. This file is the in-band
answer. The engine rewrites it (atomically, via ``os.replace``) around
every device dispatch:

- **before** entering the device: ``phase="dispatch"`` plus a ``compile``
  flag when this call traces a fresh program (its round-trip legitimately
  includes an XLA compile — allow it a longer leash);
- **after** the dispatch returns: ``phase="idle"``, ``seq`` incremented —
  exactly one increment per completed device dispatch (the same unit as
  one ``checker.dispatch_log`` entry).

File content (one JSON object)::

    {"ts": <unix seconds>, "seq": <completed dispatches>,
     "phase": "dispatch" | "idle", "compile": <bool>, ...extra gauges}

A watchdog then reads: *mtime fresh* → alive; *stale in phase="idle"* →
host-side work or a dead process (not the tunnel); *stale in
phase="dispatch", compile=true* → probably compiling, extend the leash;
*stale in phase="dispatch", compile=false* → wedged tunnel, kill and
retry. ``bench.py`` and ``tools/tpu_watch.sh`` implement exactly this.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional


class Heartbeat:
    """Writer side of the protocol (one per checker; ``seq`` is local to
    the writer — supervisors track deltas, not absolute values)."""

    __slots__ = ("path", "seq")

    def __init__(self, path: str):
        self.path = path
        self.seq = 0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def beat(self, phase: str, **info: Any) -> None:
        """Rewrite the file (atomic replace: readers never see a torn
        write; mtime always advances)."""
        payload = {"ts": time.time(), "seq": self.seq, "phase": phase}
        payload.update(info)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, default=str)
        os.replace(tmp, self.path)

    def commit(self, **info: Any) -> None:
        """One completed device dispatch: bump ``seq``, mark idle."""
        self.seq += 1
        self.beat("idle", **info)


def read(path: str) -> Optional[Dict[str, Any]]:
    """Reader side: the parsed heartbeat, or None (missing/torn file —
    torn is impossible from this writer, but the reader stays safe against
    foreign writers)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def age_s(path: str) -> Optional[float]:
    """Seconds since the last beat (mtime-based), or None if absent."""
    try:
        return max(0.0, time.time() - os.stat(path).st_mtime)
    except OSError:
        return None
