"""Span tracer: append-only JSONL + Chrome trace-event export.

One JSON object per line, flushed as written (a wedged tunnel mid-run must
not take the spans before it), schema::

    {"ts": <float, seconds since tracer start>,
     "dur": <float, seconds>,
     "name": <str>,
     "span_id": <str, unique within the file>,
     "attrs": {<span attributes>}}

plus — only when the tracer carries a **trace context** (distributed
tracing, docs/observability.md "Distributed tracing") — two more keys::

    {"trace_id": <str, the submission's fleet-wide trace id>,
     "parent_id": <str or absent, the parent span's span_id>}

A context is set explicitly (:meth:`Tracer.set_context`) or inherited
from the ``STPU_TRACE_CTX`` environment variable
(``"<trace_id>:<parent_span_id>"``, :func:`format_ctx`/:func:`parse_ctx`)
— the propagation seam across process boundaries: the service exports it
into every worker's env, so engine spans in the worker join the
submission's trace with the supervising attempt span as their parent.
Without a context the extra keys are absent and records are byte-
compatible with the pre-context schema.

The first line of every tracer is a ``trace_start`` span (dur 0) carrying
``pid`` and the absolute ``unix_ts`` of the tracer epoch, so traces from
several processes can be aligned. Span names the engines emit:

``dispatch``
    One host→device→host round-trip of a compiled superstep program (one
    or many BFS levels). Attrs: ``flavor`` (``fused``/``single``),
    ``bucket`` (run rows), ``cand`` (candidate cap, or the ladder's rung
    list under fused dispatch), ``committed`` (levels committed — 0 means
    an overflow exit), ``compile`` (this call traced+compiled a fresh XLA
    program: its wall-clock includes the compile), ``retry`` (re-run of a
    level after an overflow recovery), ``dedup``, ``compaction``, and —
    fused path — ``shrink_below`` when a shrink-exit threshold is armed.
``grow_table``
    Visited-set growth (rehash / plane copy) — the overflow-recovery
    device work. Attrs: ``dedup``, ``capacity`` (new).
``delta_flush``
    The delta structure's host-invoked ``maintain`` merge. Attrs:
    ``proactive`` (load-rule flush at a dispatch boundary vs an
    overflow-triggered one).
``host_verify``
    Host-side exact re-check of device-flagged candidates for
    host-verified properties. Attrs: ``checked``, ``confirmed``.
``phase:host_prep`` / ``phase:enqueue`` / ``phase:device_compute`` /
``phase:readback``
    The dispatch-phase profiler's sub-spans (``spawn_xla(phases=True)`` /
    ``STPU_PHASES=1``, off by default): contiguous sub-intervals of ONE
    parent ``dispatch`` span (``parent_id`` = the dispatch span's
    ``span_id``), splitting the host→device round-trip into input
    staging, the async program enqueue (compile rides here on a fresh
    program), the ``block_until_ready`` wait, and the host-side scalar
    readback. Attrs: ``bucket``. Consumed by ``tools/roofline.py
    --phases``.

The exporter (:func:`export_chrome`) rewrites a span JSONL as one Chrome
trace-event JSON object (``{"traceEvents": [...]}``, complete events,
microsecond times) — the format Perfetto and ``chrome://tracing`` load
directly; spans carrying ``lanes_active`` additionally render as Perfetto
counter tracks (mux lane occupancy over time). The multi-file merger for
whole service/fleet run dirs is :mod:`stateright_tpu.obs.collect`.
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Any, Dict, Optional, Tuple


#: The env var carrying a trace context across process boundaries:
#: ``"<trace_id>:<parent_span_id>"`` (parent part may be empty). The
#: service/fleet tiers export it into worker environments; any Tracer
#: constructed in that process inherits it.
CTX_ENV = "STPU_TRACE_CTX"


def new_trace_id() -> str:
    """A fresh submission-scoped trace id (16 hex chars)."""
    return os.urandom(8).hex()


def format_ctx(trace_id: str, parent_id: Optional[str] = None) -> str:
    """The ``STPU_TRACE_CTX`` wire form of a context."""
    return f"{trace_id}:{parent_id or ''}"


def parse_ctx(value: Optional[str]) -> Optional[Tuple[str, Optional[str]]]:
    """``(trace_id, parent_id)`` from the wire form, or None when unset/
    malformed (a bad env var must degrade to context-less tracing, not
    fail the worker)."""
    if not value:
        return None
    trace_id, _, parent = value.partition(":")
    if not trace_id:
        return None
    return trace_id, (parent or None)


class Span:
    """Context manager recording one wall-clock span; attributes may be
    added mid-span with :meth:`set` (e.g. counts only known after the
    host syncs the dispatch results). ``span_id`` is allocated at entry so
    in-flight consumers (the phase profiler) can parent sub-spans to it."""

    __slots__ = ("_tracer", "name", "attrs", "_t0", "span_id")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self.span_id = self._tracer._new_sid()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        self._tracer._emit(
            self.name, t0, time.monotonic() - t0, self.attrs,
            span_id=self.span_id,
        )
        return False


class _NullSpan:
    """The do-nothing span: tracing off costs two attribute lookups and a
    shared-singleton return — no clock reads, no allocation, no I/O."""

    __slots__ = ()

    span_id = None

    def set(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _NullTracer:
    enabled = False
    trace_id = None

    def span(self, name: str, **attrs: Any) -> _NullSpan:
        return _NULL_SPAN

    def emit(self, name: str, **kw: Any) -> Optional[str]:
        return None

    def new_span_id(self) -> Optional[str]:
        return None

    def set_context(self, trace_id: Optional[str],
                    parent_id: Optional[str] = None) -> None:
        pass

    def set_parent(self, parent_id: Optional[str]) -> None:
        pass

    def close(self) -> None:
        pass


#: The shared off-switch: engines hold this when no trace is configured.
NULL_TRACER = _NullTracer()


class Tracer:
    """Append-only JSONL span writer (see module docstring for schema)."""

    enabled = True

    def __init__(self, path: str, chrome_path: Optional[str] = None):
        self.path = path
        self.chrome_path = chrome_path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a")
        self._epoch = time.monotonic()
        # Span ids are unique within the appended file across processes
        # and attempts: pid + a 2-byte salt (pid reuse across a long
        # kill/requeue chain) + a per-tracer sequence.
        self._sid_prefix = f"{os.getpid():x}-{os.urandom(2).hex()}"
        self._sid_seq = 0
        # Distributed-trace context: inherited from STPU_TRACE_CTX (the
        # cross-process seam) unless set_context overrides it.
        ctx = parse_ctx(os.environ.get(CTX_ENV))
        self.trace_id, self._parent_id = ctx if ctx else (None, None)
        self._emit(
            "trace_start", self._epoch, 0.0,
            {"pid": os.getpid(), "unix_ts": time.time()},
        )
        if chrome_path is not None:
            # Best-effort export when the process ends — checkers have no
            # close hook, and an explicit export_chrome() call (bench.py,
            # tests) always works regardless.
            atexit.register(self.close)

    @property
    def closed(self) -> bool:
        return self._fh.closed

    def span(self, name: str, **attrs: Any) -> Span:
        return Span(self, name, attrs)

    def set_context(self, trace_id: Optional[str],
                    parent_id: Optional[str] = None) -> None:
        """Join (or leave, with None) a distributed trace: subsequent
        records carry ``trace_id`` and default their ``parent_id`` to
        ``parent_id`` until narrowed by :meth:`set_parent`."""
        self.trace_id = trace_id
        self._parent_id = parent_id

    def set_parent(self, parent_id: Optional[str]) -> None:
        """Re-root subsequent spans under ``parent_id`` (e.g. a worker's
        enclosing job span, so engine dispatch spans nest under it)."""
        self._parent_id = parent_id

    def emit(self, name: str, *, t0: float, dur: float,
             attrs: Optional[Dict[str, Any]] = None,
             parent_id: Optional[str] = None,
             trace_id: Optional[str] = None,
             span_id: Optional[str] = None) -> Optional[str]:
        """Emit one pre-timed span (``t0`` on the ``time.monotonic`` clock,
        ``dur`` seconds) and return its span_id. The phase profiler and
        the service tiers use this for intervals measured with raw stamps
        rather than a ``with`` block. ``trace_id`` overrides the tracer's
        ambient context per record — a SHARED tracer (one service file,
        many concurrent jobs) must not mutate ambient state per job — and
        ``span_id`` lets a caller pre-allocate the id
        (:meth:`new_span_id`) so children can reference a span emitted
        only after they finish (the supervising attempt span)."""
        sid = span_id if span_id is not None else self._new_sid()
        self._emit(name, t0, dur, dict(attrs or {}), span_id=sid,
                   parent_id=parent_id, trace_id=trace_id)
        return sid

    def new_span_id(self) -> str:
        """Pre-allocate a span id (for :meth:`emit`'s ``span_id=``)."""
        return self._new_sid()

    def _new_sid(self) -> str:
        self._sid_seq += 1
        return f"{self._sid_prefix}.{self._sid_seq}"

    def _emit(self, name: str, t0: float, dur: float, attrs: Dict[str, Any],
              span_id: Optional[str] = None,
              parent_id: Optional[str] = None,
              trace_id: Optional[str] = None) -> None:
        if self._fh.closed:  # post-close span from a lingering checker
            return
        rec = {
            "ts": round(t0 - self._epoch, 6),
            "dur": round(dur, 6),
            "name": name,
            "span_id": span_id if span_id is not None else self._new_sid(),
            "attrs": attrs,
        }
        tid = trace_id if trace_id is not None else self.trace_id
        if tid is not None:
            rec["trace_id"] = tid
            parent = parent_id if parent_id is not None else self._parent_id
            if parent is not None:
                rec["parent_id"] = parent
        elif parent_id is not None:
            rec["parent_id"] = parent_id
        self._fh.write(json.dumps(rec, default=str) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()
        if self.chrome_path is not None:
            try:
                export_chrome(self.path, self.chrome_path)
            except OSError:  # pragma: no cover - exit-path best effort
                pass


def export_chrome(jsonl_path: str, out_path: str) -> int:
    """Rewrites a span JSONL as Chrome trace-event JSON (complete "X"
    events, microsecond clocks) that Perfetto / ``chrome://tracing`` open
    directly. Returns the number of events written. Lines that do not
    parse (a wedge mid-write) are skipped, not fatal.

    Mux-lane telemetry renders as counter tracks: every span whose attrs
    carry ``lanes_active`` (the batched dispatch spans,
    docs/observability.md "Lane telemetry") additionally emits one "C"
    event at its start, so Perfetto charts lane occupancy over the run
    next to the slices."""
    events = []
    pid = os.getpid()
    # An appended file can hold several tracer sessions (bench retries:
    # one per worker process), each with its own zero-based monotonic
    # epoch. Rebase every session onto the first one's wall clock via
    # the unix_ts each trace_start records, so the exported timeline is
    # sequential instead of all sessions overlapping at t=0.
    base_unix = None
    offset = 0.0
    with open(jsonl_path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("name") == "trace_start":
                attrs = rec.get("attrs", {})
                pid = attrs.get("pid", pid)
                u = attrs.get("unix_ts")
                if u is not None:
                    if base_unix is None:
                        base_unix = u
                    offset = u - base_unix
                continue
            events.extend(
                chrome_events(rec, pid=pid, tid=1, offset_s=offset)
            )
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return len(events)


def chrome_events(rec: Dict[str, Any], *, pid: int, tid: int,
                  offset_s: float = 0.0) -> list:
    """The Chrome trace events for ONE span record: the complete "X"
    slice (context ids ride in ``args``), plus a ``lanes_active`` counter
    sample when the span carries lane telemetry. Shared by the
    single-file exporter above and the run-dir merger (obs/collect.py)
    so both render identically."""
    attrs = rec.get("attrs", {})
    args = dict(attrs)
    for key in ("trace_id", "span_id", "parent_id"):
        if rec.get(key) is not None:
            args[key] = rec[key]
    ts = round((rec["ts"] + offset_s) * 1e6, 3)
    out = [
        {
            "name": rec["name"],
            "cat": "stateright_tpu",
            "ph": "X",
            "ts": ts,
            "dur": round(rec["dur"] * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        }
    ]
    if "lanes_active" in attrs:
        counters = {"lanes_active": attrs["lanes_active"]}
        if "lanes" in attrs:
            counters["lanes_idle"] = (
                attrs["lanes"] - attrs["lanes_active"]
            )
        out.append(
            {
                "name": "mux lanes",
                "cat": "stateright_tpu",
                "ph": "C",
                "ts": ts,
                "pid": pid,
                "args": counters,
            }
        )
    return out
