"""Span tracer: append-only JSONL + Chrome trace-event export.

One JSON object per line, flushed as written (a wedged tunnel mid-run must
not take the spans before it), schema::

    {"ts": <float, seconds since tracer start>,
     "dur": <float, seconds>,
     "name": <str>,
     "attrs": {<span attributes>}}

The first line of every tracer is a ``trace_start`` span (dur 0) carrying
``pid`` and the absolute ``unix_ts`` of the tracer epoch, so traces from
several processes can be aligned. Span names the engines emit:

``dispatch``
    One host→device→host round-trip of a compiled superstep program (one
    or many BFS levels). Attrs: ``flavor`` (``fused``/``single``),
    ``bucket`` (run rows), ``cand`` (candidate cap, or the ladder's rung
    list under fused dispatch), ``committed`` (levels committed — 0 means
    an overflow exit), ``compile`` (this call traced+compiled a fresh XLA
    program: its wall-clock includes the compile), ``retry`` (re-run of a
    level after an overflow recovery), ``dedup``, ``compaction``, and —
    fused path — ``shrink_below`` when a shrink-exit threshold is armed.
``grow_table``
    Visited-set growth (rehash / plane copy) — the overflow-recovery
    device work. Attrs: ``dedup``, ``capacity`` (new).
``delta_flush``
    The delta structure's host-invoked ``maintain`` merge. Attrs:
    ``proactive`` (load-rule flush at a dispatch boundary vs an
    overflow-triggered one).
``host_verify``
    Host-side exact re-check of device-flagged candidates for
    host-verified properties. Attrs: ``checked``, ``confirmed``.

The exporter (:func:`export_chrome`) rewrites a span JSONL as one Chrome
trace-event JSON object (``{"traceEvents": [...]}``, complete events,
microsecond times) — the format Perfetto and ``chrome://tracing`` load
directly.
"""

from __future__ import annotations

import atexit
import json
import os
import time
from typing import Any, Dict, Optional


class Span:
    """Context manager recording one wall-clock span; attributes may be
    added mid-span with :meth:`set` (e.g. counts only known after the
    host syncs the dispatch results)."""

    __slots__ = ("_tracer", "name", "attrs", "_t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        self._tracer._emit(self.name, t0, time.monotonic() - t0, self.attrs)
        return False


class _NullSpan:
    """The do-nothing span: tracing off costs two attribute lookups and a
    shared-singleton return — no clock reads, no allocation, no I/O."""

    __slots__ = ()

    def set(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _NullTracer:
    enabled = False

    def span(self, name: str, **attrs: Any) -> _NullSpan:
        return _NULL_SPAN

    def close(self) -> None:
        pass


#: The shared off-switch: engines hold this when no trace is configured.
NULL_TRACER = _NullTracer()


class Tracer:
    """Append-only JSONL span writer (see module docstring for schema)."""

    enabled = True

    def __init__(self, path: str, chrome_path: Optional[str] = None):
        self.path = path
        self.chrome_path = chrome_path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "a")
        self._epoch = time.monotonic()
        self._emit(
            "trace_start", self._epoch, 0.0,
            {"pid": os.getpid(), "unix_ts": time.time()},
        )
        if chrome_path is not None:
            # Best-effort export when the process ends — checkers have no
            # close hook, and an explicit export_chrome() call (bench.py,
            # tests) always works regardless.
            atexit.register(self.close)

    @property
    def closed(self) -> bool:
        return self._fh.closed

    def span(self, name: str, **attrs: Any) -> Span:
        return Span(self, name, attrs)

    def _emit(self, name: str, t0: float, dur: float, attrs: Dict[str, Any]) -> None:
        if self._fh.closed:  # post-close span from a lingering checker
            return
        self._fh.write(
            json.dumps(
                {
                    "ts": round(t0 - self._epoch, 6),
                    "dur": round(dur, 6),
                    "name": name,
                    "attrs": attrs,
                },
                default=str,
            )
            + "\n"
        )
        self._fh.flush()

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()
        if self.chrome_path is not None:
            try:
                export_chrome(self.path, self.chrome_path)
            except OSError:  # pragma: no cover - exit-path best effort
                pass


def export_chrome(jsonl_path: str, out_path: str) -> int:
    """Rewrites a span JSONL as Chrome trace-event JSON (complete "X"
    events, microsecond clocks) that Perfetto / ``chrome://tracing`` open
    directly. Returns the number of events written. Lines that do not
    parse (a wedge mid-write) are skipped, not fatal."""
    events = []
    pid = os.getpid()
    # An appended file can hold several tracer sessions (bench retries:
    # one per worker process), each with its own zero-based monotonic
    # epoch. Rebase every session onto the first one's wall clock via
    # the unix_ts each trace_start records, so the exported timeline is
    # sequential instead of all sessions overlapping at t=0.
    base_unix = None
    offset = 0.0
    with open(jsonl_path) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("name") == "trace_start":
                attrs = rec.get("attrs", {})
                pid = attrs.get("pid", pid)
                u = attrs.get("unix_ts")
                if u is not None:
                    if base_unix is None:
                        base_unix = u
                    offset = u - base_unix
                continue
            events.append(
                {
                    "name": rec["name"],
                    "cat": "stateright_tpu",
                    "ph": "X",
                    "ts": round((rec["ts"] + offset) * 1e6, 3),
                    "dur": round(rec["dur"] * 1e6, 3),
                    "pid": pid,
                    "tid": 1,
                    "args": rec.get("attrs", {}),
                }
            )
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return len(events)
