"""Merged fleet timeline: stitch every span ``trace.jsonl`` under a
service/fleet run dir into ONE Perfetto-loadable Chrome trace.

A traced run scatters span files across the process tree — the fleet's
router (``<run_dir>/trace.jsonl``), each pool's service
(``device-*/trace.jsonl``), and every worker job/lane
(``.../job-*/trace.jsonl``). Each file is one or more tracer *sessions*
(kill-resume appends a fresh ``trace_start`` per attempt), each with its
own zero-based monotonic epoch. The merger:

- assigns every file a synthetic Chrome ``pid`` with a ``process_name``
  metadata track labelled by its run-dir-relative path, so the timeline
  reads as one row per service/device/job;
- rebases every session onto the EARLIEST ``trace_start`` wall clock in
  the whole run dir (the ``unix_ts`` each session records), so
  cross-process spans line up on one global time axis;
- re-emits mux-lane counter samples (``lanes_active`` attrs) as "C"
  events per process, same rendering as the single-file exporter;
- draws **flow arrows** per distributed ``trace_id`` over the anchor
  spans (``submit`` → ``route`` → ``attempt`` → ``job`` → ``lane`` →
  ``migrate``, in timestamp order), so one submission's path across
  routing, attempts, migration hops, and batched lanes is a single
  connected arc in Perfetto.

Surface: :func:`collect` returns the trace object, :func:`write` dumps
it (``tools/trace_bundle.py`` and the Explorer's ``GET /.trace.json``
are the two callers). Pure host-side file walking — no jax, no device.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .trace import chrome_events

#: Span names that anchor a distributed trace's flow arc, in causal
#: order of the tiers that emit them (ties broken by timestamp).
ANCHOR_SPANS = ("submit", "route", "attempt", "job", "lane", "migrate")


def trace_files(run_dir: str) -> List[str]:
    """Every span JSONL under ``run_dir`` (files named ``trace.jsonl``),
    sorted by relative path — the fleet/service root file first, then
    device pools, then per-job dirs."""
    found = []
    for root, _dirs, files in os.walk(run_dir):
        for name in files:
            if name == "trace.jsonl":
                found.append(os.path.join(root, name))
    return sorted(found, key=lambda p: os.path.relpath(p, run_dir))


def _read_sessions(path: str) -> List[Dict[str, Any]]:
    """Parse one span JSONL into tracer sessions: ``trace_start`` opens a
    session; records before any (a torn head) get a synthetic one.
    Unparseable lines (a kill mid-write) are skipped, never fatal."""
    sessions: List[Dict[str, Any]] = []
    cur: Optional[Dict[str, Any]] = None
    try:
        fh = open(path)
    except OSError:
        return sessions
    with fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or "name" not in rec:
                continue
            if rec.get("name") == "trace_start":
                attrs = rec.get("attrs", {})
                cur = {
                    "unix_ts": attrs.get("unix_ts"),
                    "pid": attrs.get("pid"),
                    "records": [],
                }
                sessions.append(cur)
                continue
            if cur is None:
                cur = {"unix_ts": None, "pid": None, "records": []}
                sessions.append(cur)
            cur["records"].append(rec)
    return sessions


def collect(run_dir: str) -> Dict[str, Any]:
    """The merged Chrome trace object for ``run_dir`` (see module
    docstring). Always returns a valid (possibly empty) trace."""
    files = trace_files(run_dir)
    per_file: List[Tuple[str, List[Dict[str, Any]]]] = [
        (os.path.relpath(path, run_dir), _read_sessions(path))
        for path in files
    ]
    # Global epoch: earliest session wall clock anywhere in the run dir.
    # Sessions with no unix_ts (torn head) fall back to offset 0 — their
    # spans still render, just unaligned.
    base_unix = None
    for _rel, sessions in per_file:
        for s in sessions:
            u = s["unix_ts"]
            if u is not None and (base_unix is None or u < base_unix):
                base_unix = u

    meta: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    # anchors[trace_id] -> list of (abs_ts_us, causal_rank, pid, tid)
    anchors: Dict[str, List[Tuple[float, int, int, int]]] = {}
    for index, (rel, sessions) in enumerate(per_file):
        pid = index + 1
        label = os.path.dirname(rel) or "."
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": label},
        })
        meta.append({
            "name": "process_sort_index", "ph": "M", "pid": pid,
            "args": {"sort_index": index},
        })
        for s in sessions:
            u = s["unix_ts"]
            offset = (u - base_unix) if (u is not None and
                                         base_unix is not None) else 0.0
            for rec in s["records"]:
                try:
                    evs = chrome_events(rec, pid=pid, tid=1,
                                        offset_s=offset)
                except (KeyError, TypeError):
                    continue  # a malformed record must not kill the merge
                events.extend(evs)
                tid_ = rec.get("trace_id")
                if tid_ and rec.get("name") in ANCHOR_SPANS:
                    anchors.setdefault(tid_, []).append((
                        evs[0]["ts"],
                        ANCHOR_SPANS.index(rec["name"]),
                        pid, 1,
                    ))

    # Flow arrows: one arc per trace_id through its anchors in time
    # order. Chrome binds a flow event to the slice ENCLOSING its ts at
    # that pid/tid — each anchor's own start ts qualifies.
    flows: List[Dict[str, Any]] = []
    for trace_id, marks in anchors.items():
        if len(marks) < 2:
            continue
        marks.sort()
        last = len(marks) - 1
        for i, (ts, _rank, pid, tid) in enumerate(marks):
            ev = {
                "name": "trace", "cat": "flow", "id": trace_id,
                "ts": ts, "pid": pid, "tid": tid,
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
            }
            if i == last:
                ev["bp"] = "e"  # bind the arrowhead to the enclosing slice
            flows.append(ev)

    events.sort(key=lambda e: e["ts"])
    flows.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": meta + events + flows,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": os.path.abspath(run_dir),
            "trace_files": [rel for rel, _ in per_file],
            "traces": sorted(anchors),
        },
    }


def write(run_dir: str, out_path: str) -> int:
    """Dump :func:`collect`'s merge to ``out_path``; returns the event
    count."""
    obj = collect(run_dir)
    parent = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(obj, fh)
    return len(obj["traceEvents"])
