"""Metrics time-series: ``checker.metrics()`` snapshots over wall-clock.

``checker.metrics()`` (obs/metrics.py + the engines) is a point-in-time
snapshot and the span trace is per-event; neither answers "what did the
run look like *over time*" — frontier growth, gen/s trends, occupancy
creep, queue depth under a service. :class:`MetricsRecorder` is that
layer: an append-only, rotating ``metrics.jsonl`` of snapshot rows,
sampled by the engines at quiescent superstep boundaries (the same points
the auto-checkpointer uses — the device state is a pure function of
host-visible arrays there, so sampling never adds a device sync) on a
cadence of committed levels or wall-clock seconds.

Row schema (one JSON object per line, schema-versioned)::

    {"v": 1,
     "unix_ts": <float, absolute seconds>,
     "t": <float, seconds since the recorder armed>,
     "seq": <int, rows written by this recorder>,
     "kind": "engine" | "pool" | <caller-defined>,
     "metrics": {<the snapshot, verbatim>}}

Rotation mirrors the checkpoint module's pattern (checkpoint.py): when the
live file reaches ``rotate_rows`` rows it shifts to ``<path>.1`` (``.1``
to ``.2``, ... retaining ``keep`` files) via ``os.replace`` — atomic from
any reader's view, bounded on disk at soak scale. :func:`read_series`
reads the rotation chain back oldest-first, skipping torn lines (a
SIGKILL mid-append is this system's designed failure mode).

Off by default, same pin discipline as the tracer: engines hold ``None``
and the hot-path cost is one ``is not None`` check; results are
bit-identical with recording on (pinned in tests/test_obs.py). Knobs::

    spawn_xla(metrics_to=path, metrics_every=N|"Ns", metrics_keep=K)
    STPU_METRICS_TO / STPU_METRICS_EVERY / STPU_METRICS_KEEP

Consumers: ``obs/promexport.py`` (OpenMetrics render of a series tail),
the Explorer's ``GET /.jobs/{id}/metrics.json`` + ``/.dash`` dashboard,
``tools/roofline.py --measured`` (coarse stage report when no span trace
exists), and per-job series under the CheckerService's run dir
(``service/worker.py``). Schema pinned by tests/test_obs.py; documented
in docs/observability.md "Time series".
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

#: Row schema version — consumers (promexport, the dashboard, roofline)
#: key on this; bump on any breaking row-shape change.
SCHEMA_VERSION = 1


class MetricsRecorder:
    """Append-only rotating JSONL sampler of metrics snapshots.

    The engines call :meth:`maybe` at every quiescent point (next to the
    auto-checkpoint hook); this object decides whether a row is due —
    every ``every`` committed levels, or every that many seconds with an
    ``"Ns"`` spec — and appends the snapshot. :meth:`sample` is the
    direct form (``force=True`` writes unconditionally: final rows,
    pool-side samplers, the Explorer's live ring)."""

    #: Default cadence when ``metrics_to`` is set without an explicit
    #: ``metrics_every``: frequent enough for a live dashboard, cheap
    #: enough for a soak (one small JSON line per write).
    DEFAULT_EVERY = "5s"
    DEFAULT_KEEP = 3
    #: Rows per rotation file. At one row / 5 s a file spans ~5.7 hours;
    #: keep=3 bounds a soak's series to ~17 hours of history on disk.
    DEFAULT_ROTATE_ROWS = 4096

    def __init__(
        self,
        path: str,
        every: Any = None,
        keep: Optional[int] = None,
        rotate_rows: Optional[int] = None,
    ):
        # The cadence grammar is the auto-checkpointer's (_parse_every:
        # int = committed levels, "Ns" = wall-clock seconds) — one
        # spelling for both quiescent-point consumers.
        from ..checkpoint import _parse_every

        self.path = path
        self.every_levels, self.every_seconds = _parse_every(
            self.DEFAULT_EVERY if every is None else every
        )
        self.keep = self.DEFAULT_KEEP if keep is None else int(keep)
        if self.keep < 1:
            raise ValueError(f"metrics_keep must be >= 1: {self.keep}")
        self.rotate_rows = (
            self.DEFAULT_ROTATE_ROWS if rotate_rows is None else int(rotate_rows)
        )
        if self.rotate_rows < 1:
            raise ValueError(f"rotate_rows must be >= 1: {self.rotate_rows}")
        self.seq = 0
        self._epoch = time.monotonic()
        self._last_depth: Optional[int] = None
        self._last_time: Optional[float] = None
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        # Append mode: a resumed/requeued worker continues the same
        # series file; count the rows already there so rotation bounds
        # hold across process restarts. A torn tail (SIGKILL mid-append
        # left a partial line with no trailing newline — the designed
        # failure mode) is repaired with a newline FIRST, so the next
        # row never concatenates onto the fragment and gets lost with it.
        self._rows_in_file = 0
        torn_tail = False
        if os.path.exists(path):
            try:
                last = b"\n"
                with open(path, "rb") as fh:
                    for line in fh:
                        self._rows_in_file += 1
                        last = line
                torn_tail = not last.endswith(b"\n")
            except OSError:
                pass
        self._fh = open(path, "a")
        if torn_tail:
            self._fh.write("\n")
            self._fh.flush()

    @classmethod
    def resolve(cls, metrics_to, metrics_every, metrics_keep):
        """The spawn-kwarg/env resolution the engines share (mirrors
        ``AutoCheckpointer.resolve``): ``metrics_to`` / ``STPU_METRICS_TO``
        arms recording; ``metrics_every`` / ``STPU_METRICS_EVERY`` and
        ``metrics_keep`` / ``STPU_METRICS_KEEP`` tune it. Returns None
        when off. The env path arms every checker in the process onto one
        file — rows are self-describing (``kind`` + the snapshot's own
        ``engine``/``job_id``), so a shared file stays parseable, but
        multi-checker processes that want separate series must pass
        ``metrics_to`` explicitly per spawn (the service worker does)."""
        path = metrics_to or os.environ.get("STPU_METRICS_TO") or None
        if path is None:
            return None
        every = (
            metrics_every
            if metrics_every is not None
            else os.environ.get("STPU_METRICS_EVERY") or None
        )
        keep = (
            metrics_keep
            if metrics_keep is not None
            else os.environ.get("STPU_METRICS_KEEP") or None
        )
        return cls(path, every, None if keep is None else int(keep))

    @property
    def closed(self) -> bool:
        return self._fh.closed

    # --- cadence (the AutoCheckpointer contract) --------------------------

    def arm(self, depth: int) -> None:
        """Baseline the cadence at the checker's starting point (fresh
        init or restore) — the first interval is measured from here."""
        self._last_depth = depth
        self._last_time = time.monotonic()

    def due(self, depth: int) -> bool:
        if self._last_depth is None:
            self.arm(depth)
            return False
        if self.every_levels is not None:
            return depth - self._last_depth >= self.every_levels
        return time.monotonic() - self._last_time >= self.every_seconds

    def maybe(self, checker) -> bool:
        """Engine hook at a quiescent superstep boundary: append a row if
        one is due. ``checker.metrics()`` is pure host-side reads, so this
        never adds a device sync. Returns whether it wrote."""
        depth = checker._depth
        if not self.due(depth):
            return False
        self.sample(checker.metrics(), kind="engine")
        self._last_depth = depth
        self._last_time = time.monotonic()
        return True

    # --- writing ----------------------------------------------------------

    def sample(self, metrics: Dict[str, Any], kind: str = "engine") -> None:
        """Append one row unconditionally (cadence-independent callers:
        final rows at completion, pool gauges, live dashboard rings)."""
        if self._fh.closed:  # post-close sample from a lingering checker
            return
        row = {
            "v": SCHEMA_VERSION,
            "unix_ts": time.time(),
            "t": round(time.monotonic() - self._epoch, 6),
            "seq": self.seq,
            "kind": kind,
            "metrics": metrics,
        }
        self._fh.write(json.dumps(row, default=str) + "\n")
        self._fh.flush()
        self.seq += 1
        self._rows_in_file += 1
        if self._rows_in_file >= self.rotate_rows:
            self._rotate()

    def _rotate(self) -> None:
        """Shift the full live file down the rotation chain (checkpoint.py
        pattern: ``.1`` to ``.2``, ..., live to ``.1``, retaining ``keep``
        files total) and start a fresh live file."""
        self._fh.close()
        if self.keep > 1:
            for i in range(self.keep - 1, 1, -1):
                older = f"{self.path}.{i - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{self.path}.{i}")
            os.replace(self.path, f"{self.path}.1")
        else:
            try:
                os.unlink(self.path)
            except OSError:
                pass
        self._fh = open(self.path, "a")
        self._rows_in_file = 0

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()


def series_files(path: str) -> List[str]:
    """Existing rotation files for ``path``, OLDEST first (``.K`` ...
    ``.1``, then the live file) — the read order that reassembles the
    series chronologically."""
    out = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        out.append(f"{path}.{i}")
        i += 1
    out.reverse()
    if os.path.exists(path):
        out.append(path)
    return out


def read_series(path: str, window: Optional[int] = None) -> List[Dict[str, Any]]:
    """The parsed series across the rotation chain, oldest row first;
    ``window`` keeps only the newest N rows. Lines that do not parse (a
    kill mid-append) or are not v-schema rows are skipped, not fatal."""
    rows: List[Dict[str, Any]] = []
    for f in series_files(path):
        try:
            with open(f) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict) and "v" in rec and "metrics" in rec:
                        rows.append(rec)
        except OSError:
            continue
    if window is not None and window >= 0:
        rows = rows[-window:] if window else []
    return rows
