"""Vector clocks. Mirrors ``/root/reference/src/util/vector_clock.rs``:
classic vector clocks with zero-suffix-insensitive equality/hashing
(vector_clock.rs:12-107)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def _trim(values: Sequence[int]) -> Tuple[int, ...]:
    vals = tuple(values)
    end = len(vals)
    while end and vals[end - 1] == 0:
        end -= 1
    return vals[:end]


class VectorClock:
    __slots__ = ("_values",)

    def __init__(self, values: Sequence[int] = ()):
        self._values = _trim(values)

    def get(self, index: int) -> int:
        return self._values[index] if index < len(self._values) else 0

    def incremented(self, index: int) -> "VectorClock":
        vals = list(self._values) + [0] * max(0, index + 1 - len(self._values))
        vals[index] += 1
        return VectorClock(vals)

    def merge_max(self, other: "VectorClock") -> "VectorClock":
        n = max(len(self._values), len(other._values))
        return VectorClock([max(self.get(i), other.get(i)) for i in range(n)])

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1 if self < other, 0 if equal, 1 if self > other, None if
        concurrent (incomparable)."""
        n = max(len(self._values), len(other._values))
        less = any(self.get(i) < other.get(i) for i in range(n))
        greater = any(self.get(i) > other.get(i) for i in range(n))
        if less and greater:
            return None
        if less:
            return -1
        if greater:
            return 1
        return 0

    def __lt__(self, other):
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) == -1

    def __le__(self, other):
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) in (-1, 0)

    def __gt__(self, other):
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) == 1

    def __ge__(self, other):
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) in (0, 1)

    def __eq__(self, other) -> bool:
        return isinstance(other, VectorClock) and self._values == other._values

    def __hash__(self) -> int:
        return hash(self._values)

    def __fingerprint_key__(self):
        return self._values

    def __repr__(self) -> str:
        return f"VectorClock({list(self._values)!r})"

    def __str__(self) -> str:
        # Display parity with the reference (vector_clock.rs can_display):
        # stored elements then an ellipsis for the implicit zeros.
        return "<" + "".join(f"{v}, " for v in self._values) + "...>"
