"""Dense natural-key map. Mirrors ``/root/reference/src/util/densenatmap.rs``:
a list-backed map for keys densely packed in ``0..n`` (actor ids, process
ids).  Insertion at a gap raises (densenatmap.rs:98-113)."""

from __future__ import annotations

from typing import Any, Iterator, List, Sequence, Tuple


class DenseNatMap:
    __slots__ = ("_values",)

    def __init__(self, values: Sequence[Any] = ()):
        self._values: List[Any] = list(values)

    @staticmethod
    def from_iter(values) -> "DenseNatMap":
        return DenseNatMap(list(values))

    def insert(self, key: int, value: Any) -> None:
        k = int(key)
        if k < len(self._values):
            self._values[k] = value
        elif k == len(self._values):
            self._values.append(value)
        else:
            raise IndexError(
                f"DenseNatMap keys must be dense: inserting {k} with len {len(self._values)}"
            )

    def get(self, key: int) -> Any:
        k = int(key)
        return self._values[k] if 0 <= k < len(self._values) else None

    def __getitem__(self, key: int) -> Any:
        return self._values[int(key)]

    def __setitem__(self, key: int, value: Any) -> None:
        self.insert(key, value)

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self) -> Iterator[Any]:
        return iter(self._values)

    def items(self) -> Iterator[Tuple[int, Any]]:
        return enumerate(self._values)

    def values(self) -> List[Any]:
        return list(self._values)

    def __eq__(self, other) -> bool:
        return isinstance(other, DenseNatMap) and self._values == other._values

    def __hash__(self) -> int:
        return hash(tuple(self._values))

    def __fingerprint_key__(self):
        return tuple(self._values)

    def __rewrite__(self, plan):
        """Reindexes by the plan's permutation (densenatmap.rs:223-238)."""
        return DenseNatMap(plan.reindex(self._values))

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"
