"""Utility containers: rewrite plans for symmetry reduction, dense maps,
vector clocks (reference layer L0, ``/root/reference/src/util.rs``).

The reference's ``HashableHashSet``/``HashableHashMap`` (order-insensitive
stable hashing, util.rs:73-366) have no separate classes here: plain
``frozenset``/``dict`` values already fingerprint order-insensitively via
``stateright_tpu.fingerprint``.
"""

from .densenatmap import DenseNatMap
from .rewrite_plan import RewritePlan, rewrite
from .variant import variant
from .vector_clock import VectorClock

__all__ = ["DenseNatMap", "RewritePlan", "VectorClock", "rewrite", "variant"]
