"""Enum-variant tuples: namedtuples whose Eq/Hash include the type.

A Rust enum derives ``Hash``/``PartialEq`` over its *discriminant plus*
payload, so two variants with identical payloads are never equal (e.g. the
``PaxosMsg`` variants in the reference's ``examples/paxos.rs:65-88``).
Python ``NamedTuple`` compares as a bare tuple, so ``Accept(b, p) ==
Decided(b, p)`` would be ``True`` — silently merging distinct messages in
any set or map keyed by them.  The modeled ``Network`` is exactly such a
map, so this corrupts state-space exploration.

:func:`variant` returns a ``collections.namedtuple`` subclass whose
``__eq__``/``__hash__`` are tagged by the defining module and class name,
restoring Rust enum-variant semantics while keeping all namedtuple
conveniences (``_replace``, field access, unpacking, ordering).
"""

from __future__ import annotations

import sys
from collections import namedtuple


def variant(typename: str, field_names, *, module: str = None) -> type:
    """Create a namedtuple class with type-tagged equality and hashing.

    Cross-class structural comparison (``<``, ``>``) still behaves like
    plain tuples; only ``==``/``!=``/``hash`` are tagged.
    """
    if module is None:
        try:
            module = sys._getframe(1).f_globals.get("__name__", "__main__")
        except (AttributeError, ValueError):  # pragma: no cover
            module = "__main__"
    base = namedtuple(typename, field_names)
    tag = f"{module}.{typename}"

    def __eq__(self, other):
        if type(other) is type(self):
            return tuple.__eq__(self, other)
        if isinstance(other, tuple):
            return False  # block the structural tuple fallback
        return NotImplemented  # delegate to e.g. mock.ANY's __eq__

    def __ne__(self, other):
        eq = __eq__(self, other)
        return eq if eq is NotImplemented else not eq

    def __hash__(self):
        return hash((tag, tuple.__hash__(self)))

    cls = type(
        typename,
        (base,),
        {
            "__slots__": (),
            "__eq__": __eq__,
            "__ne__": __ne__,
            "__hash__": __hash__,
            "_variant_tag": tag,
        },
    )
    cls.__module__ = module
    cls.__qualname__ = typename
    return cls
