"""Symmetry-reduction rewrite plans.

Mirrors ``/root/reference/src/checker/rewrite_plan.rs`` and ``rewrite.rs``:
a :class:`RewritePlan` is a permutation derived by (stably) sorting values;
``reindex`` permutes index-keyed collections and :func:`rewrite` recursively
remaps :class:`~stateright_tpu.actor.Id` values inside arbitrary structures.

The reference implements ``Rewrite`` as a trait with blanket impls
(rewrite.rs:24-163); here one generic function dispatches structurally, and
classes may define ``__rewrite__(plan)`` for custom behavior.
"""

from __future__ import annotations

from typing import Any, List, Sequence

from ..fingerprint import fingerprint
from .densenatmap import DenseNatMap


class RewritePlan:
    """A permutation plan: ``order[new_index] = old_index``.

    The inverse mapping lives in a :class:`DenseNatMap` keyed by old index —
    the same dense-natural-key container the reference's ``RewritePlan``
    is built on (rewrite_plan.rs:19, densenatmap.rs:75)."""

    def __init__(self, order: Sequence[int]):
        self.order = list(order)
        # Inverse: new index of each old index.
        inverse = [0] * len(self.order)
        for new, old in enumerate(self.order):
            inverse[old] = new
        self.new_of_old = DenseNatMap(inverse)

    @staticmethod
    def from_values_to_sort(values: Sequence[Any]) -> "RewritePlan":
        """Plan that would stably sort ``values`` ascending
        (rewrite_plan.rs:81-106).  Values without a total order fall back to
        sorting by stable fingerprint (deterministic across runs)."""
        idx = range(len(values))
        try:
            order = sorted(idx, key=lambda i: values[i])
        except TypeError:
            order = sorted(idx, key=lambda i: fingerprint(values[i]))
        return RewritePlan(order)

    def rewrite_id(self, id_value: int):
        """The new index of old index ``id_value`` (rewrite_plan.rs:110)."""
        from ..actor import Id

        return Id(self.new_of_old[int(id_value)])

    def reindex(self, collection: Sequence[Any]) -> List[Any]:
        """Permutes an index-keyed collection AND rewrites each element
        (rewrite_plan.rs:118-123 rewrites every element as it permutes —
        element values may themselves embed Ids that must be remapped)."""
        return [rewrite(collection[old], self) for old in self.order]


def rewrite(value: Any, plan: RewritePlan) -> Any:
    """Recursively remaps :class:`Id` values inside ``value``
    (the generic analogue of rewrite.rs's blanket impls: no-op for scalars,
    structural recursion for containers, ``__rewrite__`` for custom types).
    Unknown structured types raise rather than silently passing through —
    a missed Id remap would make symmetry reduction unsound — and the
    error NAMES THE PATH to the offending value (``state.msgs[2].src``),
    not just its type, so a model author can find the field to fix."""
    return _rewrite(value, plan, "state")


def _rewrite(value: Any, plan: RewritePlan, path: str) -> Any:
    import dataclasses
    from enum import Enum

    from ..actor import Id
    from ..actor.network import Envelope

    if isinstance(value, Id):
        return plan.rewrite_id(value)
    custom = getattr(value, "__rewrite__", None)
    if custom is not None:
        return custom(plan)
    if isinstance(value, Envelope):
        return Envelope(
            _rewrite(value.src, plan, f"{path}.src"),
            _rewrite(value.dst, plan, f"{path}.dst"),
            _rewrite(value.msg, plan, f"{path}.msg"),
        )
    t = type(value)
    if isinstance(value, tuple) and hasattr(value, "_fields"):
        return t(*(
            _rewrite(v, plan, f"{path}.{name}")
            for name, v in zip(value._fields, value)
        ))
    if t is tuple:
        return tuple(
            _rewrite(v, plan, f"{path}[{i}]") for i, v in enumerate(value)
        )
    if t is list:
        return [_rewrite(v, plan, f"{path}[{i}]") for i, v in enumerate(value)]
    if t in (set, frozenset):
        return t(_rewrite(v, plan, f"{path}{{…}}") for v in value)
    if isinstance(value, DenseNatMap):
        # Index-keyed by construction (actor/process ids): the plan
        # permutes the ENTRIES too, not just embedded Ids — the
        # reference's Rewrite impl reindexes (rewrite.rs:137-147).
        return DenseNatMap(
            [
                _rewrite(value[old], plan, f"{path}[{old}]")
                for old in plan.order
            ]
        )
    if isinstance(value, dict):
        out = {
            _rewrite(k, plan, f"{path}[key {k!r}]"):
                _rewrite(v, plan, f"{path}[{k!r}]")
            for k, v in value.items()
        }
        # dict subclasses (OrderedDict, defaultdict, Counter) rebuild as
        # their own type when the one-arg constructor accepts a mapping;
        # defaultdict's factory is restored explicitly.
        if t is dict:
            return out
        if hasattr(value, "default_factory"):
            fresh = t(value.default_factory)
            fresh.update(out)
            return fresh
        return t(out)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return type(value)(
            **{
                f.name: _rewrite(getattr(value, f.name), plan, f"{path}.{f.name}")
                for f in dataclasses.fields(value)
            }
        )
    if value is None or isinstance(
        value, (bool, int, float, complex, str, bytes, bytearray, range, Enum)
    ):
        return value
    raise TypeError(
        f"cannot rewrite {path} (type {t.__qualname__}) for symmetry "
        f"reduction: define a __rewrite__(plan) method on it."
    )
