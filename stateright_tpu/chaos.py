"""Deterministic fault injection: seeded, scriptable faults at the seams.

The recovery stack (checkpoint rotations, ``supervise.run_worker``
verdicts, the service job journal) is exercised today by a scatter of
hand-rolled SIGKILL/SIGSTOP tests. This module is the ONE scriptable
fault layer behind them: a seeded plan, parsed from ``STPU_CHAOS`` (env)
or installed explicitly (``ServiceConfig(chaos=...)``), fired at fixed
injection points in the code paths the real failures hit. Unset, every
hook is a no-op — :func:`fire` returns ``None`` without allocating a
plan, parsing anything, or touching a PRNG (pinned, like the obs layer's
zero-overhead guard).

Spec grammar (semicolon-separated clauses)::

    STPU_CHAOS = "seed=7;journal.torn@n=3:at=17;supervise.wedge@n=1"

    clause  := "seed=" INT                      (PRNG seed; default 0)
             | POINT ["@" TRIGGER] [":" PARAMS]
    TRIGGER := "n=" K      fire on the K-th invocation of POINT (1-based,
                           exactly once; invocation counts are
                           per-process, so the schedule is deterministic
                           for a deterministic caller)
             | "p=" F      fire each invocation with probability F from
                           the seeded PRNG (same seed -> same schedule)
             | (absent)    fire on every invocation
    PARAMS  := key=val ("," key=val)*           (integers where numeric)

Injection points (the seams; each is one hook call in the named owner):

- ``supervise.wedge`` — ``supervise.run_worker`` poll loop: draw a
  simulated wedge verdict (kill the worker group with a
  ``"chaos: simulated wedge verdict"`` reason, which classifies as
  ``WorkerResult.wedged`` exactly like a stale mid-dispatch heartbeat).
- ``checkpoint.torn`` — ``checkpoint.save_checkpoint``: after the atomic
  replace, truncate the live file at byte ``at`` (default: seeded random
  offset) — the torn-rotation shape ``latest_valid_checkpoint`` must
  fall back from.
- ``journal.torn`` — the service job journal's append: write only the
  first ``at`` bytes of the record, then SIGKILL the process — a crash
  mid-append, leaving the typed torn tail replay must recover from.
- ``journal.die`` — append the full record, then SIGKILL the process —
  a crash at a deterministic journal position (the restart drills' kill
  switch: "die after the K-th journal record").
- ``worker.die`` / ``worker.freeze`` — consumed by
  ``CheckerService.submit``: the matching job-level chaos flags
  (``--chaos-die-at-depth`` / ``--chaos-freeze-at-depth`` on
  ``service/worker.py``, params ``depth`` and ``once``) so a pool-level
  plan can SIGKILL or SIGSTOP-freeze the N-th submitted job's worker at
  superstep ``depth``. ``worker.freeze`` IS the heartbeat-freeze fault:
  the worker rewrites its beat to ``phase="dispatch"`` and stops.
- ``lint.timeout`` — ``CheckerService._admission_verdict``: simulate the
  admission-lint subprocess timing out (the fail-open tooling-error
  path, counted as ``lint_errors``).
- ``tenant.storm`` — consumed by ``tools/service_chaos.py``'s serve
  loop: on the N-th scheduled submission, burst ``rate`` (default 5)
  extra same-tenant submissions (params ``tenant`` = tenant id, default
  ``storm``; ``class`` = priority class, default ``best_effort``;
  ``rate`` = burst size) through the live service — the admission storm
  the QoS tier (docs/service.md "QoS & overload") must shed typed,
  hint-accurately, without starving the admitted set. Deterministic
  idempotency keys (``storm-<seed>-<i>``) make a restarted incarnation's
  re-fired storm dedupe instead of double-submitting.
- ``device.lost`` / ``device.flaky`` — consumed by
  ``FleetService.submit`` (``service/fleet.py``). ``device.lost``
  (params ``device`` = target index, default the device just routed to;
  ``after_s`` = delay, default 1) counts successful PLACEMENTS — a
  rejected submission can't swallow the seeded loss — and declares a
  whole device dead mid-job: its pool's workers are killed, its jobs
  evacuate and migrate to healthy siblings. ``device.flaky`` (params
  ``depth``, ``once``) counts submission attempts (it injects into the
  chaos dict the placement carries) and gives the routed job a one-shot
  heartbeat-freeze on its device — the wedged-tunnel signature, per
  device.

``STPU_CHAOS`` rides process boundaries by plain env inheritance: the
service passes it (or its config's spec) into worker environments, so a
``checkpoint.torn`` clause fires inside the worker that owns the
checkpoint writes. Invocation counters are per-process — each process
replays its own deterministic schedule.

Everything here is stdlib; importing it never imports jax (the
supervisor/service processes stay wedge-proof).
"""

from __future__ import annotations

import os
import random
import signal
import threading
from typing import Any, Dict, Optional

__all__ = ["ChaosPlan", "active", "fire", "install", "plan"]


class ChaosPlan:
    """One parsed ``STPU_CHAOS`` spec: per-point rules + the seeded PRNG
    + per-point invocation counters (thread-safe — the service fires
    hooks from scheduler and per-job threads)."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        #: point -> {"n": int|None, "p": float|None, "params": dict}
        self.rules: Dict[str, Dict[str, Any]] = {}
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                self.seed = int(clause[len("seed="):])
                continue
            head, _, raw_params = clause.partition(":")
            point, _, raw_trigger = head.partition("@")
            point = point.strip()
            if not point:
                raise ValueError(f"malformed STPU_CHAOS clause {clause!r}")
            rule: Dict[str, Any] = {"n": None, "p": None, "params": {}}
            if raw_trigger:
                key, eq, val = raw_trigger.partition("=")
                if key == "n" and eq:
                    rule["n"] = int(val)
                elif key == "p" and eq:
                    rule["p"] = float(val)
                else:
                    raise ValueError(
                        f"malformed STPU_CHAOS trigger {raw_trigger!r} "
                        "(expected n=K or p=F)"
                    )
            for kv in filter(None, raw_params.split(",")):
                key, eq, val = kv.partition("=")
                if not eq:
                    raise ValueError(
                        f"malformed STPU_CHAOS param {kv!r} in {clause!r}"
                    )
                try:
                    rule["params"][key.strip()] = int(val)
                except ValueError:
                    rule["params"][key.strip()] = val.strip()
            self.rules[point] = rule
        self._rng = random.Random(self.seed)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def fire(self, point: str, **ctx: Any) -> Optional[Dict[str, Any]]:
        """One invocation of ``point``: the injection params when the
        plan says fire, else None. ``ctx`` supplies defaults the caller
        knows (``size`` -> a seeded random ``at`` offset for torn
        faults)."""
        rule = self.rules.get(point)
        if rule is None:
            return None
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            if rule["n"] is not None and n != rule["n"]:
                return None
            if rule["p"] is not None and self._rng.random() >= rule["p"]:
                return None
            out = dict(rule["params"])
            size = ctx.get("size")
            if "at" not in out and isinstance(size, int) and size > 1:
                out["at"] = self._rng.randint(1, size - 1)
        return out


#: The process-wide installed plan. None + resolved means "chaos off":
#: the :func:`fire` fast path returns immediately — no parsing, no PRNG,
#: no allocation (the zero-overhead-off pin in test_service_durability).
_PLAN: Optional[ChaosPlan] = None
_RESOLVED = False


def plan() -> Optional[ChaosPlan]:
    """The active plan: an installed one, else ``STPU_CHAOS`` parsed
    lazily once per process, else None."""
    global _PLAN, _RESOLVED
    if not _RESOLVED:
        _RESOLVED = True
        spec = os.environ.get("STPU_CHAOS", "").strip()
        if spec:
            _PLAN = ChaosPlan(spec)
    return _PLAN


def install(spec: Optional[str]) -> Optional[ChaosPlan]:
    """Explicitly install (or, with None, clear) the process-wide plan —
    ``ServiceConfig(chaos=...)``'s path, and the tests'. Re-installing
    the SAME spec keeps the live plan (and its fire counters): a fleet
    installs once and its per-device pools' constructors must not reset
    a schedule already in flight. Returns the plan."""
    global _PLAN, _RESOLVED
    if spec and _RESOLVED and _PLAN is not None and _PLAN.spec == spec:
        return _PLAN
    _RESOLVED = True
    _PLAN = ChaosPlan(spec) if spec else None
    return _PLAN


def active() -> bool:
    return plan() is not None


def fire(point: str, **ctx: Any) -> Optional[Dict[str, Any]]:
    """The one hook the seams call. With no plan installed/configured
    this is a dict lookup away from a plain ``return None``."""
    p = _PLAN if _RESOLVED else plan()
    if p is None:
        return None
    return p.fire(point, **ctx)


def kill_self() -> None:  # pragma: no cover - the caller dies
    """The crash simulations' exit: SIGKILL this process (no atexit, no
    flushing — exactly what the watchdogs' designed failure mode does)."""
    os.kill(os.getpid(), signal.SIGKILL)


def tear_file(path: str, at: int) -> None:
    """Truncate ``path`` to ``at`` bytes (clamped inside the file) — the
    torn-write shape for checkpoint/journal fault injection."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    os.truncate(path, max(1, min(int(at), size - 1)) if size > 1 else 0)
