"""Stable 64-bit fingerprinting of model states.

The reference derives a ``NonZeroU64`` fingerprint from every state with a
*fixed-key* hasher so that hashes are stable across builds and runs
(``/root/reference/src/lib.rs:327-336, 356-369``); container types hash
order-insensitively by sorting per-element digests
(``/root/reference/src/util.rs:134-156``).  Stability matters because paths are
reconstructed from fingerprints after the fact, and tests assert exact counts.

This module provides the same guarantees for Python values with a splitmix64-
style mixer (public-domain finalizer constants).  The device engine uses a
32-bit-lane variant of the same construction (see ``stateright_tpu/ops``) so
that fingerprints computed on TPU agree with host fingerprints for bit-packed
states.
"""

from __future__ import annotations

import dataclasses
import struct
from enum import Enum
from typing import Any

MASK64 = (1 << 64) - 1

# splitmix64 finalizer constants (public domain, Sebastiano Vigna).
_SM1 = 0xBF58476D1CE4E5B9
_SM2 = 0x94D049BB133111EB
# Fixed keys playing the role of the reference's fixed ahash keys
# (lib.rs:359-360): any constants work; stability is what matters.
_SEED = 0x517CC1B727220A95

# Type tags so that values of different types never collide structurally.
_T_NONE = 0x01
_T_BOOL = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_TUPLE = 0x07
_T_LIST = 0x08
_T_SET = 0x09
_T_DICT = 0x0A
_T_DATACLASS = 0x0B
_T_ENUM = 0x0C
_T_CUSTOM = 0x0D


def _type_tag(t: type) -> str:
    """Module-qualified class tag: two same-named classes in different
    modules (e.g. the two adapter ``ClientState``s) must not collide."""
    return f"{t.__module__}.{t.__qualname__}"


def _mix(h: int) -> int:
    """splitmix64 finalizer: bijective 64-bit mixer."""
    h &= MASK64
    h ^= h >> 30
    h = (h * _SM1) & MASK64
    h ^= h >> 27
    h = (h * _SM2) & MASK64
    h ^= h >> 31
    return h


def _fold(acc: int, word: int) -> int:
    return _mix((acc ^ (word & MASK64)) * 0x9E3779B97F4A7C15)


def _hash_bytes(acc: int, data: bytes) -> int:
    for i in range(0, len(data), 8):
        chunk = data[i : i + 8]
        acc = _fold(acc, int.from_bytes(chunk, "little"))
    return _fold(acc, len(data))


def _digest(value: Any, acc: int) -> int:
    """Fold ``value`` into accumulator ``acc`` deterministically."""
    if value is None:
        return _fold(acc, _T_NONE)
    if value is True or value is False:
        return _fold(_fold(acc, _T_BOOL), int(value))
    t = type(value)
    if t is int:
        acc = _fold(acc, _T_INT)
        if -0x8000_0000_0000_0000 <= value < 0x8000_0000_0000_0000:
            # Two's-complement fold: injective over the 64-bit range.
            return _fold(acc, value & MASK64)
        # Arbitrary-precision ints: fold the full signed magnitude so values
        # that agree mod 2^64 don't collide.
        data = value.to_bytes((value.bit_length() + 8) // 8, "little", signed=True)
        return _hash_bytes(acc, data)
    if t is float:
        return _fold(_fold(acc, _T_FLOAT), int.from_bytes(struct.pack("<d", value), "little"))
    if t is str:
        return _hash_bytes(_fold(acc, _T_STR), value.encode("utf-8"))
    if t is bytes:
        return _hash_bytes(_fold(acc, _T_BYTES), value)
    if isinstance(value, tuple) and not hasattr(value, "__fingerprint_key__"):
        # Tuple subclasses (NamedTuples) are tagged with the class name so
        # e.g. Ping(0) and Pong(0) fingerprint differently, like Rust enum
        # variants hashing their discriminant. A __fingerprint_key__ hook
        # takes precedence (handled below).
        acc = _fold(acc, _T_TUPLE)
        if t is not tuple:
            acc = _hash_bytes(acc, _type_tag(t).encode("utf-8"))
        for item in value:
            acc = _digest(item, acc)
        return _fold(acc, len(value))
    if t is list:
        acc = _fold(acc, _T_LIST)
        for item in value:
            acc = _digest(item, acc)
        return _fold(acc, len(value))
    if t in (set, frozenset):
        # Order-insensitive: sort element digests, like the reference's
        # HashableHashSet (util.rs:134-156).
        acc = _fold(acc, _T_SET)
        for d in sorted(_digest(item, _SEED) for item in value):
            acc = _fold(acc, d)
        return _fold(acc, len(value))
    if t is dict:
        acc = _fold(acc, _T_DICT)
        for d in sorted(_digest((k, v), _SEED) for k, v in value.items()):
            acc = _fold(acc, d)
        return _fold(acc, len(value))
    if isinstance(value, Enum):
        acc = _fold(acc, _T_ENUM)
        acc = _hash_bytes(acc, _type_tag(t).encode("utf-8"))
        return _digest(value.value, acc)
    custom = getattr(value, "__fingerprint_key__", None)
    if custom is not None:
        acc = _fold(acc, _T_CUSTOM)
        acc = _hash_bytes(acc, _type_tag(type(value)).encode("utf-8"))
        return _digest(custom(), acc)
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        acc = _fold(acc, _T_DATACLASS)
        acc = _hash_bytes(acc, _type_tag(type(value)).encode("utf-8"))
        for f in dataclasses.fields(value):
            acc = _digest(getattr(value, f.name), acc)
        return acc
    if isinstance(value, int):  # bare int subclasses (exact ints returned above)
        return _fold(_fold(acc, _T_INT), int(value))
    raise TypeError(
        f"Cannot fingerprint value of type {t.__qualname__}: define a "
        f"__fingerprint_key__() method returning a canonical hashable value."
    )


def fingerprint(value: Any) -> int:
    """Convert a state to a nonzero 64-bit fingerprint.

    Mirrors ``fingerprint()`` in the reference (lib.rs:332): fixed-seed,
    stable across runs.  A zero digest is mapped to a fixed nonzero value
    (the reference panics instead; zero here is a 2^-64 event).
    """
    digest = _digest(value, _SEED)
    return digest if digest != 0 else 0x1D1AD

stable_mix = _mix
stable_fold = _fold
