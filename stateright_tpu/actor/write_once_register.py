"""Write-once-register protocol interface + test client.

Mirrors ``/root/reference/src/actor/write_once_register.rs``: the register
protocol extended with ``PutFail`` (a later write of a different value is
rejected), recording glue onto a ``WORegister`` consistency tester, and the
same Put-then-Get scripted client.  Same design delta as
``actor/register.py``: servers are added unwrapped; the client is
:class:`WORegisterClient`.
"""

from __future__ import annotations

from ..semantics import HistoryError
from ..semantics.write_once_register import Read as WORead
from ..semantics.write_once_register import ReadOk as WOReadOk
from ..semantics.write_once_register import Write as WOWrite
from ..semantics.write_once_register import WriteFail as WOWriteFail
from ..semantics.write_once_register import WriteOk as WOWriteOk
from ..utils.variant import variant

Internal = variant("Internal", ["msg"])
Put = variant("Put", ["request_id", "value"])
Get = variant("Get", ["request_id"])
PutOk = variant("PutOk", ["request_id"])
PutFail = variant("PutFail", ["request_id"])
GetOk = variant("GetOk", ["request_id", "value"])


def wo_history_codecs(values):
    """Closed-universe op/ret codes for WORegister histories over ``values``
    (``values[0]`` is the unwritten ``None``) — the WORegister analogue of
    ``register.history_codecs``, for packed models running
    :class:`~stateright_tpu.packing.BoundedHistory` over a
    ``LinearizabilityTester(WORegister(None))`` with the device check
    :class:`~stateright_tpu.semantics.device.DeviceWORegister`.

    Returns ``(op_code, code_op, ret_code, code_ret)``:
    ``Read() = 0``, ``Write(v) = 1 + values.index(v)``;
    ``WriteOk() = 0``, ``WriteFail() = 1``, ``ReadOk(v) = 2 + values.index(v)``.
    """

    def op_code(op):
        return 0 if isinstance(op, WORead) else 1 + values.index(op.value)

    def code_op(c):
        return WORead() if c == 0 else WOWrite(values[c - 1])

    def ret_code(ret):
        if isinstance(ret, WOWriteOk):
            return 0
        if isinstance(ret, WOWriteFail):
            return 1
        return 2 + values.index(ret.value)

    def code_ret(c):
        if c == 0:
            return WOWriteOk()
        if c == 1:
            return WOWriteFail()
        return WOReadOk(values[c - 2])

    return op_code, code_op, ret_code, code_ret


def record_invocations(cfg, history, env):
    """Pass to ``ActorModel.record_msg_out`` (write_once_register.rs:39-61)."""
    if isinstance(env.msg, Get):
        history = history.clone()
        try:
            history.on_invoke(env.src, WORead())
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, Put):
        history = history.clone()
        try:
            history.on_invoke(env.src, WOWrite(env.msg.value))
        except HistoryError:
            pass
        return history
    return None


def record_returns(cfg, history, env):
    """Pass to ``ActorModel.record_msg_in`` (write_once_register.rs:64-97).
    Note ``GetOk(v)`` maps to ``ReadOk(Some(v))`` — the in-protocol Get only
    returns once a value exists."""
    if isinstance(env.msg, GetOk):
        history = history.clone()
        try:
            history.on_return(env.dst, WOReadOk(env.msg.value))
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, PutOk):
        history = history.clone()
        try:
            history.on_return(env.dst, WOWriteOk())
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, PutFail):
        history = history.clone()
        try:
            history.on_return(env.dst, WOWriteFail())
        except HistoryError:
            pass
        return history
    return None


ClientState = variant("ClientState", ["awaiting", "op_count"])


class WORegisterClient:
    """Put-then-Get scripted client (write_once_register.rs:126-238);
    a ``PutFail`` response advances the script just like ``PutOk``."""

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def on_start(self, id, out):
        from . import Id

        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "WORegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id, state, src, msg, out):
        from . import Id

        current = state.get()
        if current.awaiting is None:
            return
        index = int(id)
        acked = isinstance(msg, (PutOk, PutFail)) and msg.request_id == current.awaiting
        if acked:
            unique_request_id = (current.op_count + 1) * index
            if current.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            state.set(
                ClientState(awaiting=unique_request_id, op_count=current.op_count + 1)
            )
        elif isinstance(msg, GetOk) and msg.request_id == current.awaiting:
            state.set(ClientState(awaiting=None, op_count=current.op_count + 1))

    def on_timeout(self, id, state, timer, out):
        pass
