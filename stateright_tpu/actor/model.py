"""ActorModel: adapts a system of actors to the ``Model`` interface.

Mirrors ``/root/reference/src/actor/model.rs``.  The model's nondeterminism
is exactly the reference's: for every deliverable envelope, a ``Deliver``
action (plus a ``Drop`` when the network is lossy); for every set timer, a
``Timeout``.  History ``H`` is a TLA-style auxiliary variable updated by
``record_msg_in``/``record_msg_out`` — consistency testers ride in it.

Because this sits *below* the ``Model`` contract, every checker engine —
including ``spawn_xla()`` with a packed encoding — explores actor systems
unmodified (the property the reference calls out at model.rs:200).
"""

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple, Optional, Tuple

from ..core import Expectation, Model, Property
from .model_state import ActorModelState
from .network import Envelope, Network
from .timers import Timers


class DeliverAction(NamedTuple):
    """A message can be delivered to an actor."""

    src: "Id"
    dst: "Id"
    msg: Any


class DropAction(NamedTuple):
    """A message can be dropped (lossy networks only)."""

    envelope: Envelope


class TimeoutAction(NamedTuple):
    """An actor can be notified after a timeout."""

    id: "Id"
    timer: Any


ActorModelAction = (DeliverAction, DropAction, TimeoutAction)


class ActorModel(Model):
    """A system of actors communicating over a modeled network
    (model.rs:23-37).  Build fluently::

        ActorModel(cfg=..., init_history=...)
            .actor(Server())
            .actor(Client())
            .init_network(Network.new_ordered())
            .lossy_network(True)
            .property(Expectation.ALWAYS, "safe", lambda model, state: ...)
            .record_msg_in(lambda cfg, history, env: ... or None)
            .checker().spawn_bfs()
    """

    def __init__(self, cfg: Any = None, init_history: Any = ()):
        self.actors: List[Any] = []
        self.cfg = cfg
        self.init_history = init_history
        self._init_network: Network = Network.new_unordered_duplicating()
        self._lossy: bool = False
        self._properties: List[Property] = []
        self._record_msg_in: Callable = lambda cfg, history, env: None
        self._record_msg_out: Callable = lambda cfg, history, env: None
        self._within_boundary: Callable = lambda cfg, state: True

    # --- builder (model.rs:95-164) ----------------------------------------

    def actor(self, actor) -> "ActorModel":
        self.actors.append(actor)
        return self

    def add_actors(self, actors) -> "ActorModel":
        self.actors.extend(actors)
        return self

    def init_network(self, network: Network) -> "ActorModel":
        self._init_network = network
        return self

    def lossy_network(self, lossy: bool) -> "ActorModel":
        """Whether the network loses messages (model.rs:53-57).  Losing a
        message is indistinguishable from unlimited delay unless invariants
        inspect the network, so ``False`` often checks faster."""
        self._lossy = bool(lossy)
        return self

    def property(self, *args):
        """Arity-dispatched like the reference: ``property(expectation,
        name, condition)`` is the builder (model.rs:121-135);
        ``property(name)`` is the lookup inherited from ``Model``
        (lib.rs:229)."""
        if len(args) == 1:
            return super().property(args[0])
        expectation, name, condition = args
        self._properties.append(Property(expectation, name, condition))
        return self

    def record_msg_in(self, fn: Callable) -> "ActorModel":
        """``fn(cfg, history, envelope) -> new_history | None``."""
        self._record_msg_in = fn
        return self

    def record_msg_out(self, fn: Callable) -> "ActorModel":
        self._record_msg_out = fn
        return self

    def within_boundary_fn(self, fn: Callable) -> "ActorModel":
        self._within_boundary = fn
        return self

    # --- command application (model.rs:166-197) ---------------------------

    def _apply_commands(
        self,
        id,
        out,
        network: Network,
        timers_set: List[Timers],
        history: Any,
    ) -> Tuple[Network, Any]:
        from . import CancelTimer, Send, SetTimer

        index = int(id)
        for c in out.commands:
            if isinstance(c, Send):
                env = Envelope(id, c.dst, c.msg)
                new_history = self._record_msg_out(self.cfg, history, env)
                if new_history is not None:
                    history = new_history
                network = network.send(env)
            elif isinstance(c, SetTimer):
                timers_set[index] = timers_set[index].set(c.timer)
            elif isinstance(c, CancelTimer):
                timers_set[index] = timers_set[index].cancel(c.timer)
            else:  # pragma: no cover
                raise TypeError(f"unknown command {c!r}")
        return network, history

    # --- Model implementation (model.rs:200-343) --------------------------

    def init_states(self) -> List[ActorModelState]:
        from . import Id, Out

        actor_states: List[Any] = []
        network = self._init_network
        timers_set: List[Timers] = [Timers() for _ in self.actors]
        history = self.init_history
        for index, actor in enumerate(self.actors):
            out = Out()
            state = actor.on_start(Id(index), out)
            actor_states.append(state)
            network, history = self._apply_commands(
                Id(index), out, network, timers_set, history
            )
        return [
            ActorModelState(
                actor_states=tuple(actor_states),
                network=network,
                timers_set=tuple(timers_set),
                history=history,
            )
        ]

    def actions(self, state: ActorModelState, actions: List[Any]) -> None:
        # Deliverable envelopes: Drop option first when lossy, then Deliver
        # (model.rs:228-252). Ordered networks only offer flow heads, which
        # iter_deliverable already enforces.
        for env in state.network.iter_deliverable():
            if self._lossy:
                actions.append(DropAction(env))
            if int(env.dst) < len(self.actors):  # ignore if recipient DNE
                actions.append(DeliverAction(env.src, env.dst, env.msg))
        # Timeouts (model.rs:255-259).
        from . import Id

        for index, timers in enumerate(state.timers_set):
            for timer in timers:
                actions.append(TimeoutAction(Id(index), timer))

    def next_state(
        self, last_state: ActorModelState, action: Any
    ) -> Optional[ActorModelState]:
        from . import Out, StateRef, is_no_op, is_no_op_with_timer

        if isinstance(action, DropAction):
            return ActorModelState(
                actor_states=last_state.actor_states,
                network=last_state.network.on_drop(action.envelope),
                timers_set=last_state.timers_set,
                history=last_state.history,
            )

        if isinstance(action, DeliverAction):
            index = int(action.dst)
            if index >= len(last_state.actor_states):
                return None  # not all messages can be delivered
            ref = StateRef(last_state.actor_states[index])
            out = Out()
            self.actors[index].on_msg(action.dst, ref, action.src, action.msg, out)
            if is_no_op(ref, out):
                return None  # ignored action (model.rs:286-289)
            env = Envelope(action.src, action.dst, action.msg)
            new_history = self._record_msg_in(self.cfg, last_state.history, env)
            history = new_history if new_history is not None else last_state.history

            actor_states = list(last_state.actor_states)
            if ref.changed:
                actor_states[index] = ref.get()
            network = last_state.network.on_deliver(env)
            timers_set = list(last_state.timers_set)
            network, history = self._apply_commands(
                action.dst, out, network, timers_set, history
            )
            return ActorModelState(
                tuple(actor_states), network, tuple(timers_set), history
            )

        if isinstance(action, TimeoutAction):
            index = int(action.id)
            ref = StateRef(last_state.actor_states[index])
            out = Out()
            self.actors[index].on_timeout(action.id, ref, action.timer, out)
            if is_no_op_with_timer(ref, out, action.timer):
                return None
            actor_states = list(last_state.actor_states)
            if ref.changed:
                actor_states[index] = ref.get()
            # The fired timer is no longer set (model.rs:332-334).
            timers_set = list(last_state.timers_set)
            timers_set[index] = timers_set[index].cancel(action.timer)
            network, history = self._apply_commands(
                action.id, out, last_state.network, timers_set, last_state.history
            )
            return ActorModelState(
                tuple(actor_states), network, tuple(timers_set), history
            )

        raise TypeError(f"unknown action {action!r}")  # pragma: no cover

    def properties(self) -> List[Property]:
        return list(self._properties)

    def within_boundary(self, state: ActorModelState) -> bool:
        return self._within_boundary(self.cfg, state)

    def format_action(self, action: Any) -> str:
        if isinstance(action, DeliverAction):
            return f"{action.src!r} → {action.msg!r} → {action.dst!r}"
        return repr(action)

    def format_step(self, last_state: ActorModelState, action: Any) -> Optional[str]:
        from . import Out, StateRef

        if isinstance(action, DropAction):
            return f"DROP: {action.envelope!r}"
        if isinstance(action, DeliverAction):
            index = int(action.dst)
            if index >= len(last_state.actor_states):
                return None
            ref = StateRef(last_state.actor_states[index])
            out = Out()
            self.actors[index].on_msg(action.dst, ref, action.src, action.msg, out)
        elif isinstance(action, TimeoutAction):
            index = int(action.id)
            ref = StateRef(last_state.actor_states[index])
            out = Out()
            self.actors[index].on_timeout(action.id, ref, action.timer, out)
        else:
            return None
        last = last_state.actor_states[index]
        lines = [f"OUT: {out!r}", ""]
        if ref.changed:
            lines += [f"NEXT_STATE: {ref.get()!r}", "", f"PREV_STATE: {last!r}"]
        else:
            lines += [f"UNCHANGED: {last!r}"]
        return "\n".join(lines)

    def as_svg(self, path) -> Optional[str]:
        """Sequence-diagram SVG for an actor-system path (model.rs:424-549)."""
        from . import Send, Out, StateRef

        pairs = path.into_vec()
        actor_count = len(path.last_state().actor_states)

        def plot(x, y):
            return x * 100, y * 30

        svg_w, svg_h = plot(actor_count, len(pairs))
        svg_w += 300  # extra width for event labels
        parts = [
            f"<svg version='1.1' baseProfile='full' width='{svg_w}' height='{svg_h}' "
            f"viewbox='-20 -20 {svg_w + 20} {svg_h + 20}' "
            f"xmlns='http://www.w3.org/2000/svg'>",
            "<defs><marker class='svg-event-shape' id='arrow' markerWidth='12' "
            "markerHeight='10' refX='12' refY='5' orient='auto'>"
            "<polygon points='0 0, 12 5, 0 10' /></marker></defs>",
        ]
        for i in range(actor_count):
            (x1, y1), (x2, y2) = plot(i, 0), plot(i, len(pairs))
            parts.append(
                f"<line x1='{x1}' y1='{y1}' x2='{x2}' y2='{y2}' class='svg-actor-timeline' />"
            )
            parts.append(f"<text x='{x1}' y='{y1}' class='svg-actor-label'>{i}</text>")

        send_time = {}
        for time, (state, action) in enumerate(pairs, start=1):
            if isinstance(action, DeliverAction):
                src_time = send_time.get((action.src, action.dst, action.msg), 0)
                x1, y1 = plot(int(action.src), src_time)
                x2, y2 = plot(int(action.dst), time)
                parts.append(
                    f"<line x1='{x1}' x2='{x2}' y1='{y1}' y2='{y2}' "
                    f"marker-end='url(#arrow)' class='svg-event-line' />"
                )
                index = int(action.dst)
                if index < len(state.actor_states):
                    ref = StateRef(state.actor_states[index])
                    out = Out()
                    self.actors[index].on_msg(action.dst, ref, action.src, action.msg, out)
                    for c in out.commands:
                        if isinstance(c, Send):
                            send_time[(action.dst, c.dst, c.msg)] = time
            elif isinstance(action, TimeoutAction):
                x, y = plot(int(action.id), time)
                parts.append(
                    f"<circle cx='{x}' cy='{y}' r='10' class='svg-event-shape' />"
                )
                index = int(action.id)
                if index < len(state.actor_states):
                    ref = StateRef(state.actor_states[index])
                    out = Out()
                    self.actors[index].on_timeout(action.id, ref, action.timer, out)
                    for c in out.commands:
                        if isinstance(c, Send):
                            send_time[(action.id, c.dst, c.msg)] = time

        for time, (_state, action) in enumerate(pairs, start=1):
            if isinstance(action, DeliverAction):
                x, y = plot(int(action.dst), time)
                parts.append(
                    f"<text x='{x}' y='{y}' class='svg-event-label'>{action.msg!r}</text>"
                )
            elif isinstance(action, TimeoutAction):
                x, y = plot(int(action.id), time)
                parts.append(
                    f"<text x='{x}' y='{y}' class='svg-event-label'>"
                    f"Timeout({action.timer!r})</text>"
                )
        parts.append("</svg>")
        return "\n".join(parts)
