"""Register protocol interface + test client for model checking.

Mirrors ``/root/reference/src/actor/register.rs``: a message protocol for
register-like systems (``Put``/``Get``/``PutOk``/``GetOk`` + ``Internal``),
glue that records those messages as consistency-tester invocations/returns
(register.rs:38-91), and a scripted client that Puts then Gets round-robin
across servers (register.rs:94-260).

Design delta: Rust wraps servers in ``RegisterActor::Server`` so one enum
covers both roles; under duck typing servers are added to the model directly
and the client is the plain :class:`RegisterClient` actor — so server states
appear unwrapped in ``actor_states``.
"""

from __future__ import annotations

from ..semantics import HistoryError
from ..semantics.register import Read as RegisterRead
from ..semantics.register import ReadOk as RegisterReadOk
from ..semantics.register import Write as RegisterWrite
from ..semantics.register import WriteOk as RegisterWriteOk
from ..utils.variant import variant

#: A message specific to the register system's internal protocol.
Internal = variant("Internal", ["msg"])
Put = variant("Put", ["request_id", "value"])
Get = variant("Get", ["request_id"])
PutOk = variant("PutOk", ["request_id"])
GetOk = variant("GetOk", ["request_id", "value"])


def record_invocations(cfg, history, env):
    """Pass to ``ActorModel.record_msg_out``: ``Get``→``Read`` invocation,
    ``Put``→``Write`` invocation by the sending client (register.rs:38-62).
    Invalid histories poison the tester rather than crash the check."""
    if isinstance(env.msg, Get):
        history = history.clone()
        try:
            history.on_invoke(env.src, RegisterRead())
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, Put):
        history = history.clone()
        try:
            history.on_invoke(env.src, RegisterWrite(env.msg.value))
        except HistoryError:
            pass
        return history
    return None


def record_returns(cfg, history, env):
    """Pass to ``ActorModel.record_msg_in``: ``GetOk``→``ReadOk`` return,
    ``PutOk``→``WriteOk`` return to the receiving client (register.rs:64-91)."""
    if isinstance(env.msg, GetOk):
        history = history.clone()
        try:
            history.on_return(env.dst, RegisterReadOk(env.msg.value))
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, PutOk):
        history = history.clone()
        try:
            history.on_return(env.dst, RegisterWriteOk())
        except HistoryError:
            pass
        return history
    return None


def linearizable_condition():
    """An ``always`` property condition: the history (a
    ``LinearizabilityTester`` riding in the model state) admits a legal
    serialization. ``serialized_history()`` is a backtracking search and
    histories recur across many states, so consistency is memoized per
    distinct history value (one cache per built model)."""
    cache: dict = {}

    def linearizable(_model, state) -> bool:
        h = state.history
        hit = cache.get(h)
        if hit is None:
            hit = h.serialized_history() is not None
            cache[h] = hit
        return hit

    return linearizable


def value_chosen_condition(_model=None, state=None) -> bool:
    """A ``sometimes`` property condition: some deliverable ``GetOk``
    carries a real (written) value — the register protocols' reachability
    check (e.g. single-copy-register.rs:73-82)."""
    for env in state.network.iter_deliverable():
        if isinstance(env.msg, GetOk) and env.msg.value is not None:
            return True
    return False


def history_codecs(values):
    """Closed-universe op/ret codes for register histories over ``values``
    (a list whose first element is the unwritten ``None``): used by packed
    models to run :class:`~stateright_tpu.packing.BoundedHistory` over a
    ``LinearizabilityTester`` of the ``Register`` spec.

    Returns ``(op_code, code_op, ret_code, code_ret)``:
    ``Read() = 0``, ``Write(v) = 1 + values.index(v)``;
    ``WriteOk() = 0``, ``ReadOk(v) = 1 + values.index(v)``.
    """
    def op_code(op):
        if isinstance(op, RegisterRead):
            return 0
        return 1 + values.index(op.value)

    def code_op(c):
        return RegisterRead() if c == 0 else RegisterWrite(values[c - 1])

    def ret_code(ret):
        if isinstance(ret, RegisterWriteOk):
            return 0
        return 1 + values.index(ret.value)

    def code_ret(c):
        return RegisterWriteOk() if c == 0 else RegisterReadOk(values[c - 1])

    return op_code, code_op, ret_code, code_ret


ClientState = variant("ClientState", ["awaiting", "op_count"])


class PackedClientsMixin:
    """Shared device-side machinery for packed models whose clients are
    :class:`RegisterClient` actors (register.rs:94-260, ``put_count=1``).

    Host codec + vectorized delivery bodies for the client-facing protocol
    half (PutOk/GetOk), over layout fields declared by :meth:`_client_layout`
    and a bounded history ``self._hist``
    (:class:`~stateright_tpu.packing.BoundedHistory`). Expects on ``self``:
    ``S`` (server count), ``C`` (client count), ``_layout``, ``_hist``,
    ``_OverflowError32``.

    Client state encoding: ``cl_await`` 0 = idle, 1 = awaiting PutOk of
    request ``1*i``, 2 = awaiting GetOk of request ``2*i`` (i = S + k);
    ``cl_ops`` mirrors ``ClientState.op_count``.
    """

    def _client_layout(self, b) -> None:
        b.array("cl_await", self.C, 2)
        b.array("cl_ops", self.C, 2)

    def _client_values(self):
        """The closed register-value universe: the unwritten ``None`` plus
        each client's written value (client k writes chr('A'+k))."""
        return [None] + [chr(ord("A") + k) for k in range(self.C)]

    def _val_code(self, val) -> int:
        try:
            return self.values.index(val)
        except ValueError:
            raise self._OverflowError32(f"value outside universe: {val!r}")

    # --- host codec --------------------------------------------------------

    def _pack_clients(self, fields, state) -> None:
        S, C = self.S, self.C
        fields["cl_await"] = [0] * C
        fields["cl_ops"] = [0] * C
        for k in range(C):
            i = S + k
            cs = state.actor_states[S + k]
            if cs.awaiting is None:
                fields["cl_await"][k] = 0
            elif cs.awaiting == 1 * i:
                fields["cl_await"][k] = 1
            elif cs.awaiting == 2 * i:
                fields["cl_await"][k] = 2
            else:  # pragma: no cover - unreachable by construction
                raise self._OverflowError32(f"unexpected request id {cs.awaiting}")
            fields["cl_ops"][k] = cs.op_count

    def _unpack_clients(self, f, actor_states) -> None:
        S, C = self.S, self.C
        for k in range(C):
            i = S + k
            awaiting = {0: None, 1: 1 * i, 2: 2 * i}[f["cl_await"][k]]
            actor_states.append(
                ClientState(awaiting=awaiting, op_count=f["cl_ops"][k])
            )

    # --- family machinery --------------------------------------------------
    # Models enumerate a closed envelope universe into self._handlers
    # [(kind, static params)] in code order; these helpers group contiguous
    # same-kind runs into (kind, codes, param-table) families and run one
    # vmapped traced body per family — trace size (and XLA compile time)
    # stays constant in the universe size.

    def _group_families(self, params_for):
        """Group ``self._handlers`` into families with uint32 param tables
        built by ``params_for(kind, params) -> list[int]``."""
        import numpy as np

        families = []
        start = 0
        while start < self._U:
            kind = self._handlers[start][0]
            end = start
            while end < self._U and self._handlers[end][0] == kind:
                end += 1
            rows = [
                params_for(kind, self._handlers[e][1]) for e in range(start, end)
            ]
            families.append(
                (
                    kind,
                    np.arange(start, end, dtype=np.uint32),
                    np.asarray(rows, dtype=np.uint32),
                )
            )
            start = end
        return families

    def packed_step(self, words):
        """Full action fan-out: deliver each universe envelope via its
        family's ``_body_<kind>`` method, vmapped over the parameter table."""
        import jax
        import jax.numpy as jnp

        nxts, valids, ovfs = [], [], []
        for kind, codes, prm in self._families:
            body = getattr(self, "_body_" + kind)
            nxt, valid, ovf = jax.vmap(body, in_axes=(None, 0, 0))(
                words, jnp.asarray(codes), jnp.asarray(prm)
            )
            nxts.append(nxt)
            valids.append(valid)
            ovfs.append(ovf)
        valid = jnp.concatenate(valids)
        return jnp.concatenate(nxts), valid, jnp.concatenate(ovfs) & valid

    # --- presence-bit network helpers --------------------------------------
    # The universe's non-duplicating multiset packs as a "net" 1-bit array
    # (empirically every register protocol here keeps counts at 1; a double
    # send cannot be represented and reports overflow, SURVEY §7 #2).

    def _pack_presence_net(self, fields, state) -> None:
        """Pack ``state.network.counts`` as presence bits; leaving the
        universe or exceeding count 1 fails loudly."""
        net = [0] * self._U
        for env, count in state.network.counts.items():
            code = self._env_code.get(env)
            if code is None:
                raise self._OverflowError32(f"envelope outside universe: {env!r}")
            if count > 1:
                raise self._OverflowError32(
                    f"envelope count {count} > 1 (presence-bit codec): {env!r}"
                )
            net[code] = count
        fields["net"] = net

    def _net_take(self, words, e):
        """Consume the delivered envelope; returns (was-present, words')."""
        L = self._layout
        return L.get(words, "net", e) != 0, L.set(words, "net", 0, e)

    def _net_send(self, w, idx):
        """Set a presence bit at a (possibly traced) code; returns
        (words', was-already-present)."""
        L = self._layout
        dup = L.get(w, "net", idx) != 0
        return L.set(w, "net", 1, idx), dup

    def device_linearizable_register(self, words, pattern_limit=None):
        """EXACT linearizability of the packed history, entirely on device —
        no host fallback (SURVEY §7 M4 variant (b), upgrading the
        conservative-predicate + host-serializer design of variant (a)).

        Delegates to the generalized static-enumeration serializer
        (:func:`stateright_tpu.semantics.device.device_serializable`):
        exact for any thread count / op bound whose interleaving count
        stays under ``semantics.device.MAX_PATTERNS_EXACT`` (the pattern
        axis chunks under ``lax.scan`` past the single-shot budget);
        larger shapes pass ``pattern_limit`` (a one-sided sampled pass)
        and declare the property in ``host_verified_properties``.

        Returns a bool usable directly as an ``always`` property —
        differentially tested against ``serialized_history()`` over every
        reachable history of the register models.
        """
        from ..semantics.device import DeviceRegister, device_serializable

        if not self._hist.real_time:
            raise ValueError(
                "device_linearizable_register needs a BoundedHistory with "
                "real_time=True: a prereq-free history would silently "
                "degrade the check to sequential consistency"
            )
        return device_serializable(
            self._hist,
            words,
            DeviceRegister(),
            real_time=True,
            pattern_limit=pattern_limit,
        )

    def device_sequentially_consistent_register(self, words, pattern_limit=None):
        """EXACT sequential consistency of the packed history on device:
        the same enumeration as :meth:`device_linearizable_register` with
        the real-time constraint dropped (the device counterpart of
        ``SequentialConsistencyTester``, sequential_consistency.rs:53-241).
        Correct for histories packed with either ``real_time`` setting
        (prereq snapshots are simply ignored)."""
        from ..semantics.device import DeviceRegister, device_serializable

        return device_serializable(
            self._hist,
            words,
            DeviceRegister(),
            real_time=False,
            pattern_limit=pattern_limit,
        )

    # --- vectorized delivery bodies ----------------------------------------
    # Each takes (words[W], e, prm[cols]) with traced envelope code and
    # parameter row; returns (words'[W], valid, overflow). The history
    # thread index is traced, so history ops unroll over C with masks.

    def _body_putok(self, words, e, prm):
        """PutOk -> client ``prm[0]``: record the WriteOk return, invoke the
        Read, send Get ``prm[1]`` (register.rs:170-185)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        p, get_code = prm[0], prm[1]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "cl_await", p) == u32(1))
        w = L.set(w, "cl_await", 2, p)
        w = L.set(w, "cl_ops", 2, p)
        o = jnp.bool_(False)
        for t in range(self.C):
            on = ok & (p == u32(t))
            w, ot = self._hist.on_return(w, t, u32(0), enabled=on)  # WriteOk
            w = self._hist.on_invoke(w, t, u32(0), enabled=on)  # Read
            o = o | ot
        w, dup = self._net_send(w, get_code)
        return w, ok, ok & (o | dup)

    def _body_getok(self, words, e, prm):
        """GetOk -> client ``prm[0]``: record the ReadOk return with (static)
        ret code ``prm[1]``; the script completes (register.rs:186-187)."""
        import jax.numpy as jnp

        L, u32 = self._layout, jnp.uint32
        k, ret_code = prm[0], prm[1]
        deliv, w = self._net_take(words, e)
        ok = deliv & (L.get(words, "cl_await", k) == u32(2))
        w = L.set(w, "cl_await", 0, k)
        w = L.set(w, "cl_ops", 3, k)
        o = jnp.bool_(False)
        for t in range(self.C):
            w, ot = self._hist.on_return(w, t, ret_code, enabled=ok & (k == u32(t)))
            o = o | ot
        return w, ok, ok & o


class RegisterClient:
    """A test client that performs ``put_count`` Puts, then one Get,
    round-robin across the servers (register.rs:94-260).

    Assumes servers occupy indices ``0..server_count`` so a server id is
    derivable as ``(client_index + k) % server_count`` (register.rs:118-120).
    Request ids are ``op_count * client_index``, unique per (client, op)
    because client indices exceed ``server_count >= 1``.
    """

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def on_start(self, id, out):
        from . import Id

        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index  # next will be 2 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id, state, src, msg, out):
        from . import Id

        current = state.get()
        if current.awaiting is None:
            return
        index = int(id)
        if isinstance(msg, PutOk) and msg.request_id == current.awaiting:
            unique_request_id = (current.op_count + 1) * index
            if current.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            state.set(
                ClientState(awaiting=unique_request_id, op_count=current.op_count + 1)
            )
        elif isinstance(msg, GetOk) and msg.request_id == current.awaiting:
            state.set(ClientState(awaiting=None, op_count=current.op_count + 1))

    def on_timeout(self, id, state, timer, out):
        pass
