"""Register protocol interface + test client for model checking.

Mirrors ``/root/reference/src/actor/register.rs``: a message protocol for
register-like systems (``Put``/``Get``/``PutOk``/``GetOk`` + ``Internal``),
glue that records those messages as consistency-tester invocations/returns
(register.rs:38-91), and a scripted client that Puts then Gets round-robin
across servers (register.rs:94-260).

Design delta: Rust wraps servers in ``RegisterActor::Server`` so one enum
covers both roles; under duck typing servers are added to the model directly
and the client is the plain :class:`RegisterClient` actor — so server states
appear unwrapped in ``actor_states``.
"""

from __future__ import annotations

from ..semantics import HistoryError
from ..semantics.register import Read as RegisterRead
from ..semantics.register import ReadOk as RegisterReadOk
from ..semantics.register import Write as RegisterWrite
from ..semantics.register import WriteOk as RegisterWriteOk
from ..utils.variant import variant

#: A message specific to the register system's internal protocol.
Internal = variant("Internal", ["msg"])
Put = variant("Put", ["request_id", "value"])
Get = variant("Get", ["request_id"])
PutOk = variant("PutOk", ["request_id"])
GetOk = variant("GetOk", ["request_id", "value"])


def record_invocations(cfg, history, env):
    """Pass to ``ActorModel.record_msg_out``: ``Get``→``Read`` invocation,
    ``Put``→``Write`` invocation by the sending client (register.rs:38-62).
    Invalid histories poison the tester rather than crash the check."""
    if isinstance(env.msg, Get):
        history = history.clone()
        try:
            history.on_invoke(env.src, RegisterRead())
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, Put):
        history = history.clone()
        try:
            history.on_invoke(env.src, RegisterWrite(env.msg.value))
        except HistoryError:
            pass
        return history
    return None


def record_returns(cfg, history, env):
    """Pass to ``ActorModel.record_msg_in``: ``GetOk``→``ReadOk`` return,
    ``PutOk``→``WriteOk`` return to the receiving client (register.rs:64-91)."""
    if isinstance(env.msg, GetOk):
        history = history.clone()
        try:
            history.on_return(env.dst, RegisterReadOk(env.msg.value))
        except HistoryError:
            pass
        return history
    if isinstance(env.msg, PutOk):
        history = history.clone()
        try:
            history.on_return(env.dst, RegisterWriteOk())
        except HistoryError:
            pass
        return history
    return None


def linearizable_condition():
    """An ``always`` property condition: the history (a
    ``LinearizabilityTester`` riding in the model state) admits a legal
    serialization. ``serialized_history()`` is a backtracking search and
    histories recur across many states, so consistency is memoized per
    distinct history value (one cache per built model)."""
    cache: dict = {}

    def linearizable(_model, state) -> bool:
        h = state.history
        hit = cache.get(h)
        if hit is None:
            hit = h.serialized_history() is not None
            cache[h] = hit
        return hit

    return linearizable


def value_chosen_condition(_model=None, state=None) -> bool:
    """A ``sometimes`` property condition: some deliverable ``GetOk``
    carries a real (written) value — the register protocols' reachability
    check (e.g. single-copy-register.rs:73-82)."""
    for env in state.network.iter_deliverable():
        if isinstance(env.msg, GetOk) and env.msg.value is not None:
            return True
    return False


def history_codecs(values):
    """Closed-universe op/ret codes for register histories over ``values``
    (a list whose first element is the unwritten ``None``): used by packed
    models to run :class:`~stateright_tpu.packing.BoundedHistory` over a
    ``LinearizabilityTester`` of the ``Register`` spec.

    Returns ``(op_code, code_op, ret_code, code_ret)``:
    ``Read() = 0``, ``Write(v) = 1 + values.index(v)``;
    ``WriteOk() = 0``, ``ReadOk(v) = 1 + values.index(v)``.
    """
    def op_code(op):
        if isinstance(op, RegisterRead):
            return 0
        return 1 + values.index(op.value)

    def code_op(c):
        return RegisterRead() if c == 0 else RegisterWrite(values[c - 1])

    def ret_code(ret):
        if isinstance(ret, RegisterWriteOk):
            return 0
        return 1 + values.index(ret.value)

    def code_ret(c):
        return RegisterWriteOk() if c == 0 else RegisterReadOk(values[c - 1])

    return op_code, code_op, ret_code, code_ret


ClientState = variant("ClientState", ["awaiting", "op_count"])


class RegisterClient:
    """A test client that performs ``put_count`` Puts, then one Get,
    round-robin across the servers (register.rs:94-260).

    Assumes servers occupy indices ``0..server_count`` so a server id is
    derivable as ``(client_index + k) % server_count`` (register.rs:118-120).
    Request ids are ``op_count * client_index``, unique per (client, op)
    because client indices exceed ``server_count >= 1``.
    """

    def __init__(self, put_count: int, server_count: int):
        self.put_count = put_count
        self.server_count = server_count

    def on_start(self, id, out):
        from . import Id

        index = int(id)
        if index < self.server_count:
            raise ValueError(
                "RegisterClient actors must be added to the model after servers."
            )
        if self.put_count == 0:
            return ClientState(awaiting=None, op_count=0)
        unique_request_id = 1 * index  # next will be 2 * index
        value = chr(ord("A") + index - self.server_count)
        out.send(Id(index % self.server_count), Put(unique_request_id, value))
        return ClientState(awaiting=unique_request_id, op_count=1)

    def on_msg(self, id, state, src, msg, out):
        from . import Id

        current = state.get()
        if current.awaiting is None:
            return
        index = int(id)
        if isinstance(msg, PutOk) and msg.request_id == current.awaiting:
            unique_request_id = (current.op_count + 1) * index
            if current.op_count < self.put_count:
                value = chr(ord("Z") - (index - self.server_count))
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Put(unique_request_id, value),
                )
            else:
                out.send(
                    Id((index + current.op_count) % self.server_count),
                    Get(unique_request_id),
                )
            state.set(
                ClientState(awaiting=unique_request_id, op_count=current.op_count + 1)
            )
        elif isinstance(msg, GetOk) and msg.request_id == current.awaiting:
            state.set(ClientState(awaiting=None, op_count=current.op_count + 1))

    def on_timeout(self, id, state, timer, out):
        pass
