"""Actor framework: event-driven actors that can be model checked *and* run
on a real UDP network.

Mirrors the reference's ``actor`` module (``/root/reference/src/actor.rs``):

- :class:`Actor` — ``on_start``/``on_msg``/``on_timeout`` handlers emitting
  :class:`Command`\\ s through an :class:`Out` buffer.
- :class:`Id` — actor address; an index for checked models, an encoded
  IPv4 socket address for spawned actors (spawn.rs:10-34).
- :class:`ActorModel` — adapts a system of actors to the ``Model`` interface
  so every checker engine (including ``spawn_xla``) can explore it.
- :class:`Network` — the in-state message-collection with three semantics
  (ordered / unordered duplicating / unordered non-duplicating).
- ``spawn()`` — the real-network UDP runtime.

Design deltas from the reference, intentional and Python-idiomatic:

- Rust's ``Cow``-based no-op detection (actor.rs:247-264) becomes the
  :class:`StateRef` wrapper: handlers call ``ref.set(new_state)`` (or leave
  it untouched); "unchanged and no commands" is a no-op action.
- Rust's ``choice!`` sum types for heterogeneous actor systems are
  unnecessary under duck typing: ``ActorModel.actors`` may simply mix actor
  classes (actor.rs:339-482's machinery has no Python analogue to need).
"""

from __future__ import annotations

from typing import Any, Iterable, List, NamedTuple, Optional, Tuple

from .network import Envelope, Network
from .timers import Timers


class Id(int):
    """Uniquely identifies an actor.  An index for model-checked actors; an
    encoded IPv4 address+port for spawned actors (actor.rs:108-156)."""

    def __repr__(self) -> str:
        return f"Id({int(self)})"

    @staticmethod
    def vec_from(ids: Iterable[Any]) -> List["Id"]:
        return [Id(i) for i in ids]

    @staticmethod
    def from_addr(ip: str, port: int) -> "Id":
        """Encodes ``ip:port`` in the low 6 bytes (spawn.rs:10-34)."""
        packed = 0
        for part in ip.split("."):
            packed = (packed << 8) | int(part)
        return Id((packed << 16) | port)

    def to_addr(self) -> Tuple[str, int]:
        port = int(self) & 0xFFFF
        ip_num = (int(self) >> 16) & 0xFFFFFFFF
        ip = ".".join(str((ip_num >> s) & 0xFF) for s in (24, 16, 8, 0))
        return ip, port


class Send(NamedTuple):
    """Send a message to a destination."""

    dst: Id
    msg: Any


class SetTimer(NamedTuple):
    """Set/reset a timer; duration is a (low, high) seconds range (only the
    runtime uses the range — the model treats firing as nondeterministic)."""

    timer: Any
    duration: Tuple[float, float]


class CancelTimer(NamedTuple):
    """Cancel the timer if one is set."""

    timer: Any


def model_timeout() -> Tuple[float, float]:
    """An arbitrary timeout range for model checking (model.rs:59-64)."""
    return (0.0, 0.0)


def model_peers(self_ix: int, count: int) -> List[Id]:
    """Peer ids for actor ``self_ix`` of ``count`` (model.rs:66-73)."""
    return [Id(j) for j in range(count) if j != self_ix]


def majority(count: int) -> int:
    """Minimum size of a majority quorum (actor.rs:530)."""
    return count // 2 + 1


class Out:
    """Buffer of commands emitted by an actor handler (actor.rs:169-243)."""

    def __init__(self):
        self.commands: List[Any] = []

    def send(self, recipient: Id, msg: Any) -> None:
        self.commands.append(Send(recipient, msg))

    def broadcast(self, recipients: Iterable[Id], msg: Any) -> None:
        for r in recipients:
            self.commands.append(Send(r, msg))

    def set_timer(self, timer: Any, duration: Tuple[float, float]) -> None:
        self.commands.append(SetTimer(timer, duration))

    def cancel_timer(self, timer: Any) -> None:
        self.commands.append(CancelTimer(timer))

    def append(self, other: "Out") -> None:
        self.commands.extend(other.commands)
        other.commands = []

    def __iter__(self):
        return iter(self.commands)

    def __len__(self) -> int:
        return len(self.commands)

    def __repr__(self) -> str:
        return repr(self.commands)


class StateRef:
    """Mutable-reference wrapper handed to ``on_msg``/``on_timeout``.

    The Python rendering of the reference's ``Cow<State>`` (actor.rs:311):
    ``get()`` reads the current state; ``set(new)`` replaces it and marks the
    handler as having written (even if the value is equal — matching
    ``Cow::Owned`` semantics).  Handlers that never ``set`` and emit no
    commands are no-ops, and the corresponding action is ignored by the
    model (model.rs:286-289).
    """

    __slots__ = ("_value", "changed")

    def __init__(self, value: Any):
        self._value = value
        self.changed = False

    def get(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        self._value = value
        self.changed = True


def is_no_op(state: StateRef, out: Out) -> bool:
    """True iff the handler neither updated state nor emitted commands
    (actor.rs:247-249)."""
    return not state.changed and not out.commands


def is_no_op_with_timer(state: StateRef, out: Out, timer: Any) -> bool:
    """Like :func:`is_no_op` but tolerates re-setting the same timer
    (actor.rs:254-264)."""
    keep_timer = any(
        isinstance(c, SetTimer) and c.timer == timer for c in out.commands
    )
    return not state.changed and len(out.commands) == 1 and keep_timer


class Actor:
    """An event-driven actor (actor.rs:270-337).

    Subclasses implement ``on_start`` and optionally ``on_msg``/``on_timeout``.
    States should be immutable values (tuples/frozen dataclasses): handlers
    replace them via ``state.set(...)`` rather than mutating in place.
    """

    def on_start(self, id: Id, out: Out) -> Any:
        """Returns the initial state, optionally emitting commands."""
        raise NotImplementedError

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        """Handles a received message. Default: no-op."""

    def on_timeout(self, id: Id, state: StateRef, timer: Any, out: Out) -> None:
        """Handles a timer firing. Default: no-op."""

    @property
    def name(self) -> str:
        return type(self).__name__


class ScriptActor(Actor):
    """Sends a series of ``(Id, msg)`` pairs in sequence, waiting for a
    message delivery between each — useful for driving actor systems in
    tests. The duck-typed rendering of the reference's ``Actor`` impl for
    ``Vec<(Id, Msg)>`` (actor.rs:495-527); state is the next script index.
    """

    def __init__(self, script):
        self.script = list(script)

    def on_start(self, id: Id, out: Out) -> int:
        if self.script:
            dst, msg = self.script[0]
            out.send(dst, msg)
            return 1
        return 0

    def on_msg(self, id: Id, state: StateRef, src: Id, msg: Any, out: Out) -> None:
        i = state.get()
        if i < len(self.script):
            dst, nxt = self.script[i]
            out.send(dst, nxt)
            state.set(i + 1)


from .model import (  # noqa: E402  (re-exports, mirroring actor.rs:99-106)
    ActorModel,
    ActorModelAction,
    DeliverAction,
    DropAction,
    TimeoutAction,
)
from .model_state import ActorModelState  # noqa: E402
from .spawn import spawn  # noqa: E402

__all__ = [
    "Actor",
    "ActorModel",
    "ActorModelAction",
    "ActorModelState",
    "CancelTimer",
    "DeliverAction",
    "DropAction",
    "Envelope",
    "Id",
    "Network",
    "Out",
    "ScriptActor",
    "Send",
    "SetTimer",
    "StateRef",
    "TimeoutAction",
    "Timers",
    "is_no_op",
    "is_no_op_with_timer",
    "majority",
    "model_peers",
    "model_timeout",
    "spawn",
]
