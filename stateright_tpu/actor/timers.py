"""Pending-timer sets. Mirrors ``/root/reference/src/actor/timers.rs``.

In the model a timeout is a nondeterministic action, so only the *set* of
pending timers matters — durations are irrelevant (model.rs:59-64)."""

from __future__ import annotations

from typing import Any, FrozenSet, Iterator


class Timers:
    """The set of timers currently set for one actor (timers.rs:8-48)."""

    __slots__ = ("_set",)

    def __init__(self, timers: FrozenSet[Any] = frozenset()):
        self._set = frozenset(timers)

    def set(self, timer: Any) -> "Timers":
        return Timers(self._set | {timer})

    def cancel(self, timer: Any) -> "Timers":
        return Timers(self._set - {timer})

    def contains(self, timer: Any) -> bool:
        return timer in self._set

    def __iter__(self) -> Iterator[Any]:
        # Deterministic iteration order regardless of PYTHONHASHSEED: sorted
        # by stable fingerprint (the reference gets determinism from its
        # fixed-key hasher's iteration order).
        from ..fingerprint import fingerprint

        return iter(sorted(self._set, key=fingerprint))

    def __len__(self) -> int:
        return len(self._set)

    def __eq__(self, other) -> bool:
        return isinstance(other, Timers) and self._set == other._set

    def __hash__(self) -> int:
        return hash(self._set)

    def __fingerprint_key__(self):
        return self._set

    def __repr__(self) -> str:
        return f"Timers({sorted(map(repr, self._set))})"
