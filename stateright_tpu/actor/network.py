"""In-state message collections with three delivery semantics.

Mirrors ``/root/reference/src/actor/network.rs``.  The network is a *data
structure inside each model state*, not a transport: enumerating deliverable
envelopes (plus drops for lossy networks) is what generates the
nondeterministic interleavings the checker explores.

Unlike the reference's mutate-in-place methods, operations here return new
network values — the functional style matches how the engines clone states,
and keeps networks safely shareable between states.

Determinism note: the reference gets stable iteration order from its
fixed-key hasher; Python set/dict order depends on ``PYTHONHASHSEED``, so
deliverable iteration here sorts by stable fingerprint instead.  (Witness
*validity* never depends on this; reproducibility across runs does.)
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, Iterator, List, NamedTuple, Tuple

from ..fingerprint import fingerprint


class Envelope(NamedTuple):
    """Source, destination, and message (network.rs:23-29)."""

    src: "Id"
    dst: "Id"
    msg: Any


class Network:
    """Base of the three delivery-semantics variants (network.rs:45-68).

    Construct via :meth:`new_ordered`, :meth:`new_unordered_duplicating`,
    or :meth:`new_unordered_nonduplicating`.
    """

    # --- constructors (network.rs:84-117) ---------------------------------

    @staticmethod
    def new_ordered(envelopes: List[Envelope] = ()) -> "OrderedNetwork":
        net = OrderedNetwork({})
        for env in envelopes:
            net = net.send(env)
        return net

    @staticmethod
    def new_unordered_duplicating(
        envelopes: List[Envelope] = (),
    ) -> "UnorderedDuplicatingNetwork":
        net = UnorderedDuplicatingNetwork(frozenset())
        for env in envelopes:
            net = net.send(env)
        return net

    @staticmethod
    def new_unordered_nonduplicating(
        envelopes: List[Envelope] = (),
    ) -> "UnorderedNonDuplicatingNetwork":
        net = UnorderedNonDuplicatingNetwork({})
        for env in envelopes:
            net = net.send(env)
        return net

    # --- CLI parsing (network.rs:119-146, 296-309) ------------------------

    @staticmethod
    def names() -> List[str]:
        return ["ordered", "unordered_duplicating", "unordered_nonduplicating"]

    @staticmethod
    def from_name(name: str) -> "Network":
        try:
            return {
                "ordered": Network.new_ordered,
                "unordered_duplicating": Network.new_unordered_duplicating,
                "unordered_nonduplicating": Network.new_unordered_nonduplicating,
            }[name]()
        except KeyError:
            raise ValueError(f"unable to parse network name: {name}") from None

    # --- protocol ---------------------------------------------------------

    is_ordered = False
    is_duplicating = False

    def send(self, envelope: Envelope) -> "Network":
        raise NotImplementedError

    def on_deliver(self, envelope: Envelope) -> "Network":
        raise NotImplementedError

    def on_drop(self, envelope: Envelope) -> "Network":
        raise NotImplementedError

    def iter_deliverable(self) -> Iterator[Envelope]:
        """Distinct deliverable envelopes (heads only for ordered flows)."""
        raise NotImplementedError

    def iter_all(self) -> Iterator[Envelope]:
        """Every message incl. multiplicity (network.rs:148-157)."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def __rewrite__(self, plan):
        """Remaps actor ids through a symmetry permutation by rebuilding the
        network from rewritten envelopes (network.rs:311-324)."""
        from ..utils.rewrite_plan import rewrite

        ctor = {
            OrderedNetwork: Network.new_ordered,
            UnorderedDuplicatingNetwork: Network.new_unordered_duplicating,
            UnorderedNonDuplicatingNetwork: Network.new_unordered_nonduplicating,
        }[type(self)]
        return ctor([rewrite(env, plan) for env in self.iter_all()])


def _stable_sorted(envs) -> List[Envelope]:
    return sorted(envs, key=fingerprint)


class _SortCache:
    """Networks are immutable and shared across many states, so the
    fingerprint-sorted envelope order is computed once per instance."""

    __slots__ = ("_sorted",)

    def _sorted_envs(self, envs) -> List[Envelope]:
        try:
            return self._sorted
        except AttributeError:
            self._sorted = _stable_sorted(envs)
            return self._sorted


class UnorderedDuplicatingNetwork(_SortCache, Network):
    """No ordering; delivery is a no-op so messages can be redelivered
    (network.rs:51-52, 204-205).  Drop removes the envelope entirely."""

    is_duplicating = True
    __slots__ = ("envelopes",)

    def __init__(self, envelopes: FrozenSet[Envelope]):
        self.envelopes = frozenset(envelopes)

    def send(self, envelope: Envelope) -> "UnorderedDuplicatingNetwork":
        return UnorderedDuplicatingNetwork(self.envelopes | {envelope})

    def on_deliver(self, envelope: Envelope) -> "UnorderedDuplicatingNetwork":
        return self  # redeliverable

    def on_drop(self, envelope: Envelope) -> "UnorderedDuplicatingNetwork":
        return UnorderedDuplicatingNetwork(self.envelopes - {envelope})

    def iter_deliverable(self) -> Iterator[Envelope]:
        return iter(self._sorted_envs(self.envelopes))

    def iter_all(self) -> Iterator[Envelope]:
        return iter(self._sorted_envs(self.envelopes))

    def __len__(self) -> int:
        return len(self.envelopes)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, UnorderedDuplicatingNetwork)
            and self.envelopes == other.envelopes
        )

    def __hash__(self) -> int:
        return hash(("dup", self.envelopes))

    def __fingerprint_key__(self):
        return ("dup", self.envelopes)

    def __repr__(self) -> str:
        return f"UnorderedDuplicating({sorted(map(repr, self.envelopes))})"


class UnorderedNonDuplicatingNetwork(_SortCache, Network):
    """No ordering; a *multiset* with counts so duplicate sends stay
    distinguishable (network.rs:54-55 and the regression test at
    model.rs:861-964). Delivery and drop both consume one instance."""

    __slots__ = ("counts",)

    def __init__(self, counts: Dict[Envelope, int]):
        self.counts = dict(counts)

    def send(self, envelope: Envelope) -> "UnorderedNonDuplicatingNetwork":
        counts = dict(self.counts)
        counts[envelope] = counts.get(envelope, 0) + 1
        return UnorderedNonDuplicatingNetwork(counts)

    def _remove_one(self, envelope: Envelope) -> "UnorderedNonDuplicatingNetwork":
        if envelope not in self.counts:
            raise KeyError(f"envelope not found: {envelope!r}")
        counts = dict(self.counts)
        if counts[envelope] == 1:
            del counts[envelope]
        else:
            counts[envelope] -= 1
        return UnorderedNonDuplicatingNetwork(counts)

    on_deliver = _remove_one
    on_drop = _remove_one

    def iter_deliverable(self) -> Iterator[Envelope]:
        return iter(self._sorted_envs(self.counts.keys()))

    def iter_all(self) -> Iterator[Envelope]:
        for env in self._sorted_envs(self.counts.keys()):
            for _ in range(self.counts[env]):
                yield env

    def __len__(self) -> int:
        return sum(self.counts.values())

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, UnorderedNonDuplicatingNetwork)
            and self.counts == other.counts
        )

    def __hash__(self) -> int:
        return hash(("nondup", frozenset(self.counts.items())))

    def __fingerprint_key__(self):
        return ("nondup", self.counts)

    def __repr__(self) -> str:
        return f"UnorderedNonDuplicating({sorted(map(repr, self.counts.items()))})"


class OrderedNetwork(Network):
    """Per-directed-pair FIFO flows; only flow heads are deliverable, and
    empty flows are canonicalized away (network.rs:57-67, 221-293)."""

    is_ordered = True
    __slots__ = ("flows",)

    def __init__(self, flows: Dict[Tuple[Any, Any], Tuple[Any, ...]]):
        self.flows = {k: tuple(v) for k, v in flows.items() if v}

    def send(self, envelope: Envelope) -> "OrderedNetwork":
        flows = dict(self.flows)
        key = (envelope.src, envelope.dst)
        flows[key] = flows.get(key, ()) + (envelope.msg,)
        return OrderedNetwork(flows)

    def _remove_first(self, envelope: Envelope) -> "OrderedNetwork":
        key = (envelope.src, envelope.dst)
        if key not in self.flows:
            raise KeyError(f"flow not found. src={envelope.src!r}, dst={envelope.dst!r}")
        flow = self.flows[key]
        try:
            i = flow.index(envelope.msg)
        except ValueError:
            raise KeyError(f"message not found: {envelope.msg!r}") from None
        flows = dict(self.flows)
        remaining = flow[:i] + flow[i + 1 :]
        if remaining:
            flows[key] = remaining
        else:
            del flows[key]
        return OrderedNetwork(flows)

    on_deliver = _remove_first
    on_drop = _remove_first

    def iter_deliverable(self) -> Iterator[Envelope]:
        for src, dst in sorted(self.flows.keys()):
            yield Envelope(src, dst, self.flows[(src, dst)][0])

    def iter_all(self) -> Iterator[Envelope]:
        for src, dst in sorted(self.flows.keys()):
            for msg in self.flows[(src, dst)]:
                yield Envelope(src, dst, msg)

    def __len__(self) -> int:
        return sum(len(f) for f in self.flows.values())

    def __eq__(self, other) -> bool:
        return isinstance(other, OrderedNetwork) and self.flows == other.flows

    def __hash__(self) -> int:
        return hash(("ordered", frozenset(self.flows.items())))

    def __fingerprint_key__(self):
        return ("ordered", self.flows)

    def __repr__(self) -> str:
        return f"Ordered({sorted(map(repr, self.flows.items()))})"
