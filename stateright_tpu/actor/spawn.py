"""Real-network execution of actors over UDP.

Mirrors ``/root/reference/src/actor/spawn.rs``: one OS thread per actor, a
UDP socket bound to the address encoded in the actor's :class:`Id`, a receive
loop whose read-timeout is the earliest pending timer deadline, and pluggable
serialization.  This is pure host code — deliberately outside the TPU hot
path (SURVEY.md section 2.8: the real transport is not a TPU concern).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

_PRACTICALLY_NEVER = 60 * 60 * 24 * 365.0  # spawn.rs:36-39


def serialize_json(msg: Any) -> bytes:
    """Wire format for *plain-JSON* messages (ints, strings, lists, dicts).

    NamedTuple/typed messages cannot round-trip through bare JSON (the type
    tag is lost) — use :func:`json_codec` for those, the analogue of the
    reference examples' serde_json enum tagging."""
    return json.dumps(msg).encode("utf-8")


def deserialize_json(data: bytes) -> Any:
    return json.loads(data.decode("utf-8"))


def json_codec(*msg_types: type):
    """Builds a ``(serialize, deserialize)`` pair that tags values with
    their class name *recursively* and reconstructs them on receive — so
    typed messages (NamedTuples), including nested ones and tuple/set/dict
    payloads, survive the wire like serde's tagged enums.

    ``msg_types`` are the NamedTuple classes the actors exchange; scalars
    and lists pass through untagged.
    """
    by_name = {t.__name__: t for t in msg_types}

    def _enc(v: Any) -> Any:
        from . import Id

        t = type(v)
        if t.__name__ in by_name and isinstance(v, tuple):
            return {"@": t.__name__, "f": [_enc(x) for x in v]}
        if t is Id:
            # Framework type, handled natively: actor ids ride inside
            # protocol payloads (Paxos ballots, ABD sequencers) the same
            # way the reference's serde serializes its u64 newtype.
            return {"@": "__id__", "f": int(v)}
        if t is tuple:
            return {"@": "__tuple__", "f": [_enc(x) for x in v]}
        if t in (set, frozenset):
            tag = "__set__" if t is set else "__frozenset__"
            return {"@": tag, "f": [_enc(x) for x in v]}
        if t is dict:
            return {"@": "__dict__", "f": [[_enc(k), _enc(x)] for k, x in v.items()]}
        if t is list:
            return [_enc(x) for x in v]
        if v is None or t in (bool, int, float, str):
            return v
        raise TypeError(
            f"json_codec cannot serialize {t.__qualname__}; register the "
            f"class or use a custom serialize fn"
        )

    def _dec(v: Any) -> Any:
        if isinstance(v, list):
            return [_dec(x) for x in v]
        if isinstance(v, dict):
            tag, fields = v["@"], v["f"]
            if tag == "__id__":
                from . import Id

                return Id(fields)
            if tag == "__tuple__":
                return tuple(_dec(x) for x in fields)
            if tag == "__set__":
                return set(_dec(x) for x in fields)
            if tag == "__frozenset__":
                return frozenset(_dec(x) for x in fields)
            if tag == "__dict__":
                return {_dec(k): _dec(x) for k, x in fields}
            return by_name[tag](*(_dec(x) for x in fields))
        return v

    def serialize(msg: Any) -> bytes:
        return json.dumps(_enc(msg)).encode("utf-8")

    def deserialize(data: bytes) -> Any:
        return _dec(json.loads(data.decode("utf-8")))

    return serialize, deserialize


class _ActorRuntime:
    def __init__(self, id, actor, serialize, deserialize):
        from . import CancelTimer, Out, Send, SetTimer, StateRef

        self.id = id
        self.actor = actor
        self.serialize = serialize
        self.deserialize = deserialize
        self.deadlines: Dict[Any, float] = {}
        ip, port = id.to_addr()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self._Out, self._StateRef = Out, StateRef
        self._Send, self._SetTimer, self._CancelTimer = Send, SetTimer, CancelTimer
        self.stopped = threading.Event()

    def _on_commands(self, out) -> None:
        """Applies commands: sends serialize+send_to, timers maintain a
        deadline map with randomized durations (spawn.rs:146-202)."""
        from . import Id

        for c in out.commands:
            if isinstance(c, self._Send):
                ip, port = Id(c.dst).to_addr()
                try:
                    self.sock.sendto(self.serialize(c.msg), (ip, port))
                except OSError:
                    pass  # sends are fire-and-forget over UDP
            elif isinstance(c, self._SetTimer):
                low, high = c.duration
                self.deadlines[c.timer] = time.monotonic() + random.uniform(low, high)
            elif isinstance(c, self._CancelTimer):
                # Cancel = move the deadline out of reach (spawn.rs:195-200).
                self.deadlines[c.timer] = time.monotonic() + _PRACTICALLY_NEVER
            else:  # pragma: no cover
                raise TypeError(f"unknown command {c!r}")

    def run(self) -> None:
        from . import Id

        out = self._Out()
        state = self.actor.on_start(self.id, out)
        self._on_commands(out)
        while not self.stopped.is_set():
            now = time.monotonic()
            next_deadline = min(self.deadlines.values(), default=now + 1.0)
            timeout = max(0.0, min(next_deadline - now, 1.0))
            self.sock.settimeout(timeout if timeout > 0 else 0.000001)
            try:
                data, (ip, port) = self.sock.recvfrom(65536)
            except socket.timeout:
                now = time.monotonic()
                fired = [t for t, d in self.deadlines.items() if d <= now]
                for timer in fired:
                    del self.deadlines[timer]
                    ref = self._StateRef(state)
                    out = self._Out()
                    self.actor.on_timeout(self.id, ref, timer, out)
                    if ref.changed:
                        state = ref.get()
                    self._on_commands(out)
                continue
            except OSError:
                break
            try:
                msg = self.deserialize(data)
            except Exception:
                continue  # ignore undeserializable input
            src = Id.from_addr(ip, port)
            ref = self._StateRef(state)
            out = self._Out()
            self.actor.on_msg(self.id, ref, src, msg, out)
            if ref.changed:
                state = ref.get()
            self._on_commands(out)
        self.sock.close()


def spawn(
    serialize: Callable[[Any], bytes],
    deserialize: Callable[[bytes], Any],
    actors: List[Tuple["Id", Any]],
    *,
    background: bool = False,
) -> List[Tuple[threading.Thread, _ActorRuntime]]:
    """Runs actors on UDP sockets, one thread per actor (spawn.rs:64-143).

    Blocks until interrupted unless ``background=True``, in which case the
    (thread, runtime) handles are returned; call ``runtime.stopped.set()``
    to stop an actor.
    """
    handles = []
    for id, actor in actors:
        runtime = _ActorRuntime(id, actor, serialize, deserialize)
        thread = threading.Thread(
            target=runtime.run, name=f"actor-{int(id)}", daemon=True
        )
        thread.start()
        handles.append((thread, runtime))
    if not background:
        try:
            for thread, _ in handles:
                thread.join()
        except KeyboardInterrupt:
            for _, runtime in handles:
                runtime.stopped.set()
    return handles
