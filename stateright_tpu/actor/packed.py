"""Packed actor models: the actor framework on the device engine.

The reference's strategy boundary means ``ActorModel`` runs on any checker
because it implements ``Model`` (model.rs:200). On the device engine the
extra requirement is the :class:`~stateright_tpu.xla.XlaChecker` PackedModel
protocol: a fixed-width bit-packed transition kernel. This module provides

- the packing pattern for actor systems, built on the declarative
  :mod:`stateright_tpu.packing` toolkit (``Layout`` bit-fields; for the
  modeled network either a 1-bit-per-envelope bitset over a closed
  universe — the natural codec for unordered-duplicating semantics — or a
  :class:`~stateright_tpu.packing.SlotMultiset` for the non-duplicating
  multiset), and
- :class:`PackedPingPong`, the canonical fixture (actor_test_util.rs:4-126)
  in packed form, differentially tested against the object ``ActorModel``
  (exact 4,094-state parity on the lossy max=5 configuration,
  model.rs:680).

The wrapper *delegates* the object-level ``Model`` API to the underlying
``ActorModel``, so path reconstruction, the Explorer, and property lambdas
see ordinary actor states; only the engine-facing ``packed_*`` kernels are
layout-declared. This is the M3 milestone pattern (SURVEY.md §7): pack the
state, keep the semantics.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ..core import Model
from ..packing import LayoutBuilder
from .actor_test_util import Ping, PingPongCfg, Pong, ping_pong_model
from .model_state import ActorModelState
from .network import Envelope, UnorderedDuplicatingNetwork
from .timers import Timers
from . import Id


class PackedPingPong(Model):
    """The ping-pong ``ActorModel`` with a toolkit-declared packed codec.

    Supports the unordered-duplicating network (the ``ActorModel`` default),
    lossy or lossless, with or without history. The envelope universe is
    closed — Ping(v)/Pong(v) for v in 0..max_nat (the boundary caps actor
    counts, so no larger value is ever sent) — so the network packs as one
    presence bit per universe envelope: for duplicating semantics a set of
    envelopes IS a bitset (network.rs:51-52).
    """

    def __init__(self, cfg: PingPongCfg, lossy: bool = False):
        self.cfg = cfg
        self.lossy = lossy
        inner = ping_pong_model(cfg)
        if lossy:
            inner = inner.lossy_network(True)
        self._inner = inner
        self._V = cfg.max_nat + 1
        # Universe envelope codes: Ping(v) = 2v (actor0 -> actor1),
        # Pong(v) = 2v+1 (actor1 -> actor0).
        count_bits = max(cfg.max_nat.bit_length() + 1, 1)
        self._layout = (
            LayoutBuilder()
            .uint("c0", count_bits)
            .uint("c1", count_bits)
            .uint("hin", 2 * count_bits)
            .uint("hout", 2 * count_bits)
            .array("net", 2 * self._V, 1)
            .finish()
        )
        self.state_words = self._layout.words
        # Action grid: deliver each universe envelope (+ drop it if lossy).
        self.max_actions = (2 if lossy else 1) * 2 * self._V

    # --- object-level Model API: delegate to the ActorModel ----------------

    def init_states(self) -> List[ActorModelState]:
        return self._inner.init_states()

    def actions(self, state, actions: List[Any]) -> None:
        self._inner.actions(state, actions)

    def next_state(self, state, action):
        return self._inner.next_state(state, action)

    def properties(self):
        return self._inner.properties()

    def within_boundary(self, state) -> bool:
        return self._inner.within_boundary(state)

    def format_action(self, action) -> str:
        return self._inner.format_action(action)

    # --- codec -------------------------------------------------------------

    def _env_code(self, env: Envelope) -> int:
        if isinstance(env.msg, Ping):
            return 2 * env.msg.value
        return 2 * env.msg.value + 1

    def _code_env(self, code: int) -> Envelope:
        v, is_pong = divmod(code, 2)
        if is_pong:
            return Envelope(Id(1), Id(0), Pong(v))
        return Envelope(Id(0), Id(1), Ping(v))

    def pack(self, state: ActorModelState) -> np.ndarray:
        c0, c1 = state.actor_states
        hist_in, hist_out = state.history if state.history else (0, 0)
        net = [0] * (2 * self._V)
        for env in state.network.envelopes:
            net[self._env_code(env)] = 1
        return self._layout.pack(c0=c0, c1=c1, hin=hist_in, hout=hist_out, net=net)

    def unpack(self, words) -> ActorModelState:
        f = self._layout.unpack(words)
        envs = [self._code_env(c) for c, bit in enumerate(f["net"]) if bit]
        return ActorModelState(
            actor_states=(f["c0"], f["c1"]),
            network=UnorderedDuplicatingNetwork(frozenset(envs)),
            timers_set=(Timers(), Timers()),
            history=(
                (f["hin"], f["hout"]) if self.cfg.maintains_history else (0, 0)
            ),
        )

    # --- device kernels -----------------------------------------------------

    def packed_init(self) -> np.ndarray:
        return np.stack([self.pack(s) for s in self._inner.init_states()])

    def packed_step(self, words):
        """Full action fan-out of one packed state: deliver every universe
        envelope (no-op deliveries and boundary violations masked invalid,
        the packed collapse of model.rs:286-289 and within_boundary), plus
        a drop per envelope when lossy."""
        import jax.numpy as jnp

        L = self._layout
        u = jnp.uint32
        c0 = L.get(words, "c0")
        c1 = L.get(words, "c1")
        max_nat = u(self.cfg.max_nat)
        keeps_history = self.cfg.maintains_history

        nxt, valid = [], []
        for v in range(self._V):
            uv = u(v)
            # Deliver Ping(v) to actor 1 (actor_test_util.rs on_msg): bump
            # its count, reply Pong(v). Dup network: the Ping bit stays.
            present = L.get(words, "net", 2 * v) != 0
            ok = present & (c1 == uv) & (c1 + u(1) <= max_nat)
            w = L.set(words, "c1", c1 + u(1))
            if keeps_history:
                w = L.set(w, "hin", L.get(w, "hin") + u(1))
                w = L.set(w, "hout", L.get(w, "hout") + u(1))
            w = L.set(w, "net", 1, 2 * v + 1)  # send Pong(v)
            nxt.append(w)
            valid.append(ok)
            # Deliver Pong(v) to actor 0: bump its count, send Ping(v+1).
            present = L.get(words, "net", 2 * v + 1) != 0
            ok = present & (c0 == uv) & (c0 + u(1) <= max_nat)
            w = L.set(words, "c0", c0 + u(1))
            if keeps_history:
                w = L.set(w, "hin", L.get(w, "hin") + u(1))
                w = L.set(w, "hout", L.get(w, "hout") + u(1))
            if v + 1 < self._V:
                w = L.set(w, "net", 1, 2 * (v + 1))  # send Ping(v+1)
            nxt.append(w)
            valid.append(ok)
        if self.lossy:
            for code in range(2 * self._V):
                present = L.get(words, "net", code) != 0
                nxt.append(L.set(words, "net", 0, code))
                valid.append(present)
        return jnp.stack(nxt), jnp.stack(valid)

    def packed_properties(self, words):
        """The fixture's six properties (actor_test_util.rs:68-124), in
        ``properties()`` order."""
        import jax.numpy as jnp

        L = self._layout
        u = jnp.uint32
        c0 = L.get(words, "c0")
        c1 = L.get(words, "c1")
        hist_in = L.get(words, "hin")
        hist_out = L.get(words, "hout")
        max_nat = u(self.cfg.max_nat)
        delta_ok = jnp.where(c0 > c1, c0 - c1, c1 - c0) <= u(1)
        at_max = (c0 == max_nat) | (c1 == max_nat)
        over_max = (c0 == max_nat + u(1)) | (c1 == max_nat + u(1))
        return jnp.stack(
            [
                delta_ok,  # always "delta within 1"
                at_max,  # sometimes "can reach max"
                at_max,  # eventually "must reach max"
                over_max,  # eventually "must exceed max" (falsifiable)
                hist_in <= hist_out,  # always "#in <= #out"
                hist_out <= hist_in + u(1),  # eventually "#out <= #in + 1"
            ]
        )

    def __getattr__(self, name):
        # Property lambdas receive this wrapper as `model`; expose the
        # ActorModel's attributes (cfg is set explicitly above). Private
        # names never delegate — unguarded delegation would recurse when
        # __dict__ is empty (e.g. during unpickling).
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)
