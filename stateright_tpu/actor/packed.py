"""Packed actor models: the actor framework on the device engine.

The reference's strategy boundary means ``ActorModel`` runs on any checker
because it implements ``Model`` (model.rs:200). On the device engine the
extra requirement is the :class:`~stateright_tpu.xla.XlaChecker` PackedModel
protocol: a fixed-width bit-packed transition kernel. This module provides

- the packing pattern for actor systems: per-actor state fields + the
  modeled network as a **bitmask over a closed envelope universe** (for
  unordered-duplicating semantics a set-of-envelopes IS a bitmask; bounded
  multisets/FIFOs use small counters per universe slot), and
- :class:`PackedPingPong`, the canonical fixture (actor_test_util.rs:4-126)
  in packed form, differentially tested against the object ``ActorModel``
  (exact 4,094-state parity on the lossy max=5 configuration,
  model.rs:680).

The wrapper *delegates* the object-level ``Model`` API to the underlying
``ActorModel``, so path reconstruction, the Explorer, and property lambdas
see ordinary actor states; only the engine-facing ``packed_*`` kernels are
hand-packed. This is the M3 milestone pattern (SURVEY.md §7): pack the
state, keep the semantics.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ..core import Model
from .actor_test_util import Ping, PingPongCfg, Pong, ping_pong_model
from .model_state import ActorModelState
from .network import Envelope, UnorderedDuplicatingNetwork
from .timers import Timers
from . import Id

# word 0 layout: actor counts + history counters.
_C0_SHIFT, _C1_SHIFT, _IN_SHIFT, _OUT_SHIFT = 0, 4, 8, 16
_C_MASK, _H_MASK = 0xF, 0xFF
# word 1 layout: Ping(v) presence at bit v, Pong(v) presence at bit 16+v.
_PONG_SHIFT = 16


class PackedPingPong(Model):
    """The ping-pong ``ActorModel`` with a two-word packed codec.

    Supports the unordered-duplicating network (the ``ActorModel`` default),
    lossy or lossless, with or without history. ``max_nat`` must fit the
    4-bit count fields (<= 14) and the 16 envelope-value slots (<= 14).
    """

    state_words = 2

    def __init__(self, cfg: PingPongCfg, lossy: bool = False):
        if cfg.max_nat > 14:
            raise ValueError("max_nat > 14 exceeds the packed field widths")
        self.cfg = cfg
        self.lossy = lossy
        inner = ping_pong_model(cfg)
        if lossy:
            inner = inner.lossy_network(True)
        self._inner = inner
        # Envelope-value universe: Ping(v)/Pong(v) for v in 0..max_nat
        # (boundary caps actor counts at max_nat, so no larger value is
        # ever sent; see the step kernel's boundary mask).
        self._V = cfg.max_nat + 1
        # Action grid: deliver each universe envelope (+ drop it if lossy).
        self.max_actions = (2 if lossy else 1) * 2 * self._V

    # --- object-level Model API: delegate to the ActorModel ----------------

    def init_states(self) -> List[ActorModelState]:
        return self._inner.init_states()

    def actions(self, state, actions: List[Any]) -> None:
        self._inner.actions(state, actions)

    def next_state(self, state, action):
        return self._inner.next_state(state, action)

    def properties(self):
        return self._inner.properties()

    def within_boundary(self, state) -> bool:
        return self._inner.within_boundary(state)

    def format_action(self, action) -> str:
        return self._inner.format_action(action)

    # --- codec -------------------------------------------------------------

    def pack(self, state: ActorModelState) -> np.ndarray:
        c0, c1 = state.actor_states
        hist_in, hist_out = state.history if state.history else (0, 0)
        w0 = (
            (c0 & _C_MASK)
            | ((c1 & _C_MASK) << _C1_SHIFT)
            | ((hist_in & _H_MASK) << _IN_SHIFT)
            | ((hist_out & _H_MASK) << _OUT_SHIFT)
        )
        w1 = 0
        for env in state.network.envelopes:
            if isinstance(env.msg, Ping):
                w1 |= 1 << env.msg.value
            else:
                w1 |= 1 << (_PONG_SHIFT + env.msg.value)
        return np.asarray([w0, w1], dtype=np.uint32)

    def unpack(self, words) -> ActorModelState:
        w0, w1 = (int(w) for w in words)
        envs = []
        for v in range(self._V):
            if (w1 >> v) & 1:
                envs.append(Envelope(Id(0), Id(1), Ping(v)))
            if (w1 >> (_PONG_SHIFT + v)) & 1:
                envs.append(Envelope(Id(1), Id(0), Pong(v)))
        return ActorModelState(
            actor_states=(w0 & _C_MASK, (w0 >> _C1_SHIFT) & _C_MASK),
            network=UnorderedDuplicatingNetwork(frozenset(envs)),
            timers_set=(Timers(), Timers()),
            history=(
                ((w0 >> _IN_SHIFT) & _H_MASK, (w0 >> _OUT_SHIFT) & _H_MASK)
                if self.cfg.maintains_history
                else (0, 0)
            ),
        )

    # --- device kernels -----------------------------------------------------

    def packed_init(self) -> np.ndarray:
        return np.stack([self.pack(s) for s in self._inner.init_states()])

    def packed_step(self, words):
        """Full action fan-out of one packed state: deliver every universe
        envelope (no-op deliveries and boundary violations masked invalid,
        the packed collapse of model.rs:286-289 and within_boundary), plus
        a drop per envelope when lossy."""
        import jax.numpy as jnp

        u = jnp.uint32
        w0, w1 = words[0], words[1]
        c0 = w0 & u(_C_MASK)
        c1 = (w0 >> u(_C1_SHIFT)) & u(_C_MASK)
        max_nat = u(self.cfg.max_nat)
        hist_bump = (
            u((1 << _IN_SHIFT) | (1 << _OUT_SHIFT))
            if self.cfg.maintains_history
            else u(0)
        )

        nxt, valid = [], []
        for v in range(self._V):
            uv = u(v)
            # Deliver Ping(v) to actor 1 (actor_test_util.rs on_msg): bump
            # its count, reply Pong(v). Dup network: the Ping bit stays.
            present = ((w1 >> uv) & u(1)) != 0
            effective = present & (c1 == uv)
            ok = effective & (c1 + u(1) <= max_nat)
            n_w0 = w0 + (u(1) << u(_C1_SHIFT)) + hist_bump
            n_w1 = w1 | (u(1) << (uv + u(_PONG_SHIFT)))
            nxt.append(jnp.stack([n_w0, n_w1]))
            valid.append(ok)
            # Deliver Pong(v) to actor 0: bump its count, send Ping(v+1).
            present = ((w1 >> (uv + u(_PONG_SHIFT))) & u(1)) != 0
            effective = present & (c0 == uv)
            ok = effective & (c0 + u(1) <= max_nat)
            n_w0 = w0 + u(1) + hist_bump
            n_w1 = w1 | (u(1) << (uv + u(1)))
            nxt.append(jnp.stack([n_w0, n_w1]))
            valid.append(ok)
        if self.lossy:
            for v in range(self._V):
                for bit in (v, _PONG_SHIFT + v):
                    present = ((w1 >> u(bit)) & u(1)) != 0
                    n_w1 = w1 & ~(u(1) << u(bit))
                    nxt.append(jnp.stack([w0, n_w1]))
                    valid.append(present)
        return jnp.stack(nxt), jnp.stack(valid)

    def packed_properties(self, words):
        """The fixture's six properties (actor_test_util.rs:68-124), in
        ``properties()`` order."""
        import jax.numpy as jnp

        u = jnp.uint32
        w0 = words[0]
        c0 = w0 & u(_C_MASK)
        c1 = (w0 >> u(_C1_SHIFT)) & u(_C_MASK)
        hist_in = (w0 >> u(_IN_SHIFT)) & u(_H_MASK)
        hist_out = (w0 >> u(_OUT_SHIFT)) & u(_H_MASK)
        max_nat = u(self.cfg.max_nat)
        delta_ok = jnp.where(c0 > c1, c0 - c1, c1 - c0) <= u(1)
        at_max = (c0 == max_nat) | (c1 == max_nat)
        over_max = (c0 == max_nat + u(1)) | (c1 == max_nat + u(1))
        return jnp.stack(
            [
                delta_ok,  # always "delta within 1"
                at_max,  # sometimes "can reach max"
                at_max,  # eventually "must reach max"
                over_max,  # eventually "must exceed max" (falsifiable)
                hist_in <= hist_out,  # always "#in <= #out"
                hist_out <= hist_in + u(1),  # eventually "#out <= #in + 1"
            ]
        )

    def __getattr__(self, name):
        # Property lambdas receive this wrapper as `model`; expose the
        # ActorModel's attributes (cfg is set explicitly above). Private
        # names never delegate — unguarded delegation would recurse when
        # __dict__ is empty (e.g. during unpickling).
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)
