"""Ordered reliable link (ORL): a wrapper giving lossless/ordered/
non-duplicated virtual channels over a lossy network.

Mirrors ``/root/reference/src/actor/ordered_reliable_link.rs``: sequence
numbers + acks + a periodic resend timer ("perfect link" plus ordering).
Order holds per source/destination pair; actors are assumed not to restart
(ordered_reliable_link.rs:1-15).

Deltas from the reference, intentional:

- ``SetTimer``/``CancelTimer`` from the wrapped actor raise
  ``NotImplementedError`` (the reference ``todo!()``s the same way,
  ordered_reliable_link.rs:186-192).
- The reference silently discards wrapped-state updates made in a *user*
  timeout handler (it only processes the emitted commands); here the updated
  state is written back — user timers otherwise couldn't evolve state.
"""

from __future__ import annotations

from typing import Any, Tuple

from ..utils.variant import variant

#: Payload carrier: sequence number + wrapped message.
Deliver = variant("Deliver", ["seq", "msg"])
Ack = variant("Ack", ["seq"])
#: The periodic resend timer.
NetworkTimer = variant("NetworkTimer", [])
#: A timer belonging to the wrapped actor.
UserTimer = variant("UserTimer", ["timer"])

#: ORL bookkeeping around the wrapped actor's state
#: (ordered_reliable_link.rs:50-60).  Maps are stored as sorted item tuples
#: so states stay immutable, hashable, and fingerprintable:
#: msgs_pending_ack is seq -> (dst, msg); last_delivered_seqs is src -> seq.
LinkState = variant(
    "LinkState",
    ["next_send_seq", "msgs_pending_ack", "last_delivered_seqs", "wrapped_state"],
)


def _items_set(items: Tuple, key: Any, value: Any) -> Tuple:
    d = dict(items)
    d[key] = value
    return tuple(sorted(d.items(), key=lambda kv: repr(kv[0])))


def _items_remove(items: Tuple, key: Any) -> Tuple:
    d = dict(items)
    d.pop(key, None)
    return tuple(sorted(d.items(), key=lambda kv: repr(kv[0])))


class ActorWrapper:
    """Wraps an actor to maintain message order, resend lost messages, and
    avoid redelivery (ordered_reliable_link.rs:32-205)."""

    def __init__(self, wrapped_actor, resend_interval: Tuple[float, float] = (1.0, 2.0)):
        self.wrapped_actor = wrapped_actor
        self.resend_interval = resend_interval

    @staticmethod
    def with_default_timeout(wrapped_actor) -> "ActorWrapper":
        return ActorWrapper(wrapped_actor, (1.0, 2.0))

    # -- helpers -----------------------------------------------------------

    def _process_output(self, state: LinkState, wrapped_out, out) -> LinkState:
        """Sends of the wrapped actor become sequenced Deliver envelopes and
        join the pending-ack set (ordered_reliable_link.rs:176-205)."""
        from . import CancelTimer, Send, SetTimer

        next_seq = state.next_send_seq
        pending = state.msgs_pending_ack
        for c in wrapped_out.commands:
            if isinstance(c, (SetTimer, CancelTimer)):
                raise NotImplementedError(
                    "wrapped-actor timers are not supported by the ORL yet"
                )
            if isinstance(c, Send):
                out.send(c.dst, Deliver(next_seq, c.msg))
                pending = _items_set(pending, next_seq, (c.dst, c.msg))
                next_seq += 1
        return LinkState(next_seq, pending, state.last_delivered_seqs, state.wrapped_state)

    # -- Actor interface ---------------------------------------------------

    def on_start(self, id, out):
        from . import Out

        out.set_timer(NetworkTimer(), self.resend_interval)
        wrapped_out = Out()
        wrapped_state = self.wrapped_actor.on_start(id, wrapped_out)
        state = LinkState(1, (), (), wrapped_state)
        return self._process_output(state, wrapped_out, out)

    def on_msg(self, id, state, src, msg, out):
        from . import Out, StateRef, is_no_op

        current: LinkState = state.get()
        if isinstance(msg, Deliver):
            # Always ack (even redeliveries) to stop resends
            # (ordered_reliable_link.rs:110-114).
            out.send(src, Ack(msg.seq))
            if msg.seq <= dict(current.last_delivered_seqs).get(src, 0):
                return
            ref = StateRef(current.wrapped_state)
            wrapped_out = Out()
            self.wrapped_actor.on_msg(id, ref, src, msg.msg, wrapped_out)
            if is_no_op(ref, wrapped_out):
                return
            updated = LinkState(
                current.next_send_seq,
                current.msgs_pending_ack,
                _items_set(current.last_delivered_seqs, src, msg.seq),
                ref.get(),
            )
            state.set(self._process_output(updated, wrapped_out, out))
        elif isinstance(msg, Ack):
            # Unconditional write like the reference's to_mut() — a stale
            # ack still counts as a state-touching action
            # (ordered_reliable_link.rs:146-148).
            state.set(
                LinkState(
                    current.next_send_seq,
                    _items_remove(current.msgs_pending_ack, msg.seq),
                    current.last_delivered_seqs,
                    current.wrapped_state,
                )
            )

    def on_timeout(self, id, state, timer, out):
        from . import Out, StateRef, is_no_op

        current: LinkState = state.get()
        if isinstance(timer, NetworkTimer):
            # Re-arm and resend everything unacked
            # (ordered_reliable_link.rs:157-163).  With nothing pending this
            # is a no-op-with-timer and the action is ignored.
            out.set_timer(NetworkTimer(), self.resend_interval)
            for seq, (dst, msg) in current.msgs_pending_ack:
                out.send(dst, Deliver(seq, msg))
        elif isinstance(timer, UserTimer):
            ref = StateRef(current.wrapped_state)
            wrapped_out = Out()
            self.wrapped_actor.on_timeout(id, ref, timer.timer, wrapped_out)
            if is_no_op(ref, wrapped_out):
                return
            updated = LinkState(
                current.next_send_seq,
                current.msgs_pending_ack,
                current.last_delivered_seqs,
                ref.get(),
            )
            state.set(self._process_output(updated, wrapped_out, out))
