"""Canonical actor fixture: ping-pong with history counters and all three
property kinds.  Mirrors ``/root/reference/src/actor/actor_test_util.rs``."""

from __future__ import annotations

from typing import NamedTuple, Optional

from ..core import Expectation
from ..utils.variant import variant
from . import Actor, ActorModel, Id, Out, StateRef

# variant, not NamedTuple: Ping(n) must not equal Pong(n) in the modeled
# network (Rust enum variants never compare equal across variants).
Ping = variant("Ping", ["value"])
Pong = variant("Pong", ["value"])


class PingPongActor(Actor):
    """Sends Ping(0) at start (if serving), then counts message exchanges."""

    def __init__(self, serve_to: Optional[Id] = None):
        self.serve_to = serve_to

    def on_start(self, id: Id, out: Out) -> int:
        if self.serve_to is not None:
            out.send(self.serve_to, Ping(0))
        return 0

    def on_msg(self, id: Id, state: StateRef, src: Id, msg, out: Out) -> None:
        count = state.get()
        if isinstance(msg, Pong) and count == msg.value:
            out.send(src, Ping(msg.value + 1))
            state.set(count + 1)
        elif isinstance(msg, Ping) and count == msg.value:
            out.send(src, Pong(msg.value))
            state.set(count + 1)


class PingPongCfg(NamedTuple):
    maintains_history: bool
    max_nat: int


def ping_pong_model(cfg: PingPongCfg) -> ActorModel:
    """The full fixture model (actor_test_util.rs:59-124): history counters
    ``(#in, #out)``, a boundary at ``max_nat``, and properties of every
    expectation kind (one eventually-property falsifiable via the boundary)."""

    def record_in(cfg, history, env):
        if cfg.maintains_history:
            return (history[0] + 1, history[1])
        return None

    def record_out(cfg, history, env):
        if cfg.maintains_history:
            return (history[0], history[1] + 1)
        return None

    return (
        ActorModel(cfg=cfg, init_history=(0, 0))
        .actor(PingPongActor(serve_to=Id(1)))
        .actor(PingPongActor())
        .record_msg_in(record_in)
        .record_msg_out(record_out)
        .within_boundary_fn(
            lambda cfg, state: all(c <= cfg.max_nat for c in state.actor_states)
        )
        .property(
            Expectation.ALWAYS,
            "delta within 1",
            lambda _, state: max(state.actor_states) - min(state.actor_states) <= 1,
        )
        .property(
            Expectation.SOMETIMES,
            "can reach max",
            lambda model, state: any(
                c == model.cfg.max_nat for c in state.actor_states
            ),
        )
        .property(
            Expectation.EVENTUALLY,
            "must reach max",
            lambda model, state: any(
                c == model.cfg.max_nat for c in state.actor_states
            ),
        )
        .property(
            Expectation.EVENTUALLY,
            "must exceed max",
            # Falsifiable due to the boundary.
            lambda model, state: any(
                c == model.cfg.max_nat + 1 for c in state.actor_states
            ),
        )
        .property(
            Expectation.ALWAYS,
            "#in <= #out",
            lambda _, state: state.history[0] <= state.history[1],
        )
        .property(
            Expectation.EVENTUALLY,
            "#out <= #in + 1",
            lambda _, state: state.history[1] <= state.history[0] + 1,
        )
    )
