"""System-state snapshot for actor models.

Mirrors ``/root/reference/src/actor/model_state.rs``: per-actor states, the
network, per-actor pending-timer sets, and the auxiliary history.  States are
immutable values — the model builds new snapshots rather than mutating (the
reference shares unchanged actor states via ``Arc``; Python object sharing
gives the same structure sharing for free).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

from ..utils.rewrite_plan import RewritePlan, rewrite
from .network import Network
from .timers import Timers


@dataclass(frozen=True)
class ActorModelState:
    actor_states: Tuple[Any, ...]
    network: Network
    timers_set: Tuple[Timers, ...]
    history: Any = ()

    def representative(self) -> "ActorModelState":
        """Canonical member of this state's symmetry equivalence class:
        actors sorted by state, with the network, timers, and history
        rewritten through the same permutation (model_state.rs:113-129)."""
        plan = RewritePlan.from_values_to_sort(self.actor_states)
        return ActorModelState(
            actor_states=tuple(plan.reindex(self.actor_states)),
            network=rewrite(self.network, plan),
            timers_set=tuple(plan.reindex(self.timers_set)),
            history=rewrite(self.history, plan),
        )

    def __repr__(self) -> str:
        return (
            f"ActorModelState(actor_states={self.actor_states!r}, "
            f"network={self.network!r}, timers={self.timers_set!r}, "
            f"history={self.history!r})"
        )
