"""Benchmark: states/sec of the XLA checker on two-phase commit.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}``.

The metric is generated-states per second (the reference's own notion of
throughput: ``state_count / sec`` from its reporter output, report.rs:66-73)
over a full-coverage check of 2pc with ``BENCH_RM`` resource managers
(default 8 — large enough that steady-state frontiers keep the chip busy).

Methodology: the check runs TWICE. The first run compiles every superstep
bucket the level schedule touches (compilations are cached in-process and
in ``.jax_cache`` across processes); the second run is the measured,
steady-state one. ``vs_baseline`` is the ratio against the driver-defined
north-star of 50M states/sec (BASELINE.md).

**Hang-proofing**: the axon TPU tunnel can WEDGE — not fail — at any point
(observed: ``jax.devices()`` blocking forever, and a dispatch mid-run
blocking after a successful probe). All device work therefore runs in a
child process under the **heartbeat-aware watchdog** of
``stateright_tpu/supervise.py`` (the library form of what used to live
here; the obs layer, docs/observability.md): the worker's engines rewrite
``runs/heartbeat.json`` around every device dispatch, so the parent kills
on *staleness in-band* — a worker mid-``phase="dispatch"`` whose beat goes
stale past ``BENCH_STALL_S`` is a wedged tunnel (the leash stretches 3x
when the beat says the dispatch carries a fresh XLA compile), while a
beating worker may run to the hard ``BENCH_WORKER_TIMEOUT_S`` cap.
``BENCH_TPU_RETRIES`` retries follow — each retry RESUMES from the latest
valid checkpoint the killed worker auto-wrote (``BENCH_CHECKPOINT=0``
disables; ``BENCH_CHECKPOINT_EVERY`` sets the cadence, default 60s), so a
wedge costs at most one checkpoint interval, not the whole search — and
the persistent compile cache makes the respawn cheap. Only after the
retries are spent does the harness fall back to a CPU child. Probe
diagnostics and per-pass progress go to stderr and
``runs/bench_probe.log`` so a hang is attributable post-mortem.

Per-level timing detail is written to ``runs/bench_detail.json`` (levels,
frontier widths, per-level seconds, compile vs steady split) for the
BASELINE.md breakdown. ``BENCH_MUX=K`` adds the batched-scheduling
throughput probe (K same-spec jobs multiplexed through one
CheckerService; jobs_per_sec + dispatches_per_job in the detail's
``mux`` dict — knobs ``BENCH_MUX_SPEC``, ``BENCH_MUX_BUDGET_S``).
``BENCH_SYM=1`` adds the symmetry-reduction A/B probe (one shipped spec
full-space vs symmetry-reduced back to back; class collapse + wall-clock
ratio + reduced-run audit in the detail's ``sym`` dict — knob
``BENCH_SYM_SPEC``, docs/symmetry.md). With ``STPU_TRACE`` set the workers additionally
emit the span JSONL (``tools/roofline.py --measured`` consumes it); the
trace and heartbeat paths are recorded in ``runs/bench_detail.json``.
Adding ``STPU_PHASES=1`` turns on the dispatch-phase profiler: the
measured pass's host_prep/enqueue/device_compute/readback split lands in
the detail's ``phases`` dict (``tools/roofline.py --phases`` is the full
report; docs/observability.md "Distributed tracing").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR = 50_000_000.0
REPO = os.path.dirname(os.path.abspath(__file__))
# Fresh run artifacts (detail JSON, probe log, heartbeat, traces) land
# under runs/ — the repo root stays clean (.gitignore rules match).
RUNS = os.path.join(REPO, "runs")
# Auto-checkpoint bases for the primary passes (rotated .npz files; the
# worker resumes from the latest VALID rotation after a watchdog kill).
CK_WARM = os.path.join(RUNS, "bench_ck_warm.npz")
CK_MEASURED = os.path.join(RUNS, "bench_ck_measured.npz")

# Pinned full-coverage (generated, unique) counts. Exact counts are the
# product guarantee (the reference asserts them in its example tests, e.g.
# /root/reference/examples/paxos.rs:321, examples/2pc.rs:156-170), so the
# bench re-asserts them on EVERY platform and emits ``count_ok`` — a drift
# like round 3's on-chip paxos 17,198-vs-16,668 must fail loudly, not sit
# in a log. Sources: rm=3/5 from the reference anchors; the rest pinned by
# this package's host BFS/DFS oracle and re-verified cross-engine
# (BASELINE.md; tests/test_two_phase_commit.py, tests/test_paxos.py).
EXPECTED_2PC = {
    3: (1_146, 288),
    4: (8_258, 1_568),
    5: (58_146, 8_832),
    6: (402_306, 50_816),
    7: (2_744_706, 296_448),
    8: (18_507_778, 1_745_408),
}
EXPECTED_MATRIX = {
    "linearizable-register (ABD) 2c/2s packed": (875, 544),
    "linearizable-register (ABD) 2c/2s ordered packed": (813, 564),
    "paxos 2c/3s packed": (32_971, 16_668),
    "single-copy-register 3c/1s packed": (6_778, 4_243),
    "increment_lock 3t packed": (61, 61),
}


def _count_check(name: str, expected, states: int, unique: int) -> bool | None:
    """True/False against a pinned (generated, unique) pair; None when the
    config has no pin. A False is logged CRITICAL — it means the engine's
    exact-count contract broke on this platform."""
    if expected is None:
        return None
    ok = (states, unique) == tuple(expected)
    if not ok:
        _log(
            f"COUNT DRIFT on {name}: got generated={states} unique={unique}, "
            f"pinned={expected[0]}/{expected[1]} — exact-count contract "
            "violated on this platform; see stateright_tpu/audit.py"
        )
    return ok


def _audit(checker) -> dict:
    """Host-side duplicate-key audit of the visited set (audit.py); never
    lets an audit failure take down the bench."""
    try:
        from stateright_tpu.audit import audit_table

        return audit_table(checker)
    except Exception as e:  # pragma: no cover - diagnostic path
        return {"error": f"{type(e).__name__}: {e}"}


def _phase_summary(rows) -> dict | None:
    """Folds the checker's ``phase_log`` (the dispatch-phase profiler,
    STPU_PHASES=1) into the bench_detail ``phases`` provenance dict:
    steady-state per-phase seconds, host-RTT share, device occupancy,
    and the projected pipelined wall — the same numbers
    ``tools/roofline.py --phases`` reports from the span trace. None
    when the profiler was off (no rows)."""
    if not rows:
        return None
    names = ("host_prep", "enqueue", "device_compute", "readback")
    steady = [r for r in rows if not r.get("compile")]
    tot = {k: round(sum(r[k] for r in steady), 4) for k in names}
    host = tot["host_prep"] + tot["enqueue"] + tot["readback"]
    dev = tot["device_compute"]
    total = host + dev
    return {
        "dispatches": len(rows),
        "steady_dispatches": len(steady),
        "steady": tot,
        "host_share": round(host / max(total, 1e-12), 3),
        "device_occupancy": round(dev / max(total, 1e-12), 3),
        "projected_pipelined_sec": round(max(host, dev), 4),
    }


#: This bench process's start, for concurrency checks against artifacts
#: other tools write (a sweep that ended before we started never
#: perturbed this run's measurement).
_T0_UNIX = time.time()


def _artifact_fresh(path: str) -> bool:
    """Whether a lint-family artifact is FRESH: newer than every package
    source file and the waiver file. An artifact older than any of its
    inputs is a verdict about some other tree. Raises on a missing
    artifact (callers treat any failure as None-provenance)."""
    mtime = os.path.getmtime(path)
    inputs = [os.path.join(REPO, ".stpu-lint-waivers.toml")]
    pkg = os.path.join(REPO, "stateright_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        inputs += [
            os.path.join(dirpath, fn)
            for fn in filenames
            if fn.endswith(".py")
        ]
    return all(
        os.path.getmtime(p) <= mtime
        for p in inputs
        if os.path.exists(p)
    )


def _lint_ok() -> bool | None:
    """The stpu-lint verdict from runs/lint.json (written by
    tools/smoke.sh's lint stage / tools/stpu_lint.py --json-out), as
    tri-state provenance: True/False, or None when no artifact exists,
    it does not parse, it records a PARTIAL (--only/--rules filtered)
    run, or it is STALE (_artifact_fresh). An absent, partial, or stale
    lint run is not a pass."""
    try:
        path = os.path.join(RUNS, "lint.json")
        if not _artifact_fresh(path):
            return None
        with open(path) as fh:
            report = json.load(fh)
            if report.get("partial"):
                return None
            return bool(report["ok"])
    except Exception:
        return None


def _compile_plan() -> dict | None:
    """STPU007 compile-plan provenance from runs/compile_plan.json (the
    census a full stpu-lint run banks): per-spec distinct program-shape
    counts, or None when the artifact is missing, unparseable, or STALE
    (_artifact_fresh — a census about some other tree). The bench's own
    run may compile MORE shapes than the census (growth events double
    capacities); the census records the declared plan."""
    try:
        path = os.path.join(RUNS, "compile_plan.json")
        if not _artifact_fresh(path):
            return None
        with open(path) as fh:
            census = json.load(fh)
        return {
            "tree": census.get("tree"),
            "distinct_programs": {
                spec: {p: plan["distinct_programs"] for p, plan in plans.items()}
                for spec, plans in census["specs"].items()
            },
        }
    except Exception:
        return None


def _journal_provenance() -> dict | None:
    """Durable-service journal provenance from runs/service_chaos.json
    (the SLO line tools/service_chaos.py banks): per-scenario records
    replayed / jobs re-adopted on restart, or None when the artifact is
    missing, unparseable, or STALE (_artifact_fresh). Sits next to the
    "resume" dict: resume is THIS run's recovery story, journal is the
    service tier's."""
    try:
        path = os.path.join(RUNS, "service_chaos.json")
        if not _artifact_fresh(path):
            return None
        with open(path) as fh:
            line = json.load(fh)
        return {
            "seed": line.get("seed"),
            "ok": line.get("ok"),
            "scenarios": {
                name: rep.get("journal")
                for name, rep in line.get("scenarios", {}).items()
            },
        }
    except Exception:
        return None


def _fleet_provenance() -> dict | None:
    """Fleet-service provenance from the latest runs/service_chaos.json
    sweep (docs/service.md "Fleet"): device count and migration totals
    across the scenarios — next to "journal"/"resume" so
    tools/bench_regress.py can tell a clean line from one measured while
    the fleet was migrating work between devices. None when the sweep
    never ran in fleet mode (or is stale). `migrations` (bench_regress's
    throughput-skip trigger, whose claim is "measured AMID failover")
    only reports a sweep still writing after this bench started — an
    older sweep is device/ok provenance, not a perturbation, and must
    not permanently disable the regression gate."""
    try:
        path = os.path.join(RUNS, "service_chaos.json")
        if not _artifact_fresh(path):
            return None
        concurrent = os.path.getmtime(path) >= _T0_UNIX
        with open(path) as fh:
            line = json.load(fh)
        if not line.get("fleet_devices"):
            return None
        return {
            "devices": line["fleet_devices"],
            "ok": line.get("ok"),
            "migrations": (
                sum(
                    (rep.get("fleet") or {}).get("migrations") or 0
                    for rep in line.get("scenarios", {}).values()
                )
                if concurrent
                else 0
            ),
            "concurrent": concurrent,
            "sessions": line.get("sessions"),
        }
    except Exception:
        return None


def _regress_provenance() -> dict | None:
    """The latest perf-regression verdict from runs/regress.json (written
    by tools/bench_regress.py — the gate judging a fresh primary line
    against the archived runs/archive/BENCH_r*.json trajectory), or None
    when no verdict has been produced. Tolerant of a missing or empty
    archive by construction: the gate itself reports the typed
    "no_baseline" verdict there (fresh clones carry no trajectory), so
    this hook never crashes the bench over absent history."""
    try:
        with open(os.path.join(RUNS, "regress.json")) as fh:
            line = json.load(fh)
        return {
            "verdict": line.get("verdict"),
            "platform": line.get("platform"),
            "baseline": (line.get("baseline") or {}).get("best"),
        }
    except Exception:
        return None


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)
    os.makedirs(RUNS, exist_ok=True)
    with open(os.path.join(RUNS, "bench_probe.log"), "a") as fh:
        fh.write(f"{time.strftime('%H:%M:%S')} {msg}\n")


def _tpu_available(timeout_s: int) -> bool:
    """Probe TPU availability in a subprocess: a killed probe counts as
    unavailable. The probe's own stderr is logged, not swallowed."""
    code = (
        "import jax; ds = jax.devices(); "
        "print('ok', [str(d) for d in ds], ds[0].platform)"
    )
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        _log(
            f"TPU probe timed out after {timeout_s}s; stderr tail: "
            f"{(e.stderr or b'')[-500:] if isinstance(e.stderr, bytes) else (e.stderr or '')[-500:]}"
        )
        return False
    _log(
        f"TPU probe rc={proc.returncode} in {time.monotonic()-t0:.1f}s; "
        f"stdout={proc.stdout.strip()[:200]!r} stderr tail={proc.stderr[-500:]!r}"
    )
    return proc.returncode == 0 and "ok" in proc.stdout


def _run_check(model, detail: list | None, budget_s: float = float("inf"), **spawn_kwargs):
    """A check bounded by wall-clock ``budget_s``: runs whole dispatch
    blocks until done or out of budget; returns (generated_states, seconds,
    checker, completed, states_at_start). The budget means an arbitrarily
    large ``BENCH_RM`` space still yields a steady-state number in bounded
    time. ``states_at_start`` is nonzero only on a checkpoint resume — the
    throughput numerator is the states generated by THIS process."""
    # Deliberately IDENTICAL capacity kwargs for the warm and measured
    # passes (the learned-capacity hints are NOT merged in): every grown
    # capacity changes array shapes, so a measured pass spawned at the warm
    # pass's grown capacities re-traces every bucket program — paying
    # minutes of XLA compile to save a millisecond rehash. With identical
    # kwargs the measured pass replays the warm schedule (including the
    # same proactive growth points) and hits the compile cache at every
    # step. (checkpoint_to/checkpoint_every ride along freely: they change
    # no array shapes.)
    checker = model.checker().spawn_xla(**spawn_kwargs)
    states0 = checker.state_count() if spawn_kwargs.get("checkpoint") else 0
    t0 = time.monotonic()
    while not checker.is_done():
        if time.monotonic() - t0 > budget_s:
            _log(
                f"budget {budget_s:.0f}s exhausted at depth {checker._depth} "
                f"({checker.state_count()} states generated); "
                "reporting partial-coverage throughput"
            )
            break
        lvl_t0 = time.monotonic()
        log_mark = len(checker.level_log)
        checker._run_block()
        if detail is not None:
            # One row per device dispatch (its wall-clock is the tunnel-
            # visible unit) carrying the engine's per-level telemetry.
            detail.append(
                {
                    "sec": round(time.monotonic() - lvl_t0, 4),
                    "levels": checker.level_log[log_mark:],
                }
            )
    elapsed = time.monotonic() - t0
    completed = checker.is_done()
    if completed:
        checker.assert_properties()
    # state_count() includes init states (the reference's reporter counts
    # them too, report.rs:66-73) — generated >= unique at every scale.
    return checker.state_count(), elapsed, checker, completed, states0


def _run_matrix(platform: str) -> list:
    """Secondary configs (BASELINE.json metric: states/sec/chip AND
    time-to-full-coverage): the flagship actor examples on the device
    engine. Warm + measured pass each; small spaces, so these anchor
    time-to-coverage rather than steady-state throughput."""
    from stateright_tpu.models.increment_lock import PackedIncrementLock
    from stateright_tpu.models.linearizable_register import (
        PackedAbd,
        PackedAbdOrdered,
    )
    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    rows = []
    for name, build, kwargs in [
        (
            "linearizable-register (ABD) 2c/2s packed",
            lambda: PackedAbd(2, 2),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
        (
            # The reference harness's ordered-channel config: BASELINE.json's
            # `linearizable-register check 2 ordered` (bench.sh:33 runs the
            # same model at 3 clients) — ABD over FifoLanes.
            "linearizable-register (ABD) 2c/2s ordered packed",
            lambda: PackedAbdOrdered(2, 2),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
        (
            "paxos 2c/3s packed",
            lambda: PackedPaxos(2, 3),
            dict(frontier_capacity=1 << 12, table_capacity=1 << 16),
        ),
        (
            # BASELINE.json's "single-copy-register check 3": 3 clients,
            # linearizability checked device-exact over the 3-thread
            # interleaving enumeration.
            "single-copy-register 3c/1s packed",
            lambda: PackedSingleCopyRegister(3, 1),
            dict(frontier_capacity=1 << 11, table_capacity=1 << 14),
        ),
        (
            "increment_lock 3t packed",
            lambda: PackedIncrementLock(3),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 13),
        ),
    ]:
        try:
            budget = float(os.environ.get("BENCH_MATRIX_BUDGET_S", "300"))
            model = build()
            t0 = time.monotonic()
            _run_check(model, None, budget_s=budget, **kwargs)  # warm: compiles
            warm = time.monotonic() - t0
            states, sec, checker, done, _ = _run_check(
                model, None, budget_s=budget, **kwargs
            )
            if not done:
                rows.append(
                    {"config": name, "error": f"budget {budget:.0f}s exhausted"}
                )
                _log(f"matrix {name}: budget exhausted")
                continue
            rows.append(
                {
                    "config": name,
                    "platform": platform,
                    "generated_states": states,
                    "unique_states": checker.unique_state_count(),
                    "warm_pass_sec": round(warm, 3),
                    "time_to_full_coverage_sec": round(sec, 3),
                    "states_per_sec": round(states / max(sec, 1e-9), 1),
                    "count_ok": _count_check(
                        name,
                        EXPECTED_MATRIX.get(name),
                        states,
                        checker.unique_state_count(),
                    ),
                    "audit": _audit(checker),
                }
            )
            _log(f"matrix {name}: {rows[-1]}")
        except Exception as e:  # keep the primary metric alive no matter what
            _log(f"matrix {name} FAILED: {type(e).__name__}: {e}")
            rows.append({"config": name, "error": f"{type(e).__name__}: {e}"})
    return rows


def _run_mux_throughput(platform: str) -> dict:
    """``BENCH_MUX=K``: the batched-scheduling throughput probe
    (docs/service.md "Batched scheduling"). K same-spec small jobs
    (``BENCH_MUX_SPEC``, default 2pc:3) through ONE CheckerService with
    ``mux_k=K`` — the scheduler folds them into one ``worker.py --mux``
    group, so the whole batch pays one program's dispatch sequence.
    Reports jobs_per_sec and dispatches_per_job; the exactness and the
    >= 3x dispatch-saving acceptance live in tests/test_mux.py — this
    row is the trend line bench_regress watches."""
    import shutil

    from stateright_tpu.service.core import CheckerService, ServiceConfig

    k = int(os.environ.get("BENCH_MUX", "0") or 0)
    spec = os.environ.get("BENCH_MUX_SPEC", "2pc:3")
    budget = float(os.environ.get("BENCH_MUX_BUDGET_S", "420"))
    run_dir = os.path.join(RUNS, "bench_mux")
    shutil.rmtree(run_dir, ignore_errors=True)
    svc = CheckerService(ServiceConfig(
        run_dir=run_dir,
        platform="cpu" if platform == "cpu" else "default",
        mux_k=k,
        # One group wants all K members startable at once.
        max_inflight=k,
        max_queue=2 * k,
        default_max_seconds=budget,
        admission_lint=False,  # shipped spec; the lint gate has its own pins
        probe_auto=False,
    ))
    try:
        t0 = time.monotonic()
        jobs = [svc.submit(spec, max_seconds=budget) for _ in range(k)]
        svc.wait_all(timeout=budget * 1.5)
        elapsed = time.monotonic() - t0
        done = [j for j in jobs if j.status == "done"]
        lane_metrics = [j.result.get("metrics", {}) for j in done]
        dispatches = max(
            (m.get("dispatches", 0) for m in lane_metrics), default=0
        )
        gauges = svc.gauges()
        return {
            "spec": spec,
            "k": k,
            "jobs_done": len(done),
            "jobs_failed": len(jobs) - len(done),
            "seconds": round(elapsed, 3),
            "jobs_per_sec": round(len(done) / max(elapsed, 1e-9), 3),
            "dispatches": dispatches,
            "dispatches_per_job": round(dispatches / max(len(done), 1), 2),
            "dispatches_saved": max(
                (m.get("mux_dispatches_saved", 0) for m in lane_metrics),
                default=0,
            ),
            "mux_groups": gauges.get("mux_groups", 0),
            "mux_lanes": gauges.get("mux_lanes", 0),
        }
    finally:
        svc.close()


def _run_sym_ab(platform: str) -> dict:
    """``BENCH_SYM=1``: the symmetry-reduction A/B probe
    (docs/symmetry.md). One shipped spec (``BENCH_SYM_SPEC``, default
    2pc:4) runs full-space and symmetry-reduced back to back in this
    worker on the same engine configuration — reporting the class
    collapse (unique_full/unique_reduced), the wall-clock ratio (the
    in-superstep canonicalization network should be ~free against the
    table sorts it shrinks), and the reduced run's duplicate-key audit.
    Exactness pins live in tests/test_symmetry.py; this row is the
    trend line bench_regress watches."""
    from stateright_tpu.service import registry

    spec = os.environ.get("BENCH_SYM_SPEC", "2pc:4")
    runs = {}
    for mode in ("off", "on"):
        model, caps = registry.resolve(spec)
        t0 = time.monotonic()
        checker = model.checker().spawn_xla(symmetry=mode, **caps).join()
        runs[mode] = (time.monotonic() - t0, checker)
    off_sec, off_c = runs["off"]
    on_sec, on_c = runs["on"]
    full = off_c.unique_state_count()
    reduced = on_c.unique_state_count()
    return {
        "spec": spec,
        "sym_tag": on_c.metrics().get("symmetry"),
        "unique_full": full,
        "unique_reduced": reduced,
        "collapse": round(full / max(reduced, 1), 3),
        "off_sec": round(off_sec, 3),
        "on_sec": round(on_sec, 3),
        "speedup": round(off_sec / max(on_sec, 1e-9), 3),
        "audit": _audit(on_c),
    }


def _worker(platform: str) -> None:
    """Child-process body: the actual measurement, on ``platform``. Writes
    bench_detail.json and prints the final JSON line on stdout. The parent
    holds the watchdog; this process just works."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        # Persistent compilation cache: supersteps recompile identically
        # across rounds/processes/retries; this turns the ~1 min/bucket TPU
        # compile into a disk hit after the first attempt. (CPU loads are
        # skipped: XLA:CPU AOT reload warns about machine-feature
        # mismatches.)
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # pragma: no cover - older jax
            _log(f"compilation cache unavailable: {e}")

    rm = int(os.environ.get("BENCH_RM", "8"))
    frontier_pow = int(os.environ.get("BENCH_FRONTIER_POW", "19"))
    # The default table size follows the EFFECTIVE dedup structure, because
    # the two families want opposite sizing: sorted/delta pay one
    # [capacity + batch] sort per level, so oversizing costs every level —
    # 2^22 holds rm=8's 1.74M uniques within the 3/4-load growth rule with
    # no growth recompiles. The hash structure wants probe-chain headroom
    # under its 1/4-load rule — 2^24 keeps an rm=8 A/B run (BENCH_DEDUP=
    # hash) from paying a mid-measurement growth recompile at 2^22, which
    # would skew exactly the hash-vs-sorted comparison the knob exists for.
    # A pallas/bsearch compaction request forces a planes-engine dedup.
    # spawn_xla's own auto resolves the same way since r5e (and raises
    # on an explicit hash + planes-only combination); mirroring it here
    # keeps the logged/reported dedup truthful.
    planes_only_compaction = os.environ.get("STPU_COMPACTION") in (
        "pallas",
        "bsearch",
    )
    effective_dedup = os.environ.get("BENCH_DEDUP") or (
        "hash" if platform == "cpu" and not planes_only_compaction
        else "sorted"
    )
    default_table_pow = "24" if effective_dedup == "hash" else "22"
    table_pow = int(os.environ.get("BENCH_TABLE_POW", default_table_pow))
    if platform == "cpu":
        rm = min(rm, int(os.environ.get("BENCH_CPU_RM", "7")))
        frontier_pow = min(frontier_pow, 17)
        table_pow = min(table_pow, 21)
    _log(
        f"worker platform={platform} rm={rm} frontier=2^{frontier_pow} "
        f"table=2^{table_pow} dedup={effective_dedup}"
    )

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    # ONE model instance for both passes: compiled supersteps are cached on
    # the model, so pass 2 reuses every bucket compilation from pass 1.
    model = PackedTwoPhaseSys(rm)

    # TPU warm passes pay one XLA compile per superstep bucket (~1 min each
    # over the tunnel, ~6 buckets at rm=8) — the warm budget must cover them
    # or the measured pass inherits the leftovers and reads artificially low.
    default_warm = "600" if platform == "cpu" else "1500"
    warm_budget = float(os.environ.get("BENCH_WARM_BUDGET_S", default_warm))
    measure_budget = float(os.environ.get("BENCH_MEASURE_BUDGET_S", "300"))
    # Primary-pass ladder, platform-resolved. On 1-core CPU "ramp" wins:
    # every level runs at its snug bucket and padded lanes are real work.
    # On TPU the round-5 A/B measured "jump" FASTER even on the measured
    # pass (6.81s vs 8.70s at rm=8, tpu_profile_r5.log vs bench_detail):
    # padding a level costs almost nothing on-chip while every extra
    # bucket is another compiled program the dispatch pipeline switches
    # through. Both run the same count-checked full coverage.
    # BENCH_LADDER overrides for the on-chip A/B.
    spawn_kwargs = dict(
        frontier_capacity=1 << frontier_pow,
        table_capacity=1 << table_pow,
        ladder=os.environ.get("BENCH_LADDER")
        or ("ramp" if platform == "cpu" else "jump"),
    )
    # Visited-set structure: ALWAYS pinned to the dedup this worker logs
    # and records. spawn_xla's own auto resolves from the REAL jax
    # backend, and the axon plugin can probe "ok" while yielding a CPU
    # device (bench_probe.log: ok ['TFRT_CPU_0'] cpu) — under auto that
    # tpu-labeled worker would silently measure the hash engine (ladder
    # off, lane_words all 0) while bench_detail.json claims sorted.
    # Pinning keeps the artifact truthful to its label on every backend.
    spawn_kwargs["dedup"] = effective_dedup

    # Crash recovery (stateright_tpu/checkpoint.py + supervise.py): the
    # primary passes auto-checkpoint to rotated files under runs/, and a
    # RELAUNCHED worker (the parent's watchdog killed a wedged
    # predecessor) resumes from the latest valid rotation instead of
    # restarting from level 0 — the parent clears stale rotations at the
    # start of every bench invocation, so an on-disk checkpoint always
    # belongs to THIS bench run. A measured-pass checkpoint wins (the warm
    # compiles are already banked in .jax_cache); a warm one resumes the
    # warm pass. Validation guards the CPU fallback: its smaller
    # BENCH_CPU_RM model must not resume a checkpoint of a different
    # configuration.
    from stateright_tpu.checkpoint import (
        latest_valid_checkpoint,
        validate_model,
    )

    checkpointing = os.environ.get("BENCH_CHECKPOINT", "1") != "0"
    ck_every = os.environ.get("BENCH_CHECKPOINT_EVERY", "60s")
    prop_names = [p.name for p in model.properties()]

    def _valid_resume(base, skip_completed=False):
        # with_meta: validation already paid the decompress+digest pass —
        # at soak-scale tables a second load_checkpoint here costs minutes.
        path, meta = latest_valid_checkpoint(base, with_meta=True)
        if path is None:
            return None, None
        try:
            validate_model(meta, model, prop_names)
            # Every v3 checkpoint writes "done" (wider than the
            # exhausted/target_reached flags — see checkpoint.py); a v3
            # file without it is malformed and lands in the except arm.
            done = meta["done"]
        except Exception as e:
            _log(f"not resuming from {path}: {type(e).__name__}: {e}")
            return None, None
        if skip_completed and done:
            # A COMPLETED measured pass whose primary line never made it
            # out (killed in the gap before printing): resuming it would
            # measure zero work. Fall back to the warm checkpoint — a
            # completed warm resume is instant and the measured pass
            # re-runs fresh, yielding a real number.
            _log(f"not resuming from {path}: already-completed run")
            return None, None
        return path, meta

    resumed_from = resume_phase = resume_meta = None
    if checkpointing:
        resumed_from, resume_meta = _valid_resume(CK_MEASURED, skip_completed=True)
        if resumed_from is not None:
            resume_phase = "measured"
        else:
            resumed_from, resume_meta = _valid_resume(CK_WARM)
            if resumed_from is not None:
                resume_phase = "warm"

    def _ck_kwargs(base):
        if not checkpointing:
            return {}
        return dict(
            checkpoint_to=base, checkpoint_every=ck_every, checkpoint_keep=3
        )

    if resume_phase == "measured":
        # The wedge hit mid-measurement: skip the warm pass (its compiles
        # are on disk) and continue the measured pass where it left off.
        _log(
            f"resuming measured pass from {resumed_from} "
            f"(depth {resume_meta['depth']}, "
            f"{resume_meta['state_count']} states); warm pass skipped"
        )
        warm_states, warm_sec = 0, 0.0
    else:
        wkw = dict(spawn_kwargs, **_ck_kwargs(CK_WARM))
        if resume_phase == "warm":
            _log(
                f"resuming warm pass from {resumed_from} "
                f"(depth {resume_meta['depth']})"
            )
            wkw["checkpoint"] = resumed_from
        warm_states, warm_sec, _, _, _ = _run_check(
            model, None, budget_s=warm_budget, **wkw
        )
        _log(
            f"warm pass: {warm_states} states in {warm_sec:.2f}s "
            "(compile included)"
        )

    mkw = dict(spawn_kwargs, **_ck_kwargs(CK_MEASURED))
    if resume_phase == "measured":
        mkw["checkpoint"] = resumed_from
    detail: list = []
    states, elapsed, checker, completed, states0 = _run_check(
        model, detail, budget_s=measure_budget, **mkw
    )
    value = (states - states0) / max(elapsed, 1e-9)
    resumed_note = (
        f", resumed at depth {resume_meta['depth']}"
        if resume_phase == "measured"
        else ""
    )
    _log(
        f"measured pass: {states} states ({checker.unique_state_count()} unique, "
        f"depth {checker.max_depth()}, {'full' if completed else 'partial'} "
        f"coverage{resumed_note}) in {elapsed:.2f}s -> {value:,.0f} states/s"
    )
    # Exact-count self-check (pure host arithmetic — safe before the
    # primary print; only full coverage pins the totals). The table AUDIT
    # is a device-to-host readback of the whole key planes and therefore
    # runs AFTER the primary line is out: a tunnel wedge mid-transfer must
    # not take the already-measured number with it.
    count_ok = (
        _count_check(f"2pc rm={rm}", EXPECTED_2PC.get(rm), states,
                     checker.unique_state_count())
        if completed
        else None
    )

    # The primary metric line goes out IMMEDIATELY: the matrix below may
    # outlive the parent's watchdog, and a killed worker must not take the
    # already-measured number with it (the parent salvages stdout).
    print(
        json.dumps(
            {
                "metric": f"2pc(rm={rm}) generated states/sec, spawn_xla, {platform}",
                "value": round(value, 1),
                "unit": "states/sec",
                "vs_baseline": round(value / NORTH_STAR, 4),
                "count_ok": count_ok,
                # The REAL backend, not the platform label: the axon
                # plugin can probe ok while yielding a CPU device, and a
                # chip-labeled row banking CPU numbers poisons the A/B
                # record (same convention as tools/cand_ab.py).
                "backend": jax.default_backend(),
                # Resume provenance: a resumed line measures the tail of a
                # space from a checkpoint, not a cold full pass — it must
                # be distinguishable at a glance (detail in
                # bench_detail.json's "resume" dict).
                "resumed": resume_phase,
            }
        ),
        flush=True,
    )

    # Host-side duplicate-key audit (tri-state like count_ok: an audit
    # that itself errored reports the error, not a corruption verdict).
    # The result reaches the driver via bench_detail.json and the logged
    # line in bench_probe.log.
    audit = _audit(checker)
    if "error" in audit:
        _log(f"table audit ERRORED (no verdict): {audit}")
    elif not audit.get("ok", False):
        _log(f"TABLE AUDIT FAILED: {audit}")
    else:
        _log(f"table audit: {audit}")

    # Candidate-ladder telemetry (attack #2 evidence for the A/B record):
    # the level rows inside ``detail`` carry the chosen per-level
    # bucket/cand_cap and the cost-law lane-words; summarize them here so
    # BENCH_r06+ carries the engine-measured numbers at the top level.
    import statistics

    _rows = [l for block in detail for l in block.get("levels", [])]
    _lane = sorted(l["lane_words"] for l in _rows if "lane_words" in l)
    lane_summary = (
        {
            # statistics.median everywhere (here, roofline, cand_ab) so
            # the attack-#2 evidence artifacts agree on even-length logs.
            "median": statistics.median(_lane),
            "mean": round(sum(_lane) / len(_lane)),
            "max": _lane[-1],
            "total": sum(_lane),
        }
        if _lane
        else None
    )

    # Dispatch-phase provenance (tools/roofline.py --phases): when the
    # profiler ran (STPU_PHASES=1, needs STPU_TRACE), the measured
    # pass's per-call host/enqueue/device/readback split summarizes
    # here, so a banked row carries the pipelining-attack numbers.
    phase_summary = _phase_summary(getattr(checker, "phase_log", None))

    mux_info = None
    sym_info = None

    def write_detail(matrix):
        os.makedirs(RUNS, exist_ok=True)
        with open(os.path.join(RUNS, "bench_detail.json"), "w") as fh:
            json.dump(
                {
                    "platform": platform,
                    "backend": jax.default_backend(),
                    "rm": rm,
                    # Obs artifacts of this run (docs/observability.md):
                    # the span JSONL (tools/roofline.py --measured reads
                    # it), the watchdog heartbeat, and the metrics
                    # time-series (roofline's fallback source when no
                    # span trace exists), when enabled.
                    "trace": os.environ.get("STPU_TRACE") or None,
                    "heartbeat": os.environ.get("STPU_HEARTBEAT") or None,
                    "metrics_series": os.environ.get("STPU_METRICS_TO") or None,
                    "metrics": checker.metrics(),
                    "table_capacity": checker._table.capacity,
                    "cand_ladder": checker._cand_ladder_k,
                    "cand_retries": checker.cand_retries,
                    "lane_words_per_level": lane_summary,
                    # Dispatch-phase split (STPU_PHASES=1; None when the
                    # profiler was off).
                    "phases": phase_summary,
                    # Resume provenance: which checkpoint (if any) this
                    # worker resumed from, which pass it belonged to, and
                    # the attempt index the parent stamped. levels_replayed
                    # is 0 by construction — a resume starts AT the
                    # checkpoint's depth; nothing before it re-runs (the
                    # alternative, a level-0 restart, replays everything).
                    "resume": {
                        "resumed_from": resumed_from,
                        "phase": resume_phase,
                        "attempt": int(os.environ.get("BENCH_ATTEMPT", "0")),
                        "resume_depth": (
                            resume_meta["depth"] if resume_meta else None
                        ),
                        "states_at_resume": states0,
                        "levels_replayed": 0,
                    },
                    # Durable-service provenance (docs/service.md
                    # "Durability & recovery"): the latest seeded
                    # service_chaos sweep's journal verdicts — records
                    # replayed and jobs re-adopted across restarts.
                    "journal": _journal_provenance(),
                    # Fleet provenance (docs/service.md "Fleet"): device
                    # count + migrations from the latest fleet-mode
                    # sweep — bench_regress skips honestly on lines
                    # measured amid cross-device migrations.
                    "fleet": _fleet_provenance(),
                    # Perf-regression provenance (tools/bench_regress.py):
                    # the last gate verdict against the archived
                    # trajectory, when one exists. The gate runs AFTER a
                    # bench (it consumes this very file), so this records
                    # the previous verdict — trajectory context, not this
                    # run's judgment.
                    "regress": _regress_provenance(),
                    # stpu-lint provenance (docs/static-analysis.md):
                    # the latest runs/lint.json verdict — True/False, or
                    # None when no lint artifact exists (run
                    # tools/smoke.sh or tools/stpu_lint.py --json-out
                    # runs/lint.json). A banked bench row should carry
                    # lint_ok: true — numbers measured on a tree that
                    # violates a pinned-miscompile rule are suspect.
                    "lint_ok": _lint_ok(),
                    # STPU007 census provenance: the compile-shape plan
                    # this tree declares (what warm_cache pre-seeds and
                    # the tunnel window should expect to pay).
                    "compile_plan": _compile_plan(),
                    "generated_states": states,
                    "unique_states": checker.unique_state_count(),
                    "max_depth": checker.max_depth(),
                    "warm_pass_sec": round(warm_sec, 3),
                    "measured_sec": round(elapsed, 3),
                    "full_coverage": completed,
                    "states_per_sec": round(value, 1),
                    "count_ok": count_ok,
                    "audit": audit,
                    # Batched-scheduling throughput (BENCH_MUX=K;
                    # docs/service.md "Batched scheduling"): jobs/sec and
                    # dispatches/job for K same-spec jobs multiplexed
                    # through one service. None unless the knob is set.
                    "mux": mux_info,
                    # Symmetry-reduction A/B (BENCH_SYM=1;
                    # docs/symmetry.md): class collapse and wall-clock
                    # ratio for one spec, full-space vs reduced. None
                    # unless the knob is set.
                    "sym": sym_info,
                    "levels": detail,
                    "matrix": matrix,
                },
                fh,
                indent=1,
            )

    # Write the detail now (sans matrix) so a watchdog kill mid-matrix
    # cannot lose it, then rewrite with the matrix rows.
    write_detail([{"note": "matrix still running (or killed mid-run)"}])
    matrix = []
    if os.environ.get("BENCH_MATRIX", "1") != "0":
        try:
            matrix = _run_matrix(platform)
        except Exception as e:  # the primary metric line must survive
            _log(f"matrix runner FAILED: {type(e).__name__}: {e}")
            matrix = [{"error": f"{type(e).__name__}: {e}"}]
    if int(os.environ.get("BENCH_MUX", "0") or 0) > 1:
        try:
            mux_info = _run_mux_throughput(platform)
            _log(f"mux throughput: {mux_info}")
        except Exception as e:  # same contract as the matrix
            _log(f"mux throughput FAILED: {type(e).__name__}: {e}")
            mux_info = {"error": f"{type(e).__name__}: {e}"}
    if os.environ.get("BENCH_SYM", "0") not in ("", "0"):
        try:
            sym_info = _run_sym_ab(platform)
            _log(f"sym A/B: {sym_info}")
        except Exception as e:  # same contract as the matrix
            _log(f"sym A/B FAILED: {type(e).__name__}: {e}")
            sym_info = {"error": f"{type(e).__name__}: {e}"}
    write_detail(matrix)


def _json_lines(text) -> list:
    if isinstance(text, bytes):
        text = text.decode(errors="replace")
    return [l for l in (text or "").splitlines() if l.strip().startswith("{")]


def _spawn_worker(platform: str, timeout_s: float, attempt: int = 0) -> str | None:
    """Runs ``bench.py --worker <platform>`` under the heartbeat-aware
    watchdog of ``stateright_tpu/supervise.py`` (the generalized library
    form of the loop that used to live here — bench holds NO watchdog
    logic of its own); returns the worker's primary JSON line or None.

    The worker's engines rewrite the heartbeat file around every device
    dispatch (STPU_HEARTBEAT, injected by run_worker unless
    BENCH_HEARTBEAT=0), so the watchdog distinguishes in-band instead of
    guessing from one hard timeout: a stale beat in ``phase="dispatch"``
    is a wedged tunnel (leash ``BENCH_STALL_S``, stretched 3x when the
    beat flags an XLA compile); a worker that never beats gets
    ``BENCH_STARTUP_GRACE_S`` (imports + init inserts can wedge before the
    first dispatch); a beating worker may run to the hard ``timeout_s``
    cap. A worker killed mid-matrix still counts as success if it printed
    the primary line first (stdout salvage below). ``attempt`` is stamped
    into the worker env as BENCH_ATTEMPT for resume provenance."""
    from stateright_tpu import supervise as sup

    os.makedirs(RUNS, exist_ok=True)
    env = dict(os.environ)
    env["BENCH_ATTEMPT"] = str(attempt)
    hb_path = None
    if platform != "cpu" and os.environ.get("BENCH_HEARTBEAT", "1") != "0":
        hb_path = os.environ.get("STPU_HEARTBEAT") or os.path.join(
            RUNS, "heartbeat.json"
        )
    if platform == "cpu":
        # No tunnel, no wedge: the staleness kill exists for the axon
        # transport, and on this 1-core box a long steady dispatch is
        # routine — only the hard timeout supervises the CPU fallback.
        # Popped from the child env too: an outer watcher
        # (tools/tpu_watch.sh) supervising the same heartbeat path must
        # not see CPU-paced dispatch beats and kill the fallback run.
        env.pop("STPU_HEARTBEAT", None)
    res = sup.run_worker(
        [sys.executable, os.path.abspath(__file__), "--worker", platform],
        heartbeat=hb_path,
        timeout_s=timeout_s,
        # The leash must out-wait a HEALTHY steady dispatch: a fused
        # device call covers up to levels_per_dispatch=32 BFS levels with
        # no beat in between, which at soak scale legitimately runs many
        # minutes.
        stall_s=float(os.environ.get("BENCH_STALL_S", "1200")),
        startup_grace_s=float(os.environ.get("BENCH_STARTUP_GRACE_S", "900")),
        env=env,
        cwd=REPO,
        # Worker stdout goes to a file, not a pipe: the parent never reads
        # concurrently, so a pipe could deadlock a chatty worker; a file
        # also survives for post-mortem salvage no matter how the worker
        # dies.
        stdout_path=os.path.join(RUNS, f"worker_{platform}.out"),
        log=_log,
    )
    with open(res.stdout_path) as fh:
        lines = _json_lines(fh.read())
    if res.killed is not None:
        if lines:
            _log(
                f"{platform} worker killed ({res.killed}) but the primary "
                "metric was already out; using it"
            )
            return lines[0]
        _log(f"{platform} worker killed: {res.killed}")
        return None
    if not lines:
        _log(f"{platform} worker rc={res.rc} in {res.seconds:.0f}s, no JSON line")
        return None
    if res.rc != 0:
        # Died (wedged mid-matrix and externally terminated, OOM, ...)
        # AFTER the primary metric went out: the measurement happened —
        # use it, exactly like the watchdog salvage above.
        _log(
            f"{platform} worker rc={res.rc} in {res.seconds:.0f}s but the "
            "primary metric was already out; using it"
        )
        return lines[0]
    _log(f"{platform} worker ok in {res.seconds:.0f}s")
    return lines[0]


def _clear_checkpoints() -> None:
    """A fresh bench invocation must not resume a PREVIOUS invocation's
    checkpoints: clear every rotation of both bases up front, so an
    on-disk checkpoint always means 'written by this run's earlier
    attempt'."""
    from stateright_tpu.checkpoint import rotations

    for base in (CK_WARM, CK_MEASURED):
        for path in rotations(base):
            try:
                os.unlink(path)
            except OSError:
                pass


def main() -> None:
    sys.path.insert(0, REPO)
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker(sys.argv[2])
        return

    probe_s = int(os.environ.get("BENCH_TPU_PROBE_S", "300"))
    worker_timeout = float(os.environ.get("BENCH_WORKER_TIMEOUT_S", "2400"))
    retries = int(os.environ.get("BENCH_TPU_RETRIES", "2"))

    _clear_checkpoints()
    line = None
    if _tpu_available(probe_s):
        for attempt in range(1 + retries):
            if attempt:
                _log(
                    f"TPU retry {attempt}/{retries} (compile cache warm; "
                    "resuming from the latest valid checkpoint, not level 0)"
                )
            line = _spawn_worker("tpu", worker_timeout, attempt=attempt)
            if line is not None:
                break
    else:
        _log("TPU unavailable; skipping to CPU fallback")
    if line is None:
        line = _spawn_worker("cpu", worker_timeout)
    if line is None:  # last resort: the driver always gets a line
        line = json.dumps(
            {
                "metric": "2pc generated states/sec, spawn_xla, none (all workers failed)",
                "value": 0.0,
                "unit": "states/sec",
                "vs_baseline": 0.0,
            }
        )
    print(line)


if __name__ == "__main__":
    main()
