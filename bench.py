"""Benchmark: states/sec of the XLA checker on two-phase commit.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}``.

The metric is generated-states per second (the reference's own notion of
throughput: ``state_count / sec`` from its reporter output, report.rs:66-73)
over a full-coverage check of 2pc with ``BENCH_RM`` resource managers
(default 8 — large enough that steady-state frontiers keep the chip busy).

Methodology: the check runs TWICE. The first run compiles every superstep
bucket the level schedule touches (compilations are cached in-process and
in ``.jax_cache`` across processes); the second run is the measured,
steady-state one. ``vs_baseline`` is the ratio against the driver-defined
north-star of 50M states/sec (BASELINE.md).

**Hang-proofing**: the axon TPU tunnel can WEDGE — not fail — at any point
(observed: ``jax.devices()`` blocking forever, and a dispatch mid-run
blocking after a successful probe). All device work therefore runs in a
child process under a watchdog that is **heartbeat-aware** (the obs layer,
docs/observability.md): the worker's engines rewrite
``runs/heartbeat.json`` around every device dispatch, so the parent kills
on *staleness in-band* — a worker mid-``phase="dispatch"`` whose beat goes
stale past ``BENCH_STALL_S`` is a wedged tunnel (the leash stretches 3x
when the beat says the dispatch carries a fresh XLA compile), while a
beating worker may run to the hard ``BENCH_WORKER_TIMEOUT_S`` cap.
``BENCH_TPU_RETRIES`` retries follow (the persistent compile cache makes
retries cheap); only after the retries are spent does the harness fall
back to a CPU child. Probe diagnostics and per-pass progress go to stderr
and ``runs/bench_probe.log`` so a hang is attributable post-mortem.

Per-level timing detail is written to ``runs/bench_detail.json`` (levels,
frontier widths, per-level seconds, compile vs steady split) for the
BASELINE.md breakdown. With ``STPU_TRACE`` set the workers additionally
emit the span JSONL (``tools/roofline.py --measured`` consumes it); the
trace and heartbeat paths are recorded in ``runs/bench_detail.json``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR = 50_000_000.0
REPO = os.path.dirname(os.path.abspath(__file__))
# Fresh run artifacts (detail JSON, probe log, heartbeat, traces) land
# under runs/ — the repo root stays clean (.gitignore rules match).
RUNS = os.path.join(REPO, "runs")

# Pinned full-coverage (generated, unique) counts. Exact counts are the
# product guarantee (the reference asserts them in its example tests, e.g.
# /root/reference/examples/paxos.rs:321, examples/2pc.rs:156-170), so the
# bench re-asserts them on EVERY platform and emits ``count_ok`` — a drift
# like round 3's on-chip paxos 17,198-vs-16,668 must fail loudly, not sit
# in a log. Sources: rm=3/5 from the reference anchors; the rest pinned by
# this package's host BFS/DFS oracle and re-verified cross-engine
# (BASELINE.md; tests/test_two_phase_commit.py, tests/test_paxos.py).
EXPECTED_2PC = {
    3: (1_146, 288),
    4: (8_258, 1_568),
    5: (58_146, 8_832),
    6: (402_306, 50_816),
    7: (2_744_706, 296_448),
    8: (18_507_778, 1_745_408),
}
EXPECTED_MATRIX = {
    "linearizable-register (ABD) 2c/2s packed": (875, 544),
    "linearizable-register (ABD) 2c/2s ordered packed": (813, 564),
    "paxos 2c/3s packed": (32_971, 16_668),
    "single-copy-register 3c/1s packed": (6_778, 4_243),
    "increment_lock 3t packed": (61, 61),
}


def _count_check(name: str, expected, states: int, unique: int) -> bool | None:
    """True/False against a pinned (generated, unique) pair; None when the
    config has no pin. A False is logged CRITICAL — it means the engine's
    exact-count contract broke on this platform."""
    if expected is None:
        return None
    ok = (states, unique) == tuple(expected)
    if not ok:
        _log(
            f"COUNT DRIFT on {name}: got generated={states} unique={unique}, "
            f"pinned={expected[0]}/{expected[1]} — exact-count contract "
            "violated on this platform; see stateright_tpu/audit.py"
        )
    return ok


def _audit(checker) -> dict:
    """Host-side duplicate-key audit of the visited set (audit.py); never
    lets an audit failure take down the bench."""
    try:
        from stateright_tpu.audit import audit_table

        return audit_table(checker)
    except Exception as e:  # pragma: no cover - diagnostic path
        return {"error": f"{type(e).__name__}: {e}"}


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)
    os.makedirs(RUNS, exist_ok=True)
    with open(os.path.join(RUNS, "bench_probe.log"), "a") as fh:
        fh.write(f"{time.strftime('%H:%M:%S')} {msg}\n")


def _tpu_available(timeout_s: int) -> bool:
    """Probe TPU availability in a subprocess: a killed probe counts as
    unavailable. The probe's own stderr is logged, not swallowed."""
    code = (
        "import jax; ds = jax.devices(); "
        "print('ok', [str(d) for d in ds], ds[0].platform)"
    )
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        _log(
            f"TPU probe timed out after {timeout_s}s; stderr tail: "
            f"{(e.stderr or b'')[-500:] if isinstance(e.stderr, bytes) else (e.stderr or '')[-500:]}"
        )
        return False
    _log(
        f"TPU probe rc={proc.returncode} in {time.monotonic()-t0:.1f}s; "
        f"stdout={proc.stdout.strip()[:200]!r} stderr tail={proc.stderr[-500:]!r}"
    )
    return proc.returncode == 0 and "ok" in proc.stdout


def _run_check(model, detail: list | None, budget_s: float = float("inf"), **spawn_kwargs):
    """A check bounded by wall-clock ``budget_s``: runs whole dispatch
    blocks until done or out of budget; returns (generated_states, seconds,
    checker, completed). The budget means an arbitrarily large ``BENCH_RM``
    space still yields a steady-state number in bounded time."""
    # Deliberately IDENTICAL spawn kwargs for the warm and measured passes
    # (the learned-capacity hints are NOT merged in): every grown capacity
    # changes array shapes, so a measured pass spawned at the warm pass's
    # grown capacities re-traces every bucket program — paying minutes of
    # XLA compile to save a millisecond rehash. With identical kwargs the
    # measured pass replays the warm schedule (including the same proactive
    # growth points) and hits the compile cache at every step.
    checker = model.checker().spawn_xla(**spawn_kwargs)
    t0 = time.monotonic()
    while not checker.is_done():
        if time.monotonic() - t0 > budget_s:
            _log(
                f"budget {budget_s:.0f}s exhausted at depth {checker._depth} "
                f"({checker.state_count()} states generated); "
                "reporting partial-coverage throughput"
            )
            break
        lvl_t0 = time.monotonic()
        log_mark = len(checker.level_log)
        checker._run_block()
        if detail is not None:
            # One row per device dispatch (its wall-clock is the tunnel-
            # visible unit) carrying the engine's per-level telemetry.
            detail.append(
                {
                    "sec": round(time.monotonic() - lvl_t0, 4),
                    "levels": checker.level_log[log_mark:],
                }
            )
    elapsed = time.monotonic() - t0
    completed = checker.is_done()
    if completed:
        checker.assert_properties()
    # state_count() includes init states (the reference's reporter counts
    # them too, report.rs:66-73) — generated >= unique at every scale.
    return checker.state_count(), elapsed, checker, completed


def _run_matrix(platform: str) -> list:
    """Secondary configs (BASELINE.json metric: states/sec/chip AND
    time-to-full-coverage): the flagship actor examples on the device
    engine. Warm + measured pass each; small spaces, so these anchor
    time-to-coverage rather than steady-state throughput."""
    from stateright_tpu.models.increment_lock import PackedIncrementLock
    from stateright_tpu.models.linearizable_register import (
        PackedAbd,
        PackedAbdOrdered,
    )
    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    rows = []
    for name, build, kwargs in [
        (
            "linearizable-register (ABD) 2c/2s packed",
            lambda: PackedAbd(2, 2),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
        (
            # The reference harness's ordered-channel config: BASELINE.json's
            # `linearizable-register check 2 ordered` (bench.sh:33 runs the
            # same model at 3 clients) — ABD over FifoLanes.
            "linearizable-register (ABD) 2c/2s ordered packed",
            lambda: PackedAbdOrdered(2, 2),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
        (
            "paxos 2c/3s packed",
            lambda: PackedPaxos(2, 3),
            dict(frontier_capacity=1 << 12, table_capacity=1 << 16),
        ),
        (
            # BASELINE.json's "single-copy-register check 3": 3 clients,
            # linearizability checked device-exact over the 3-thread
            # interleaving enumeration.
            "single-copy-register 3c/1s packed",
            lambda: PackedSingleCopyRegister(3, 1),
            dict(frontier_capacity=1 << 11, table_capacity=1 << 14),
        ),
        (
            "increment_lock 3t packed",
            lambda: PackedIncrementLock(3),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 13),
        ),
    ]:
        try:
            budget = float(os.environ.get("BENCH_MATRIX_BUDGET_S", "300"))
            model = build()
            t0 = time.monotonic()
            _run_check(model, None, budget_s=budget, **kwargs)  # warm: compiles
            warm = time.monotonic() - t0
            states, sec, checker, done = _run_check(
                model, None, budget_s=budget, **kwargs
            )
            if not done:
                rows.append(
                    {"config": name, "error": f"budget {budget:.0f}s exhausted"}
                )
                _log(f"matrix {name}: budget exhausted")
                continue
            rows.append(
                {
                    "config": name,
                    "platform": platform,
                    "generated_states": states,
                    "unique_states": checker.unique_state_count(),
                    "warm_pass_sec": round(warm, 3),
                    "time_to_full_coverage_sec": round(sec, 3),
                    "states_per_sec": round(states / max(sec, 1e-9), 1),
                    "count_ok": _count_check(
                        name,
                        EXPECTED_MATRIX.get(name),
                        states,
                        checker.unique_state_count(),
                    ),
                    "audit": _audit(checker),
                }
            )
            _log(f"matrix {name}: {rows[-1]}")
        except Exception as e:  # keep the primary metric alive no matter what
            _log(f"matrix {name} FAILED: {type(e).__name__}: {e}")
            rows.append({"config": name, "error": f"{type(e).__name__}: {e}"})
    return rows


def _worker(platform: str) -> None:
    """Child-process body: the actual measurement, on ``platform``. Writes
    bench_detail.json and prints the final JSON line on stdout. The parent
    holds the watchdog; this process just works."""
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    else:
        # Persistent compilation cache: supersteps recompile identically
        # across rounds/processes/retries; this turns the ~1 min/bucket TPU
        # compile into a disk hit after the first attempt. (CPU loads are
        # skipped: XLA:CPU AOT reload warns about machine-feature
        # mismatches.)
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # pragma: no cover - older jax
            _log(f"compilation cache unavailable: {e}")

    rm = int(os.environ.get("BENCH_RM", "8"))
    frontier_pow = int(os.environ.get("BENCH_FRONTIER_POW", "19"))
    # The default table size follows the EFFECTIVE dedup structure, because
    # the two families want opposite sizing: sorted/delta pay one
    # [capacity + batch] sort per level, so oversizing costs every level —
    # 2^22 holds rm=8's 1.74M uniques within the 3/4-load growth rule with
    # no growth recompiles. The hash structure wants probe-chain headroom
    # under its 1/4-load rule — 2^24 keeps an rm=8 A/B run (BENCH_DEDUP=
    # hash) from paying a mid-measurement growth recompile at 2^22, which
    # would skew exactly the hash-vs-sorted comparison the knob exists for.
    # A pallas/bsearch compaction request forces a planes-engine dedup.
    # spawn_xla's own auto resolves the same way since r5e (and raises
    # on an explicit hash + planes-only combination); mirroring it here
    # keeps the logged/reported dedup truthful.
    planes_only_compaction = os.environ.get("STPU_COMPACTION") in (
        "pallas",
        "bsearch",
    )
    effective_dedup = os.environ.get("BENCH_DEDUP") or (
        "hash" if platform == "cpu" and not planes_only_compaction
        else "sorted"
    )
    default_table_pow = "24" if effective_dedup == "hash" else "22"
    table_pow = int(os.environ.get("BENCH_TABLE_POW", default_table_pow))
    if platform == "cpu":
        rm = min(rm, int(os.environ.get("BENCH_CPU_RM", "7")))
        frontier_pow = min(frontier_pow, 17)
        table_pow = min(table_pow, 21)
    _log(
        f"worker platform={platform} rm={rm} frontier=2^{frontier_pow} "
        f"table=2^{table_pow} dedup={effective_dedup}"
    )

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    # ONE model instance for both passes: compiled supersteps are cached on
    # the model, so pass 2 reuses every bucket compilation from pass 1.
    model = PackedTwoPhaseSys(rm)

    # TPU warm passes pay one XLA compile per superstep bucket (~1 min each
    # over the tunnel, ~6 buckets at rm=8) — the warm budget must cover them
    # or the measured pass inherits the leftovers and reads artificially low.
    default_warm = "600" if platform == "cpu" else "1500"
    warm_budget = float(os.environ.get("BENCH_WARM_BUDGET_S", default_warm))
    measure_budget = float(os.environ.get("BENCH_MEASURE_BUDGET_S", "300"))
    # Primary-pass ladder, platform-resolved. On 1-core CPU "ramp" wins:
    # every level runs at its snug bucket and padded lanes are real work.
    # On TPU the round-5 A/B measured "jump" FASTER even on the measured
    # pass (6.81s vs 8.70s at rm=8, tpu_profile_r5.log vs bench_detail):
    # padding a level costs almost nothing on-chip while every extra
    # bucket is another compiled program the dispatch pipeline switches
    # through. Both run the same count-checked full coverage.
    # BENCH_LADDER overrides for the on-chip A/B.
    spawn_kwargs = dict(
        frontier_capacity=1 << frontier_pow,
        table_capacity=1 << table_pow,
        ladder=os.environ.get("BENCH_LADDER")
        or ("ramp" if platform == "cpu" else "jump"),
    )
    # Visited-set structure: ALWAYS pinned to the dedup this worker logs
    # and records. spawn_xla's own auto resolves from the REAL jax
    # backend, and the axon plugin can probe "ok" while yielding a CPU
    # device (bench_probe.log: ok ['TFRT_CPU_0'] cpu) — under auto that
    # tpu-labeled worker would silently measure the hash engine (ladder
    # off, lane_words all 0) while bench_detail.json claims sorted.
    # Pinning keeps the artifact truthful to its label on every backend.
    spawn_kwargs["dedup"] = effective_dedup
    warm_states, warm_sec, _, _ = _run_check(
        model, None, budget_s=warm_budget, **spawn_kwargs
    )
    _log(f"warm pass: {warm_states} states in {warm_sec:.2f}s (compile included)")

    detail: list = []
    states, elapsed, checker, completed = _run_check(
        model, detail, budget_s=measure_budget, **spawn_kwargs
    )
    value = states / max(elapsed, 1e-9)
    _log(
        f"measured pass: {states} states ({checker.unique_state_count()} unique, "
        f"depth {checker.max_depth()}, {'full' if completed else 'partial'} "
        f"coverage) in {elapsed:.2f}s -> {value:,.0f} states/s"
    )
    # Exact-count self-check (pure host arithmetic — safe before the
    # primary print; only full coverage pins the totals). The table AUDIT
    # is a device-to-host readback of the whole key planes and therefore
    # runs AFTER the primary line is out: a tunnel wedge mid-transfer must
    # not take the already-measured number with it.
    count_ok = (
        _count_check(f"2pc rm={rm}", EXPECTED_2PC.get(rm), states,
                     checker.unique_state_count())
        if completed
        else None
    )

    # The primary metric line goes out IMMEDIATELY: the matrix below may
    # outlive the parent's watchdog, and a killed worker must not take the
    # already-measured number with it (the parent salvages stdout).
    print(
        json.dumps(
            {
                "metric": f"2pc(rm={rm}) generated states/sec, spawn_xla, {platform}",
                "value": round(value, 1),
                "unit": "states/sec",
                "vs_baseline": round(value / NORTH_STAR, 4),
                "count_ok": count_ok,
                # The REAL backend, not the platform label: the axon
                # plugin can probe ok while yielding a CPU device, and a
                # chip-labeled row banking CPU numbers poisons the A/B
                # record (same convention as tools/cand_ab.py).
                "backend": jax.default_backend(),
            }
        ),
        flush=True,
    )

    # Host-side duplicate-key audit (tri-state like count_ok: an audit
    # that itself errored reports the error, not a corruption verdict).
    # The result reaches the driver via bench_detail.json and the logged
    # line in bench_probe.log.
    audit = _audit(checker)
    if "error" in audit:
        _log(f"table audit ERRORED (no verdict): {audit}")
    elif not audit.get("ok", False):
        _log(f"TABLE AUDIT FAILED: {audit}")
    else:
        _log(f"table audit: {audit}")

    # Candidate-ladder telemetry (attack #2 evidence for the A/B record):
    # the level rows inside ``detail`` carry the chosen per-level
    # bucket/cand_cap and the cost-law lane-words; summarize them here so
    # BENCH_r06+ carries the engine-measured numbers at the top level.
    import statistics

    _rows = [l for block in detail for l in block.get("levels", [])]
    _lane = sorted(l["lane_words"] for l in _rows if "lane_words" in l)
    lane_summary = (
        {
            # statistics.median everywhere (here, roofline, cand_ab) so
            # the attack-#2 evidence artifacts agree on even-length logs.
            "median": statistics.median(_lane),
            "mean": round(sum(_lane) / len(_lane)),
            "max": _lane[-1],
            "total": sum(_lane),
        }
        if _lane
        else None
    )

    def write_detail(matrix):
        os.makedirs(RUNS, exist_ok=True)
        with open(os.path.join(RUNS, "bench_detail.json"), "w") as fh:
            json.dump(
                {
                    "platform": platform,
                    "backend": jax.default_backend(),
                    "rm": rm,
                    # Obs artifacts of this run (docs/observability.md):
                    # the span JSONL (tools/roofline.py --measured reads
                    # it) and the watchdog heartbeat, when enabled.
                    "trace": os.environ.get("STPU_TRACE") or None,
                    "heartbeat": os.environ.get("STPU_HEARTBEAT") or None,
                    "metrics": checker.metrics(),
                    "table_capacity": checker._table.capacity,
                    "cand_ladder": checker._cand_ladder_k,
                    "cand_retries": checker.cand_retries,
                    "lane_words_per_level": lane_summary,
                    "generated_states": states,
                    "unique_states": checker.unique_state_count(),
                    "max_depth": checker.max_depth(),
                    "warm_pass_sec": round(warm_sec, 3),
                    "measured_sec": round(elapsed, 3),
                    "full_coverage": completed,
                    "states_per_sec": round(value, 1),
                    "count_ok": count_ok,
                    "audit": audit,
                    "levels": detail,
                    "matrix": matrix,
                },
                fh,
                indent=1,
            )

    # Write the detail now (sans matrix) so a watchdog kill mid-matrix
    # cannot lose it, then rewrite with the matrix rows.
    write_detail([{"note": "matrix still running (or killed mid-run)"}])
    matrix = []
    if os.environ.get("BENCH_MATRIX", "1") != "0":
        try:
            matrix = _run_matrix(platform)
        except Exception as e:  # the primary metric line must survive
            _log(f"matrix runner FAILED: {type(e).__name__}: {e}")
            matrix = [{"error": f"{type(e).__name__}: {e}"}]
    write_detail(matrix)


def _json_lines(text) -> list:
    if isinstance(text, bytes):
        text = text.decode(errors="replace")
    return [l for l in (text or "").splitlines() if l.strip().startswith("{")]


def _hb_read(path: str) -> dict | None:
    """Parsed heartbeat, or None (inline stdlib read — the parent stays
    free of package imports; schema: stateright_tpu/obs/heartbeat.py)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _spawn_worker(platform: str, timeout_s: float) -> str | None:
    """Runs ``bench.py --worker <platform>`` under the heartbeat-aware
    watchdog; returns the worker's primary JSON line or None.

    The worker's engines rewrite the heartbeat file around every device
    dispatch (STPU_HEARTBEAT, injected here unless BENCH_HEARTBEAT=0), so
    the parent distinguishes in-band instead of guessing from one hard
    timeout: a stale beat in ``phase="dispatch"`` is a wedged tunnel
    (leash ``BENCH_STALL_S``, stretched 3x when the beat flags an XLA
    compile); a worker that never beats gets ``BENCH_STARTUP_GRACE_S``
    (imports + init inserts can wedge before the first dispatch); a
    beating worker may run to the hard ``timeout_s`` cap. A worker killed
    mid-matrix still counts as success if it printed the primary line
    first. The worker's stderr streams to ours (it logs to
    runs/bench_probe.log)."""
    os.makedirs(RUNS, exist_ok=True)
    env = dict(os.environ)
    hb_path = None
    if os.environ.get("BENCH_HEARTBEAT", "1") != "0":
        hb_path = os.environ.get("STPU_HEARTBEAT") or os.path.join(
            RUNS, "heartbeat.json"
        )
        env["STPU_HEARTBEAT"] = hb_path
    if platform == "cpu":
        # No tunnel, no wedge: the staleness kill exists for the axon
        # transport, and on this 1-core box a long steady dispatch is
        # routine — only the hard timeout supervises the CPU fallback.
        # Popped from the child env too: an outer watcher
        # (tools/tpu_watch.sh) supervising the same heartbeat path must
        # not see CPU-paced dispatch beats and kill the fallback run.
        hb_path = None
        env.pop("STPU_HEARTBEAT", None)
    # The leash must out-wait a HEALTHY steady dispatch: a fused device
    # call covers up to levels_per_dispatch=32 BFS levels with no beat in
    # between, which at soak scale legitimately runs many minutes.
    stall_s = float(os.environ.get("BENCH_STALL_S", "1200"))
    startup_grace_s = float(os.environ.get("BENCH_STARTUP_GRACE_S", "900"))
    t0 = time.monotonic()
    wall0 = time.time()  # beats older than this are a previous run's
    # Worker stdout goes to a file, not a pipe: the parent never reads
    # concurrently, so a pipe could deadlock a chatty worker; a file also
    # survives for post-mortem salvage no matter how the worker dies.
    stdout_path = os.path.join(RUNS, f"worker_{platform}.out")
    stdout_fh = open(stdout_path, "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", platform],
        stdout=stdout_fh,
        text=True,
        cwd=REPO,
        env=env,
    )
    killed = None
    while True:
        try:
            proc.wait(timeout=5)
            break
        except subprocess.TimeoutExpired:
            pass
        elapsed = time.monotonic() - t0
        if elapsed > timeout_s:
            killed = f"hard timeout {timeout_s:.0f}s"
            break
        if hb_path is None:
            continue
        try:
            mtime = os.stat(hb_path).st_mtime
        except OSError:
            mtime = None
        if mtime is None or mtime < wall0:
            # No beat from THIS worker yet: startup (jax import, model
            # build, init inserts) gets its own grace, then counts as a
            # pre-dispatch wedge.
            if elapsed > startup_grace_s:
                killed = f"no heartbeat within {startup_grace_s:.0f}s startup grace"
                break
            continue
        age = time.time() - mtime
        rec = _hb_read(hb_path) or {}
        if rec.get("phase") != "dispatch":
            # Stale in phase="idle" is HOST-side work (audit readbacks,
            # matrix model builds, witness reconstruction), not the
            # tunnel — the protocol says leave it alone (a dead process
            # is caught by proc.wait above, a runaway host loop by the
            # hard timeout).
            continue
        allow = stall_s * (3 if rec.get("compile") else 1)
        if age > allow:
            killed = (
                f"heartbeat stale {age:.0f}s > {allow:.0f}s mid-dispatch "
                f"(compile={bool(rec.get('compile'))}, "
                f"seq={rec.get('seq', '?')}) — wedged tunnel"
            )
            break
    def _clear_heartbeat():
        # The heartbeat is LIVE supervision state, not an artifact: once
        # this worker is gone its file must not linger — a dead worker's
        # final phase="dispatch" beat would read as a wedged tunnel to an
        # outer watcher (tools/tpu_watch.sh) and get the stage's whole
        # process group killed while a retry / CPU fallback is healthy.
        if hb_path:
            try:
                os.unlink(hb_path)
            except OSError:
                pass

    if killed is not None:
        proc.kill()
        proc.wait()
        _clear_heartbeat()
        stdout_fh.close()
        with open(stdout_path) as fh:
            salvage = _json_lines(fh.read())
        if salvage:
            _log(
                f"{platform} worker killed ({killed}) but the primary "
                "metric was already out; using it"
            )
            return salvage[0]
        _log(f"{platform} worker killed: {killed}")
        return None
    _clear_heartbeat()
    stdout_fh.close()
    with open(stdout_path) as fh:
        out = fh.read()
    dt = time.monotonic() - t0
    lines = _json_lines(out)
    if not lines:
        _log(f"{platform} worker rc={proc.returncode} in {dt:.0f}s, no JSON line")
        return None
    if proc.returncode != 0:
        # Died (wedged mid-matrix and externally terminated, OOM, ...)
        # AFTER the primary metric went out: the measurement happened —
        # use it, exactly like the watchdog salvage above.
        _log(
            f"{platform} worker rc={proc.returncode} in {dt:.0f}s but the "
            "primary metric was already out; using it"
        )
        return lines[0]
    _log(f"{platform} worker ok in {dt:.0f}s")
    return lines[0]


def main() -> None:
    sys.path.insert(0, REPO)
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker(sys.argv[2])
        return

    probe_s = int(os.environ.get("BENCH_TPU_PROBE_S", "300"))
    worker_timeout = float(os.environ.get("BENCH_WORKER_TIMEOUT_S", "2400"))
    retries = int(os.environ.get("BENCH_TPU_RETRIES", "2"))

    line = None
    if _tpu_available(probe_s):
        for attempt in range(1 + retries):
            if attempt:
                _log(f"TPU retry {attempt}/{retries} (compile cache warm)")
            line = _spawn_worker("tpu", worker_timeout)
            if line is not None:
                break
    else:
        _log("TPU unavailable; skipping to CPU fallback")
    if line is None:
        line = _spawn_worker("cpu", worker_timeout)
    if line is None:  # last resort: the driver always gets a line
        line = json.dumps(
            {
                "metric": "2pc generated states/sec, spawn_xla, none (all workers failed)",
                "value": 0.0,
                "unit": "states/sec",
                "vs_baseline": 0.0,
            }
        )
    print(line)


if __name__ == "__main__":
    main()
