"""Benchmark: states/sec of the XLA checker on two-phase commit.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}``.

The metric is generated-states per second (the reference's own notion of
throughput: ``state_count / sec`` from its reporter output, report.rs:66-73)
over a full-coverage check of 2pc with ``BENCH_RM`` resource managers
(default 8 — large enough that steady-state frontiers keep the chip busy).

Methodology: the check runs TWICE. The first run compiles every superstep
bucket the level schedule touches (compilations are cached in-process and
in ``.jax_cache`` across processes); the second run is the measured,
steady-state one. ``vs_baseline`` is the ratio against the driver-defined
north-star of 50M states/sec (BASELINE.md).

Runs on the default JAX platform (the axon TPU under the driver); falls
back to CPU if the TPU tunnel doesn't come up inside ``BENCH_TPU_PROBE_S``
(default 600) so the driver always gets a line. Probe diagnostics go to
stderr and ``bench_probe.log`` — round-1's silent fallback is the bug this
fixes (VERDICT.md weak #1).

Per-level timing detail is written to ``bench_detail.json`` (levels,
frontier widths, per-level seconds, compile vs steady split) for the
BASELINE.md breakdown.
"""

from __future__ import annotations

import json
import os
import sys
import time

NORTH_STAR = 50_000_000.0
REPO = os.path.dirname(os.path.abspath(__file__))


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)
    with open(os.path.join(REPO, "bench_probe.log"), "a") as fh:
        fh.write(f"{time.strftime('%H:%M:%S')} {msg}\n")


def _tpu_available(timeout_s: int) -> bool:
    """Probe TPU availability in a subprocess: the axon tunnel can HANG
    (not fail) for many minutes inside jax.devices(), which would eat the
    whole bench budget. A killed probe counts as unavailable. The probe's
    own stderr is logged, not swallowed."""
    import subprocess

    code = (
        "import jax; ds = jax.devices(); "
        "print('ok', [str(d) for d in ds], ds[0].platform)"
    )
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired as e:
        _log(
            f"TPU probe timed out after {timeout_s}s; stderr tail: "
            f"{(e.stderr or b'')[-500:] if isinstance(e.stderr, bytes) else (e.stderr or '')[-500:]}"
        )
        return False
    _log(
        f"TPU probe rc={proc.returncode} in {time.monotonic()-t0:.1f}s; "
        f"stdout={proc.stdout.strip()[:200]!r} stderr tail={proc.stderr[-500:]!r}"
    )
    return proc.returncode == 0 and "ok" in proc.stdout


def _run_check(model, detail: list | None, budget_s: float = float("inf"), **spawn_kwargs):
    """A check bounded by wall-clock ``budget_s``: runs whole BFS levels
    until done or out of budget; returns (generated_states, seconds,
    checker, completed).

    The budget is what makes the bench un-hangable: the states/sec metric
    only needs steady-state levels, not full coverage, so an arbitrarily
    large ``BENCH_RM`` space still yields a number in bounded time (the
    round-1/2 failure mode was a warm pass chasing full coverage for the
    driver's whole time limit)."""
    checker = model.checker().spawn_xla(**spawn_kwargs)
    t0 = time.monotonic()
    states0 = checker.state_count()
    while not checker.is_done():
        if time.monotonic() - t0 > budget_s:
            _log(
                f"budget {budget_s:.0f}s exhausted at depth {checker._depth} "
                f"({checker.state_count() - states0} states generated); "
                "reporting partial-coverage throughput"
            )
            break
        lvl_t0 = time.monotonic()
        width = checker._frontier_count
        checker._run_block()
        if detail is not None:
            detail.append(
                {
                    "depth": checker._depth - 1,
                    "frontier": width,
                    "sec": round(time.monotonic() - lvl_t0, 4),
                }
            )
    elapsed = time.monotonic() - t0
    completed = checker.is_done()
    if completed:
        checker.assert_properties()
    return checker.state_count() - states0, elapsed, checker, completed


def _run_matrix(platform: str) -> list:
    """Secondary configs (BASELINE.json metric: states/sec/chip AND
    time-to-full-coverage): the flagship actor examples on the device
    engine. Warm + measured pass each; small spaces, so these anchor
    time-to-coverage rather than steady-state throughput."""
    from stateright_tpu.models.linearizable_register import PackedAbd
    from stateright_tpu.models.paxos import PackedPaxos
    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    rows = []
    for name, build, kwargs in [
        (
            "linearizable-register (ABD) 2c/2s packed",
            lambda: PackedAbd(2, 2),
            dict(
                frontier_capacity=1 << 10,
                table_capacity=1 << 12,
                host_verified_cap=1024,
            ),
        ),
        (
            "paxos 2c/3s packed",
            lambda: PackedPaxos(2, 3),
            dict(
                frontier_capacity=1 << 12,
                table_capacity=1 << 16,
                host_verified_cap=4096,
            ),
        ),
        (
            "single-copy-register 2c/1s packed",
            lambda: PackedSingleCopyRegister(2, 1),
            dict(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
    ]:
        try:
            budget = float(os.environ.get("BENCH_MATRIX_BUDGET_S", "300"))
            model = build()
            t0 = time.monotonic()
            _run_check(model, None, budget_s=budget, **kwargs)  # warm: compiles
            warm = time.monotonic() - t0
            states, sec, checker, done = _run_check(
                model, None, budget_s=budget, **kwargs
            )
            if not done:
                rows.append(
                    {"config": name, "error": f"budget {budget:.0f}s exhausted"}
                )
                _log(f"matrix {name}: budget exhausted")
                continue
            rows.append(
                {
                    "config": name,
                    "platform": platform,
                    "generated_states": states,
                    "unique_states": checker.unique_state_count(),
                    "warm_pass_sec": round(warm, 3),
                    "time_to_full_coverage_sec": round(sec, 3),
                    "states_per_sec": round(states / max(sec, 1e-9), 1),
                }
            )
            _log(f"matrix {name}: {rows[-1]}")
        except Exception as e:  # keep the primary metric alive no matter what
            _log(f"matrix {name} FAILED: {type(e).__name__}: {e}")
            rows.append({"config": name, "error": f"{type(e).__name__}: {e}"})
    return rows


def main() -> None:
    rm = int(os.environ.get("BENCH_RM", "8"))
    probe_s = int(os.environ.get("BENCH_TPU_PROBE_S", "600"))
    sys.path.insert(0, REPO)

    use_tpu = _tpu_available(probe_s)
    import jax

    if use_tpu:
        # Persistent compilation cache: supersteps recompile identically
        # across rounds/processes; this turns the ~1 min/bucket TPU compile
        # into a disk hit after the first round. (CPU loads are skipped:
        # XLA:CPU AOT reload warns about machine-feature mismatches.)
        try:
            jax.config.update(
                "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
            )
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # pragma: no cover - older jax
            _log(f"compilation cache unavailable: {e}")

    frontier_pow = int(os.environ.get("BENCH_FRONTIER_POW", "19"))
    table_pow = int(os.environ.get("BENCH_TABLE_POW", "24"))
    if use_tpu:
        platform = jax.devices()[0].platform
    else:  # TPU tunnel unavailable — fall back to CPU
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    if platform == "cpu":
        rm = min(rm, int(os.environ.get("BENCH_CPU_RM", "7")))
        frontier_pow = min(frontier_pow, 17)
        table_pow = min(table_pow, 21)
    _log(f"platform={platform} rm={rm} frontier=2^{frontier_pow} table=2^{table_pow}")

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    # ONE model instance for both passes: compiled supersteps are cached on
    # the model, so pass 2 reuses every bucket compilation from pass 1.
    model = PackedTwoPhaseSys(rm)

    # Pass 1: warm every superstep bucket (compile time, excluded).
    warm_budget = float(os.environ.get("BENCH_WARM_BUDGET_S", "600"))
    measure_budget = float(os.environ.get("BENCH_MEASURE_BUDGET_S", "300"))
    spawn_kwargs = dict(
        frontier_capacity=1 << frontier_pow, table_capacity=1 << table_pow
    )
    warm_states, warm_sec, _, _ = _run_check(
        model, None, budget_s=warm_budget, **spawn_kwargs
    )
    _log(f"warm pass: {warm_states} states in {warm_sec:.2f}s (compile included)")

    # Pass 2: measured steady-state run.
    detail: list = []
    states, elapsed, checker, completed = _run_check(
        model, detail, budget_s=measure_budget, **spawn_kwargs
    )
    value = states / max(elapsed, 1e-9)
    _log(
        f"measured pass: {states} states ({checker.unique_state_count()} unique, "
        f"depth {checker.max_depth()}, {'full' if completed else 'partial'} "
        f"coverage) in {elapsed:.2f}s -> {value:,.0f} states/s"
    )

    matrix = []
    if os.environ.get("BENCH_MATRIX", "1") != "0":
        try:
            matrix = _run_matrix(platform)
        except Exception as e:  # the primary metric line must survive
            _log(f"matrix runner FAILED: {type(e).__name__}: {e}")
            matrix = [{"error": f"{type(e).__name__}: {e}"}]

    with open(os.path.join(REPO, "bench_detail.json"), "w") as fh:
        json.dump(
            {
                "platform": platform,
                "rm": rm,
                "generated_states": states,
                "unique_states": checker.unique_state_count(),
                "max_depth": checker.max_depth(),
                "warm_pass_sec": round(warm_sec, 3),
                "measured_sec": round(elapsed, 3),
                "full_coverage": completed,
                "states_per_sec": round(value, 1),
                "levels": detail,
                "matrix": matrix,
            },
            fh,
            indent=1,
        )

    print(
        json.dumps(
            {
                "metric": f"2pc(rm={rm}) generated states/sec, spawn_xla, {platform}",
                "value": round(value, 1),
                "unit": "states/sec",
                "vs_baseline": round(value / NORTH_STAR, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
