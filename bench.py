"""Benchmark: states/sec of the XLA checker on two-phase commit.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}``.

The metric is generated-states per second (the reference's own notion of
throughput: ``state_count / sec`` from its reporter output, report.rs:66-73)
over a full-coverage check of 2pc with ``BENCH_RM`` resource managers
(default 8 — large enough that steady-state frontiers keep the chip busy).
Compilation is excluded (the first super-step triggers it; timing starts
after).  ``vs_baseline`` is the ratio against the driver-defined north-star
of 50M states/sec (BASELINE.md).

Runs on the default JAX platform (the axon TPU under the driver); falls back
to CPU if TPU init fails so the driver always gets a line.
"""

from __future__ import annotations

import json
import os
import sys
import time

NORTH_STAR = 50_000_000.0


def _tpu_available(timeout_s: int = 120) -> bool:
    """Probe TPU availability in a subprocess: the axon tunnel can HANG
    (not fail) for many minutes inside jax.devices(), which would eat the
    whole bench budget. A killed probe counts as unavailable."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    rm = int(os.environ.get("BENCH_RM", "8"))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    use_tpu = _tpu_available()
    import jax

    frontier_pow = int(os.environ.get("BENCH_FRONTIER_POW", "19"))
    table_pow = int(os.environ.get("BENCH_TABLE_POW", "24"))
    if use_tpu:
        platform = jax.devices()[0].platform
    else:  # TPU tunnel unavailable — fall back to CPU
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    if platform == "cpu":
        rm = min(rm, 6)
        # The insert's per-round claim buffer is O(table); TPU-sized tables
        # drown a CPU run. The engine grows the table on demand anyway.
        frontier_pow = min(frontier_pow, 14)
        table_pow = min(table_pow, 17)

    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    checker = PackedTwoPhaseSys(rm).checker().spawn_xla(
        frontier_capacity=1 << frontier_pow,
        table_capacity=1 << table_pow,
    )
    # First block compiles; exclude it from timing but count its states.
    checker._run_block()
    t0 = time.monotonic()
    states_before = checker.state_count()
    checker.join()
    elapsed = time.monotonic() - t0
    states = checker.state_count() - states_before
    value = states / max(elapsed, 1e-9)
    checker.assert_properties()

    print(
        json.dumps(
            {
                "metric": f"2pc(rm={rm}) generated states/sec, spawn_xla, {platform}",
                "value": round(value, 1),
                "unit": "states/sec",
                "vs_baseline": round(value / NORTH_STAR, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
