"""CPU baseline measurements for BASELINE.md's "published" section.

The reference's own harness (``bench.sh:18-34``) runs its example binaries
under ``cargo run --release`` and greps the reporter's ``sec=`` line; this
container has no Rust toolchain, so those numbers cannot be produced here.
This script measures the equivalents this framework CAN run on the host:

- the **host oracle engines** (single-threaded Python BFS/DFS — the
  correctness oracles, not the performance path) on the BASELINE.json
  config matrix, and
- the **XLA engine on CPU** (the same compiled superstep the TPU runs) on
  the packed models, which anchors the device-vs-host comparison when no
  chip is reachable.

Run: ``python bench_cpu.py`` (forces the CPU backend). Prints one JSON line
per config; paste the table into BASELINE.md.
"""

from __future__ import annotations

import json
import time


def _time_checker(build):
    t0 = time.monotonic()
    checker = build()
    if hasattr(checker, "join"):
        checker.join()
    sec = time.monotonic() - t0
    return {
        "states": checker.state_count(),
        "unique": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "sec": round(sec, 3),
        "states_per_sec": round(checker.state_count() / max(sec, 1e-9), 1),
    }


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from stateright_tpu.actor import Network
    from stateright_tpu.models.increment_lock import IncrementLock
    from stateright_tpu.models.linearizable_register import (
        PackedAbd,
        linearizable_register_model,
    )
    from stateright_tpu.models.paxos import PackedPaxos, paxos_model
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
        single_copy_register_model,
    )
    from stateright_tpu.models.two_phase_commit import (
        PackedTwoPhaseSys,
        TwoPhaseSys,
    )

    configs = [
        # Host oracle engines on the BASELINE.json config matrix
        # (bench.sh runs `check` = DFS in the reference examples).
        ("2pc rm=3, host dfs", lambda: TwoPhaseSys(3).checker().spawn_dfs()),
        ("2pc rm=5, host dfs", lambda: TwoPhaseSys(5).checker().spawn_dfs()),
        (
            "paxos 2c/3s, host dfs",
            lambda: paxos_model(2, 3).checker().spawn_dfs(),
        ),
        (
            "single-copy-register 3c/1s, host dfs",
            lambda: single_copy_register_model(3, 1).checker().spawn_dfs(),
        ),
        (
            "linearizable-register 2c/2s, host dfs",
            lambda: linearizable_register_model(2, 2).checker().spawn_dfs(),
        ),
        (
            "linearizable-register 2c/2s ordered, host dfs",
            lambda: linearizable_register_model(
                2, 2, Network.new_ordered()
            ).checker().spawn_dfs(),
        ),
        (
            "increment_lock, host dfs",
            lambda: IncrementLock().checker().spawn_dfs(),
        ),
        # The XLA engine on the CPU backend (same compiled superstep as TPU).
        (
            "2pc rm=5 packed, spawn_xla cpu",
            lambda: PackedTwoPhaseSys(5)
            .checker()
            .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 15),
        ),
        (
            "paxos 2c/3s packed, spawn_xla cpu",
            lambda: PackedPaxos(2, 3)
            .checker()
            .spawn_xla(
                frontier_capacity=1 << 12,
                table_capacity=1 << 16,
                host_verified_cap=4096,
            ),
        ),
        (
            "single-copy-register 2c/1s packed, spawn_xla cpu",
            lambda: PackedSingleCopyRegister(2, 1)
            .checker()
            .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 12),
        ),
        # Round-3 configurations: 3-thread device-exact linearizability.
        (
            "linearizable-register 3c/2s, host bfs",
            lambda: linearizable_register_model(3, 2).checker().spawn_bfs(),
        ),
        (
            "linearizable-register 3c/2s packed, spawn_xla cpu",
            lambda: PackedAbd(3, 2)
            .checker()
            .spawn_xla(frontier_capacity=1 << 12, table_capacity=1 << 16),
        ),
        (
            "single-copy-register 3c/1s packed, spawn_xla cpu",
            lambda: PackedSingleCopyRegister(3, 1)
            .checker()
            .spawn_xla(frontier_capacity=1 << 11, table_capacity=1 << 14),
        ),
    ]
    for name, build in configs:
        row = _time_checker(build)
        row["config"] = name
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
