"""The host-verified property machinery, exercised on its own.

The shipped register models now check linearizability EXACTLY on device
(``device_linearizable_register``), so none of them routes through the
engine's host-verification path anymore. That path remains part of the
engine contract (``stateright_tpu.xla`` module docstring) for models whose
exact conditions cannot run on device — e.g. histories too large for the
static interleaving enumeration. This test pins it with a model variant
that deliberately uses the conservative device predicate
(``BoundedHistory.valid_with_no_return_geq``: "valid and no completed
read", exact in one direction) and relies on the engine to confirm
candidates with the exact backtracking serializer on the host.
"""

import pytest

from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister


class ConservativeSingleCopy(PackedSingleCopyRegister):
    """Single-copy register with the M4(a)-style conservative device
    predicate + host verification, instead of the exact device check."""

    host_verified_properties = frozenset({"linearizable"})

    def packed_properties(self, words):
        props = super().packed_properties(words)
        # Certainly-linearizable iff unpoisoned with no completed read
        # (ReadOk codes are >= 1); anything else becomes a host candidate.
        return props.at[0].set(self._hist.valid_with_no_return_geq(words, 1))


@pytest.mark.parametrize("dedup", ["hash", "sorted"])
def test_host_verified_full_coverage_confirms_no_candidate(dedup):
    """1 server: every flagged candidate passes the exact host check, so
    full coverage completes with no discovery for the always-property
    (both visited-set structures: the sorted one also routes the hv
    candidate compaction through the planes superstep)."""
    m = ConservativeSingleCopy(2, 1)
    xc = m.checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12, host_verified_cap=1024,
        dedup=dedup,
    ).join()
    assert xc.unique_state_count() == 93  # single-copy-register.rs:110
    xc.assert_properties()


@pytest.mark.parametrize("dedup", ["hash", "sorted"])
def test_host_verified_confirms_the_real_counterexample(dedup):
    """2 servers: the host serializer must reject spuriously-flagged
    candidates and confirm only a genuinely non-linearizable state."""
    m = ConservativeSingleCopy(2, 2)
    xc = m.checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12, host_verified_cap=1024,
        dedup=dedup,
    ).join()
    witness = xc.discoveries()["linearizable"]
    assert witness.last_state().history.serialized_history() is None
