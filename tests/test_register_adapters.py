"""Register / write-once-register adapter tests: a minimal in-memory server
plus scripted clients, with consistency testers riding in the model history
(the shape of the reference's register.rs / write_once_register.rs usage).

The 93-unique-state count for 2 clients + 1 server matches the reference's
single-copy-register example (examples/single-copy-register.rs:110), which
uses exactly this topology.
"""

from stateright_tpu import Expectation
from stateright_tpu.actor import ActorModel, Network
from stateright_tpu.actor import register as reg
from stateright_tpu.actor import write_once_register as woreg
from stateright_tpu.semantics import LinearizabilityTester
from stateright_tpu.semantics.register import Register
from stateright_tpu.semantics.write_once_register import WORegister


class SingleRegisterServer:
    """Unreplicated register server: stores the latest Put value."""

    def on_start(self, id, out):
        return None

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, reg.Put):
            state.set(msg.value)
            out.send(src, reg.PutOk(msg.request_id))
        elif isinstance(msg, reg.Get):
            out.send(src, reg.GetOk(msg.request_id, state.get()))

    def on_timeout(self, id, state, timer, out):
        pass


class SingleWORegisterServer:
    """Write-once server: first Put wins, conflicting Puts fail."""

    def on_start(self, id, out):
        return None

    def on_msg(self, id, state, src, msg, out):
        if isinstance(msg, woreg.Put):
            if state.get() is None or state.get() == msg.value:
                state.set(msg.value)
                out.send(src, woreg.PutOk(msg.request_id))
            else:
                out.send(src, woreg.PutFail(msg.request_id))
        elif isinstance(msg, woreg.Get):
            out.send(src, woreg.GetOk(msg.request_id, state.get()))

    def on_timeout(self, id, state, timer, out):
        pass


def test_single_server_register_is_linearizable():
    m = (
        ActorModel(cfg=None, init_history=LinearizabilityTester(Register(None)))
        .actor(SingleRegisterServer())
        .actor(reg.RegisterClient(put_count=1, server_count=1))
        .actor(reg.RegisterClient(put_count=1, server_count=1))
        .init_network(Network.new_unordered_nonduplicating())
        .record_msg_out(reg.record_invocations)
        .record_msg_in(reg.record_returns)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda _, s: s.history.serialized_history() is not None,
        )
    )
    checker = m.checker().spawn_bfs().join()
    checker.assert_no_discovery("linearizable")
    assert checker.unique_state_count() == 93


def test_single_server_wo_register_is_linearizable():
    m = (
        ActorModel(cfg=None, init_history=LinearizabilityTester(WORegister(None)))
        .actor(SingleWORegisterServer())
        .actor(woreg.WORegisterClient(put_count=1, server_count=1))
        .actor(woreg.WORegisterClient(put_count=1, server_count=1))
        .init_network(Network.new_unordered_nonduplicating())
        .record_msg_out(woreg.record_invocations)
        .record_msg_in(woreg.record_returns)
        .property(
            Expectation.ALWAYS,
            "linearizable",
            lambda _, s: s.history.serialized_history() is not None,
        )
    )
    checker = m.checker().spawn_bfs().join()
    checker.assert_no_discovery("linearizable")
    assert checker.unique_state_count() > 0


def test_client_script_shape():
    """The client performs put_count Puts then one Get, rotating servers;
    request ids are (op_count)*index at each step (register.rs:118-120)."""
    from stateright_tpu.actor import Id, Out

    client = reg.RegisterClient(put_count=2, server_count=2)
    out = Out()
    state = client.on_start(Id(3), out)
    assert state == reg.ClientState(awaiting=3, op_count=1)
    assert out.commands[0].dst == Id(1) and out.commands[0].msg == reg.Put(3, "B")

    from stateright_tpu.actor import StateRef

    ref = StateRef(state)
    out = Out()
    client.on_msg(Id(3), ref, Id(1), reg.PutOk(3), out)
    assert ref.get() == reg.ClientState(awaiting=6, op_count=2)
    assert out.commands[0].dst == Id(0) and out.commands[0].msg == reg.Put(6, "Y")

    ref2 = StateRef(ref.get())
    out = Out()
    client.on_msg(Id(3), ref2, Id(0), reg.PutOk(6), out)
    assert ref2.get() == reg.ClientState(awaiting=9, op_count=3)
    assert out.commands[0].dst == Id(1) and out.commands[0].msg == reg.Get(9)
