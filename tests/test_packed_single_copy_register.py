"""Packed single-copy register on the device engine: the first packed model
with a LinearizabilityTester riding in its state (SURVEY §7 M4a).

Oracles from the reference's own tests (single-copy-register.rs:110,136):
93 unique states at 2 clients / 1 server (full coverage, linearizable);
with 2 servers the stale-read counterexample must be confirmed — on the
device engine via the host-verified property machinery (conservative device
predicate + exact backtracking serializer on candidates).
"""

import numpy as np

from stateright_tpu.models.single_copy_register import (
    PackedSingleCopyRegister,
    single_copy_register_model,
)


def test_codec_round_trips_every_reachable_state():
    """pack/unpack must be a bijection over the reachable space — the
    foundation for fingerprint agreement between engines."""
    from stateright_tpu.checker.visitor import StateRecorder

    model = PackedSingleCopyRegister(2, 1)
    rec, get_states = StateRecorder.new_with_accessor()
    single_copy_register_model(2, 1).checker().visitor(rec).spawn_bfs().join()
    states = get_states()
    assert len(states) >= 93
    seen_words = set()
    for s in states:
        words = model.pack(s)
        rebuilt = model.unpack(words)
        assert rebuilt == s, f"codec round-trip mismatch for {s!r}"
        np.testing.assert_array_equal(model.pack(rebuilt), words)
        seen_words.add(tuple(int(w) for w in words))
    # distinct states -> distinct words (injective)
    assert len(seen_words) == len(set(states))


def test_xla_one_server_matches_oracle_full_coverage():
    model = PackedSingleCopyRegister(2, 1)
    xc = model.checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12
    ).join()
    bc = single_copy_register_model(2, 1).checker().spawn_bfs().join()
    assert bc.unique_state_count() == 93  # single-copy-register.rs:110
    assert xc.unique_state_count() == 93
    # Linearizable with one copy: no counterexample; the reachability
    # example exists and its witness path replays.
    xc.assert_properties()
    path = xc.discoveries()["value chosen"]
    final = path.last_state()
    assert any(
        getattr(env.msg, "value", None) is not None
        and type(env.msg).__name__ == "GetOk"
        for env in final.network.iter_deliverable()
    )


def test_xla_two_servers_finds_linearizability_counterexample():
    model = PackedSingleCopyRegister(2, 2)
    xc = model.checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12
    ).join()
    discoveries = xc.discoveries()
    assert "linearizable" in discoveries  # the always-property fails
    # The witness really is a non-linearizable history per the exact
    # backtracking serializer (not just the conservative device flag).
    final = discoveries["linearizable"].last_state()
    assert final.history.serialized_history() is None
    # Level-synchronous BFS finds a counterexample at the same depth as
    # the state-at-a-time oracle (both explore in BFS level order; the
    # reference's 20-state early-stop count is a mid-level artifact its
    # own BFS/DFS also disagree on).
    oracle = single_copy_register_model(2, 2).checker().spawn_bfs().join()
    assert "linearizable" in oracle.discoveries()
    assert len(discoveries["linearizable"]) == len(oracle.discoveries()["linearizable"])
