"""Exact device-side linearizability vs the backtracking serializer.

``PackedClientsMixin.device_linearizable_register`` statically enumerates
all interleavings of the bounded client histories (2 threads x (<=2
completed + optional in-flight) over the Register spec). It must agree
bit-for-bit with the exact host serializer
(``BacktrackingTester.serialized_history``, the port of
linearizability.rs:197-284) on every reachable state — including the
single-copy 2-server configuration whose whole point is a NON-linearizable
history (single-copy-register.rs:136).
"""

from collections import deque

import numpy as np
import pytest

from stateright_tpu.models.linearizable_register import PackedAbd
from stateright_tpu.models.paxos import PackedPaxos
from stateright_tpu.models.single_copy_register import (
    PackedSingleCopyRegister,
    PackedSingleCopyRegisterOrdered,
)


def _reachable(model, cap=20000):
    seen = set()
    q = deque()
    for s in model.init_states():
        seen.add(s)
        q.append(s)
    while q and len(seen) < cap:
        s = q.popleft()
        for _a, ns in model.next_steps(s):
            if ns not in seen:
                seen.add(ns)
                q.append(ns)
    assert not q, f"state cap {cap} too small for an exhaustive check"
    return sorted(seen, key=repr)


@pytest.mark.parametrize(
    "make",
    [
        lambda: PackedSingleCopyRegister(2, 1),
        lambda: PackedSingleCopyRegister(2, 2),  # the non-linearizable config
        pytest.param(lambda: PackedSingleCopyRegister(3, 1), marks=pytest.mark.slow),
        lambda: PackedAbd(2, 2),
        lambda: PackedSingleCopyRegisterOrdered(2),
        pytest.param(lambda: PackedPaxos(2, 3), marks=pytest.mark.slow),
    ],
    ids=["single-copy-1s", "single-copy-2s", "single-copy-3c", "abd", "ordered", "paxos"],
)
def test_device_predicate_matches_serializer_on_every_reachable_state(make):
    import jax
    import jax.numpy as jnp

    m = make()
    states = _reachable(m._inner)
    packed = np.stack([m.pack(s) for s in states])
    got = np.asarray(
        jax.jit(jax.vmap(m.device_linearizable_register))(jnp.asarray(packed))
    )
    verdicts = {}  # histories repeat across states; serialize each once
    mismatches = []
    n_false = 0
    for s, g in zip(states, got):
        h = s.history
        want = verdicts.get(h)
        if want is None:
            want = h.serialized_history() is not None
            verdicts[h] = want
        if not want:
            n_false += 1
        if bool(g) != want:
            mismatches.append((want, bool(g), h))
    assert not mismatches, f"{len(mismatches)} disagreements; first: {mismatches[0]}"
    if isinstance(m, PackedSingleCopyRegister) and m.S == 2:
        assert n_false > 0, "the 2-server config must reach non-linearizable states"
