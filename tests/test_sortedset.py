"""The sort-merge visited set (ops/sortedset.py): op-level differential
parity against the hash set, exact overflow semantics, and engine-level
parity of ``spawn_xla(dedup="sorted")`` vs ``dedup="hash"``.

The two structures implement the same contract (hashset.insert's
docstring): is_new in original batch order, lowest-batch-index winner
among in-batch duplicates, parent values stored for winners. The sorted
set is the TPU-native lowering (BASELINE.md cost model: sort ~1.3 G
keys/s on-chip vs 0.24 M ins/s for the scatter-election insert)."""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.ops import hashset, sortedset


def _rand_batch(rng, m, universe):
    hi = jnp.asarray(rng.integers(1, universe, m, dtype=np.uint32))
    lo = jnp.asarray(rng.integers(1, universe, m, dtype=np.uint32))
    vh = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    vl = jnp.asarray(rng.integers(0, 2**32, m, dtype=np.uint32))
    act = jnp.asarray(rng.integers(0, 2, m).astype(bool))
    return hi, lo, vh, vl, act


@pytest.mark.parametrize("universe", [40, 2**31])  # heavy duplicates / near-unique
def test_insert_lookup_differential_vs_hashset(universe):
    rng = np.random.default_rng(11)
    ss = sortedset.make(1 << 11, jnp)
    hs = hashset.make(1 << 13, jnp)
    for rnd in range(8):
        hi, lo, vh, vl, act = _rand_batch(rng, 257, universe)
        ss, s_new, s_ovf = sortedset.insert(ss, hi, lo, vh, vl, act)
        hs, h_new, h_ovf = hashset.insert(hs, hi, lo, vh, vl, act)
        assert np.array_equal(np.asarray(s_new), np.asarray(h_new)), rnd
        assert not bool(s_ovf) and not bool(np.any(np.asarray(h_ovf)))
        qh = jnp.asarray(rng.integers(1, min(universe + 20, 2**32 - 1), 128, dtype=np.uint32))
        ql = jnp.asarray(rng.integers(1, min(universe + 20, 2**32 - 1), 128, dtype=np.uint32))
        for a, b in zip(sortedset.lookup(ss, qh, ql), hashset.lookup(hs, qh, ql)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), rnd


def test_sorted_invariant_and_grow():
    rng = np.random.default_rng(3)
    ss = sortedset.make(1 << 9, jnp)
    hi, lo, vh, vl, act = _rand_batch(rng, 300, 2**20)
    ss, _, _ = sortedset.insert(ss, hi, lo, vh, vl, act)
    n = int(ss.n)
    kh = np.asarray(ss.key_hi)
    kl = np.asarray(ss.key_lo)
    keys = (kh[:n].astype(np.uint64) << 32) | kl[:n]
    assert np.all(keys[1:] > keys[:-1]), "occupied prefix must be strictly sorted"
    assert not np.any(kh[n:]) and not np.any(kl[n:]), "pads must be zeros"

    grown = sortedset.grow(ss, 1 << 11, jnp)
    assert grown.capacity == 1 << 11 and int(grown.n) == n
    found, gvh, gvl = sortedset.lookup(grown, jnp.asarray(kh[:n]), jnp.asarray(kl[:n]))
    assert bool(jnp.all(found))
    assert np.array_equal(np.asarray(gvh), np.asarray(ss.val_hi)[:n])
    assert np.array_equal(np.asarray(gvl), np.asarray(ss.val_lo)[:n])


def test_exact_overflow_flag():
    """Unlike the hash set's probe-budget overflow, the sorted set reports
    overflow exactly when merged uniques exceed capacity."""
    ss = sortedset.make(16, jnp)
    m = 24
    hi = jnp.arange(1, m + 1, dtype=jnp.uint32)
    lo = jnp.ones((m,), jnp.uint32)
    z = jnp.zeros((m,), jnp.uint32)
    act = jnp.ones((m,), bool)
    _, _, ovf = sortedset.insert(ss, hi, lo, z, z, act)
    assert bool(ovf)
    _, _, ovf16 = sortedset.insert(ss, hi[:16], lo[:16], z[:16], z[:16], act[:16])
    assert not bool(ovf16)  # exactly at capacity: fits


def test_winner_is_lowest_batch_index():
    ss = sortedset.make(16, jnp)
    hi = jnp.asarray([5, 5, 5], dtype=jnp.uint32)
    lo = jnp.asarray([9, 9, 9], dtype=jnp.uint32)
    vh = jnp.asarray([100, 200, 300], dtype=jnp.uint32)
    vl = jnp.zeros((3,), jnp.uint32)
    ss, is_new, _ = sortedset.insert(ss, hi, lo, vh, vl, jnp.ones((3,), bool))
    assert np.asarray(is_new).tolist() == [True, False, False]
    found, got_vh, _ = sortedset.lookup(ss, hi[:1], lo[:1])
    assert bool(found[0]) and int(got_vh[0]) == 100  # winner's value stored


def test_from_entries_roundtrip():
    rng = np.random.default_rng(5)
    n = 100
    kh = rng.permutation(np.arange(1, n + 1, dtype=np.uint32))
    kl = rng.integers(1, 2**32, n, dtype=np.uint32)
    vh = rng.integers(0, 2**32, n, dtype=np.uint32)
    vl = rng.integers(0, 2**32, n, dtype=np.uint32)
    ss = sortedset.from_entries(kh, kl, vh, vl, 128, jnp)
    found, got_vh, got_vl = sortedset.lookup(ss, jnp.asarray(kh), jnp.asarray(kl))
    assert bool(jnp.all(found))
    assert np.array_equal(np.asarray(got_vh), vh)
    assert np.array_equal(np.asarray(got_vl), vl)


# --- engine-level parity ----------------------------------------------------


def _counts(c):
    return c.state_count(), c.unique_state_count(), c.max_depth()


def test_engine_parity_two_phase_commit():
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    a = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="hash").join()
    b = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted").join()
    assert _counts(a) == _counts(b) == (1146, 288, 11)
    assert set(a.discoveries()) == set(b.discoveries())


def test_gather_compact_cap_exceeds_mask_length():
    """Regression: the gather-compact lowering must handle compaction caps
    larger than the source array (cand_cap = next_pow2 rounding past the
    grid; frontier caps above cand caps for small action counts)."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    c = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(dedup="sorted", table_capacity=1 << 8, frontier_capacity=1 << 5)
        .join()
    )
    assert _counts(c) == (1146, 288, 11)


def test_engine_parity_under_forced_growth():
    """Tiny capacities force the overflow-retry + growth path of both
    structures (sorted growth = plane copy, hash growth = rehash)."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    kw = dict(table_capacity=1 << 8, frontier_capacity=1 << 6)
    a = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="hash", **kw).join()
    b = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted", **kw).join()
    assert _counts(a) == _counts(b) == (1146, 288, 11)


def test_engine_parity_discovery_model():
    """A model with a real counterexample: discovery names and witness
    paths must agree across dedup structures."""
    from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister

    a = PackedSingleCopyRegister(2, 2).checker().spawn_xla(dedup="hash").join()
    b = PackedSingleCopyRegister(2, 2).checker().spawn_xla(dedup="sorted").join()
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db) and da
    for name in da:
        assert len(da[name]) == len(db[name])


def test_engine_parity_symmetry():
    from stateright_tpu.models.increment import PackedIncrement

    a = PackedIncrement(3).checker().symmetry().spawn_xla(dedup="hash").join()
    b = PackedIncrement(3).checker().symmetry().spawn_xla(dedup="sorted").join()
    assert _counts(a) == _counts(b)


def test_checkpoint_crosses_dedup_structures(tmp_path):
    """A checkpoint written by a hash-table run restores into a sorted-set
    run (and vice versa): the snapshot format is structure-independent."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    path = str(tmp_path / "ck.npz")
    a = PackedTwoPhaseSys(3).checker().spawn_xla(
        dedup="hash", levels_per_dispatch=1
    )
    for _ in range(4):
        a._run_block()
    a.save_checkpoint(path)
    resumed = PackedTwoPhaseSys(3).checker().spawn_xla(
        dedup="sorted", checkpoint=path
    ).join()
    full = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted").join()
    assert _counts(resumed) == _counts(full) == (1146, 288, 11)


def test_fingerprint_planes_matches_words():
    """The plane-major fingerprint (the engine's structure-of-arrays path)
    is bit-identical to the row fingerprint, under numpy and under jit."""
    import jax

    from stateright_tpu.ops import fphash

    rng = np.random.default_rng(7)
    for W in (1, 2, 5, 12):
        rows = rng.integers(0, 2**32, (257, W), dtype=np.uint32)
        wh, wl = fphash.fingerprint_words(rows, np)
        ph, pl = fphash.fingerprint_planes(rows.T.copy(), np)
        assert np.array_equal(wh, ph) and np.array_equal(wl, pl)
        jh, jl = jax.jit(lambda p: fphash.fingerprint_planes(p, jnp))(
            jnp.asarray(rows.T.copy())
        )
        assert np.array_equal(wh, np.asarray(jh))
        assert np.array_equal(wl, np.asarray(jl))


def test_insert_values_via_sort_matches_gather(monkeypatch):
    """The payload-through-sort insert lowering is bit-identical to the
    gather lowering (STPU_SORTEDSET_VALUES; which is faster is a hardware
    question, correctness is not)."""
    rng = np.random.default_rng(23)
    ss_a = sortedset.make(1 << 11, jnp)
    ss_b = sortedset.make(1 << 11, jnp)
    for rnd in range(6):
        hi, lo, vh, vl, act = _rand_batch(rng, 257, 300)
        monkeypatch.setattr(sortedset, "VALUES_VIA", "gather")
        ss_a, new_a, ovf_a = sortedset.insert(ss_a, hi, lo, vh, vl, act)
        monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
        ss_b, new_b, ovf_b = sortedset.insert(ss_b, hi, lo, vh, vl, act)
        for a, b in zip(ss_a, ss_b):
            assert np.array_equal(np.asarray(a), np.asarray(b)), rnd
        assert np.array_equal(np.asarray(new_a), np.asarray(new_b)), rnd
        assert bool(ovf_a) == bool(ovf_b)


def test_engine_compaction_lowerings_match():
    """All three compaction lowerings — "gather", "sort" (payload through
    the sorts, with the round-5 derived-parent grid sort), and "bsearch"
    (cumsum + rank binary-search) — reproduce identical counts and
    witness paths."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    kw = dict(frontier_capacity=1 << 6, table_capacity=1 << 9, dedup="sorted")
    a = PackedTwoPhaseSys(3).checker().spawn_xla(compaction="gather", **kw).join()
    da = a.discoveries()
    assert da
    for mode in ("sort", "bsearch"):
        b = PackedTwoPhaseSys(3).checker().spawn_xla(compaction=mode, **kw).join()
        assert _counts(a) == _counts(b), mode
        db = b.discoveries()
        assert set(da) == set(db), mode
        for name in da:
            assert da[name].into_states() == db[name].into_states(), mode


def test_insert_packed_keys_match_pair(monkeypatch):
    """STPU_SORTEDSET_KEYS=packed (u64-folded key/value lanes, 3 sort
    operands) is bit-identical to the u32-pair lowering. Needs x64 for
    the u64 lanes; restored after."""
    import jax

    monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
    rng = np.random.default_rng(41)
    ss_a = sortedset.make(1 << 11, jnp)
    ss_b = sortedset.make(1 << 11, jnp)
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        for rnd in range(6):
            hi, lo, vh, vl, act = _rand_batch(rng, 257, 300)
            monkeypatch.setattr(sortedset, "KEYS_VIA", "pair")
            ss_a, new_a, ovf_a = sortedset.insert(ss_a, hi, lo, vh, vl, act)
            monkeypatch.setattr(sortedset, "KEYS_VIA", "packed")
            ss_b, new_b, ovf_b = sortedset.insert(ss_b, hi, lo, vh, vl, act)
            for a, b in zip(ss_a, ss_b):
                assert np.array_equal(np.asarray(a), np.asarray(b)), rnd
            assert np.array_equal(np.asarray(new_a), np.asarray(new_b)), rnd
            assert bool(ovf_a) == bool(ovf_b)
    finally:
        jax.config.update("jax_enable_x64", prev_x64)


def test_packed_keys_guardrails(monkeypatch):
    """packed without x64 or with the gather values family must raise,
    not silently truncate keys to 32 bits."""
    import jax

    import pytest as _pytest

    monkeypatch.setattr(sortedset, "KEYS_VIA", "packed")
    monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
    rng = np.random.default_rng(43)
    ss = sortedset.make(1 << 8, jnp)
    hi, lo, vh, vl, act = _rand_batch(rng, 65, 300)
    with _pytest.raises(ValueError, match="x64"):
        sortedset.insert(ss, hi, lo, vh, vl, act)
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        monkeypatch.setattr(sortedset, "VALUES_VIA", "gather")
        with _pytest.raises(ValueError, match="sort-values"):
            sortedset.insert(ss, hi, lo, vh, vl, act)
    finally:
        jax.config.update("jax_enable_x64", prev_x64)
