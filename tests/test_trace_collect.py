"""The distributed-trace merge (stateright_tpu/obs/collect.py) and the
tier-0 trace drill (docs/observability.md "Distributed tracing").

Unit pins: run-dir trace discovery order, session parsing under torn
heads and garbage lines, and the flow-arrow contract (one arc per
trace_id over the anchor spans, Chrome "s"/"t"/"f" phases with the
arrowhead bound to the enclosing slice).

``test_smoke_trace_merge`` is the <30s drill that rides in
``tools/smoke.sh``: one packed-model run traced with the dispatch-phase
profiler on, one 2-job service round with tracing on, merged via
``obs.collect`` into a single Chrome trace — validated for schema,
per-process time alignment, resolvable flow arrows (every admitted
job's spans share one trace_id from submit through dispatch), and the
phases-partition-their-dispatch invariant the roofline report rests on.
"""

import json
import os

from stateright_tpu.obs import collect
from stateright_tpu.service import CheckerService, ServiceConfig

#: Pinned full-coverage (generated, unique) counts for 2pc:3.
PINNED_2PC3 = (1_146, 288)

#: The four chrome event kinds the merger may emit, plus flow phases.
SLICE_KEYS = {"name", "cat", "ph", "ts", "dur", "pid", "tid", "args"}


def _write_trace(path, records, unix_ts=1000.0, pid=7):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps({
            "ts": 0.0, "dur": 0.0, "name": "trace_start", "span_id": "x.0",
            "attrs": {"pid": pid, "unix_ts": unix_ts},
        }) + "\n")
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def _span(name, ts, dur=0.1, sid="x.1", **extra):
    rec = {"ts": ts, "dur": dur, "name": name, "span_id": sid, "attrs": {}}
    rec.update(extra)
    return rec


# --- unit pins --------------------------------------------------------------


def test_trace_files_discovery_order(tmp_path):
    root = str(tmp_path / "run")
    for rel in ("device-1/job-0001", "device-0", "."):
        _write_trace(os.path.join(root, rel, "trace.jsonl"), [])
    rels = [os.path.relpath(p, root) for p in collect.trace_files(root)]
    assert rels == [
        "device-0/trace.jsonl", "device-1/job-0001/trace.jsonl",
        "trace.jsonl",
    ]
    assert collect.trace_files(str(tmp_path / "nope")) == []


def test_sessions_tolerate_torn_head_and_garbage(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps(_span("dispatch", 0.5)) + "\n")  # torn head
        fh.write("{not json\n")
        fh.write(json.dumps({"v": 1}) + "\n")  # dict, but not a span
    _write_trace(path, [_span("dispatch", 1.0)])  # appended real session
    sessions = collect._read_sessions(path)
    assert len(sessions) == 2
    assert sessions[0]["unix_ts"] is None  # synthetic, for the torn head
    assert len(sessions[0]["records"]) == 1
    assert sessions[1]["unix_ts"] == 1000.0


def test_merge_aligns_sessions_and_draws_flows(tmp_path):
    """Two processes, staggered wall clocks, one shared trace_id: the
    merged timeline rebases onto the earliest session and draws one
    s→t→f arc over the anchors in causal-time order."""
    root = str(tmp_path / "run")
    tid = "ab" * 8
    _write_trace(
        os.path.join(root, "trace.jsonl"),
        [_span("submit", 0.0, sid="a.1", trace_id=tid),
         _span("route", 0.001, sid="a.2", trace_id=tid)],
        unix_ts=1000.0,
    )
    _write_trace(
        os.path.join(root, "svc", "job-0001", "trace.jsonl"),
        [_span("job", 0.0, dur=1.0, sid="b.1", trace_id=tid,
               parent_id="a.1")],
        unix_ts=1002.0,  # this process started 2s later
    )
    doc = collect.collect(root)
    assert doc["otherData"]["traces"] == [tid]
    assert doc["otherData"]["trace_files"] == [
        "svc/job-0001/trace.jsonl", "trace.jsonl",
    ]
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert {(m["name"], m["pid"]) for m in meta} >= {
        ("process_name", 1), ("process_name", 2),
    }
    # The later process's job span lands 2s (2e6 us) after the epoch.
    job = next(e for e in evs if e["ph"] == "X" and e["name"] == "job")
    assert job["ts"] == 2e6
    assert job["args"]["trace_id"] == tid
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert all(f["id"] == tid for f in flows)
    assert flows[-1]["bp"] == "e"
    assert [f["ts"] for f in flows] == sorted(f["ts"] for f in flows)
    # submit (ts 0) starts the arc; the job anchor ends it on pid 1
    # (the job-dir file sorts first and so owns pid 1).
    assert flows[0]["pid"] == 2 and flows[-1]["pid"] == 1
    # A single-anchor trace draws no arrows (nothing to connect).
    _write_trace(os.path.join(root, "trace.jsonl"),
                 [_span("submit", 5.0, sid="a.9", trace_id="cd" * 8)],
                 unix_ts=1010.0)
    doc2 = collect.collect(root)
    assert "cd" * 8 in doc2["otherData"]["traces"]
    assert all(e["id"] == tid for e in doc2["traceEvents"]
               if e["ph"] in ("s", "t", "f"))


def test_write_dumps_valid_json(tmp_path):
    root = str(tmp_path / "run")
    _write_trace(os.path.join(root, "trace.jsonl"), [_span("submit", 0.0)])
    out = str(tmp_path / "merged.json")
    n = collect.write(root, out)
    with open(out) as fh:
        doc = json.load(fh)
    assert len(doc["traceEvents"]) == n > 0


def test_explorer_merged_trace_route(tmp_path):
    """``GET /.trace.json``: 404 without a service or without any trace
    in the run dir; 200 = the mtime-cached merged export's raw bytes."""
    from stateright_tpu.checker.explorer import ExplorerApp
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    ck = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13,
    )
    assert ExplorerApp(ck).merged_trace()[0] == 404  # no service

    base = dict(
        platform="cpu", probe_auto=False, admission_lint=False,
        max_inflight=0,
    )
    dark = CheckerService(ServiceConfig(
        run_dir=str(tmp_path / "dark"), **base))
    try:
        # Tracing off: nothing to merge.
        assert ExplorerApp(ck, service=dark).merged_trace()[0] == 404
    finally:
        dark.close()

    svc = CheckerService(ServiceConfig(
        run_dir=str(tmp_path / "svc"), trace=True, **base))
    try:
        app = ExplorerApp(ck, service=svc)
        code, body = app.merged_trace()
        assert code == 200
        doc = json.loads(body)
        assert any(e.get("ph") == "M" for e in doc["traceEvents"])
        assert doc["otherData"]["trace_files"] == ["trace.jsonl"]
        # Second hit serves the cached export (same bytes, no rewrite).
        merged = os.path.join(str(tmp_path / "svc"), "trace.merged.json")
        mtime = os.stat(merged).st_mtime_ns
        assert app.merged_trace()[0] == 200
        assert os.stat(merged).st_mtime_ns == mtime
    finally:
        svc.close()


# --- the tier-0 drill -------------------------------------------------------


def test_smoke_trace_merge(tmp_path):
    """The <30s smoke drill (tools/smoke.sh): a phases-profiled packed
    model plus a traced 2-job service round merge into one valid Chrome
    trace with resolvable flow arrows; phases partition their dispatch."""
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    run_dir = str(tmp_path / "run")
    # Tier 1 of the merge: an in-process engine run, phase profiler on.
    model_trace = os.path.join(run_dir, "model", "trace.jsonl")
    ck = PackedTwoPhaseSys(3).checker().spawn_xla(
        trace=model_trace, phases=True,
        frontier_capacity=1 << 10, table_capacity=1 << 13,
    ).join()
    assert ck.unique_state_count() == PINNED_2PC3[1]
    assert len(ck.phase_log) == len(ck.dispatch_log) > 0

    # Tier 2: a real 2-job service round, service-level tracing on.
    svc = CheckerService(ServiceConfig(
        run_dir=run_dir, platform="cpu", trace=True,
        default_max_seconds=420.0, stall_s=8.0, startup_grace_s=240.0,
        poll_s=0.2, backoff_s=0.1, probe_auto=False, admission_lint=False,
        max_inflight=2,
    ))
    try:
        jobs = [svc.submit("2pc:3"), svc.submit("2pc:3")]
        assert svc.wait_all(timeout=240), svc.metrics()
        for job in jobs:
            assert job.status == "done", job.error
            assert (job.result["generated"], job.result["unique"]) \
                == PINNED_2PC3
        trace_ids = {j.trace_id for j in jobs}
        assert len(trace_ids) == 2 and all(trace_ids)
        merged = svc.merged_trace_chrome()
    finally:
        svc.close()

    assert merged == os.path.join(run_dir, "trace.merged.json")
    with open(merged) as fh:
        doc = json.load(fh)
    evs = doc["traceEvents"]
    assert doc["otherData"]["traces"] == sorted(trace_ids)
    assert len(doc["otherData"]["trace_files"]) == 4  # model + svc + 2 jobs

    # Chrome schema: only the event kinds the merger emits, X slices
    # complete, and the slice/counter timeline monotonic (meta first,
    # flows last — the order Perfetto ingests).
    phases_seen = set()
    last_ts = None
    for ev in evs:
        assert ev["ph"] in ("X", "C", "M", "s", "t", "f"), ev
        phases_seen.add(ev["ph"])
        if ev["ph"] == "X":
            assert SLICE_KEYS <= set(ev)
            assert ev["dur"] >= 0
        if ev["ph"] in ("X", "C"):
            if last_ts is not None:
                assert ev["ts"] >= last_ts
            last_ts = ev["ts"]
    assert {"X", "M", "s", "f"} <= phases_seen

    # Flow arrows resolve: one arc per admitted job, s first / f last,
    # every arrow's id a known trace_id, timestamps non-decreasing.
    arcs = {}
    for ev in evs:
        if ev["ph"] in ("s", "t", "f"):
            assert ev["id"] in trace_ids
            arcs.setdefault(ev["id"], []).append(ev)
    assert set(arcs) == trace_ids
    for arc in arcs.values():
        assert arc[0]["ph"] == "s" and arc[-1]["ph"] == "f"
        assert arc[-1]["bp"] == "e"
        assert [e["ts"] for e in arc] == sorted(e["ts"] for e in arc)

    # Every admitted job's spans share ONE trace id from submit through
    # engine dispatch, with parent links resolving across files.
    slices = [e for e in evs if e["ph"] == "X"]
    by_trace = {}
    sids = set()
    for e in slices:
        sids.add(e["args"].get("span_id"))
        t = e["args"].get("trace_id")
        if t:
            by_trace.setdefault(t, set()).add(e["name"])
    for t in trace_ids:
        assert {"submit", "attempt", "job", "dispatch"} <= by_trace[t]
    for e in slices:
        parent = e["args"].get("parent_id")
        if parent is not None:
            assert parent in sids, e

    # The phase profiler's invariant: the four sub-spans partition their
    # parent dispatch span (bookkeeping slack only).
    disp = {e["args"]["span_id"]: e for e in slices
            if e["name"] == "dispatch"}
    phase = [e for e in slices if e["name"].startswith("phase:")]
    assert phase, "the model tier ran with phases on"
    by_parent = {}
    for e in phase:
        by_parent.setdefault(e["args"]["parent_id"], 0.0)
        by_parent[e["args"]["parent_id"]] += e["dur"]
    for sid, total in by_parent.items():
        slack = disp[sid]["dur"] - total
        assert 0.0 <= slack < 0.05 * 1e6, (sid, slack)
