"""In-program candidate-width ladder (``spawn_xla(cand_ladder=)`` /
``STPU_CAND_LADDER``): snug per-level candidate sorts inside the fused
superstep via ``lax.switch`` sub-width branches.

The load-bearing claims pinned here:

- counts are exact BY CONSTRUCTION under the ladder: a committed snug
  level is bit-identical to the full-width level (same candidate order,
  same winner election), and an UNDERESTIMATE of the candidate width
  falls through to the full-width branch in-program — never dropping a
  candidate and never adding a host dispatch (the growth-spike model
  below is the analogue of the committed==0 livelock guard in
  test_ladder.py);
- the ladder is per-checker state: two checkers over one model cannot
  cross-contaminate candidate sizing (the old model-level cap dict did),
  while a fresh checker still inherits learned growths via model hints;
- the per-level ``lane_words`` telemetry (the round-5 cost law's x-axis)
  drops at narrow levels with the ladder on — the engine-measured form
  of the BASELINE.md attack-#2 evidence;
- the K=3 fused program lowers for the TPU target from this CPU-only box
  (registry #6 pre-flight — a ``lax.switch`` branch carries the
  [table ‖ cand] merge sort, the registry-#4-adjacent shape, so the
  runtime verdict still needs the tunnel window; see tools/cand_ab.py).
"""

import numpy as np
import pytest

from stateright_tpu.core import Model
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.xla import XlaChecker

KW = dict(frontier_capacity=1 << 12, table_capacity=1 << 13)


def _join(checker):
    while not checker.is_done():
        checker._run_block()
    return checker


def _summary(c):
    return (
        c.state_count(),
        c.unique_state_count(),
        c.max_depth(),
        {n: p.into_actions() for n, p in c.discoveries().items()},
    )


# --- the growth-spike fall-through -------------------------------------


class _ChainSpike(Model):
    """Synthetic PackedModel shaped to UNDERESTIMATE: 600 parallel chains
    generate 600 states/level for two levels (so the device-side growth
    extrapolation predicts ~600 * growth 1 * margin), then every chain
    state fans out 16-wide at once — 9,600 candidates against the snug
    rung's 4,096-lane buffer. The spike successors collide down to 800
    uniques, so the post-spike frontier still fits the bucket and the
    ONLY overflow in the whole run is the snug branch's in-program one.
    """

    M = 100_000  # wave stride in the packed word

    def __init__(self):
        self.state_words = 1
        self.max_actions = 16

    # Object model (witness reconstruction parity is not exercised here;
    # the packed kernel is the system under test).
    def init_states(self):
        return list(range(600))

    def actions(self, state, actions):
        wave = state // self.M
        if wave < 2:
            actions.append(0)
        elif wave == 2:
            actions.extend(range(16))

    def next_state(self, state, action):
        wave, i = divmod(state, self.M)
        if wave < 2:
            return state + self.M
        return 3 * self.M + action * 50 + i % 50

    def pack(self, state):
        return np.asarray([state], np.uint32)

    def unpack(self, words):
        return int(words[0])

    def packed_init(self):
        return np.arange(600, dtype=np.uint32)[:, None]

    def packed_step(self, words):
        import jax.numpy as jnp

        M = jnp.uint32(self.M)
        wave = words[0] // M
        i = words[0] % M
        a = jnp.arange(16, dtype=jnp.uint32)
        chain = words[0] + M  # next wave, same chain
        leaves = jnp.uint32(3) * M + a * jnp.uint32(50) + i % jnp.uint32(50)
        nxt = jnp.where(wave < 2, chain, leaves)[:, None]
        valid = jnp.where(wave < 2, a == 0, wave == jnp.uint32(2))
        return nxt, valid

    def packed_properties(self, words):
        import jax.numpy as jnp

        return jnp.zeros((0,), jnp.bool_)


# Exact totals: 600 init + (600 + 600 + 9,600) generated; uniques
# 600 * 3 waves + 800 colliding leaves; leaves counted at depth 4.
SPIKE_PINNED = dict(generated=11_400, unique=2_600, depth=4)


def _run_spike(cand_ladder):
    c = _ChainSpike().checker().spawn_xla(
        dedup="sorted",
        cand_ladder=cand_ladder,
        frontier_capacity=1 << 13,
        table_capacity=1 << 13,
    )
    return _join(c)


def test_growth_spike_falls_through_full_width():
    off = _run_spike(1)
    on = _run_spike(3)
    for c in (off, on):
        assert c.state_count() == SPIKE_PINNED["generated"]
        assert c.unique_state_count() == SPIKE_PINNED["unique"]
        assert c.max_depth() == SPIKE_PINNED["depth"]
    # The spike level picked a snug rung off the flat-growth estimate,
    # overflowed it, and fell through IN-PROGRAM: at least one retry,
    # zero added host dispatches, and the committed spike level ran (and
    # is recorded) at the full candidate width.
    assert on.cand_retries >= 1, on.level_log
    assert off.cand_retries == 0
    assert len(on.dispatch_log) == len(off.dispatch_log), (
        on.dispatch_log,
        off.dispatch_log,
    )
    spike_rows = [r for r in on.level_log if r["generated"] == 9_600]
    assert spike_rows and all(
        r["cand_cap"] == off.level_log[0]["cand_cap"] for r in spike_rows
    ), on.level_log


# --- exact counts across the packed models -----------------------------


def _models_small():
    from stateright_tpu.models.increment import PackedIncrement
    from stateright_tpu.models.increment_lock import PackedIncrementLock
    from stateright_tpu.models.puzzle import PackedPuzzle
    from stateright_tpu.models.single_copy_register import (
        PackedSingleCopyRegister,
    )

    return [
        ("2pc rm=3", lambda: PackedTwoPhaseSys(3)),
        ("increment 2t", lambda: PackedIncrement(2)),
        ("increment_lock 3t", lambda: PackedIncrementLock(3)),
        ("single-copy 2c/1s", lambda: PackedSingleCopyRegister(2, 1)),
        ("puzzle 2x2", lambda: PackedPuzzle([0, 2, 1, 3], side=2)),
    ]


def _models_slow():
    from stateright_tpu.models.linearizable_register import PackedAbd
    from stateright_tpu.models.paxos import PackedPaxos

    return [
        ("ABD 2c/2s", lambda: PackedAbd(2, 2)),
        ("paxos 2c/3s", lambda: PackedPaxos(2, 3)),
    ]


def _ladder_ab(name, build, monkeypatch, **kw):
    # Rung floor 16 pulls the ladder into the 64-row floor buckets these
    # small spaces run at, so every model genuinely executes through
    # lax.switch branches instead of the trivial K=1 program.
    monkeypatch.setattr(XlaChecker, "CAND_RUNG_FLOOR", 16)
    monkeypatch.setenv("STPU_CAND_LADDER", "3")
    on = _join(build().checker().spawn_xla(dedup="sorted", **kw))
    assert on._cand_ladder_k == 3, name
    monkeypatch.setenv("STPU_CAND_LADDER", "1")
    off = _join(build().checker().spawn_xla(dedup="sorted", **kw))
    assert _summary(on) == _summary(off), name
    return on


def test_ladder_counts_exact_small_models(monkeypatch):
    for name, build in _models_small():
        _ladder_ab(name, build, monkeypatch, **KW)


def test_ladder_counts_exact_2pc_pinned(monkeypatch):
    on = _ladder_ab("2pc rm=4", lambda: PackedTwoPhaseSys(4), monkeypatch, **KW)
    assert (on.state_count(), on.unique_state_count()) == (8_258, 1_568)


@pytest.mark.slow
def test_ladder_counts_exact_slow_models(monkeypatch):
    kw = dict(frontier_capacity=1 << 12, table_capacity=1 << 16)
    for name, build in _models_slow():
        _ladder_ab(name, build, monkeypatch, **kw)


def test_ladder_counts_exact_delta(monkeypatch):
    monkeypatch.setenv("STPU_CAND_LADDER", "3")
    c = _join(
        PackedTwoPhaseSys(4).checker().spawn_xla(dedup="delta", **KW)
    )
    assert (c.state_count(), c.unique_state_count()) == (8_258, 1_568)


# --- telemetry: the cost-law lane-words drop ---------------------------


def test_lane_words_drop_at_narrow_levels():
    """The engine-measured attack-#2 evidence at test scale: with the
    ladder on, the median level of 2pc rm=4 sorts at least 2x fewer lane
    words than the ladder-off engine, at identical counts and identical
    dispatch count (the acceptance-scale rm=6/7 A/B lives in
    tools/cand_ab.py)."""
    model = PackedTwoPhaseSys(4)
    off = _join(model.checker().spawn_xla(dedup="sorted", cand_ladder=1, **KW))
    on = _join(model.checker().spawn_xla(dedup="sorted", cand_ladder=3, **KW))
    assert _summary(on) == _summary(off)
    assert len(on.dispatch_log) == len(off.dispatch_log)
    lw_off = sorted(r["lane_words"] for r in off.level_log)
    lw_on = sorted(r["lane_words"] for r in on.level_log)
    med = len(lw_off) // 2
    assert lw_on[med] * 2 <= lw_off[med], (lw_on, lw_off)
    # Every row carries the chosen sub-widths, and no committed level
    # ever ran wider than the peak ladder-off shapes.
    peak_cand = max(r["cand_cap"] for r in off.level_log)
    for r in on.level_log:
        assert r["cand_cap"] <= peak_cand
        assert r["bucket"] <= max(cap for cap, _ in on.dispatch_log)


# --- per-checker candidate sizing (the aliasing fix) -------------------


def test_two_checkers_do_not_share_cand_caps():
    model = PackedTwoPhaseSys(3)
    model.__dict__.pop("_xla_cand_cap_hints", None)
    c1 = model.checker().spawn_xla(**KW)
    c2 = model.checker().spawn_xla(**KW)
    base = c2._cand_cap_for(1024)
    assert c1._cand_cap_for(1024) == base
    c1._grow_cand_cap(1024)
    assert c1._cand_cap_for(1024) == base * 4
    # The sibling's sizing is untouched mid-run (pre-fix the model-level
    # dict leaked the growth straight into c2's next dispatch shapes).
    assert c2._cand_cap_for(1024) == base
    # A FRESH checker inherits the learned growth via the model hint, so
    # the bench's measured pass still replays the warm pass's shapes.
    c3 = model.checker().spawn_xla(**KW)
    assert c3._cand_cap_for(1024) == base * 4


def test_grow_does_not_evict_live_sibling_programs():
    """The eviction half of the aliasing fix: the superstep cache stays
    model-shared (the bench's warm->measured handoff depends on it), so
    a growth in one checker must not delete compiled programs a LIVE
    sibling still sizes at the old cap — but once no live checker can
    reach a key, eviction resumes (stale executables are memory)."""
    import gc

    model = PackedTwoPhaseSys(3)
    model.__dict__.pop("_xla_cand_cap_hints", None)
    model.__dict__.pop("_xla_superstep_cache", None)
    c1 = model.checker().spawn_xla(**KW)
    c2 = model.checker().spawn_xla(**KW)
    base = c2._cand_cap_for(1024)
    key = (
        1024, base, c2._sym_tag, c2._max_probes, c2._dedup, c2._compaction,
    )
    c2._superstep_cache[key] = marker = object()
    c1._grow_cand_cap(1024)
    assert c1._cand_cap_for(1024) == base * 4
    # c2 still sizes bucket 1024 at base, so its program survived.
    assert c2._superstep_cache.get(key) is marker
    del c1, c2
    gc.collect()
    # With no live sibling at the old cap, the next growth cycle evicts:
    # re-grow from a fresh checker whose caps start at the hinted base*4.
    c3 = model.checker().spawn_xla(**KW)
    stale = (
        1024, base * 4, c3._sym_tag, c3._max_probes, c3._dedup,
        c3._compaction,
    )
    c3._superstep_cache[stale] = object()
    c3._grow_cand_cap(1024)
    assert stale not in c3._superstep_cache
    # ...while the base-cap key is simply not this growth's target.
    assert c3._superstep_cache.get(key) is marker


# --- knob plumbing and rung shapes -------------------------------------


def test_cand_ladder_validation():
    with pytest.raises(ValueError, match="cand_ladder"):
        PackedTwoPhaseSys(3).checker().spawn_xla(cand_ladder="sideways", **KW)
    with pytest.raises(ValueError, match="cand_ladder"):
        PackedTwoPhaseSys(3).checker().spawn_xla(
            cand_ladder=5, dedup="sorted", **KW
        )
    # Explicit ladder on the rows/hash engine is a config error (the
    # compaction-knob precedent: never silently measure the wrong engine).
    with pytest.raises(ValueError, match="plane-major"):
        PackedTwoPhaseSys(3).checker().spawn_xla(
            cand_ladder=3, dedup="hash", **KW
        )


def test_env_knob_and_hash_warning(monkeypatch):
    monkeypatch.setenv("STPU_CAND_LADDER", "2")
    c = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted", **KW)
    assert c._cand_ladder_k == 2
    assert len(c._cand_rungs(1 << 14)) == 2
    # Env-driven A/B against the hash engine warns (arg raises above).
    monkeypatch.setenv("STPU_CAND_LADDER", "3")
    with pytest.warns(RuntimeWarning, match="STPU_CAND_LADDER"):
        c = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="hash", **KW)
    assert c._cand_ladder_k == 1


def test_rung_shapes():
    c = PackedTwoPhaseSys(3).checker().spawn_xla(dedup="sorted", **KW)
    assert c._cand_ladder_k == 3
    # Floor buckets have nothing to snug.
    assert c._cand_rungs(64) == [(64, c._cand_cap_for(64))]
    # The rung floor truncates K before the pow-4 ladder does.
    assert [F for F, _ in c._cand_rungs(1024)] == [256, 1024]
    rungs = c._cand_rungs(1 << 14)
    assert [F for F, _ in rungs] == [1 << 10, 1 << 12, 1 << 14]
    # Each rung is that bucket's own (rows, cand-cap) shape.
    assert all(C == c._cand_cap_for(F) for F, C in rungs)


def test_rung_caps_stay_monotone_after_subbucket_growth(monkeypatch):
    """A cc_ovf growth at a small bucket (paid on that bucket's own host
    dispatches) must not make a 'snug' rung carry a WIDER candidate
    buffer than the branch above it — the rungs clamp to a monotone
    envelope, so the ladder can only ever sort narrower, matching the
    invariant test_lane_words_drop_at_narrow_levels pins at runtime."""
    monkeypatch.setenv("STPU_CAND_FRAC", "16")  # accelerator-style start
    model = PackedTwoPhaseSys(3)
    model.__dict__.pop("_xla_cand_cap_hints", None)
    c = model.checker().spawn_xla(dedup="sorted", **KW)
    full_grid = c._next_pow2(1024 * c._A)
    while c._cand_cap_for(1024) < full_grid:
        c._grow_cand_cap(1024)
    assert c._cand_cap_for(1024) > c._cand_cap_for(4096)  # the hazard
    caps = [C for _, C in c._cand_rungs(4096)]
    assert caps == sorted(caps)
    assert caps[-1] == c._cand_cap_for(4096)


# --- registry #6 pre-flight: the chip program lowers for TPU -----------


def test_fused_ladder_lowers_for_tpu(monkeypatch):
    """Trace the accelerator-shaped K=3 fused program (sort-family
    values + sort compaction — the TPU defaults) and lower it for the
    TPU target from this CPU-only process. Catches missing lowerings for
    the new ``lax.switch``-around-big-sort shape without a tunnel
    window; the registry-#4 class of RUNTIME fault can only be ruled out
    on chip (tools/cand_ab.py, staged in the r5e watcher)."""
    import jax
    import jax.numpy as jnp

    from stateright_tpu.ops import sortedset

    monkeypatch.setattr(sortedset, "VALUES_VIA", "sort")
    model = PackedTwoPhaseSys(3)
    c = model.checker().spawn_xla(
        dedup="sorted", compaction="sort", cand_ladder=3, **KW
    )
    rungs = tuple(c._cand_rungs(4096))
    assert len(rungs) == 3
    fn = jax.jit(c._build_fused(4096, rungs))
    args = (
        jnp.zeros((4096, model.state_words), jnp.uint32),
        jnp.zeros((4096,), jnp.uint32),
        jnp.int32(1),
        c._table,
        c._disc_found,
        c._disc_fp,
        jnp.int32(32),
        jnp.int32(2**31 - 1),
        jnp.zeros((len(c._prop_names),), jnp.bool_),
        jnp.int32(0),
        jnp.int32(0),
        jnp.int32(0),
    )
    lowered = fn.trace(*args).lower(lowering_platforms=("tpu",))
    assert lowered is not None
