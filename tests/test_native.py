"""Native host-kit tests: the C++ library must agree bit-for-bit with the
Python mirrors, and the engines must work with either backend."""

import shutil

import numpy as np
import pytest

from stateright_tpu import native
from stateright_tpu.ops import fphash


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_builds_when_toolchain_present():
    # The build image ships g++; if this fails the lazy build broke. On
    # toolchain-less machines the package works via the Python fallbacks.
    assert native.available()


def test_fingerprint_parity_with_python():
    rng = np.random.default_rng(11)
    for w in (1, 2, 3, 8):
        words = rng.integers(0, 2**32, size=(257, w), dtype=np.uint32)
        nh, nl = native.fingerprint_words(words)
        ph, pl = fphash.fingerprint_words(words, np)
        np.testing.assert_array_equal(nh, ph)
        np.testing.assert_array_equal(nl, pl)


def test_parentmap_lookup_and_chain():
    # Build a synthetic 3-link chain: c -> b -> a -> 0.
    def lanes(fp64):
        return np.uint32(fp64 >> 32), np.uint32(fp64 & 0xFFFFFFFF)

    a, b, c = 0x1111_2222_3333, 0x4444_5555_6666, 0x7777_8888_9999
    kh = np.zeros(64, np.uint32)
    kl = np.zeros(64, np.uint32)
    vh = np.zeros(64, np.uint32)
    vl = np.zeros(64, np.uint32)
    for slot, (key, parent) in enumerate([(a, 0), (b, a), (c, b)]):
        kh[slot], kl[slot] = lanes(key)
        vh[slot], vl[slot] = lanes(parent)
    pm = native.ParentMap(kh, kl, vh, vl)
    assert len(pm) == 3
    assert pm[c] == b and pm[b] == a and pm[a] == 0
    assert pm.chain(c) == [c, b, a]
    assert pm.get(0xDEAD) is None
    with pytest.raises(KeyError):
        pm.chain(0xDEAD)


def test_parentmap_python_fallback_matches(monkeypatch):
    # Force the dict fallback and compare against the native index.
    rng = np.random.default_rng(12)
    kh = rng.integers(1, 2**32, size=200, dtype=np.uint32)
    kl = rng.integers(1, 2**32, size=200, dtype=np.uint32)
    vh = rng.integers(0, 2**32, size=200, dtype=np.uint32)
    vl = rng.integers(0, 2**32, size=200, dtype=np.uint32)
    fast = native.ParentMap(kh, kl, vh, vl)
    monkeypatch.setattr(native, "_load", lambda: None)
    slow = native.ParentMap(kh, kl, vh, vl)
    assert slow._dict is not None
    assert len(fast) == len(slow)
    for i in range(0, 200, 17):
        key = (int(kh[i]) << 32) | int(kl[i])
        assert fast.get(key) == slow.get(key)


def test_fallback_chain_detects_cycles(monkeypatch):
    # a -> b -> a: the dict fallback must raise, not hang.
    def lanes(fp64):
        return np.uint32(fp64 >> 32), np.uint32(fp64 & 0xFFFFFFFF)

    a, b = 0x1111_2222_3333, 0x4444_5555_6666
    kh = np.zeros(64, np.uint32)
    kl = np.zeros(64, np.uint32)
    vh = np.zeros(64, np.uint32)
    vl = np.zeros(64, np.uint32)
    for slot, (key, parent) in enumerate([(a, b), (b, a)]):
        kh[slot], kl[slot] = lanes(key)
        vh[slot], vl[slot] = lanes(parent)
    monkeypatch.setattr(native, "_load", lambda: None)
    pm = native.ParentMap(kh, kl, vh, vl)
    with pytest.raises(RuntimeError, match="max_len"):
        pm.chain(a, max_len=100)


def test_xla_discoveries_use_native_parent_map():
    # End to end: witness reconstruction through the native index.
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    checker = (
        PackedTwoPhaseSys(3)
        .checker()
        .spawn_xla(frontier_capacity=1 << 10, table_capacity=1 << 13)
        .join()
    )
    checker.assert_properties()
    assert checker.discoveries()
