"""Multiplexed-superstep pins (ISSUE 16).

``stateright_tpu/xla_mux.py`` claims each lane of a K-job batched fused
dispatch is bit-identical to that job's solo run — counts, depths, and
discoveries — while paying the per-level fixed cost (sort + dispatch)
once for the whole batch. These tests pin that claim and the machinery
around it:

- **Exactness**: >=3 packed models x both non-delta dedup structures,
  every lane vs its solo ground truth; stragglers (per-lane state-count /
  depth targets, including a lane that is done at spawn) ride masked
  without perturbing siblings; K=1 degenerates bit-identically.
- **The ISSUE acceptance pin**: K=8 same-spec rm<=3 jobs through one mux
  = >=3x fewer device dispatches than 8 solo runs, counts bit-identical.
- **Typed ineligibility**: every ``MuxError`` precondition.
- **Lane telemetry** (docs/observability.md "Lane telemetry"): the mux
  ``dispatch_log`` 4-tuples, each lane's pinned 2-tuple ``dispatch_log``
  reconciling with its ``level_log``, per-row ``lanes``/``lanes_active``,
  and the ``mux_dispatches_saved`` accounting.
- **The census mux sub-dict** (STPU007, ``analysis/census.py``): opt-in,
  family-gated, summed into the compile-shape budget.
- **The <30s service drill** ``test_smoke_mux`` (rides in
  ``tools/smoke.sh``): a ``mux_k`` pool batches three same-spec jobs into
  ONE worker invocation — exact pinned counts, per-lane ``mux`` result
  provenance, pool gauges, and journaled ``mux_group`` starts.
"""

import pytest

from stateright_tpu.models.increment import PackedIncrement
from stateright_tpu.models.increment_lock import PackedIncrementLock
from stateright_tpu.models.single_copy_register import PackedSingleCopyRegister
from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.xla_mux import MuxChecker, MuxError

KW = dict(frontier_capacity=1 << 10, table_capacity=1 << 13)


def _summary(c):
    return (
        c.state_count(),
        c.unique_state_count(),
        c.max_depth(),
        {n: p.into_actions() for n, p in c.discoveries().items()},
    )


def _lanes(model, k, dedup, builder=lambda b: b):
    return [
        builder(model.checker()).spawn_xla(dedup=dedup, **KW)
        for _ in range(k)
    ]


# --- engine exactness -----------------------------------------------------


@pytest.mark.parametrize("dedup", ["hash", "sorted"])
@pytest.mark.parametrize(
    "factory",
    [
        lambda: PackedTwoPhaseSys(3),
        lambda: PackedIncrement(3),
        lambda: PackedIncrementLock(3),
    ],
    ids=["2pc", "increment", "increment-lock"],
)
def test_mux_lanes_bit_identical_to_solo(factory, dedup):
    model = factory()
    solo = _summary(model.checker().spawn_xla(dedup=dedup, **KW).join())
    lanes = _lanes(model, 3, dedup)
    mux = MuxChecker(lanes)
    mux.run_to_completion()
    assert mux.is_done()
    for ln in lanes:
        assert _summary(ln) == solo
    assert mux.state_count() == 3 * solo[0]
    assert mux.unique_state_count() == 3 * solo[1]


@pytest.mark.parametrize("dedup", ["hash", "sorted"])
def test_mux_straggler_lanes(dedup):
    """Uneven lane lifetimes: a depth-capped lane, a state-count-capped
    lane, a lane that is DONE at spawn (its init already meets the
    target), and an uncapped lane — each must finish bit-identical to a
    solo run with the same target, masked out without perturbing the
    lanes still running."""
    model = PackedTwoPhaseSys(3)
    shapers = [
        lambda b: b.target_max_depth(2),
        lambda b: b.target_state_count(40),
        lambda b: b.target_state_count(1),  # done after its first level
        lambda b: b,
    ]
    solos = [
        _summary(sh(model.checker()).spawn_xla(dedup=dedup, **KW).join())
        for sh in shapers
    ]
    lanes = [
        sh(model.checker()).spawn_xla(dedup=dedup, **KW) for sh in shapers
    ]
    mux = MuxChecker(lanes)
    mux.run_to_completion()
    assert [_summary(ln) for ln in lanes] == solos
    # The stragglers genuinely stopped early (targets are
    # level-granular, so the earliest lane still commits one level).
    assert lanes[0].max_depth() < lanes[3].max_depth()
    assert (
        lanes[2].state_count()
        < 40
        <= lanes[1].state_count()
        < lanes[3].state_count()
    )


def test_mux_k1_degenerates_bit_identically():
    model = PackedIncrementLock(3)
    solo = _summary(model.checker().spawn_xla(**KW).join())
    lane = model.checker().spawn_xla(**KW)
    mux = MuxChecker([lane])
    mux.run_to_completion()
    assert _summary(lane) == solo
    assert all(lanes == 1 for _, _, lanes, _ in mux.dispatch_log)
    # A single lane saves nothing; the accounting must say so.
    assert mux.metrics()["mux_dispatches_saved"] == 0


def test_mux_dispatch_acceptance_k8():
    """The ISSUE 16 acceptance criterion: K=8 same-spec rm<=3 jobs via
    mux take >=3x fewer device dispatches than 8 solo runs, with every
    lane's counts bit-identical to its solo run."""
    model = PackedTwoPhaseSys(3)
    solos = [model.checker().spawn_xla(**KW).join() for _ in range(8)]
    solo_dispatches = sum(len(c.dispatch_log) for c in solos)
    solo = _summary(solos[0])
    assert all(_summary(c) == solo for c in solos[1:])

    lanes = _lanes(model, 8, "auto")
    mux = MuxChecker(lanes)
    mux.run_to_completion()
    for ln in lanes:
        assert _summary(ln) == solo
    assert len(mux.dispatch_log) * 3 <= solo_dispatches, (
        mux.dispatch_log,
        solo_dispatches,
    )


# --- typed ineligibility --------------------------------------------------


def test_mux_error_pins():
    model = PackedTwoPhaseSys(3)
    with pytest.raises(MuxError, match="at least one lane"):
        MuxChecker([])
    ln = model.checker().spawn_xla(**KW)
    with pytest.raises(MuxError, match="distinct"):
        MuxChecker([ln, ln])
    with pytest.raises(MuxError, match="ONE model"):
        MuxChecker([ln, PackedTwoPhaseSys(3).checker().spawn_xla(**KW)])
    with pytest.raises(MuxError, match="disagree on dedup"):
        MuxChecker(
            [ln, model.checker().spawn_xla(dedup="sorted", **KW)]
        )
    with pytest.raises(MuxError, match="capacities"):
        MuxChecker(
            [
                ln,
                model.checker().spawn_xla(
                    frontier_capacity=1 << 9, table_capacity=1 << 13
                ),
            ]
        )
    with pytest.raises(MuxError, match="delta"):
        MuxChecker([model.checker().spawn_xla(dedup="delta", **KW)])
    with pytest.raises(MuxError, match="visitors"):
        MuxChecker(
            [model.checker().visitor(lambda path: None).spawn_xla(**KW)]
        )


class _HvSingleCopy(PackedSingleCopyRegister):
    """The shipped scr model with a property demoted to host
    verification — the structure ``registry.MUX_FAMILIES`` excludes
    statically and ``_check_lanes`` rejects typed."""

    host_verified_properties = frozenset({"linearizable"})


def test_mux_error_host_verified():
    model = _HvSingleCopy(2, 1)
    lane = model.checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 12
    )
    with pytest.raises(MuxError, match="host-verified"):
        MuxChecker([lane])


def test_mux_families_exclude_conditionally_host_verified():
    from stateright_tpu.service.registry import FAMILIES, MUX_FAMILIES

    assert "scr" not in MUX_FAMILIES
    assert MUX_FAMILIES == frozenset(FAMILIES) - {"scr"}


# --- lane telemetry -------------------------------------------------------


def test_mux_lane_telemetry():
    model = PackedTwoPhaseSys(3)
    lanes = _lanes(model, 2, "auto")
    mux = MuxChecker(lanes)
    mux.run_to_completion()
    # Mux dispatch_log: (run_cap, committed, lanes, lanes_active).
    assert mux.dispatch_log
    for run_cap, committed, k, active in mux.dispatch_log:
        assert k == 2 and 0 <= active <= k and committed >= 0
    # Each lane keeps the engine's pinned 2-tuple schema, reconciling
    # with its own level_log (the tests/test_obs.py invariant).
    for ln in lanes:
        assert all(len(t) == 2 for t in ln.dispatch_log)
        assert sum(c for _, c in ln.dispatch_log) == len(ln.level_log)
        for row in ln.level_log:
            assert {
                "bucket", "cand_cap", "lane_words", "lanes", "lanes_active"
            } <= set(row)
            assert row["lanes"] == 2
            assert 1 <= row["lanes_active"] <= 2
    m = mux.metrics()
    assert m["engine"] == "xla-mux"
    assert m["mux_lanes"] == 2
    assert m["mux_lanes_active"] == 0
    assert m["dispatches"] == len(mux.dispatch_log)
    assert m["mux_dispatches_saved"] == sum(
        max(0, active - 1) for _, _, _, active in mux.dispatch_log
    )
    assert m["mux_dispatches_saved"] >= 1


# --- the STPU007 census sub-dict ------------------------------------------


def test_census_mux_shapes_opt_in_and_family_gated():
    from stateright_tpu.analysis.census import census_findings, plan_for

    solo = plan_for("2pc:3", "tpu")
    assert "mux" not in solo
    plan = plan_for("2pc:3", "tpu", mux_k=4)
    assert plan["mux"]["k"] == 4
    # One batched program per solo bucket — the mux engine has no
    # in-program cand ladder, so its shape class is (k, bucket, cand_cap).
    assert [s["bucket"] for s in plan["mux"]["shapes"]] == [
        s["bucket"] for s in plan["shapes"]
    ]
    assert plan["mux"]["distinct_programs"] == plan["distinct_programs"]
    # The solo half of a mux-enabled plan is unchanged (warm_cache's
    # derivation and the banked compile_plan.json stay stable).
    assert {k: v for k, v in plan.items() if k != "mux"} == solo
    # Statically ineligible family: no mux sub-dict even when asked.
    assert "mux" not in plan_for("scr:3,1", "tpu", mux_k=4)
    # STPU007 prices the TOTAL: solo programs + batched programs.
    tight = dict(plan, budget=plan["distinct_programs"])
    findings = census_findings({"specs": {"2pc:3": {"tpu": tight}}})
    assert [f.rule for f in findings] == ["STPU007"]
    assert not census_findings({"specs": {"2pc:3": {"tpu": solo}}})


# --- the service drill (tools/smoke.sh) -----------------------------------


def test_smoke_mux(tmp_path):
    """The tier-0 batching drill: three same-spec jobs co-queued in a
    ``mux_k=3`` pool run as ONE ``worker.py --mux`` invocation — exact
    pinned counts per member, per-lane ``mux`` provenance in each
    result, pool gauges, and journaled ``mux_group`` starts."""
    from stateright_tpu.service import CheckerService, ServiceConfig
    from stateright_tpu.service.journal import read_journal

    cfg = ServiceConfig(
        run_dir=str(tmp_path / "svc"),
        platform="cpu",
        # Closed pool while submitting: the scheduler is event-driven,
        # so with open slots the first submission could start solo
        # before its siblings are queued. Deterministic co-queuing =
        # submit into zero slots, then open the pool once.
        max_inflight=0,
        mux_k=3,
        default_max_seconds=240.0,
        stall_s=8.0,
        startup_grace_s=240.0,
        poll_s=0.2,
        backoff_s=0.1,
        probe_auto=False,
        admission_lint=False,
    )
    svc = CheckerService(cfg)
    try:
        jobs = [svc.submit("2pc:3") for _ in range(3)]
        with svc._cond:
            cfg.max_inflight = 3
            svc._cond.notify_all()
        for job in jobs:
            assert job.wait(timeout=240), job.snapshot()
            assert job.status == "done", job.error
            assert (job.result["generated"], job.result["unique"]) == (
                1_146, 288,
            )
            assert job.result["mux"]["lanes"] == 3
            assert job.result["metrics"]["mux_lanes"] == 3
        assert len({j.result["mux"]["group"] for j in jobs}) == 1
        g = svc.gauges()
        assert g["mux_groups"] == 1
        assert g["mux_lanes"] == 3
        assert g["mux_dispatches_saved"] >= 1
        started = [
            r
            for r in read_journal(
                str(tmp_path / "svc" / "journal.jsonl")
            ).records
            if r.get("event") == "started"
        ]
        assert started and all(
            r.get("mux_group") and r.get("mux_lanes") == 3 for r in started
        )
    finally:
        svc.close()
