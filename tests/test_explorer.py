"""Explorer handler tests, driven without a live HTTP server.

Mirrors the reference's approach of calling the actix handlers directly with
TestRequest (explorer.rs:314-588): init-state views, next-state JSON with
fingerprints, ignored actions, 404s on bad fingerprint paths, status smoke
test, and run-to-completion.
"""

from typing import Any, List, Optional

from stateright_tpu.checker.explorer import make_app
from stateright_tpu.core import Model, Property
from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.test_util import BinaryClock


class _WithIgnoredAction(Model):
    """0 -> 1 via "go"; "stuck" is always proposed but always ignored."""

    def init_states(self) -> List[int]:
        return [0]

    def actions(self, state: int, actions: List[Any]) -> None:
        actions.extend(["go", "stuck"])

    def next_state(self, state: int, action: Any) -> Optional[int]:
        if action == "go" and state == 0:
            return 1
        return None

    def properties(self) -> List[Property]:
        return [Property.sometimes("reaches 1", lambda _m, s: s == 1)]


def test_init_states_view():
    app, _checker = make_app(BinaryClock().checker())
    code, body = app.states("/")
    assert code == 200
    assert len(body) == 2
    for view, state in zip(body, (0, 1)):
        assert view["state"] == repr(state)
        assert view["fingerprint"] == str(fingerprint(state))
        assert "action" not in view
        # (expectation, name, discovery) triples
        assert view["properties"][0][0] == "Always"
        assert view["properties"][0][1] == "in [0, 1]"


def test_next_states_view_includes_actions_and_outcomes():
    model = BinaryClock()
    app, _checker = make_app(model.checker())
    fp0 = fingerprint(0)
    code, body = app.states(f"/{fp0}")
    assert code == 200
    assert len(body) == 1
    (view,) = body
    assert view["action"] == "GoHigh"
    assert view["fingerprint"] == str(fingerprint(1))
    assert view["outcome"] is not None


def test_ignored_actions_are_reported_without_state():
    app, _checker = make_app(_WithIgnoredAction().checker())
    code, body = app.states(f"/{fingerprint(0)}")
    assert code == 200
    # "go" produces a state; "stuck" is ignored but still listed
    # (explorer.rs:292-300).
    # Default format_action is repr (lib.rs:224-230 analogue).
    assert [v["action"] for v in body] == ["'go'", "'stuck'"]
    assert "fingerprint" in body[0]
    assert "fingerprint" not in body[1]
    assert "state" not in body[1]


def test_unparseable_fingerprints_404():
    app, _checker = make_app(BinaryClock().checker())
    code, body = app.states("/not-a-number")
    assert code == 404
    assert "Unable to parse" in body


def test_unknown_fingerprint_404():
    app, _checker = make_app(BinaryClock().checker())
    code, body = app.states("/123456789")
    assert code == 404
    assert "Unable to find state" in body


def test_status_reflects_demand_driven_progress():
    app, checker = make_app(TwoPhaseSys(2).checker())
    status = app.status()
    assert status["model"] == "TwoPhaseSys"
    assert status["done"] is False
    assert status["state_count"] == 1  # only the init state so far
    names = [p[1] for p in status["properties"]]
    assert names == [p.name for p in TwoPhaseSys(2).properties()]

    # Walking init states asks the checker to expand them on demand.
    app.states("/")
    assert app.status()["state_count"] >= status["state_count"]


def test_run_to_completion_finishes_via_drive():
    app, checker = make_app(TwoPhaseSys(2).checker())
    app.run_to_completion()
    while not checker.is_done():
        app.drive()
    status = app.status()
    assert status["done"] is True
    bfs = TwoPhaseSys(2).checker().spawn_bfs().join()
    assert status["unique_state_count"] == bfs.unique_state_count()
    # Discovered "sometimes" properties carry an encoded path usable as a
    # /.states URL (explorer.rs:187-205).
    discovered = [p for p in status["properties"] if p[2] is not None]
    assert discovered
    code, _body = app.states("/" + discovered[0][2])
    assert code == 200


def test_recent_path_snapshot_populates():
    app, checker = make_app(TwoPhaseSys(2).checker())
    app.run_to_completion()
    app.drive()
    assert app.status()["recent_path"] is not None


def test_explorer_live_socket_smoke():
    """One real HTTP round-trip: bind a loopback server on an ephemeral
    port, GET /.status and a state page, assert the JSON contract — the
    live-socket complement to the framework-free handler tests."""
    import json
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from stateright_tpu.checker.explorer import _ExplorerHandler, make_app
    from stateright_tpu.models.two_phase_commit import TwoPhaseSys

    app, checker = make_app(TwoPhaseSys(2).checker())

    class Handler(_ExplorerHandler):
        explorer_app = app

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.status", timeout=5
        ) as resp:
            status = json.load(resp)
        assert status["model"] == "TwoPhaseSys"
        assert "consistent" in [p[1] for p in status["properties"]]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/.states/", timeout=5
        ) as resp:
            states = json.load(resp)
        assert len(states) == 1  # the single 2pc init state
    finally:
        server.shutdown()
        t.join(timeout=5)
