"""OnDemandChecker semantics (reference: src/checker/on_demand.rs).

The demand-driven engine must compute nothing until asked, expand exactly
the requested frontier entry per ``check_fingerprint``, and behave like the
batch BFS once ``run_to_completion`` is called.
"""

import pytest

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.test_util import BinaryClock


def test_computes_nothing_until_asked():
    checker = BinaryClock().checker().spawn_on_demand()
    assert checker.state_count() == 2  # just the init states
    assert checker.unique_state_count() == 2
    assert checker.max_depth() == 0
    assert not checker.is_done()


def test_check_fingerprint_expands_exactly_one_entry():
    model = BinaryClock()
    checker = model.checker().spawn_on_demand()
    init0 = model.init_states()[0]
    before = checker.unique_state_count()
    checker.check_fingerprint(fingerprint(init0))
    # binary clock: each state has exactly one successor (the other bit).
    assert checker.unique_state_count() == before  # successor is the other init
    assert checker.max_depth() == 1


def test_unknown_fingerprint_is_ignored():
    checker = BinaryClock().checker().spawn_on_demand()
    checker.check_fingerprint(0xDEADBEEF)
    assert checker.state_count() == 2
    assert checker.max_depth() == 0


def test_run_to_completion_matches_bfs():
    on_demand = TwoPhaseSys(3).checker().spawn_on_demand()
    on_demand.run_to_completion()
    on_demand.join()
    bfs = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert on_demand.unique_state_count() == bfs.unique_state_count() == 288
    assert set(on_demand.discoveries()) == set(bfs.discoveries())


def test_join_without_run_to_completion_raises():
    checker = BinaryClock().checker().spawn_on_demand()
    with pytest.raises(RuntimeError, match="run_to_completion"):
        checker.join()


def test_exhausted_frontier_reports_done_while_waiting():
    # Driving every pending entry by hand exhausts the 2-state space; a
    # fully-explored on-demand checker must report done (and join cleanly)
    # even though run_to_completion was never called.
    checker = BinaryClock().checker().spawn_on_demand()
    while checker._pending:
        checker.check_fingerprint(checker._pending[-1][1])
    assert checker.is_done()
    checker.join()  # must not raise


def test_demand_driven_discovery_completes():
    # Driving the frontier by hand can still complete the check when every
    # property finds a discovery along the driven path.
    model = TwoPhaseSys(2)
    checker = model.checker().spawn_on_demand()
    # Repeatedly expand whatever is pending until the checker reports done.
    for _ in range(10_000):
        if checker.is_done() or not checker._pending:
            break
        checker.check_fingerprint(checker._pending[-1][1])
    full = TwoPhaseSys(2).checker().spawn_bfs().join()
    # Driving every pending entry visits the whole space.
    assert checker.unique_state_count() == full.unique_state_count()


def test_block_size_expands_clicked_subtree():
    # The reference's granularity: one click pre-computes up to a
    # 1500-state block of the clicked subtree (on_demand.rs:209-218).
    # 2pc(3) has 288 reachable states, so a big-block click on the single
    # init state computes the ENTIRE space in one request.
    model = TwoPhaseSys(3)
    checker = model.checker().spawn_on_demand(block_size=1500)
    init_fp = fingerprint(model.init_states()[0])
    checker.check_fingerprint(init_fp)
    assert checker.unique_state_count() == 288
    assert checker.is_done()  # the driven frontier ran dry

    # A bounded block stops at the budget.
    bounded = model.checker().spawn_on_demand(block_size=10)
    bounded.check_fingerprint(init_fp)
    assert not bounded.is_done()
    assert 10 <= bounded.unique_state_count() < 288


def test_block_size_one_is_exact_entry():
    model = BinaryClock()
    checker = model.checker().spawn_on_demand(block_size=1)
    checker.check_fingerprint(fingerprint(model.init_states()[0]))
    assert checker.max_depth() == 1
    assert checker.unique_state_count() == 2


def test_block_expansion_respects_target_state_count():
    # The block must stop as soon as the engine signals stop (the
    # reference's check_block bails mid-block too) — a state-count target
    # set below the block budget caps the click's expansion.
    model = TwoPhaseSys(3)
    checker = model.checker().target_state_count(50).spawn_on_demand(
        block_size=1500
    )
    checker.check_fingerprint(fingerprint(model.init_states()[0]))
    assert checker.is_done()
    # One overshooting expansion at most (the signal lands after the
    # expansion that crosses the target, as in _run_block).
    assert checker.state_count() < 50 + 16
