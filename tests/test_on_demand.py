"""OnDemandChecker semantics (reference: src/checker/on_demand.rs).

The demand-driven engine must compute nothing until asked, expand exactly
the requested frontier entry per ``check_fingerprint``, and behave like the
batch BFS once ``run_to_completion`` is called.
"""

import pytest

from stateright_tpu.fingerprint import fingerprint
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.test_util import BinaryClock


def test_computes_nothing_until_asked():
    checker = BinaryClock().checker().spawn_on_demand()
    assert checker.state_count() == 2  # just the init states
    assert checker.unique_state_count() == 2
    assert checker.max_depth() == 0
    assert not checker.is_done()


def test_check_fingerprint_expands_exactly_one_entry():
    model = BinaryClock()
    checker = model.checker().spawn_on_demand()
    init0 = model.init_states()[0]
    before = checker.unique_state_count()
    checker.check_fingerprint(fingerprint(init0))
    # binary clock: each state has exactly one successor (the other bit).
    assert checker.unique_state_count() == before  # successor is the other init
    assert checker.max_depth() == 1


def test_unknown_fingerprint_is_ignored():
    checker = BinaryClock().checker().spawn_on_demand()
    checker.check_fingerprint(0xDEADBEEF)
    assert checker.state_count() == 2
    assert checker.max_depth() == 0


def test_run_to_completion_matches_bfs():
    on_demand = TwoPhaseSys(3).checker().spawn_on_demand()
    on_demand.run_to_completion()
    on_demand.join()
    bfs = TwoPhaseSys(3).checker().spawn_bfs().join()
    assert on_demand.unique_state_count() == bfs.unique_state_count() == 288
    assert set(on_demand.discoveries()) == set(bfs.discoveries())


def test_join_without_run_to_completion_raises():
    checker = BinaryClock().checker().spawn_on_demand()
    with pytest.raises(RuntimeError, match="run_to_completion"):
        checker.join()


def test_exhausted_frontier_reports_done_while_waiting():
    # Driving every pending entry by hand exhausts the 2-state space; a
    # fully-explored on-demand checker must report done (and join cleanly)
    # even though run_to_completion was never called.
    checker = BinaryClock().checker().spawn_on_demand()
    while checker._pending:
        checker.check_fingerprint(checker._pending[-1][1])
    assert checker.is_done()
    checker.join()  # must not raise


def test_demand_driven_discovery_completes():
    # Driving the frontier by hand can still complete the check when every
    # property finds a discovery along the driven path.
    model = TwoPhaseSys(2)
    checker = model.checker().spawn_on_demand()
    # Repeatedly expand whatever is pending until the checker reports done.
    for _ in range(10_000):
        if checker.is_done() or not checker._pending:
            break
        checker.check_fingerprint(checker._pending[-1][1])
    full = TwoPhaseSys(2).checker().spawn_bfs().join()
    # Driving every pending entry visits the whole space.
    assert checker.unique_state_count() == full.unique_state_count()
