"""Pallas stream-compaction kernels (ops/pallas_compact.py): kernel-level
equality against numpy, and full-engine equality of compaction="pallas"
against the sort lowering — counts AND witness paths.

On CPU the kernels run in pallas interpret mode (they have no CPU
lowering); on TPU the same code compiles for real — the tools/ A/B
measures whether the O(n) stream beats the O(n log^2 n) sort there.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from stateright_tpu.ops.pallas_compact import compact_pallas_staged


@pytest.mark.parametrize("kernel", [compact_pallas_staged])
def test_kernel_matches_numpy(kernel):
    rng = np.random.default_rng(9)
    P, M, cap, B = 5, 1 << 12, 1 << 11, 256
    mask_np = rng.integers(0, 5, M) == 0
    planes_np = rng.integers(0, 2**32, (P, M), dtype=np.uint32)
    out = kernel(
        jnp.asarray(mask_np), jnp.asarray(planes_np), cap, block=B, interpret=True
    )
    n = int(mask_np.sum())
    assert np.array_equal(np.asarray(out)[:, :n], planes_np[:, mask_np])


def test_kernel_overflow_lanes_are_dropped_not_written():
    """Survivors past ``cap`` must not fault or wrap: the kernel skips
    whole chunks that would cross the cap (the engine's cc_ovf retry
    handles the loss)."""
    rng = np.random.default_rng(11)
    P, M, cap, B = 3, 1 << 10, 256, 128
    mask_np = np.ones(M, bool)  # every lane survives: 1024 >> cap 256
    planes_np = rng.integers(0, 2**32, (P, M), dtype=np.uint32)
    out = compact_pallas_staged(
        jnp.asarray(mask_np), jnp.asarray(planes_np), cap, block=B, interpret=True
    )
    assert np.array_equal(np.asarray(out)[:, :cap], planes_np[:, :cap])


def test_engine_compaction_pallas_matches_sort(monkeypatch):
    """Full-engine differential at a kernel block small enough that the
    tiny test space actually engages the kernel (bigger buckets only)."""
    monkeypatch.setenv("STPU_PALLAS_BLOCK", "128")
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    kw = dict(frontier_capacity=1 << 10, table_capacity=1 << 12, dedup="sorted")
    a = PackedTwoPhaseSys(3).checker().spawn_xla(compaction="sort", **kw).join()
    b = PackedTwoPhaseSys(3).checker().spawn_xla(compaction="pallas", **kw).join()
    assert (a.state_count(), a.unique_state_count(), a.max_depth()) == (
        b.state_count(),
        b.unique_state_count(),
        b.max_depth(),
    ) == (1146, 288, 11)
    da, db = a.discoveries(), b.discoveries()
    assert set(da) == set(db) and da
    for name in da:
        assert da[name].into_states() == db[name].into_states()


def test_pallas_requires_planes_engine():
    from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

    with pytest.raises(ValueError, match="plane-major"):
        PackedTwoPhaseSys(3).checker().spawn_xla(
            compaction="pallas", dedup="hash",
            frontier_capacity=1 << 8, table_capacity=1 << 10,
        )
