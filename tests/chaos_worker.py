"""Fault-injection worker for the supervisor chaos tests.

Runs a packed-model check under in-loop auto-checkpointing and (via the
``STPU_HEARTBEAT`` env the supervisor injects) the heartbeat protocol,
optionally sabotaging itself at a given depth — exactly once, gated by a
marker file, so the supervised RELAUNCH runs clean:

- ``--die-at-depth N``: SIGKILL itself at the first quiescent point at or
  past depth N (a crash mid-run; nothing gets to flush);
- ``--freeze-at-depth N``: rewrite the heartbeat to ``phase="dispatch"``
  and SIGSTOP itself — the exact signature of a wedged tunnel (a frozen
  process mid-device-call), which the supervisor must detect by heartbeat
  staleness and kill.

At completion the final counts/discoveries land in ``--out`` (atomic
write), for the test to compare bit-for-bit against an uninterrupted run.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_model(spec: str):
    if spec.startswith("2pc"):
        from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys

        return PackedTwoPhaseSys(int(spec[3:])), dict(
            frontier_capacity=1 << 10, table_capacity=1 << 13
        )
    if spec == "scr31":
        from stateright_tpu.models.single_copy_register import (
            PackedSingleCopyRegister,
        )

        return PackedSingleCopyRegister(3, 1), dict(
            frontier_capacity=1 << 11, table_capacity=1 << 14
        )
    raise SystemExit(f"unknown model spec {spec!r}")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", required=True)  # 2pc3 | 2pc4 | scr31
    p.add_argument("--engine", default="single")  # single | sharded
    p.add_argument("--checkpoint", required=True)  # auto-checkpoint base
    p.add_argument("--resume", default=None)
    p.add_argument("--every", default="1")  # cadence (levels by default)
    p.add_argument("--keep", type=int, default=3)
    p.add_argument("--die-at-depth", type=int, default=None)
    p.add_argument("--freeze-at-depth", type=int, default=None)
    p.add_argument("--chaos-marker", default=None)
    p.add_argument("--out", required=True)
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    model, kw = _build_model(args.model)
    kw.update(
        # One level per dispatch: fine-grained quiescent points, so the
        # chaos depth and the checkpoint cadence line up deterministically.
        levels_per_dispatch=1,
        checkpoint_to=args.checkpoint,
        checkpoint_every=args.every,
        checkpoint_keep=args.keep,
    )
    if args.resume:
        kw["checkpoint"] = args.resume
    if args.engine == "sharded":
        from stateright_tpu.parallel import default_mesh

        kw["mesh"] = default_mesh()
    checker = model.checker().spawn_xla(**kw)
    start_depth = checker._depth

    armed = args.chaos_marker is not None and not os.path.exists(
        args.chaos_marker
    )

    def trip():
        # Exactly-once: mark BEFORE the signal so the relaunch runs clean.
        with open(args.chaos_marker, "w") as fh:
            fh.write("tripped\n")

    while not checker.is_done():
        checker._run_block()
        depth = checker._depth
        if armed and args.die_at_depth is not None and depth >= args.die_at_depth:
            trip()
            os.kill(os.getpid(), signal.SIGKILL)
        if (
            armed
            and args.freeze_at_depth is not None
            and depth >= args.freeze_at_depth
        ):
            trip()
            # A wedged tunnel's signature: the engine entered a device
            # dispatch (heartbeat phase="dispatch", no compile in flight)
            # and never came back.
            if checker._heartbeat is not None:
                checker._heartbeat.beat("dispatch", compile=False)
            os.kill(os.getpid(), signal.SIGSTOP)

    result = {
        "model": args.model,
        "engine": args.engine,
        "generated": checker.state_count(),
        "unique": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "discoveries": {
            name: [repr(a) for a in path.into_actions()]
            for name, path in sorted(checker.discoveries().items())
        },
        "resumed_from": args.resume,
        "start_depth": start_depth,
        "checkpoints_written": checker.metrics()["checkpoints_written"],
        "last_checkpoint_level": checker.metrics()["last_checkpoint_level"],
    }
    tmp = args.out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh)
    os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
