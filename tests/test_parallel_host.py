"""Parallel host BFS (``threads(n)``) vs the sequential oracle.

Full-coverage runs must match the sequential engine's counts exactly; the
witness for a given property must be a valid path whose final state
satisfies/violates the property as required. Early-exit timing (mid-level
vs end-of-level) is the one documented divergence, so count assertions here
use full-coverage configurations.
"""

import pytest

from stateright_tpu.core import Property
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.checker.parallel_host import ParallelBfsChecker
from stateright_tpu.test_util import DGraph, Guess, LinearEquation


def test_threads_dispatches_to_parallel_engine():
    c = TwoPhaseSys(3).checker().threads(3).spawn_bfs()
    assert isinstance(c, ParallelBfsChecker)
    c.join()
    assert c.unique_state_count() == 288


def test_parallel_2pc_matches_oracle_counts():
    seq = TwoPhaseSys(3).checker().spawn_bfs().join()
    par = TwoPhaseSys(3).checker().threads(4).spawn_bfs().join()
    assert par.unique_state_count() == seq.unique_state_count() == 288
    assert par.state_count() == seq.state_count()
    assert par.max_depth() == seq.max_depth()


def test_parallel_discovery_is_valid_witness():
    # "sometimes committed" should yield a real path ending in a committed
    # state; BFS witnesses are depth-minimal in both engines.
    seq = TwoPhaseSys(3).checker().spawn_bfs().join()
    par = TwoPhaseSys(3).checker().threads(3).spawn_bfs().join()
    assert set(par.discoveries()) == set(seq.discoveries())
    for name, path in par.discoveries().items():
        assert len(path) == len(seq.discoveries()[name]), name


def test_parallel_eventually_counterexample():
    # Terminal even node with the eventually-odd property: the parallel
    # engine must surface the same counterexample class.
    g = (
        DGraph.with_property(Property.eventually("odd", lambda _, s: s % 2 == 1))
        .with_path([0, 2, 4])
        .with_path([0, 1])
    )
    par = g.checker().threads(2).spawn_bfs().join()
    disc = par.discoveries()
    assert "odd" in disc
    assert disc["odd"].last_state() % 2 == 0


def test_parallel_full_enumeration():
    # Unsolvable LinearEquation enumerates all 256*256 states
    # (bfs.rs:494-503): the largest full-coverage parity check.
    par = LinearEquation(2, 4, 7).checker().threads(4).spawn_bfs().join()
    assert par.is_done()
    par.assert_no_discovery("solvable")
    assert par.unique_state_count() == 256 * 256


def test_parallel_early_exit_discovery():
    # Early-exit run: counts may differ from sequential (level granularity),
    # but the BFS witness must still be depth-minimal and valid.
    par = LinearEquation(2, 10, 14).checker().threads(3).spawn_bfs().join()
    assert len(par.discovery("solvable").into_actions()) == 3
    par.assert_discovery("solvable", [Guess.INCREASE_Y] * 27)


def test_parallel_target_max_depth():
    seq = TwoPhaseSys(3).checker().target_max_depth(3).spawn_bfs().join()
    par = TwoPhaseSys(3).checker().threads(3).target_max_depth(3).spawn_bfs().join()
    assert par.max_depth() == seq.max_depth() == 3
    assert par.unique_state_count() == seq.unique_state_count()


def test_parallel_target_state_count():
    par = TwoPhaseSys(3).checker().threads(3).target_state_count(50).spawn_bfs().join()
    assert par.state_count() >= 50


def test_parallel_visitor_falls_back_to_sequential():
    # Visitors observe per-state paths sequentially; the builder routes to
    # the sequential engine (direct construction raises instead).
    c = TwoPhaseSys(3).checker().threads(3).visitor(lambda path: None).spawn_bfs()
    assert not isinstance(c, ParallelBfsChecker)
    c.join()
    assert c.unique_state_count() == 288


class _ExplodingModel(TwoPhaseSys):
    """next_state raises once expansion reaches depth 2."""

    def next_state(self, state, action):
        nxt = super().next_state(state, action)
        if nxt is not None and len(nxt.msgs) >= 2:
            raise RuntimeError("boom in model callback")
        return nxt


def test_parallel_worker_failure_raises_not_hangs():
    c = _ExplodingModel(3).checker().threads(3).spawn_bfs()
    with pytest.raises(RuntimeError, match="boom in model callback"):
        c.join()


def test_parallel_close_before_start_is_harmless():
    c = TwoPhaseSys(3).checker().threads(3).spawn_bfs()
    c.close()  # nothing started yet; must not poison the lifecycle
    c.join()
    assert c.unique_state_count() == 288
    assert set(c.discoveries()) == {"abort agreement", "commit agreement"}


def test_eval_properties_clears_ebits_after_discovery():
    # Regression (unit-level, because the end-to-end effect is masked by the
    # main process's per-name discovery dedup): a worker that records an
    # EVENTUALLY discovery mid-level used to skip the ebit-clearing branch
    # for LATER frontier states in the same level, so their children
    # inherited a stale eventually-bit.
    from stateright_tpu.checker.parallel_host import _eval_properties

    props = [Property.eventually("odd", lambda _, s: s % 2 == 1)]
    discoveries = {0: 0xDEAD}  # "odd" already discovered this level
    ebits = _eval_properties(None, props, 3, 0xBEEF, frozenset({0}), discoveries)
    assert ebits == frozenset()  # condition held -> bit must clear anyway
    assert discoveries == {0: 0xDEAD}  # and the recorded witness is untouched
    # A non-satisfying state keeps its bit.
    ebits = _eval_properties(None, props, 2, 0xF00D, frozenset({0}), discoveries)
    assert ebits == frozenset({0})


def test_parallel_path_query_after_close_raises_descriptive():
    # discoveries() for a fingerprint whose path was never cached must fail
    # loudly once the pool is gone, not hang on a dead pipe.
    c = TwoPhaseSys(3).checker().threads(2).spawn_bfs()
    # Run levels until a discovery is recorded but the check is not done.
    while not c._discoveries and not c.is_done():
        c._run_block()
    assert c._discoveries and not c.is_done()
    c.close()
    with pytest.raises(RuntimeError, match="closed"):
        c.discoveries()


def test_parallel_symmetry_deterministic_and_sound():
    # Under symmetry reduction the visited-class count depends on which
    # class member continues the search (canonicalization is sound but
    # order-dependent — the reachable 2pc(3) set spans 120 classes, of
    # which sequential BFS visits 94 and this engine 102), so counts are
    # compared run-to-run (determinism) rather than engine-to-engine.
    seq = TwoPhaseSys(3).checker().symmetry().spawn_bfs().join()
    a = TwoPhaseSys(3).checker().threads(3).symmetry().spawn_bfs().join()
    b = TwoPhaseSys(3).checker().threads(3).symmetry().spawn_bfs().join()
    assert a.unique_state_count() == b.unique_state_count()
    assert a.state_count() == b.state_count()
    assert a.unique_state_count() < 288  # the reduction reduces
    assert set(a.discoveries()) == set(seq.discoveries())
