"""Docs are executable: every ```python block in README.md and
docs/tutorial.md runs, in file order, sharing one namespace per file.

This is the parity answer to the reference's doc-tests (its sliding-puzzle
first model lives in a `lib.rs` doc-test the Rust toolchain executes,
lib.rs:40-115; the logical-clock actor in actor.rs:11-79). Python has no
rustdoc, so this test extracts and execs the fenced blocks instead — a doc
snippet that drifts from the API fails CI, same guarantee.
"""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FENCE = re.compile(r"```python\n(.*?)```", re.DOTALL)


def _blocks(relpath):
    with open(os.path.join(REPO, relpath)) as fh:
        return _FENCE.findall(fh.read())


@pytest.mark.parametrize("relpath", ["README.md", "docs/tutorial.md"])
def test_doc_code_blocks_run(relpath):
    blocks = _blocks(relpath)
    assert blocks, f"{relpath} has no ```python blocks"
    ns = {"__name__": f"doc:{relpath}"}
    for i, src in enumerate(blocks):
        try:
            exec(compile(src, f"{relpath}[block {i}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - the failure IS the signal
            raise AssertionError(
                f"{relpath} code block {i} failed: {type(e).__name__}: {e}\n"
                f"--- block source ---\n{src}"
            ) from e
