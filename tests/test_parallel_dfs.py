"""Job-market parallel DFS (``threads(n)`` + ``spawn_dfs``) vs the
sequential oracle.

Full-coverage counts are engine-invariant (every unique state expands
exactly once); visit order and early-exit timing are scheduling-dependent,
exactly as in the reference's racing worker threads (dfs.rs:92-215), so
count assertions here use full-coverage configurations.
"""

import pytest

from stateright_tpu.checker.parallel_dfs import ParallelDfsChecker
from stateright_tpu.core import Property
from stateright_tpu.models.two_phase_commit import TwoPhaseSys
from stateright_tpu.test_util import DGraph, LinearEquation


def test_threads_dispatches_to_parallel_dfs():
    c = TwoPhaseSys(3).checker().threads(3).spawn_dfs()
    assert isinstance(c, ParallelDfsChecker)
    c.join()
    assert c.unique_state_count() == 288


def test_parallel_dfs_full_coverage_parity():
    seq = TwoPhaseSys(3).checker().spawn_dfs().join()
    par = TwoPhaseSys(3).checker().threads(4).spawn_dfs().join()
    assert par.unique_state_count() == seq.unique_state_count() == 288
    assert par.state_count() == seq.state_count()
    # max_depth is first-visit depth: scheduling-dependent under parallel
    # DFS, bounded below by the BFS eccentricity (11 for 2pc(3)). The
    # sequential engine's visit order is deterministic, so its depth stays
    # pinned exactly.
    assert seq.max_depth() == 11
    assert par.max_depth() >= 11
    assert set(par.discoveries()) == set(seq.discoveries())
    par.assert_properties()


def test_parallel_dfs_witnesses_are_valid():
    par = TwoPhaseSys(3).checker().threads(3).spawn_dfs().join()
    for name, path in par.discoveries().items():
        # Witness paths need not be depth-minimal (DFS), but must replay
        # from init to a state with the discovered property.
        par.assert_discovery(name, path.into_actions())


def test_parallel_dfs_symmetry_sound():
    # Canonicalization under racing workers is sound but class-choice is
    # scheduling-dependent (as in dfs.rs:357-366): the reduction must
    # reduce and find the same discovery set, counts may vary run-to-run.
    seq = TwoPhaseSys(3).checker().spawn_dfs().join()
    s = TwoPhaseSys(3).checker().threads(3).symmetry().spawn_dfs().join()
    assert s.unique_state_count() < 288
    assert set(s.discoveries()) == set(seq.discoveries())


def test_parallel_dfs_eventually_terminal_counterexample():
    # A cycle-free terminal even node violates the eventually property;
    # the parallel engine surfaces the same counterexample class.
    graph = (
        DGraph.with_property(Property.eventually("odd", lambda _, s: s % 2 == 1))
        .with_path([0, 2, 4])
        .with_path([4, 6])
    )
    c = graph.checker().threads(2).spawn_dfs().join()
    assert "odd" in c.discoveries()


def test_parallel_dfs_target_state_count_stops_early():
    # Unsatisfiable parity: no discovery can end the search early, so the
    # state-count target is what stops it.
    c = (
        LinearEquation(2, 2, 1)
        .checker()
        .target_state_count(1000)
        .threads(3)
        .spawn_dfs()
        .join()
    )
    assert c.state_count() >= 1000
    # well short of the 65,536-state full space
    assert c.state_count() < 10_000


def test_parallel_dfs_linear_equation_full_space():
    # The 65,536-state full-enumeration anchor (bfs.rs:502): a solution
    # exists, so the search early-exits on discovery; with no solution
    # (unsatisfiable parity) it must sweep the whole space.
    sat = LinearEquation(2, 10, 14).checker().threads(3).spawn_dfs().join()
    assert "solvable" in sat.discoveries()
    unsat = LinearEquation(2, 2, 1).checker().threads(3).spawn_dfs().join()
    assert "solvable" not in unsat.discoveries()
    assert unsat.unique_state_count() == 65_536


def test_parallel_dfs_discovery_survives_target_trip():
    # A violation found on the very state whose expansion trips the
    # state-count target must still be reported (review regression).
    graph = DGraph.with_property(
        Property.always("small", lambda _, s: s < 7)
    ).with_path(list(range(10)))
    seq = graph.checker().target_state_count(9).spawn_dfs().join()
    par = graph.checker().target_state_count(9).threads(2).spawn_dfs().join()
    assert "small" in seq.discoveries()
    assert "small" in par.discoveries()


def test_parallel_dfs_duplicate_init_state_count_parity():
    # The oracle expands every seeded init, duplicates included; the
    # parallel engine must match full-coverage generated counts exactly
    # (review regression).
    graph = DGraph.with_property(
        Property.always("hold", lambda _, s: True)
    ).with_path(list(range(10)))
    base_inits = graph.init_states()
    graph.init_states = lambda: base_inits * 2  # duplicate init states
    seq = graph.checker().spawn_dfs().join()
    par = graph.checker().threads(2).spawn_dfs().join()
    assert par.state_count() == seq.state_count()
    assert par.unique_state_count() == seq.unique_state_count()


def test_parallel_dfs_zero_property_model_stops_after_one_state():
    # With zero properties nothing awaits a discovery: one state is
    # evaluated, then the search stops (bfs.rs:326-328; review regression).
    graph = DGraph().with_path(list(range(50)))
    seq = graph.checker().spawn_dfs().join()
    par = graph.checker().threads(2).spawn_dfs().join()
    assert par.unique_state_count() == seq.unique_state_count()
    assert par.unique_state_count() < 50
