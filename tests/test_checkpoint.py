"""Checkpoint/resume tests: stop after any super-step, resume later, on a
different engine/mesh — counts and discoveries must come out identical to an
uninterrupted run."""

import numpy as np
import pytest

import jax

from stateright_tpu.models.two_phase_commit import PackedTwoPhaseSys
from stateright_tpu.parallel import default_mesh


def _full_run_reference():
    checker = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    ).join()
    return checker


def test_single_chip_save_resume_roundtrip(tmp_path):
    ref = _full_run_reference()
    path = str(tmp_path / "ck.npz")

    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(4):  # part-way through the 14-level space
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    )
    assert resumed.state_count() == partial.state_count()
    assert resumed.unique_state_count() == partial.unique_state_count()
    resumed.join()
    assert resumed.unique_state_count() == ref.unique_state_count() == 1_568
    assert resumed.state_count() == ref.state_count()
    assert resumed.max_depth() == ref.max_depth()
    assert set(resumed.discoveries()) == set(ref.discoveries())
    resumed.assert_properties()


def test_resume_with_different_capacities(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(4):
        partial._run_block()
    partial.save_checkpoint(path)
    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 5, table_capacity=1 << 6, checkpoint=path
    ).join()
    assert resumed.unique_state_count() == 1_568


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_cross_engine_single_chip_to_sharded(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(5):
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8),
        frontier_capacity=1 << 10,
        table_capacity=1 << 13,
        checkpoint=path,
    )
    assert resumed.unique_state_count() == partial.unique_state_count()
    resumed.join()
    assert resumed.unique_state_count() == 1_568
    resumed.assert_properties()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs the 8-device mesh")
def test_cross_engine_sharded_to_single_chip(tmp_path):
    path = str(tmp_path / "ck.npz")
    partial = PackedTwoPhaseSys(4).checker().spawn_xla(
        mesh=default_mesh(8), frontier_capacity=1 << 10, table_capacity=1 << 13
    )
    for _ in range(5):
        partial._run_block()
    partial.save_checkpoint(path)

    resumed = PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    ).join()
    assert resumed.unique_state_count() == 1_568
    resumed.assert_properties()


def test_checkpoint_rejects_wrong_model(tmp_path):
    path = str(tmp_path / "ck.npz")
    PackedTwoPhaseSys(4).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    ).save_checkpoint(path)
    with pytest.raises(ValueError, match="does not match"):
        PackedTwoPhaseSys(5).checker().spawn_xla(
            frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
        )


def test_checkpoint_preserves_discovery_pins(tmp_path):
    # Run to completion (both sometimes-properties found), checkpoint, and
    # resume: the resumed checker must report the same witnesses without
    # re-searching.
    path = str(tmp_path / "ck.npz")
    done = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13
    ).join()
    done.save_checkpoint(path)
    resumed = PackedTwoPhaseSys(3).checker().spawn_xla(
        frontier_capacity=1 << 10, table_capacity=1 << 13, checkpoint=path
    )
    assert resumed._found_names == done._found_names
    a = {n: p.into_actions() for n, p in done.discoveries().items()}
    b = {n: p.into_actions() for n, p in resumed.discoveries().items()}
    assert a == b
